"""Measured KV-transfer cost tables.

Every KV movement on the serving path records ``(src, dst, path,
bytes, seconds)`` here:

    path="ici"      LocalKvTransferClient — same-host/slice shortcut
                    (in-process; the ICI/devicemem path on TPU)
    path="dcn"      KvTransferClient over TCP — the cross-host DCN hop
    path="persist"  persist-tier restore (shared-store read +
                    restore-through-host)

Per key the table keeps lifetime totals plus an EWMA of throughput
(MB/s) and per-call latency — the measured cost term NetKV-style
transfer-aware disagg routing needs (`overlap − kv_usage − slot_usage
− transfer_cost`, ROADMAP item 1).  Exported on ``/metrics`` as

    dynamo_tpu_kv_transfer_calls_total{src,dst,path}
    dynamo_tpu_kv_transfer_bytes_total{src,dst,path}
    dynamo_tpu_kv_transfer_seconds_total{src,dst,path}
    dynamo_tpu_kv_transfer_mbps{src,dst,path}           (EWMA)
    dynamo_tpu_kv_transfer_latency_ms{src,dst,path}     (EWMA)

Process-global singleton, same idiom as ``engine/counters.py``: the
kv layer records, the http layer renders, benchmarks read.
"""

from __future__ import annotations

import threading
import time
from typing import Callable

__all__ = ["TransferCostTable", "transfer_costs"]


class TransferCostTable:
    def __init__(self, alpha: float = 0.2,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self._alpha = alpha
        # injectable so the load plane's macro-simulation can run the
        # EWMAs at DetLoop virtual time instead of silently mixing
        # wall-clock into a simulated trace
        self._clock = clock
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        """Test isolation hook."""
        # (src, dst, path) -> dict of running stats
        self.table: dict[tuple, dict] = {}

    def record(self, src: str, dst: str, path: str,
               nbytes: int, seconds: float) -> None:
        if seconds <= 0:
            seconds = 1e-9  # clock granularity floor; keep the sample
        mbps = nbytes / seconds / 1e6
        key = (src, dst, path)
        a = self._alpha
        now = self._clock()
        with self._lock:
            e = self.table.get(key)
            if e is None:
                self.table[key] = {
                    "calls": 1, "bytes": nbytes, "seconds": seconds,
                    "ewma_mbps": mbps, "ewma_latency_s": seconds,
                    "updated_at": now,
                }
                return
            e["calls"] += 1
            e["bytes"] += nbytes
            e["seconds"] += seconds
            e["ewma_mbps"] = (1 - a) * e["ewma_mbps"] + a * mbps
            e["ewma_latency_s"] = (1 - a) * e["ewma_latency_s"] + a * seconds
            e["updated_at"] = now

    def cost_s(self, src: str, dst: str, path: str,
               nbytes: int) -> float:
        """Predicted seconds to move ``nbytes`` over an edge.

        Measured edges use the EWMA throughput.  Never-observed edges
        fall back to the dtperf topology prior (derated link bandwidth
        + hop latency, ``obs.topology.prior_cost_s``) so transfer-aware
        routing always has a finite cost instead of a cold-miss
        surprise; the first real transfer replaces the prior.  Use
        :meth:`measured` to distinguish the two.
        """
        with self._lock:
            e = self.table.get((src, dst, path))
            if e is None or e["ewma_mbps"] <= 0:
                from dynamo_tpu.obs.topology import prior_cost_s
                return prior_cost_s(path, nbytes)
            return nbytes / (e["ewma_mbps"] * 1e6)

    def measured(self, src: str, dst: str, path: str) -> bool:
        """True when the edge has at least one recorded transfer (so
        ``cost_s`` is measurement, not the topology prior)."""
        with self._lock:
            return (src, dst, path) in self.table

    def snapshot(self) -> dict[tuple, dict]:
        with self._lock:
            return {k: dict(v) for k, v in self.table.items()}


transfer_costs = TransferCostTable()
