"""dtspan — the request-tracing plane.

Zero-dependency observability for the five-process serving path:

- ``obs.tracing``: trace/span core with contextvar propagation, wire
  inject/extract helpers, and a bounded per-process ring-buffer
  collector.  Near-zero cost when disabled (one module-bool check, no
  allocation on the token path).
- ``obs.timeline``: the engine step timeline — per-phase wall-time
  attribution for ``EngineCore.step`` (host scheduling, upload, jitted
  dispatch, readback, post-processing).  Always on; a handful of
  ``perf_counter`` calls per step.
- ``obs.costs``: measured KV-transfer cost tables (EWMA per
  (src, dst, path)) fed by spans around ICI/DCN transfers and persist
  restores — the routing input NetKV-style transfer-aware disagg
  needs.  Never-observed edges fall back to the ``obs.topology``
  bandwidth prior instead of a cold miss.
- ``obs.topology``: the versioned per-topology hardware constants
  table (v5e peaks, ICI/DCN link bandwidths) shared with the dtperf
  lint plane; the committed perf manifest pins its version.
- ``obs.perfmodel``: runtime reconciliation of the dtperf roofline —
  engine dispatch sites offer their live signatures, predictions are
  traced lazily, and ``/metrics`` exports the predicted-vs-measured
  model-error gauge per dispatch kind.
- ``obs.export``: Chrome trace-event JSON (Perfetto-loadable) export,
  including the predicted-vs-measured dispatch counter track.
"""

from dynamo_tpu.obs.tracing import (  # noqa: F401
    attach,
    collector,
    current,
    detach,
    enable,
    enabled,
    extract,
    inject,
    set_process,
    start_span,
)
from dynamo_tpu.obs.timeline import step_timeline  # noqa: F401
from dynamo_tpu.obs.costs import transfer_costs  # noqa: F401
from dynamo_tpu.obs.perfmodel import perf_model  # noqa: F401
from dynamo_tpu.obs.export import chrome_trace  # noqa: F401
