"""Runtime reconciliation of the dtperf roofline model (predicted vs
measured dispatch latency).

The perf lint plane (``analysis/perfcheck.py``) prices entrypoint
jaxprs statically; this module closes the loop at runtime.  Each
engine dispatch site *offers* its jitted callable and live operand
shapes once per dispatch kind (``offer`` converts everything to
``ShapeDtypeStruct`` eagerly — no device arrays are retained — and is
a dict-lookup no-op afterwards).  The roofline prediction itself is
computed lazily on first read (``predicted_ms``), off the dispatch hot
path, by tracing the offered signature through
``perfcheck.estimate_callable``.

``reconcile()`` joins the predictions against the per-kind measured
dispatch seconds the step timeline accumulates
(``step_phase_seconds{phase="dispatch"}`` split by kind) into the
model-error rows that ``/metrics`` exports as

    dynamo_tpu_perf_predicted_dispatch_ms{kind}
    dynamo_tpu_perf_measured_dispatch_ms{kind}
    dynamo_tpu_perf_model_error_ratio{kind}      (predicted/measured)

and that serve_bench prints as the predicted-vs-measured table.  A
ratio near 1 means the static gate's tolerance bands are meaningful;
a drifting ratio is itself the signal that the cost model needs
re-calibration (new kernel, new fusion behavior, hardware change).

Process-global singleton with a ``reset()`` test hook, same idiom as
``engine/counters.py``.  Never raises into the engine: a prediction
failure is recorded as None and reported as an absent gauge.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

__all__ = ["PerfModel", "perf_model"]


def _shape_only(tree):
    """Pytree of device arrays -> pytree of ShapeDtypeStructs (non-array
    leaves pass through; they trace as weak-typed scalars)."""
    import jax

    def leaf(x):
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            return jax.ShapeDtypeStruct(tuple(x.shape), x.dtype)
        return x

    return jax.tree.map(leaf, tree)


class PerfModel:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        """Test isolation hook."""
        self.enabled = True
        # kind -> {fn, args, kw, statics, predicted (dict|None|"pending")}
        self._entries: dict[str, dict] = {}

    # ------------------------------------------------------------ hot path
    def wants(self, kind: str) -> bool:
        """True until a dispatch of this kind has been offered — the
        per-dispatch cost afterwards is this one dict lookup."""
        return self.enabled and kind not in self._entries

    def offer(self, kind: str, fn: Callable, args: tuple,
              kw: Optional[dict] = None,
              statics: Optional[dict] = None) -> None:
        """Record one dispatch signature: positional operands, device
        kwarg operands, and static kwargs.  Shapes are captured
        eagerly (no device-array references survive this call); the
        prediction is traced lazily on first read."""
        if not self.wants(kind):
            return
        try:
            entry = {
                "fn": fn,
                "args": _shape_only(tuple(args)),
                "kw": _shape_only(dict(kw or {})),
                "statics": dict(statics or {}),
                "predicted": "pending",
            }
        except Exception:
            return  # monitoring must never break the dispatch
        with self._lock:
            self._entries.setdefault(kind, entry)

    # ------------------------------------------------------------- readers
    def kinds(self) -> list[str]:
        return sorted(self._entries)

    def predicted(self, kind: str) -> Optional[dict]:
        """Full roofline estimate for an offered kind (traced on first
        call, cached; None if never offered or the trace failed)."""
        e = self._entries.get(kind)
        if e is None:
            return None
        if e["predicted"] != "pending":
            return e["predicted"]
        with self._lock:
            if e["predicted"] != "pending":
                return e["predicted"]
            try:
                import warnings

                # lazy import: obs stays a zero-dependency base layer;
                # the analysis plane is only pulled in when someone
                # actually reads a prediction
                from dynamo_tpu.analysis.perfcheck import (
                    estimate_callable,
                )

                fn, statics = e["fn"], e["statics"]
                names = sorted(e["kw"])
                pos = tuple(e["args"])
                npos = len(pos)
                kw_vals = tuple(e["kw"][n] for n in names)

                def call(*a):
                    kws = dict(zip(names, a[npos:]))
                    kws.update(statics)
                    return fn(*a[:npos], **kws)

                with warnings.catch_warnings():
                    warnings.simplefilter("ignore")
                    e["predicted"] = estimate_callable(
                        call, pos + kw_vals)
            except Exception:
                e["predicted"] = None
        return e["predicted"]

    def predicted_ms(self, kind: str) -> Optional[float]:
        est = self.predicted(kind)
        if est is None:
            return None
        return est["predicted"]["total_ms"]

    def reconcile(self) -> list[dict]:
        """Predicted-vs-measured rows per dispatch kind, joining the
        lazy roofline predictions with the step timeline's per-kind
        measured dispatch seconds."""
        from dynamo_tpu.obs.timeline import step_timeline

        snap = step_timeline.snapshot()
        measured = snap.get("dispatch_kinds", {})
        rows: list[dict] = []
        for kind in sorted(set(self.kinds()) | set(measured)):
            m = measured.get(kind, {})
            n = m.get("count", 0)
            meas_ms = (round(m.get("seconds", 0.0) / n * 1e3, 6)
                       if n else None)
            pred_ms = self.predicted_ms(kind)
            rows.append({
                "kind": kind,
                "predicted_ms": pred_ms,
                "measured_ms": meas_ms,
                "dispatches": n,
                # 4 significant digits, not 4 decimals: on CPU a v5e-
                # predicted ms is orders of magnitude under the measured
                # one and fixed rounding would collapse the ratio to 0
                "error_ratio": (
                    float(f"{pred_ms / meas_ms:.4g}")
                    if pred_ms is not None and meas_ms else None
                ),
            })
        return rows


perf_model = PerfModel()
