"""Canonical registry of every metric name on the ``/metrics`` surface.

The same ``protocol.py`` CoordOp idiom that pinned the coordinator op
strings: plain class-level ``NAME = "literal"`` constants, one class
per metric family, every name spelled in FULL (no prefix composition)
so the metrics lint plane (``analysis/metcheck.py``, dtmet) can bottom
every render/scrape site out at its literal through the dtwire-style
const table.  Render sites (``llm/http/metrics.py``,
``components/metrics.py``), scrape sites (``benchmarks/scrape.py``)
and tests all import these names — renaming a metric is one edit here,
and a missed consumer becomes an ImportError or an MT002 finding,
never a silently-zero bench column.

``SCHEMA`` is the committed name -> (type, label set) contract the
dtmet census is checked against; ``docs/observability.md``'s metric
reference table is generated from it (drift fails ``lint --metrics``).

Zero-dependency base layer (like the rest of ``obs/``): importable
from the engine, llm, components, benchmarks and tests without cycles.
"""

from __future__ import annotations

__all__ = [
    "HTTP_PREFIX", "FAULT_PREFIX", "ENGINE_PREFIX", "KV_PREFIX",
    "STREAM_PREFIX", "SHARD_PREFIX", "PERF_PREFIX", "ROUTER_PREFIX",
    "HttpMetric", "FaultMetric", "EngineMetric", "KvTransferMetric",
    "KvStreamMetric", "KvShardMetric", "PerfMetric", "RouterMetric",
    "SCHEMA", "metric_names",
]

# family prefixes — kept ONLY for prefix-scoped scraping/grouping
# (benchmarks/scrape.py family reads); metric names below never
# compose them at runtime
HTTP_PREFIX = "dynamo_tpu_http_service"
FAULT_PREFIX = "dynamo_tpu_fault"
ENGINE_PREFIX = "dynamo_tpu_engine"
KV_PREFIX = "dynamo_tpu_kv_transfer"
STREAM_PREFIX = "dynamo_tpu_kv_stream"
SHARD_PREFIX = "dynamo_tpu_kv_shard"
PERF_PREFIX = "dynamo_tpu_perf"
ROUTER_PREFIX = "dynamo_tpu"


class HttpMetric:
    """HTTP service plane (``llm/http/metrics.py`` Metrics.render)."""

    REQUESTS_TOTAL = "dynamo_tpu_http_service_requests_total"
    INFLIGHT_REQUESTS = "dynamo_tpu_http_service_inflight_requests"
    OUTPUT_TOKENS_TOTAL = "dynamo_tpu_http_service_output_tokens_total"
    ADMISSION_SHED_TOTAL = "dynamo_tpu_http_service_admission_shed_total"
    TTFT_SECONDS = "dynamo_tpu_http_service_ttft_seconds"
    INTER_TOKEN_SECONDS = "dynamo_tpu_http_service_inter_token_seconds"
    QUEUE_WAIT_SECONDS = "dynamo_tpu_http_service_queue_wait_seconds"
    REQUEST_SECONDS = "dynamo_tpu_http_service_request_seconds"


class FaultMetric:
    """Fault plane (``fault/counters.py`` process-global counters)."""

    MIGRATIONS_TOTAL = "dynamo_tpu_fault_migrations_total"
    DRAINS_IN_PROGRESS = "dynamo_tpu_fault_drains_in_progress"
    SUSPECT_INSTANCES = "dynamo_tpu_fault_suspect_instances"


class EngineMetric:
    """Engine plane: prefill batching, unified dispatch, lookahead,
    persist tier (``engine/counters.py``) and the step timeline
    (``obs/timeline.py``)."""

    PREFILL_DISPATCHES_TOTAL = "dynamo_tpu_engine_prefill_dispatches_total"
    PREFILL_TOKENS_TOTAL = "dynamo_tpu_engine_prefill_tokens_total"
    PREFILL_BATCH_OCCUPANCY = "dynamo_tpu_engine_prefill_batch_occupancy"
    PREFILL_BUDGET_UTILIZATION = (
        "dynamo_tpu_engine_prefill_budget_utilization")
    UNIFIED_DISPATCHES_TOTAL = "dynamo_tpu_engine_unified_dispatches_total"
    UNIFIED_DECODE_ROWS_TOTAL = "dynamo_tpu_engine_unified_decode_rows_total"
    UNIFIED_PREFILL_TOKENS_TOTAL = (
        "dynamo_tpu_engine_unified_prefill_tokens_total")
    UNIFIED_BUDGET_UTILIZATION = (
        "dynamo_tpu_engine_unified_budget_utilization")
    LOOKAHEAD_BURSTS_TOTAL = "dynamo_tpu_engine_lookahead_bursts_total"
    LOOKAHEAD_HITS_TOTAL = "dynamo_tpu_engine_lookahead_hits_total"
    LOOKAHEAD_MISPREDICTS_TOTAL = (
        "dynamo_tpu_engine_lookahead_mispredicts_total")
    LOOKAHEAD_COMMITS_TOTAL = "dynamo_tpu_engine_lookahead_commits_total"
    LOOKAHEAD_FLUSHES_TOTAL = "dynamo_tpu_engine_lookahead_flushes_total"
    LOOKAHEAD_DISPATCH_DEPTH = "dynamo_tpu_engine_lookahead_dispatch_depth"
    PERSIST_HITS_TOTAL = "dynamo_tpu_engine_persist_hits_total"
    PERSIST_MISSES_TOTAL = "dynamo_tpu_engine_persist_misses_total"
    PERSIST_RESTORED_TOKENS_TOTAL = (
        "dynamo_tpu_engine_persist_restored_tokens_total")
    PERSIST_SPILL_BYTES_TOTAL = "dynamo_tpu_engine_persist_spill_bytes_total"
    PERSIST_RESIDENT_BYTES = "dynamo_tpu_engine_persist_resident_bytes"
    STEPS_TOTAL = "dynamo_tpu_engine_steps_total"
    BUSY_STEPS_TOTAL = "dynamo_tpu_engine_busy_steps_total"
    STEP_WALL_SECONDS_TOTAL = "dynamo_tpu_engine_step_wall_seconds_total"
    STEP_PHASE_SECONDS_TOTAL = "dynamo_tpu_engine_step_phase_seconds_total"
    HOST_GAP_MS_PER_TURN = "dynamo_tpu_engine_host_gap_ms_per_turn"
    STEP_WALL_MS_EWMA = "dynamo_tpu_engine_step_wall_ms_ewma"
    HOST_GAP_MS_EWMA = "dynamo_tpu_engine_host_gap_ms_ewma"


class KvTransferMetric:
    """Measured KV-transfer cost edges (``obs/costs.py``)."""

    CALLS_TOTAL = "dynamo_tpu_kv_transfer_calls_total"
    BYTES_TOTAL = "dynamo_tpu_kv_transfer_bytes_total"
    SECONDS_TOTAL = "dynamo_tpu_kv_transfer_seconds_total"
    MBPS = "dynamo_tpu_kv_transfer_mbps"
    LATENCY_MS = "dynamo_tpu_kv_transfer_latency_ms"


class KvStreamMetric:
    """Streamed KV handoff (``llm/kv/stream.py`` counters)."""

    SESSIONS_TOTAL = "dynamo_tpu_kv_stream_sessions_total"
    LAYERS_SENT_TOTAL = "dynamo_tpu_kv_stream_layers_sent_total"
    BYTES_TOTAL = "dynamo_tpu_kv_stream_bytes_total"
    FALLBACKS_TOTAL = "dynamo_tpu_kv_stream_fallbacks_total"
    OVERLAP_RATIO = "dynamo_tpu_kv_stream_overlap_ratio"


class KvShardMetric:
    """Sharded control plane (``llm/kv_router/shards/`` counters)."""

    SCATTERS_TOTAL = "dynamo_tpu_kv_shard_scatters_total"
    GATHER_PARTIAL_TOTAL = "dynamo_tpu_kv_shard_gather_partial_total"
    GENERATION = "dynamo_tpu_kv_shard_generation"
    FANOUT_LATENCY_MS = "dynamo_tpu_kv_shard_fanout_latency_ms"
    LAST_FAN_OUT = "dynamo_tpu_kv_shard_last_fan_out"
    INDEX_BLOCKS = "dynamo_tpu_kv_shard_index_blocks"
    RESIDENT_KEYS = "dynamo_tpu_kv_shard_resident_keys"


class PerfMetric:
    """dtperf plane: static roofline predictions + runtime
    predicted-vs-measured reconciliation (``obs/perfmodel.py``)."""

    PREDICTED_STEP_MS = "dynamo_tpu_perf_predicted_step_ms"
    PREDICTED_DISPATCH_MS = "dynamo_tpu_perf_predicted_dispatch_ms"
    MEASURED_DISPATCH_MS = "dynamo_tpu_perf_measured_dispatch_ms"
    DISPATCHES_TOTAL = "dynamo_tpu_perf_dispatches_total"
    MODEL_ERROR_RATIO = "dynamo_tpu_perf_model_error_ratio"


class RouterMetric:
    """Standalone metrics aggregation component
    (``components/metrics.py`` PrometheusMetricsCollector)."""

    KV_BLOCKS_ACTIVE = "dynamo_tpu_kv_blocks_active"
    KV_BLOCKS_TOTAL = "dynamo_tpu_kv_blocks_total"
    REQUEST_ACTIVE_SLOTS = "dynamo_tpu_request_active_slots"
    REQUESTS_WAITING = "dynamo_tpu_requests_waiting"
    KV_CACHE_USAGE = "dynamo_tpu_kv_cache_usage"
    ROUTING_DECISIONS_TOTAL = "dynamo_tpu_routing_decisions_total"
    KV_HIT_RATE_PERCENT = "dynamo_tpu_kv_hit_rate_percent"


# name -> (type, labels) — the committed label-schema contract.
# Histogram entries list their sample labels WITHOUT the implicit "le"
# (the render side adds it on _bucket lines); the dtmet census
# normalizes the same way before comparing.
SCHEMA: dict[str, tuple[str, tuple[str, ...]]] = {
    HttpMetric.REQUESTS_TOTAL: ("counter", ("model", "endpoint", "status")),
    HttpMetric.INFLIGHT_REQUESTS: ("gauge", ("model",)),
    HttpMetric.OUTPUT_TOKENS_TOTAL: ("counter", ("model",)),
    HttpMetric.ADMISSION_SHED_TOTAL: ("counter", ("model", "priority")),
    HttpMetric.TTFT_SECONDS: ("histogram", ("model",)),
    HttpMetric.INTER_TOKEN_SECONDS: ("histogram", ("model",)),
    HttpMetric.QUEUE_WAIT_SECONDS: ("histogram", ("model",)),
    HttpMetric.REQUEST_SECONDS: ("histogram", ("model", "status")),
    FaultMetric.MIGRATIONS_TOTAL: ("counter", ()),
    FaultMetric.DRAINS_IN_PROGRESS: ("gauge", ()),
    FaultMetric.SUSPECT_INSTANCES: ("gauge", ()),
    EngineMetric.PREFILL_DISPATCHES_TOTAL: ("counter", ()),
    EngineMetric.PREFILL_TOKENS_TOTAL: ("counter", ()),
    EngineMetric.PREFILL_BATCH_OCCUPANCY: ("gauge", ()),
    EngineMetric.PREFILL_BUDGET_UTILIZATION: ("gauge", ()),
    EngineMetric.UNIFIED_DISPATCHES_TOTAL: ("counter", ()),
    EngineMetric.UNIFIED_DECODE_ROWS_TOTAL: ("counter", ()),
    EngineMetric.UNIFIED_PREFILL_TOKENS_TOTAL: ("counter", ()),
    EngineMetric.UNIFIED_BUDGET_UTILIZATION: ("gauge", ()),
    EngineMetric.LOOKAHEAD_BURSTS_TOTAL: ("counter", ()),
    EngineMetric.LOOKAHEAD_HITS_TOTAL: ("counter", ()),
    EngineMetric.LOOKAHEAD_MISPREDICTS_TOTAL: ("counter", ()),
    EngineMetric.LOOKAHEAD_COMMITS_TOTAL: ("counter", ()),
    EngineMetric.LOOKAHEAD_FLUSHES_TOTAL: ("counter", ()),
    EngineMetric.LOOKAHEAD_DISPATCH_DEPTH: ("gauge", ()),
    EngineMetric.PERSIST_HITS_TOTAL: ("counter", ()),
    EngineMetric.PERSIST_MISSES_TOTAL: ("counter", ()),
    EngineMetric.PERSIST_RESTORED_TOKENS_TOTAL: ("counter", ()),
    EngineMetric.PERSIST_SPILL_BYTES_TOTAL: ("counter", ()),
    EngineMetric.PERSIST_RESIDENT_BYTES: ("gauge", ()),
    EngineMetric.STEPS_TOTAL: ("counter", ()),
    EngineMetric.BUSY_STEPS_TOTAL: ("counter", ()),
    EngineMetric.STEP_WALL_SECONDS_TOTAL: ("counter", ()),
    EngineMetric.STEP_PHASE_SECONDS_TOTAL: ("counter", ("phase",)),
    EngineMetric.HOST_GAP_MS_PER_TURN: ("gauge", ()),
    EngineMetric.STEP_WALL_MS_EWMA: ("gauge", ()),
    EngineMetric.HOST_GAP_MS_EWMA: ("gauge", ()),
    KvTransferMetric.CALLS_TOTAL: ("counter", ("src", "dst", "path")),
    KvTransferMetric.BYTES_TOTAL: ("counter", ("src", "dst", "path")),
    KvTransferMetric.SECONDS_TOTAL: ("counter", ("src", "dst", "path")),
    KvTransferMetric.MBPS: ("gauge", ("src", "dst", "path")),
    KvTransferMetric.LATENCY_MS: ("gauge", ("src", "dst", "path")),
    KvStreamMetric.SESSIONS_TOTAL: ("counter", ()),
    KvStreamMetric.LAYERS_SENT_TOTAL: ("counter", ()),
    KvStreamMetric.BYTES_TOTAL: ("counter", ()),
    KvStreamMetric.FALLBACKS_TOTAL: ("counter", ()),
    KvStreamMetric.OVERLAP_RATIO: ("gauge", ()),
    KvShardMetric.SCATTERS_TOTAL: ("counter", ()),
    KvShardMetric.GATHER_PARTIAL_TOTAL: ("counter", ()),
    KvShardMetric.GENERATION: ("gauge", ()),
    KvShardMetric.FANOUT_LATENCY_MS: ("histogram", ()),
    KvShardMetric.LAST_FAN_OUT: ("gauge", ()),
    KvShardMetric.INDEX_BLOCKS: ("gauge", ("shard",)),
    KvShardMetric.RESIDENT_KEYS: ("gauge", ("shard",)),
    PerfMetric.PREDICTED_STEP_MS: (
        "gauge", ("entrypoint", "config", "signature", "bound")),
    PerfMetric.PREDICTED_DISPATCH_MS: ("gauge", ("kind",)),
    PerfMetric.MEASURED_DISPATCH_MS: ("gauge", ("kind",)),
    PerfMetric.DISPATCHES_TOTAL: ("counter", ("kind",)),
    PerfMetric.MODEL_ERROR_RATIO: ("gauge", ("kind",)),
    RouterMetric.KV_BLOCKS_ACTIVE: ("gauge", ("worker",)),
    RouterMetric.KV_BLOCKS_TOTAL: ("gauge", ("worker",)),
    RouterMetric.REQUEST_ACTIVE_SLOTS: ("gauge", ("worker",)),
    RouterMetric.REQUESTS_WAITING: ("gauge", ("worker",)),
    RouterMetric.KV_CACHE_USAGE: ("gauge", ("worker",)),
    RouterMetric.ROUTING_DECISIONS_TOTAL: ("counter", ("worker",)),
    RouterMetric.KV_HIT_RATE_PERCENT: ("gauge", ("worker",)),
}


def metric_names() -> list[str]:
    """Every registered metric name, sorted (registry coverage tests)."""
    return sorted(SCHEMA)
