"""Chrome trace-event JSON export (Perfetto-loadable).

Converts collector span records into the Trace Event Format that
``chrome://tracing`` and https://ui.perfetto.dev both load: one
complete event (``"ph": "X"``) per span plus process-name metadata
events, timestamps in wall-clock microseconds (each process's
monotonic clock is re-anchored via :data:`tracing.EPOCH_NS`, so spans
collected from different processes line up on one axis).

``engine.step`` spans that carry the dtperf roofline envelope
(``predicted_dispatch_ms`` / ``measured_dispatch_ms`` attrs, see
``obs/timeline.py``) additionally emit a counter event (``"ph": "C"``)
per step, so the predicted-vs-measured dispatch latency renders as a
stacked counter track above the step spans.
"""

from __future__ import annotations

from dynamo_tpu.obs import tracing

__all__ = ["chrome_trace", "trace_for_request"]


def _pid_for(proc: str, pids: dict) -> int:
    if proc not in pids:
        pids[proc] = len(pids) + 1
    return pids[proc]


def chrome_trace(spans: list[dict]) -> dict:
    """Build a Chrome trace-event document from collector records
    (``tracing.Collector`` dicts).  Spans from any mix of traces and
    processes are accepted; each distinct ``proc`` gets its own track."""
    pids: dict = {}
    events = []
    for s in spans:
        pid = _pid_for(s.get("proc") or "proc", pids)
        args = {
            "trace_id": s["trace"],
            "span_id": s["span"],
        }
        if s.get("parent"):
            args["parent_id"] = s["parent"]
        args.update(s.get("attrs") or {})
        events.append({
            "ph": "X",
            "name": s["name"],
            "cat": "dtspan",
            "ts": (tracing.EPOCH_NS + s["ts"]) / 1e3,   # wall-clock us
            "dur": s["dur"] / 1e3,                       # us
            "pid": pid,
            "tid": 1,
            "args": args,
        })
        attrs = s.get("attrs") or {}
        if "measured_dispatch_ms" in attrs:
            # dtperf counter track: predicted-vs-measured dispatch ms
            counter = {"measured": attrs["measured_dispatch_ms"]}
            if "predicted_dispatch_ms" in attrs:
                counter["predicted"] = attrs["predicted_dispatch_ms"]
            events.append({
                "ph": "C",
                "name": "dispatch_ms (dtperf predicted vs measured)",
                "cat": "dtperf",
                "ts": (tracing.EPOCH_NS + s["ts"]) / 1e3,
                "pid": pid,
                "tid": 1,
                "args": counter,
            })
    for proc, pid in pids.items():
        events.append({
            "ph": "M", "name": "process_name", "pid": pid, "tid": 1,
            "args": {"name": proc},
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def trace_for_request(request_id: str) -> dict | None:
    """Chrome trace for one request id (backs
    ``/debug/traces/{request_id}`` and the ``dynamo-tpu trace`` CLI);
    None when the request was never traced or has aged out of the
    ring."""
    trace_id = tracing.collector.trace_for_request(request_id)
    if trace_id is None:
        return None
    spans = tracing.collector.spans_for_trace(trace_id)
    if not spans:
        return None
    return chrome_trace(spans)
