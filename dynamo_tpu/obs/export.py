"""Chrome trace-event JSON export (Perfetto-loadable).

Converts collector span records into the Trace Event Format that
``chrome://tracing`` and https://ui.perfetto.dev both load: one
complete event (``"ph": "X"``) per span plus process-name metadata
events, timestamps in wall-clock microseconds (each process's
monotonic clock is re-anchored via :data:`tracing.EPOCH_NS`, so spans
collected from different processes line up on one axis).
"""

from __future__ import annotations

from dynamo_tpu.obs import tracing

__all__ = ["chrome_trace", "trace_for_request"]


def _pid_for(proc: str, pids: dict) -> int:
    if proc not in pids:
        pids[proc] = len(pids) + 1
    return pids[proc]


def chrome_trace(spans: list[dict]) -> dict:
    """Build a Chrome trace-event document from collector records
    (``tracing.Collector`` dicts).  Spans from any mix of traces and
    processes are accepted; each distinct ``proc`` gets its own track."""
    pids: dict = {}
    events = []
    for s in spans:
        pid = _pid_for(s.get("proc") or "proc", pids)
        args = {
            "trace_id": s["trace"],
            "span_id": s["span"],
        }
        if s.get("parent"):
            args["parent_id"] = s["parent"]
        args.update(s.get("attrs") or {})
        events.append({
            "ph": "X",
            "name": s["name"],
            "cat": "dtspan",
            "ts": (tracing.EPOCH_NS + s["ts"]) / 1e3,   # wall-clock us
            "dur": s["dur"] / 1e3,                       # us
            "pid": pid,
            "tid": 1,
            "args": args,
        })
    for proc, pid in pids.items():
        events.append({
            "ph": "M", "name": "process_name", "pid": pid, "tid": 1,
            "args": {"name": proc},
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def trace_for_request(request_id: str) -> dict | None:
    """Chrome trace for one request id (backs
    ``/debug/traces/{request_id}`` and the ``dynamo-tpu trace`` CLI);
    None when the request was never traced or has aged out of the
    ring."""
    trace_id = tracing.collector.trace_for_request(request_id)
    if trace_id is None:
        return None
    spans = tracing.collector.spans_for_trace(trace_id)
    if not spans:
        return None
    return chrome_trace(spans)
