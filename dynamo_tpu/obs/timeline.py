"""Engine step timeline: per-phase wall-time attribution for
``EngineCore.step``.

The model is mark-based: :meth:`StepTimeline.begin` opens a step,
``mark(phase)`` attributes *all elapsed time since the previous mark*
to ``phase``, and :meth:`end` attributes the residue to ``host_post``
— so the phase sum equals the step wall time **by construction** (the
>= 95 % acceptance bound holds with slack; the only loss is float
rounding).

Phases (what the marks mean, in step order):

    kv_spill_restore  host<->device KV block traffic (_drain_offload)
    host_ops          cross-thread op/abort queues
    admission         _admit: block allocation, grammar budget, slots
    host_build        numpy dispatch-operand builds (tokens, block
                      tables, penalty buffers, grammar rows)
    upload            the ONE batched jax.device_put per dispatch
    dispatch          the jitted call itself (trace/en-queue; on CPU
                      backends this includes compute)
    readback          jax.device_get — blocks until device compute
                      lands, so device time not overlapped with host
                      work shows up here
    host_post         sampled-token append, stop conditions, emit

The headline derived number is **host_gap_ms_per_turn** — wall time
per dispatching step spent *outside* dispatch+readback, i.e. the host
bubble ROADMAP item 3 (double-buffered dispatch) must close.  The
aggregates are always on (a handful of ``perf_counter`` calls per
step, no allocation); full per-step records are kept only in a small
ring buffer, and per-step *spans* are emitted only when the tracing
plane is enabled.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

__all__ = ["StepTimeline", "step_timeline", "PHASES"]

PHASES = (
    "kv_spill_restore",
    "host_ops",
    "admission",
    "host_build",
    "upload",
    "dispatch",
    "readback",
    "host_post",
)

_DISPATCH_PHASES = ("upload", "dispatch", "readback")


class StepTimeline:
    """Process-global (one engine thread writes, metrics readers read;
    torn reads of monotonically-increasing floats are acceptable for
    monitoring)."""

    def __init__(self, keep_steps: int = 256) -> None:
        self._lock = threading.Lock()
        self.recent: deque = deque(maxlen=keep_steps)
        self.reset()

    def reset(self) -> None:
        """Test isolation hook."""
        self.steps_total = 0          # begin/end pairs seen
        self.busy_steps_total = 0     # steps that ran >= 1 device dispatch
        self.wall_s_total = 0.0       # busy-step wall time
        self.phase_s_total = {p: 0.0 for p in PHASES}
        self.host_gap_s_total = 0.0   # busy wall - dispatch - readback
        self.ewma_wall_s = 0.0
        self.ewma_host_gap_s = 0.0
        self._alpha = 0.05
        self._t0: Optional[float] = None
        self._last = 0.0
        self._phases: dict = {}

    # ------------------------------------------------------------ hot path
    def begin(self) -> None:
        now = time.perf_counter()
        self._t0 = now
        self._last = now
        self._phases = {}

    def mark(self, phase: str) -> None:
        if self._t0 is None:
            return  # dispatch helper invoked outside step() (tests)
        now = time.perf_counter()
        self._phases[phase] = self._phases.get(phase, 0.0) + (now - self._last)
        self._last = now

    def end(self) -> None:
        if self._t0 is None:
            return
        now = time.perf_counter()
        phases = self._phases
        phases["host_post"] = phases.get("host_post", 0.0) + (now - self._last)
        wall = now - self._t0
        self._t0 = None
        busy = any(phases.get(p) for p in _DISPATCH_PHASES)
        self.steps_total += 1
        if not busy:
            return  # idle polls would drown the per-turn numbers
        gap = wall - phases.get("dispatch", 0.0) - phases.get("readback", 0.0)
        self.busy_steps_total += 1
        self.wall_s_total += wall
        self.host_gap_s_total += gap
        for p, v in phases.items():
            self.phase_s_total[p] = self.phase_s_total.get(p, 0.0) + v
        a = self._alpha
        self.ewma_wall_s = wall if self.busy_steps_total == 1 else (
            (1 - a) * self.ewma_wall_s + a * wall)
        self.ewma_host_gap_s = gap if self.busy_steps_total == 1 else (
            (1 - a) * self.ewma_host_gap_s + a * gap)
        self.recent.append({"wall_s": wall, "phases": dict(phases)})

    # ------------------------------------------------------------- readers
    @property
    def host_gap_ms_per_turn(self) -> float:
        """Mean host bubble per dispatching step — the committed
        before-number for ROADMAP item 3."""
        if not self.busy_steps_total:
            return 0.0
        return self.host_gap_s_total / self.busy_steps_total * 1e3

    def snapshot(self) -> dict:
        """Dict for /metrics rendering and serve_bench banking."""
        return {
            "steps_total": self.steps_total,
            "busy_steps_total": self.busy_steps_total,
            "wall_seconds_total": self.wall_s_total,
            "host_gap_ms_per_turn": self.host_gap_ms_per_turn,
            "ewma_wall_ms": self.ewma_wall_s * 1e3,
            "ewma_host_gap_ms": self.ewma_host_gap_s * 1e3,
            "phases": {p: self.phase_s_total.get(p, 0.0) for p in PHASES},
        }


step_timeline = StepTimeline()
