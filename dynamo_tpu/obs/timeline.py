"""Engine step timeline: per-phase wall-time attribution for
``EngineCore.step``.

The model is mark-based: :meth:`StepTimeline.begin` opens a step,
``mark(phase)`` attributes *all elapsed time since the previous mark*
to ``phase``, and :meth:`end` attributes the residue to ``host_post``
— so the phase sum equals the step wall time **by construction** (the
>= 95 % acceptance bound holds with slack; the only loss is float
rounding).

Phases (what the marks mean, in step order):

    kv_spill_restore  host<->device KV block traffic (_drain_offload)
    host_ops          cross-thread op/abort queues
    admission         _admit: block allocation, grammar budget, slots
    host_build        numpy dispatch-operand builds (tokens, block
                      tables, penalty buffers, grammar rows)
    upload            the ONE batched jax.device_put per dispatch
    dispatch          the jitted call itself (trace/en-queue; on CPU
                      backends this includes compute)
    overlap           host work performed *while the device computes*
                      (lookahead dispatch: next-turn speculative build
                      + waiting-queue drain between dispatch and
                      readback) — concurrent with device time, so it
                      is excluded from the host gap
    readback          jax.device_get — blocks until device compute
                      lands, so device time not overlapped with host
                      work shows up here
    host_post         sampled-token append, stop conditions, emit

The headline derived number is **host_gap_ms_per_turn** — wall time
per dispatching step spent *outside* dispatch+overlap+readback, i.e.
the host bubble ROADMAP item 3 (double-buffered dispatch) must close.
Overlapped host work is not a bubble: the device is busy underneath
it, so the phase-sum==wall invariant holds while the gap shrinks.  The
aggregates are always on (a handful of ``perf_counter`` calls per
step, no allocation); full per-step records are kept only in a small
ring buffer, and per-step *spans* are emitted only when the tracing
plane is enabled.

The dispatch mark additionally takes the **dispatch kind** (``step``,
``decode_multi``, ``prefill_ragged``, ``unified``, ``sp_prefill``,
``spec_verify``) so measured dispatch seconds split per jitted
entrypoint — the denominator of the dtperf predicted-vs-measured
model-error gauge (``obs/perfmodel.py``).  When tracing is enabled,
``end`` also emits one ``engine.step`` span per busy step carrying the
phase breakdown and the roofline-predicted dispatch envelope, which
the Chrome export renders as a predicted-vs-measured counter track.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Optional

__all__ = ["StepTimeline", "step_timeline", "PHASES"]

PHASES = (
    "kv_spill_restore",
    "host_ops",
    "admission",
    "host_build",
    "upload",
    "dispatch",
    "overlap",
    "readback",
    "host_post",
)

_DISPATCH_PHASES = ("upload", "dispatch", "readback")


class StepTimeline:
    """Process-global (one engine thread writes, metrics readers read;
    torn reads of monotonically-increasing floats are acceptable for
    monitoring)."""

    def __init__(self, keep_steps: int = 256,
                 clock: Callable[[], float] = time.perf_counter) -> None:
        self._lock = threading.Lock()
        # injectable so simulated engines (load plane) can stamp steps
        # at virtual time; the default stays the high-resolution counter
        self._clock = clock
        self.recent: deque = deque(maxlen=keep_steps)
        self.reset()

    def reset(self) -> None:
        """Test isolation hook."""
        self.steps_total = 0          # begin/end pairs seen
        self.busy_steps_total = 0     # steps that ran >= 1 device dispatch
        self.wall_s_total = 0.0       # busy-step wall time
        self.phase_s_total = {p: 0.0 for p in PHASES}
        self.host_gap_s_total = 0.0   # busy wall - dispatch-overlap-readback
        self.ewma_wall_s = 0.0
        self.ewma_host_gap_s = 0.0
        # measured dispatch time split by jitted-entrypoint kind — the
        # denominator of the dtperf model-error gauge
        self.dispatch_kind_s: dict[str, float] = {}
        self.dispatch_kind_n: dict[str, int] = {}
        self._alpha = 0.05
        self._t0: Optional[float] = None
        self._t0_ns = 0
        self._last = 0.0
        self._phases: dict = {}
        self._step_kinds: dict = {}

    # ------------------------------------------------------------ hot path
    def begin(self) -> None:
        now = self._clock()
        self._t0 = now
        self._last = now
        self._phases = {}
        self._step_kinds = {}
        self._t0_ns = time.monotonic_ns()

    def mark(self, phase: str, kind: Optional[str] = None) -> None:
        if self._t0 is None:
            return  # dispatch helper invoked outside step() (tests)
        now = self._clock()
        delta = now - self._last
        self._phases[phase] = self._phases.get(phase, 0.0) + delta
        if kind is not None:
            self.dispatch_kind_s[kind] = \
                self.dispatch_kind_s.get(kind, 0.0) + delta
            self.dispatch_kind_n[kind] = \
                self.dispatch_kind_n.get(kind, 0) + 1
            self._step_kinds[kind] = \
                self._step_kinds.get(kind, 0.0) + delta
        self._last = now

    def end(self, trace: Optional[tuple] = None) -> None:
        if self._t0 is None:
            return
        now = self._clock()
        phases = self._phases
        phases["host_post"] = phases.get("host_post", 0.0) + (now - self._last)
        wall = now - self._t0
        t0_ns = self._t0_ns
        self._t0 = None
        busy = any(phases.get(p) for p in _DISPATCH_PHASES)
        self.steps_total += 1
        if not busy:
            return  # idle polls would drown the per-turn numbers
        gap = (wall - phases.get("dispatch", 0.0)
               - phases.get("overlap", 0.0)
               - phases.get("readback", 0.0))
        self.busy_steps_total += 1
        self.wall_s_total += wall
        self.host_gap_s_total += gap
        for p, v in phases.items():
            self.phase_s_total[p] = self.phase_s_total.get(p, 0.0) + v
        a = self._alpha
        self.ewma_wall_s = wall if self.busy_steps_total == 1 else (
            (1 - a) * self.ewma_wall_s + a * wall)
        self.ewma_host_gap_s = gap if self.busy_steps_total == 1 else (
            (1 - a) * self.ewma_host_gap_s + a * gap)
        self.recent.append({"wall_s": wall, "phases": dict(phases)})
        self._emit_step_span(trace, t0_ns, wall, phases)

    # ----------------------------------------------------------- trace emit
    def _emit_step_span(self, trace: Optional[tuple], t0_ns: int,
                        wall: float, phases: dict) -> None:
        """One ``engine.step`` span per busy step when the tracing
        plane is on: phase breakdown, per-kind dispatch ms, and the
        roofline-predicted dispatch envelope (the Chrome export turns
        the predicted/measured pair into a counter track)."""
        from dynamo_tpu.obs import tracing

        if not tracing.enabled():
            return
        kinds = dict(self._step_kinds)
        attrs: dict = {
            "phases_ms": {
                p: round(v * 1e3, 3) for p, v in sorted(phases.items())
            },
            "dispatch_kinds": sorted(kinds),
            "measured_dispatch_ms": round(
                sum(kinds.values()) * 1e3, 3),
        }
        # predicted envelope: lazy roofline per offered kind — only
        # priced under tracing (first read traces the jaxpr once)
        try:
            from dynamo_tpu.obs.perfmodel import perf_model

            preds = [perf_model.predicted_ms(k) for k in kinds]
            if preds and all(p is not None for p in preds):
                attrs["predicted_dispatch_ms"] = round(sum(preds), 3)
        except Exception:
            pass  # monitoring must never break the step loop
        trace_id, parent = (trace if trace else
                            (tracing.new_trace_id(), None))
        tracing.collector.add({
            "name": "engine.step",
            "trace": trace_id,
            "span": tracing._new_span_id(),
            "parent": parent,
            "ts": t0_ns,
            "dur": int(wall * 1e9),
            "proc": tracing.process_name(),
            "attrs": attrs,
        })

    # ------------------------------------------------------------- readers
    @property
    def host_gap_ms_per_turn(self) -> float:
        """Mean host bubble per dispatching step — the committed
        before-number for ROADMAP item 3."""
        if not self.busy_steps_total:
            return 0.0
        return self.host_gap_s_total / self.busy_steps_total * 1e3

    def snapshot(self) -> dict:
        """Dict for /metrics rendering and serve_bench banking."""
        return {
            "steps_total": self.steps_total,
            "busy_steps_total": self.busy_steps_total,
            "wall_seconds_total": self.wall_s_total,
            "host_gap_ms_per_turn": self.host_gap_ms_per_turn,
            "ewma_wall_ms": self.ewma_wall_s * 1e3,
            "ewma_host_gap_ms": self.ewma_host_gap_s * 1e3,
            "phases": {p: self.phase_s_total.get(p, 0.0) for p in PHASES},
            "dispatch_kinds": {
                k: {
                    "seconds": self.dispatch_kind_s[k],
                    "count": self.dispatch_kind_n.get(k, 0),
                }
                for k in sorted(self.dispatch_kind_s)
            },
        }


step_timeline = StepTimeline()
