"""Span core for the dtspan tracing plane.

Design constraints (ISSUE 11 tentpole):

- **Near-zero cost when disabled.**  Every entrypoint first checks one
  module-level bool; the disabled path returns a preallocated no-op
  span singleton — no object allocation, no clock read, no contextvar
  write on the token path.
- **Contextvar propagation.**  The current span context rides a
  ``contextvars.ContextVar`` so it follows ``asyncio`` task switches
  for free.  Threads that are *not* spawned per-request (the engine
  thread) carry context explicitly: ``EngineRequest.trace`` holds the
  ``(trace_id, span_id)`` pair and engine-side spans pass it as
  ``parent=``.
- **Wire propagation.**  :func:`inject` stamps the current context
  into a JSON-framed message header under the
  ``protocol.TRACE_FIELD`` key; :func:`extract` reads it back on the
  receiving side.  One trace id thus stitches frontend -> router ->
  prefill -> KV transfer -> decode across processes.
- **Bounded collector.**  Finished spans land in a per-process ring
  buffer (``deque(maxlen=...)``); a bounded ``request_id -> trace_id``
  map backs ``/debug/traces/{request_id}``.  Memory is O(ring size)
  regardless of traffic.

Timestamps are monotonic (``time.monotonic_ns``) for correct
durations; the module records one wall-clock anchor at import so the
exporter can place spans from different processes on a shared
wall-clock axis (see :data:`EPOCH_NS`).
"""

from __future__ import annotations

import contextvars
import os
import threading
import time
import uuid
from collections import OrderedDict, deque
from typing import Optional

__all__ = [
    "Span",
    "attach",
    "collector",
    "current",
    "detach",
    "enable",
    "enabled",
    "extract",
    "inject",
    "new_trace_id",
    "set_process",
    "start_span",
]

# wall-clock anchor: wall_ns = EPOCH_NS + monotonic_ns.  Each process
# computes its own at import; all are anchored to the same wall clock,
# so cross-process spans line up to NTP precision — plenty for
# millisecond-scale serving phases.
EPOCH_NS = time.time_ns() - time.monotonic_ns()

_enabled = bool(os.environ.get("DYNAMO_TRACE"))

# (trace_id, span_id) of the active span, or None
_current: contextvars.ContextVar[Optional[tuple]] = contextvars.ContextVar(
    "dtspan_current", default=None
)

_proc = os.environ.get("DYN_TRACE_PROC") or f"proc-{os.getpid()}"


def enable(on: bool = True) -> None:
    """Turn the tracing plane on/off process-wide (also settable via the
    ``DYNAMO_TRACE=1`` environment variable at import)."""
    global _enabled
    _enabled = bool(on)


def enabled() -> bool:
    return _enabled


def set_process(name: str) -> None:
    """Name this process's track in exported traces (e.g. ``frontend``,
    ``prefill-0``).  Defaults to ``DYN_TRACE_PROC`` or ``proc-{pid}``."""
    global _proc
    _proc = name


def process_name() -> str:
    return _proc


def new_trace_id() -> str:
    return uuid.uuid4().hex


def _new_span_id() -> str:
    return uuid.uuid4().hex[:16]


class Collector:
    """Bounded ring buffer of finished span records.

    Records are plain dicts (immutable once appended); ``deque.append``
    is atomic under the GIL, so the hot path takes no lock.  The
    ``request_id -> trace_id`` map (for ``/debug/traces/{rid}``) is
    bounded by LRU-ish FIFO eviction under a small lock — it is only
    touched once per request, never per token.
    """

    def __init__(self, maxlen: int = 4096, max_requests: int = 2048) -> None:
        self.spans: deque = deque(maxlen=maxlen)
        self._rid_to_trace: OrderedDict[str, str] = OrderedDict()
        self._max_requests = max_requests
        self._lock = threading.Lock()

    def add(self, record: dict) -> None:
        self.spans.append(record)

    def bind_request(self, request_id: str, trace_id: str) -> None:
        with self._lock:
            self._rid_to_trace[request_id] = trace_id
            while len(self._rid_to_trace) > self._max_requests:
                self._rid_to_trace.popitem(last=False)

    def trace_for_request(self, request_id: str) -> Optional[str]:
        with self._lock:
            return self._rid_to_trace.get(request_id)

    def spans_for_trace(self, trace_id: str) -> list[dict]:
        return [s for s in list(self.spans) if s["trace"] == trace_id]

    def reset(self) -> None:
        """Test isolation hook."""
        self.spans.clear()
        with self._lock:
            self._rid_to_trace.clear()


collector = Collector()


class Span:
    """One timed operation.  Create via :func:`start_span`; finish with
    :meth:`end` or use as a context manager.  ``set()`` attaches
    key/value attributes (kept small — they ride the ring buffer)."""

    __slots__ = (
        "name", "trace_id", "span_id", "parent_id",
        "start_ns", "attrs", "_token", "_ended",
    )

    def __init__(self, name: str, trace_id: str, parent_id: Optional[str],
                 attrs: Optional[dict] = None) -> None:
        self.name = name
        self.trace_id = trace_id
        self.span_id = _new_span_id()
        self.parent_id = parent_id
        self.attrs = dict(attrs) if attrs else {}
        self.start_ns = time.monotonic_ns()
        self._token = _current.set((trace_id, self.span_id))
        self._ended = False

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def context(self) -> tuple:
        """(trace_id, span_id) — pass as ``parent=`` across threads."""
        return (self.trace_id, self.span_id)

    def end(self) -> None:
        if self._ended:
            return
        self._ended = True
        end_ns = time.monotonic_ns()
        try:
            _current.reset(self._token)
        except ValueError:
            # ended in a different context than it started (e.g. a span
            # handed across tasks) — clearing beats leaking
            _current.set(None)
        collector.add({
            "name": self.name,
            "trace": self.trace_id,
            "span": self.span_id,
            "parent": self.parent_id,
            "ts": self.start_ns,
            "dur": end_ns - self.start_ns,
            "proc": _proc,
            "attrs": self.attrs,
        })

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, *exc) -> None:
        self.end()


class _NopSpan:
    """Disabled-path span: every method is a no-op returning self, so
    call sites never branch.  One process-wide instance — zero
    allocation when tracing is off."""

    __slots__ = ()
    name = ""
    trace_id = ""
    span_id = ""
    parent_id = None
    attrs: dict = {}

    def set(self, **attrs) -> "_NopSpan":
        return self

    def context(self) -> None:
        return None

    def end(self) -> None:
        pass

    def __enter__(self) -> "_NopSpan":
        return self

    def __exit__(self, *exc) -> None:
        pass


NOP_SPAN = _NopSpan()


def start_span(name: str, parent: Optional[tuple] = None,
               attrs: Optional[dict] = None):
    """Start a span.  ``parent`` overrides the contextvar (explicit
    cross-thread handoff); otherwise the current context is the parent;
    otherwise a fresh trace id is minted (root span).  Returns the
    no-op singleton when tracing is disabled."""
    if not _enabled:
        return NOP_SPAN
    ctx = parent if parent is not None else _current.get()
    if ctx is not None:
        trace_id, parent_id = ctx
    else:
        trace_id, parent_id = new_trace_id(), None
    return Span(name, trace_id, parent_id, attrs)


def current() -> Optional[tuple]:
    """(trace_id, span_id) of the active context, or None."""
    if not _enabled:
        return None
    return _current.get()


def attach(ctx: Optional[tuple]):
    """Make ``ctx`` the current context (e.g. after :func:`extract` on
    a server); returns a token for :func:`detach`.  None ctx is fine —
    the token still restores the previous state."""
    return _current.set(tuple(ctx) if ctx else None)


def detach(token) -> None:
    try:
        _current.reset(token)
    except ValueError:
        _current.set(None)


# --------------------------------------------------------------- wire helpers
# The field name lives in transports/protocol.py (single source of
# truth for wire literals — the dtwire plane audits it there); import
# lazily to keep obs dependency-free for non-wire users.

def _trace_field() -> str:
    from dynamo_tpu.runtime.transports.protocol import TRACE_FIELD
    return TRACE_FIELD


def inject(header: dict) -> dict:
    """Stamp the current trace context into a wire message header (a
    JSON-framed dict).  No-op (and no allocation) when tracing is off
    or no context is active.  Returns ``header`` for chaining."""
    if not _enabled:
        return header
    ctx = _current.get()
    if ctx is not None:
        header[_trace_field()] = [ctx[0], ctx[1]]
    return header


def extract(header: dict) -> Optional[tuple]:
    """Read a trace context out of a received wire header; None when
    absent or malformed (never raises — tracing must not take down the
    data path)."""
    if not _enabled:
        return None
    raw = header.get(_trace_field())
    if (
        isinstance(raw, (list, tuple)) and len(raw) == 2
        and all(isinstance(x, str) for x in raw)
    ):
        return (raw[0], raw[1])
    return None
