"""Versioned per-topology hardware constants (dtperf).

One table, two consumers:

- ``analysis/perfcheck.py`` (the perf lint plane) folds these into the
  roofline model: predicted step latency is
  ``max(FLOPs/peak_flops, bytes/peak_bw) + sum(collective costs)``
  where the collective terms come from mesh axis sizes and the link
  bandwidths below.
- ``obs/costs.py`` seeds never-observed (src, dst, path) transfer
  edges with a bandwidth prior so transfer-aware routing has a cost
  estimate before the first measured transfer replaces it (EWMA).

The table is *versioned*: ``CONSTANTS_VERSION`` is recorded in the
committed ``analysis/perf_manifest.json`` header, and the perf plane
raises PF001 (key ``"constants"``) whenever the committed version and
this module disagree — so a constants tweak re-trips the latency gate
explicitly instead of silently moving every baseline.

Numbers are public datasheet / round-2 bench figures for TPU v5e
(197 bf16 TFLOP/s per chip, 16 GiB HBM @ 819 GB/s, 4x ICI links);
DCN assumes a 25 Gbps NIC and the persist tier a shared-store read at
~1 GB/s.  They are deliberately coarse — the model's job is to rank
and gate, and its calibration is itself observable through the
predicted-vs-measured gauge on ``/metrics``.
"""

from __future__ import annotations

__all__ = [
    "AXIS_DATA",
    "AXIS_MODEL",
    "AXIS_SP",
    "CONSTANTS_VERSION",
    "DEFAULT_TOPOLOGY",
    "MESH_AXES",
    "TOPOLOGIES",
    "collective_cost_s",
    "path_prior_bw",
    "prior_cost_s",
]

# Bump on ANY numeric change below; the perf manifest header pins it.
CONSTANTS_VERSION = "v5e-2026.08.1"

# Canonical mesh axis names — the single source both the runtime
# (engine CLI, multihost bootstrap, ring attention) and the sharding
# lint plane (analysis/shardcheck.py) build meshes from, so the specs
# shardcheck audits are provably the specs the engine lowers under.
# Construction lives in dynamo_tpu/utils/mesh.py (build_mesh).
AXIS_DATA = "data"    # DP / sequence-parallel axis: spans hosts (DCN)
AXIS_MODEL = "model"  # TP axis: last mesh axis, intra-host over ICI
AXIS_SP = "sp"        # standalone seq-parallel axis (ring-attention rigs)
MESH_AXES = (AXIS_DATA, AXIS_MODEL)  # the engine's (dp, tp) mesh layout

DEFAULT_TOPOLOGY = "v5e"

TOPOLOGIES: dict[str, dict] = {
    "v5e": {
        # Per-chip peak compute by accumulation input dtype, FLOP/s.
        "peak_flops": {
            "bfloat16": 197e12,
            "float16": 197e12,
            "float32": 98.5e12,   # MXU halves throughput at f32
            "int8": 394e12,
            "int4": 394e12,       # v5e has no 4-bit MXU mode; int8 rate
        },
        "default_flops": 197e12,
        # HBM: 16 GiB @ 819 GB/s per chip.
        "hbm_bytes": 16 << 30,
        "hbm_bw": 819e9,
        # ICI: 4 links/chip in a 2D torus, ~50 GB/s per link per
        # direction (1600 Gbps aggregate).
        "ici_bw": 50e9,
        "ici_latency_s": 1e-6,
        # DCN: 25 Gbps NIC -> ~3.125 GB/s, plus TCP hop latency.
        "dcn_bw": 3.125e9,
        "dcn_latency_s": 50e-6,
        # Persist tier: shared-store read + restore-through-host.
        "persist_bw": 1e9,
        "persist_latency_s": 1e-3,
    },
}

# Derate applied to *priors* for never-measured transfer edges: real
# transfers pay serialization / host hops the link number ignores, so
# the prior deliberately under-promises until a measurement lands.
_PRIOR_EFFICIENCY = 0.6

# Transfer-path name (obs/costs.py vocabulary) -> constants keys.
_PATH_KEYS = {
    "ici": ("ici_bw", "ici_latency_s"),
    "dcn": ("dcn_bw", "dcn_latency_s"),
    "persist": ("persist_bw", "persist_latency_s"),
}


def path_prior_bw(path: str, topology: str = DEFAULT_TOPOLOGY) -> float:
    """Derated bytes/s prior for a transfer path; unknown paths get
    the slowest (persist) prior so they are never free."""
    topo = TOPOLOGIES[topology]
    bw_key, _ = _PATH_KEYS.get(path, _PATH_KEYS["persist"])
    return topo[bw_key] * _PRIOR_EFFICIENCY


def prior_cost_s(path: str, nbytes: int,
                 topology: str = DEFAULT_TOPOLOGY) -> float:
    """Heuristic seconds to move ``nbytes`` over a never-measured
    path: latency floor + derated-bandwidth term."""
    topo = TOPOLOGIES[topology]
    bw_key, lat_key = _PATH_KEYS.get(path, _PATH_KEYS["persist"])
    return topo[lat_key] + nbytes / (topo[bw_key] * _PRIOR_EFFICIENCY)


def collective_cost_s(op: str, axis_size: int, payload_bytes: int,
                      topology: str = DEFAULT_TOPOLOGY,
                      link: str = "ici") -> float:
    """Analytic cost of one collective over a ring of ``axis_size``
    chips moving ``payload_bytes`` (per-shard payload).

    Ring algorithms: all-reduce moves 2(n-1)/n of the payload over the
    bottleneck link, all-gather / reduce-scatter / all-to-all move
    (n-1)/n, a ppermute shift moves the payload once.  Each ring step
    pays one link-latency hop.
    """
    if axis_size <= 1:
        return 0.0
    topo = TOPOLOGIES[topology]
    bw_key, lat_key = _PATH_KEYS.get(link, _PATH_KEYS["ici"])
    bw, lat = topo[bw_key], topo[lat_key]
    n = axis_size
    if op in ("psum", "all_reduce", "psum_scatter_gather"):
        traffic = 2.0 * (n - 1) / n * payload_bytes
        hops = 2 * (n - 1)
    elif op in ("all_gather", "reduce_scatter", "psum_scatter",
                "all_to_all"):
        traffic = (n - 1) / n * payload_bytes
        hops = n - 1
    elif op == "ppermute":
        traffic = float(payload_bytes)
        hops = 1
    else:  # unknown collective: charge a full all-reduce
        traffic = 2.0 * (n - 1) / n * payload_bytes
        hops = 2 * (n - 1)
    return traffic / bw + hops * lat
