"""Token-block sequences with chained content hashes.

This is the foundation the whole KV-routing scheme rests on: a prompt is
split into fixed-size blocks of token ids; each block gets

  * a ``block_hash``    — hash of the block's tokens alone, and
  * a ``sequence_hash`` — chained hash of (parent sequence_hash, tokens),

so that two requests sharing a prefix produce identical sequence hashes for
the shared blocks.  Workers publish {stored, removed} events keyed by
sequence hash; the router's radix tree matches incoming prompts against them.

Reference parity: lib/tokens/src/lib.rs:44-300 (Tokens, TokenBlock,
PartialTokenBlock, TokenBlockSequence, xxh3 chained hashing with salt) and
lib/llm/src/kv_router/indexer.rs:99 (compute_block_hash, seed 1337).

Design notes (TPU rebuild): hashing is plain xxh3-64 over little-endian
u32 token bytes, chained through a u64 parent hash.  This is pure-Python +
xxhash (C speed); block hashing of a full prompt is vectorised via a single
pass over a memoryview, not per-token Python loops.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable, Optional, Sequence

import numpy as np
import xxhash

# Same seed the reference pins (lib/llm/src/kv_router/indexer.rs:64) so that
# recorded event streams hash identically across implementations.
BLOCK_HASH_SEED = 1337

__all__ = [
    "BLOCK_HASH_SEED",
    "compute_hash",
    "compute_block_hash",
    "compute_seq_hash",
    "block_hashes",
    "sequence_hashes",
    "TokenBlock",
    "PartialTokenBlock",
    "TokenBlockSequence",
]


def _tokens_to_bytes(tokens: Sequence[int]) -> bytes:
    return np.asarray(tokens, dtype=np.uint32).tobytes()


def compute_hash(data: bytes, seed: int = BLOCK_HASH_SEED) -> int:
    """xxh3-64 of raw bytes (reference: lib/tokens/src/lib.rs:44)."""
    return xxhash.xxh3_64_intdigest(data, seed=seed)


def compute_block_hash(tokens: Sequence[int]) -> int:
    """Hash of a block's tokens alone (local hash, no chaining)."""
    return compute_hash(_tokens_to_bytes(tokens))


def compute_seq_hash(parent: Optional[int], tokens: Sequence[int], salt: int = 0) -> int:
    """Chained sequence hash.

    The root block mixes in ``salt`` (lets a deployment partition its cache
    space, reference lib/tokens/src/lib.rs:277); children mix in the parent's
    sequence hash.
    """
    if parent is None:
        prefix = np.uint64(salt).tobytes()
    else:
        prefix = np.uint64(parent).tobytes()
    return compute_hash(prefix + _tokens_to_bytes(tokens))


def block_hashes(tokens: Sequence[int], block_size: int) -> list[int]:
    """Local hashes for each *complete* block of ``tokens``."""
    toks = np.asarray(tokens, dtype=np.uint32)
    n_full = len(toks) // block_size
    raw = toks[: n_full * block_size].tobytes()
    bs = block_size * 4
    return [compute_hash(raw[i * bs : (i + 1) * bs]) for i in range(n_full)]


def sequence_hashes(tokens: Sequence[int], block_size: int, salt: int = 0) -> list[int]:
    """Chained sequence hashes for each complete block — the fast path used
    by the router on every request (no TokenBlock object churn)."""
    toks = np.asarray(tokens, dtype=np.uint32)
    n_full = len(toks) // block_size
    out: list[int] = []
    parent: Optional[int] = None
    raw = toks[: n_full * block_size].tobytes()
    bs = block_size * 4
    for i in range(n_full):
        chunk = raw[i * bs : (i + 1) * bs]
        prefix = np.uint64(salt if parent is None else parent).tobytes()
        parent = compute_hash(prefix + chunk)
        out.append(parent)
    return out


@dataclass(frozen=True)
class TokenBlock:
    """An immutable, complete block of ``block_size`` token ids."""

    tokens: tuple[int, ...]
    block_hash: int
    sequence_hash: int
    parent_sequence_hash: Optional[int]
    position: int  # block index within its sequence

    @staticmethod
    def build(
        tokens: Sequence[int],
        parent: Optional["TokenBlock"],
        position: int,
        salt: int = 0,
    ) -> "TokenBlock":
        parent_hash = parent.sequence_hash if parent is not None else None
        return TokenBlock(
            tokens=tuple(int(t) for t in tokens),
            block_hash=compute_block_hash(tokens),
            sequence_hash=compute_seq_hash(parent_hash, tokens, salt),
            parent_sequence_hash=parent_hash,
            position=position,
        )


@dataclass
class PartialTokenBlock:
    """Mutable tail block being filled (reference lib/tokens/src/lib.rs:221)."""

    block_size: int
    tokens: list[int] = field(default_factory=list)

    @property
    def remaining(self) -> int:
        return self.block_size - len(self.tokens)

    def push(self, token: int) -> bool:
        """Append one token; returns True when the block became full."""
        if self.remaining <= 0:
            raise ValueError("pushing into a full partial block")
        self.tokens.append(int(token))
        return self.remaining == 0


class TokenBlockSequence:
    """A growing token sequence maintaining complete blocks + a partial tail.

    Reference parity: lib/tokens/src/lib.rs:300 (TokenBlockSequence).
    Supports O(1) append (per token), bulk extend, and truncate — the ops the
    engine's request state machine needs while decoding.
    """

    def __init__(self, tokens: Iterable[int] = (), block_size: int = 16, salt: int = 0):
        if block_size <= 0:
            raise ValueError("block_size must be positive")
        self.block_size = block_size
        self.salt = salt
        self.blocks: list[TokenBlock] = []
        self.partial = PartialTokenBlock(block_size)
        self.extend(tokens)

    # ------------------------------------------------------------------ state
    @property
    def total_tokens(self) -> int:
        return len(self.blocks) * self.block_size + len(self.partial.tokens)

    @property
    def tokens(self) -> list[int]:
        out: list[int] = []
        for b in self.blocks:
            out.extend(b.tokens)
        out.extend(self.partial.tokens)
        return out

    def sequence_hashes(self) -> list[int]:
        return [b.sequence_hash for b in self.blocks]

    # ---------------------------------------------------------------- updates
    def append(self, token: int) -> Optional[TokenBlock]:
        """Append one token; returns the newly completed block, if any."""
        if self.partial.push(token):
            parent = self.blocks[-1] if self.blocks else None
            block = TokenBlock.build(
                self.partial.tokens, parent, position=len(self.blocks), salt=self.salt
            )
            self.blocks.append(block)
            self.partial = PartialTokenBlock(self.block_size)
            return block
        return None

    def extend(self, tokens: Iterable[int]) -> list[TokenBlock]:
        """Append many tokens; returns all blocks completed by this call."""
        completed: list[TokenBlock] = []
        for t in tokens:
            b = self.append(t)
            if b is not None:
                completed.append(b)
        return completed

    def truncate(self, n_tokens: int) -> None:
        """Shrink the sequence to its first ``n_tokens`` tokens."""
        if n_tokens > self.total_tokens or n_tokens < 0:
            raise ValueError("truncate out of range")
        toks = self.tokens[:n_tokens]
        self.blocks = []
        self.partial = PartialTokenBlock(self.block_size)
        self.extend(toks)

    def __len__(self) -> int:
        return self.total_tokens

    def __repr__(self) -> str:  # pragma: no cover
        return (
            f"TokenBlockSequence(blocks={len(self.blocks)}, "
            f"partial={len(self.partial.tokens)}/{self.block_size})"
        )
