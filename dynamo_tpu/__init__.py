"""dynamo_tpu — a TPU-native distributed LLM inference-serving framework.

A ground-up rebuild of the capabilities of NVIDIA Dynamo (the reference at
/root/reference) designed for TPU hardware: an in-process JAX/XLA engine with
paged attention (Pallas) and continuous batching, a KV-cache-aware smart
router, disaggregated prefill/decode workers with ICI/DCN KV-block handoff,
and an asyncio distributed runtime (coordinator-based control plane, TCP
response streaming) replacing the reference's etcd+NATS+NIXL stack.

Layer map (bottom-up, mirroring SURVEY.md §1):

  tokens      — token-block hashing (reference: lib/tokens)
  runtime     — AsyncEngine, Context/cancellation, pipeline, distributed
                runtime + transports (reference: lib/runtime)
  llm         — OpenAI protocol, preprocessor, detokenizing backend, KV block
                manager, KV-aware router, HTTP service (reference: lib/llm)
  ops         — Pallas TPU kernels: paged attention, block copy
                (reference: lib/llm/src/kernels/block_copy.cu + vLLM engine)
  models      — JAX model implementations (Llama, MoE) — the "engine" the
                reference delegates to vLLM/SGLang is in-process here
  engine      — continuous-batching scheduler + executor on the JAX models
  parallel    — mesh/sharding utilities, collectives layout (TP/DP/EP/SP)
"""

__version__ = "0.1.0"
