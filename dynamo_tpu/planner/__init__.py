"""SLA-driven planner subsystem (reference Planner parity,
docs/architecture.md:47): admission control for the HTTP frontend, a
pure planning policy over live ForwardPassMetrics, and pluggable
actuation backends (sdk supervisor, k8s operator).

See docs/planner.md for the policy's inputs/outputs, admission
semantics, and the role-flip state machine.
"""

from dynamo_tpu.planner.admission import (
    AdmissionConfig,
    AdmissionController,
    AdmissionRejected,
    PriorityClass,
    TokenBucket,
)
from dynamo_tpu.planner.core import (
    LogActuator,
    PlannerLoop,
    PrewarmActuator,
    SupervisorActuator,
)
from dynamo_tpu.planner.policy import (
    MetricsSnapshot,
    Plan,
    PlannerConfig,
    PlannerPolicy,
    PolicyState,
    PoolSnapshot,
    WorkerSample,
    decode_replica_target,
    plan,
    prefill_replica_target,
    step_replicas,
)

__all__ = [
    "AdmissionConfig",
    "AdmissionController",
    "AdmissionRejected",
    "PriorityClass",
    "TokenBucket",
    "LogActuator",
    "PlannerLoop",
    "PrewarmActuator",
    "SupervisorActuator",
    "MetricsSnapshot",
    "Plan",
    "PlannerConfig",
    "PlannerPolicy",
    "PolicyState",
    "PoolSnapshot",
    "WorkerSample",
    "decode_replica_target",
    "plan",
    "prefill_replica_target",
    "step_replicas",
]
