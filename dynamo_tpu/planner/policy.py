"""Pure planner policy: metrics snapshot in → plan out.

Reference parity: the Dynamo Planner (docs/architecture.md:47) continuously
re-plans worker allocation from live KV/queue metrics.  This module is the
decision kernel of our planner subsystem — deterministic and free of IO,
clocks, and randomness, so the whole policy is testable by simulation on
CPU (tests/test_planner.py drives it through a scripted load trace).

Three cooperating decision surfaces:

  * **prefill_replica_target** — queue-depth levelling for prefill pools
    (replicas toward ceil(depth / target_per_replica)).
  * **decode_replica_target** — HPA-style levelling on decode saturation
    (max of slot/KV usage per worker, averaged over the REPORTING workers).
    Stale-metrics rule: when fewer workers report fresh metrics than are
    registered, the policy HOLDS current replicas — silent workers may be
    saturated, and multiplying average usage by the fresh-only count would
    shrink the product and drive a bogus scale-down (ADVICE r5).
  * **plan()** — the full per-tick decision: both pool targets, plus the
    prefill↔decode role-flip state machine (hysteresis via patience +
    cooldown tick counters carried in an explicit, immutable PolicyState).

Every consumer shares these functions: the planner loop (planner/core.py),
the k8s operator's autoscaler (deploy/operator.py), and the sdk supervisor
actuator (planner/core.py SupervisorActuator) — one formula, three
actuation backends.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, replace
from typing import Optional

__all__ = [
    "WorkerSample",
    "PoolSnapshot",
    "MetricsSnapshot",
    "PlannerConfig",
    "PolicyState",
    "Plan",
    "PlannerPolicy",
    "plan",
    "prefill_replica_target",
    "decode_replica_target",
    "step_replicas",
]


def _clamp(v: int, lo: int, hi: int) -> int:
    return min(hi, max(lo, v))


@dataclass(frozen=True)
class WorkerSample:
    """One worker's fresh ForwardPassMetrics, reduced to the planner's
    inputs (ref kv_router/protocols.rs:30-47)."""

    worker_id: int
    request_active_slots: int = 0
    request_total_slots: int = 1
    kv_active_blocks: int = 0
    kv_total_blocks: int = 1
    num_requests_waiting: int = 0

    @property
    def usage(self) -> float:
        """Saturation = max(slot usage, KV usage): a worker is full when
        EITHER resource runs out (slots gate admission, KV gates length)."""
        slot = self.request_active_slots / max(self.request_total_slots, 1)
        kv = self.kv_active_blocks / max(self.kv_total_blocks, 1)
        return max(slot, kv)


@dataclass(frozen=True)
class PoolSnapshot:
    """One pool (prefill or decode) as the planner sees it this tick."""

    replicas: int = 1        # current desired replica count (last plan)
    registered: int = 0      # live coordinator registrations
    samples: tuple = ()      # WorkerSamples with FRESH metrics (reporting subset)
    queue_depth: int = 0     # pending work (remote-prefill queue for prefill)

    @property
    def usage(self) -> Optional[float]:
        """Mean saturation over reporting workers; None when nobody reports."""
        if not self.samples:
            return None
        return sum(s.usage for s in self.samples) / len(self.samples)


@dataclass(frozen=True)
class MetricsSnapshot:
    """Everything plan() may look at for one tick.  ``tick`` is the only
    notion of time — the policy never reads a clock."""

    tick: int
    prefill: PoolSnapshot
    decode: PoolSnapshot
    isl_mean: float = 0.0    # observed input-length mix (tokens)
    osl_mean: float = 0.0    # observed output-length mix (tokens)


@dataclass(frozen=True)
class PlannerConfig:
    prefill_min: int = 1
    prefill_max: int = 8
    decode_min: int = 1
    decode_max: int = 8
    # prefill queue levelling: replicas toward ceil(depth / per_replica)
    queue_target_per_replica: int = 4
    # decode saturation levelling (HPA target)
    decode_target_usage: float = 0.7
    # role-flip state machine
    flip_high: float = 0.85       # a pool at/above this is "hot"
    flip_low: float = 0.25        # a pool at/below this is "idle"
    flip_patience: int = 3        # consecutive hot ticks before flipping
    flip_cooldown: int = 10       # ticks between flips (no thrash)
    # a mix counts as decode-heavy when osl_mean >= ratio * isl_mean —
    # the long-OSL regime where decode capacity, not prefill, is scarce
    decode_heavy_osl_ratio: float = 1.0


@dataclass(frozen=True)
class PolicyState:
    """Flip hysteresis, carried explicitly: plan() is a pure transition
    (state, snapshot) -> (state', Plan)."""

    prefill_hot_ticks: int = 0
    decode_hot_ticks: int = 0
    cooldown: int = 0


@dataclass(frozen=True)
class Plan:
    """One tick's decision.  ``flip`` is advisory role conversion — the
    replica numbers already include its effect, so an actuator that only
    understands per-pool scaling still converges to the same shape."""

    tick: int
    prefill_replicas: int
    decode_replicas: int
    flip: Optional[str] = None   # "prefill_to_decode" | "decode_to_prefill"
    decode_usage: Optional[float] = None
    prefill_queue_depth: int = 0
    reason: str = ""


def step_replicas(current: int, want: int) -> int:
    """Asymmetric levelling: scale up jumps straight to the target (queued
    work is latency), scale down steps ONE replica per tick (cheap
    hysteresis — a transiently cool signal must not flap the pool)."""
    if want > current:
        return want
    if want < current:
        return current - 1
    return current


def prefill_replica_target(queue_depth: int, current: int, per_replica: int,
                           lo: int, hi: int) -> int:
    """Queue-depth levelling: replicas toward ceil(depth / per_replica),
    clamped to [lo, hi]."""
    per = max(1, per_replica)
    return _clamp(math.ceil(queue_depth / per), lo, hi)


def decode_replica_target(
    current: int,
    registered: int,
    usages: list[float] | tuple[float, ...],
    target_usage: float,
    lo: int,
    hi: int,
) -> tuple[int, Optional[float]]:
    """(want, usage) from decode-side saturation with the HPA formula
    ceil(reporting × usage / target).

    The multiplier is the REPORTING worker count, not desired replicas:
    during a scale-up the new pods haven't registered yet, and multiplying
    by the desired count would compound the same saturation into max
    within two ticks.

    Stale-metrics rule (ADVICE r5): when fewer workers report than are
    registered — publisher lag, worker startup, a wedged engine — HOLD at
    the clamped current value exactly like the no-metrics case.  The
    silent workers may be saturated; shrinking the product to the fresh
    subset would scale DOWN on absence of evidence.  [lo, hi] edits still
    apply on hold."""
    if not usages or len(usages) < registered:
        return _clamp(current, lo, hi), None
    usage = sum(usages) / len(usages)
    target = max(1e-3, target_usage)
    want = _clamp(math.ceil(len(usages) * usage / target), lo, hi)
    return want, usage


def plan(cfg: PlannerConfig, state: PolicyState,
         snap: MetricsSnapshot) -> tuple[PolicyState, Plan]:
    """One planning tick: level both pools toward their signals, then run
    the role-flip state machine.

    Flip rules (all deterministic on the snapshot + carried state):

      * prefill→decode: decode hot (usage ≥ flip_high), prefill idle
        (empty queue, usage ≤ flip_low), and the traffic mix decode-heavy
        (osl_mean ≥ ratio·isl_mean), sustained for ``flip_patience``
        consecutive ticks — then one prefill worker converts to decode.
      * decode→prefill: prefill queue over capacity while decode idle,
        sustained likewise.
      * after any flip, ``flip_cooldown`` ticks must pass before another.

    A flip moves ONE replica between pools on top of the levelled targets
    (bounded by each pool's [min, max]), so repeated decisions converge
    instead of oscillating."""
    pf, dc = snap.prefill, snap.decode

    pf_want = prefill_replica_target(
        pf.queue_depth, pf.replicas, cfg.queue_target_per_replica,
        cfg.prefill_min, cfg.prefill_max)
    dc_want, dc_usage = decode_replica_target(
        dc.replicas, dc.registered, [s.usage for s in dc.samples],
        cfg.decode_target_usage, cfg.decode_min, cfg.decode_max)
    pf_repl = step_replicas(pf.replicas, pf_want)
    dc_repl = step_replicas(dc.replicas, dc_want)

    pf_usage = pf.usage
    prefill_hot = pf.queue_depth > cfg.queue_target_per_replica * max(pf.registered, 1)
    prefill_idle = pf.queue_depth == 0 and (pf_usage is None or pf_usage <= cfg.flip_low)
    decode_hot = dc_usage is not None and dc_usage >= cfg.flip_high
    decode_idle = dc_usage is not None and dc_usage <= cfg.flip_low
    decode_heavy_mix = snap.osl_mean >= cfg.decode_heavy_osl_ratio * max(snap.isl_mean, 1.0)

    decode_hot_ticks = (
        state.decode_hot_ticks + 1
        if decode_hot and prefill_idle and decode_heavy_mix else 0
    )
    prefill_hot_ticks = (
        state.prefill_hot_ticks + 1 if prefill_hot and decode_idle else 0
    )
    cooldown = max(0, state.cooldown - 1)

    flip = None
    reason = f"queue={pf.queue_depth} decode_usage=" + (
        f"{dc_usage:.3f}" if dc_usage is not None else "hold")
    # the donor gate checks PRE-levelling replicas (the pool still has a
    # worker to give at tick start): both the flip and a step-down remove
    # exactly one worker per tick, so the flip REPLACES the donor's
    # levelling step rather than stacking on it — the receiving pool gets
    # one replica beyond its own levelled target
    if cooldown == 0:
        if decode_hot_ticks >= cfg.flip_patience and pf.replicas > cfg.prefill_min:
            flip = "prefill_to_decode"
        elif prefill_hot_ticks >= cfg.flip_patience and dc.replicas > cfg.decode_min:
            flip = "decode_to_prefill"
    if flip == "prefill_to_decode":
        pf_repl = max(cfg.prefill_min, min(pf_repl, pf.replicas - 1))
        dc_repl = min(cfg.decode_max, dc_repl + 1)
    elif flip == "decode_to_prefill":
        dc_repl = max(cfg.decode_min, min(dc_repl, dc.replicas - 1))
        pf_repl = min(cfg.prefill_max, pf_repl + 1)
    if flip:
        reason += f" flip={flip}"
        cooldown = cfg.flip_cooldown
        decode_hot_ticks = prefill_hot_ticks = 0

    new_state = PolicyState(
        prefill_hot_ticks=prefill_hot_ticks,
        decode_hot_ticks=decode_hot_ticks,
        cooldown=cooldown,
    )
    return new_state, Plan(
        tick=snap.tick,
        prefill_replicas=pf_repl,
        decode_replicas=dc_repl,
        flip=flip,
        decode_usage=dc_usage,
        prefill_queue_depth=pf.queue_depth,
        reason=reason,
    )


class PlannerPolicy:
    """Thin stateful wrapper over plan() for callers that don't want to
    thread PolicyState themselves (planner loop, interactive use).  All
    decision logic stays in the pure function."""

    def __init__(self, config: Optional[PlannerConfig] = None):
        self.config = config or PlannerConfig()
        self.state = PolicyState()

    def plan(self, snap: MetricsSnapshot) -> Plan:
        self.state, decided = plan(self.config, self.state, snap)
        return decided

    def reset(self) -> None:
        self.state = PolicyState()
