"""Admission control for the HTTP frontend: rate limits, priorities,
deadline-aware shedding.

A frontend facing heavy traffic must bound its queues — accepting every
request lets queue wait grow without limit and blows every SLA at once.
This controller sits in front of engine dispatch (llm/http/service.py)
and decides, per request:

  * **token-bucket rate limiting** per tenant (header ``x-tenant``):
    sustained rate + burst; over-rate requests shed immediately with a
    Retry-After derived from the bucket's refill time.
  * **priority classes** (header ``x-priority`` or body ``priority``):
    ``high`` / ``normal`` / ``low`` map to levels; when the service is at
    capacity, waiters are dispatched strictly by level (FIFO within one).
  * **bounded queues + deadline-aware shedding**: each class has a queue
    bound and a max wait.  At enqueue time the controller estimates this
    request's queue wait from live TTFT/service-time EWMAs (fed by the
    frontend metrics plane) and the number of same-or-higher-priority
    waiters ahead; an estimate past the class deadline sheds NOW (429 +
    Retry-After) instead of letting the client burn its own timeout in
    our queue.  A request whose ACTUAL wait hits the deadline is shed at
    expiry too (the estimate was optimistic).

The clock is injectable so every decision is deterministic under test.
"""

from __future__ import annotations

import asyncio
import heapq
import math
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

__all__ = [
    "AdmissionRejected",
    "PriorityClass",
    "AdmissionConfig",
    "TokenBucket",
    "AdmissionController",
    "Ticket",
]


class AdmissionRejected(Exception):
    """Shed decision: HTTP 429 with a Retry-After hint (seconds)."""

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = max(1, math.ceil(retry_after_s))


@dataclass(frozen=True)
class PriorityClass:
    name: str
    level: int               # lower = more important
    max_queue_depth: int     # waiters of this class beyond this are shed
    max_wait_s: float        # deadline: estimated/actual wait past this sheds


def default_priorities() -> dict[str, PriorityClass]:
    return {
        "high": PriorityClass("high", 0, max_queue_depth=64, max_wait_s=30.0),
        "normal": PriorityClass("normal", 1, max_queue_depth=32, max_wait_s=10.0),
        "low": PriorityClass("low", 2, max_queue_depth=16, max_wait_s=2.0),
    }


@dataclass
class AdmissionConfig:
    max_concurrent: int = 8
    # per-tenant token bucket; rate <= 0 disables rate limiting
    rate_tokens_per_s: float = 0.0
    burst_tokens: float = 16.0
    priorities: dict[str, PriorityClass] = field(default_factory=default_priorities)
    default_priority: str = "normal"
    # prior estimate of one request's service time, used until live
    # TTFT/duration observations arrive from the metrics plane
    default_service_s: float = 0.5
    ewma_alpha: float = 0.2

    @classmethod
    def from_dict(cls, d: dict) -> "AdmissionConfig":
        """Build from a YAML/JSON ``admission:`` block (example graph
        configs, ServiceConfig).  Priority entries override the defaults
        by name: ``{low: {level: 2, max_wait_s: 1.5}}``."""
        priorities = default_priorities()
        for name, pc in (d.get("priorities") or {}).items():
            base = priorities.get(name)
            priorities[name] = PriorityClass(
                name=name,
                level=int(pc.get("level", base.level if base else 1)),
                max_queue_depth=int(pc.get(
                    "max_queue_depth", base.max_queue_depth if base else 32)),
                max_wait_s=float(pc.get(
                    "max_wait_s", base.max_wait_s if base else 10.0)),
            )
        return cls(
            max_concurrent=int(d.get("max_concurrent", 8)),
            rate_tokens_per_s=float(d.get("rate_tokens_per_s", 0.0)),
            burst_tokens=float(d.get("burst_tokens", 16.0)),
            priorities=priorities,
            default_priority=str(d.get("default_priority", "normal")),
            default_service_s=float(d.get("default_service_s", 0.5)),
        )


class TokenBucket:
    """Classic token bucket with an injectable clock (held by caller)."""

    def __init__(self, rate: float, burst: float, now: float):
        self.rate = rate
        self.burst = burst
        self.tokens = burst
        self.last = now

    def _refill(self, now: float) -> None:
        self.tokens = min(self.burst, self.tokens + (now - self.last) * self.rate)
        self.last = now

    def try_take(self, n: float, now: float) -> bool:
        self._refill(now)
        if self.tokens >= n:
            self.tokens -= n
            return True
        return False

    def time_until(self, n: float, now: float) -> float:
        """Seconds until ``n`` tokens will be available (0 if now)."""
        self._refill(now)
        if self.tokens >= n:
            return 0.0
        if self.rate <= 0:
            return math.inf
        return (n - self.tokens) / self.rate


class Ticket:
    """An admitted request's capacity hold; release() frees the slot and
    feeds the service-time EWMA."""

    def __init__(self, controller: "AdmissionController", started: float):
        self._controller = controller
        self._started = started
        self._released = False

    def release(self) -> None:
        if self._released:
            return
        self._released = True
        self._controller._release(self._started)


class _Waiter:
    __slots__ = ("level", "seq", "future", "shed")

    def __init__(self, level: int, seq: int, future: asyncio.Future):
        self.level = level
        self.seq = seq
        self.future = future
        self.shed = False

    def __lt__(self, other: "_Waiter") -> bool:
        return (self.level, self.seq) < (other.level, other.seq)


class AdmissionController:
    def __init__(self, config: Optional[AdmissionConfig] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.config = config or AdmissionConfig()
        self.clock = clock
        self._buckets: dict[str, TokenBucket] = {}
        self._running = 0
        self._waiters: list[_Waiter] = []  # heap by (level, seq)
        self._seq = 0
        # live latency estimates (EWMA, seconds) — fed by the frontend's
        # metrics plane (Metrics.ttft_listeners) and completed tickets
        self.ttft_ewma: Optional[float] = None
        self.itl_ewma: Optional[float] = None
        self.service_ewma: Optional[float] = None
        # counters for the Prometheus surface
        self.admitted_total = 0
        self.shed_total: dict[str, int] = {}

    # -------------------------------------------------------------- estimates
    def _ewma(self, cur: Optional[float], v: float) -> float:
        a = self.config.ewma_alpha
        return v if cur is None else (1 - a) * cur + a * v

    def observe_ttft(self, seconds: float) -> None:
        self.ttft_ewma = self._ewma(self.ttft_ewma, seconds)

    def observe_itl(self, seconds: float) -> None:
        self.itl_ewma = self._ewma(self.itl_ewma, seconds)

    def observe_service(self, seconds: float) -> None:
        self.service_ewma = self._ewma(self.service_ewma, seconds)

    def estimated_service_s(self) -> float:
        """Best current estimate of one request's engine occupancy: the
        duration EWMA when we have one, else TTFT (a lower bound — the
        queue estimate stays optimistic, the deadline check at expiry
        backstops it), else the configured prior."""
        if self.service_ewma is not None:
            return self.service_ewma
        if self.ttft_ewma is not None:
            return self.ttft_ewma
        return self.config.default_service_s

    # ------------------------------------------------------------- admission
    def _priority(self, name: Optional[str]) -> PriorityClass:
        cfg = self.config
        return cfg.priorities.get(name or "", cfg.priorities[cfg.default_priority])

    def _shed(self, pc: PriorityClass, msg: str, retry_after: float) -> AdmissionRejected:
        self.shed_total[pc.name] = self.shed_total.get(pc.name, 0) + 1
        return AdmissionRejected(msg, retry_after)

    async def acquire(self, tenant: str = "default",
                      priority: Optional[str] = None,
                      cost: float = 1.0) -> Ticket:
        """Admit or shed.  Raises AdmissionRejected on shed; returns a
        Ticket (caller must release()) on admit."""
        now = self.clock()
        pc = self._priority(priority)
        cfg = self.config
        if cfg.rate_tokens_per_s > 0:
            bucket = self._buckets.get(tenant)
            if bucket is None:
                bucket = self._buckets[tenant] = TokenBucket(
                    cfg.rate_tokens_per_s, cfg.burst_tokens, now)
            if not bucket.try_take(cost, now):
                wait = bucket.time_until(cost, now)
                raise self._shed(
                    pc, f"tenant {tenant!r} over rate limit", wait)

        # drop stale shed/timed-out waiters so they can't block the fast path
        while self._waiters and (self._waiters[0].shed or self._waiters[0].future.done()):
            heapq.heappop(self._waiters)

        if self._running < cfg.max_concurrent and not self._waiters:
            self._running += 1
            self.admitted_total += 1
            return Ticket(self, now)

        # queue bound per class
        depth = sum(1 for w in self._waiters
                    if not w.shed and w.level == pc.level)
        if depth >= pc.max_queue_depth:
            raise self._shed(
                pc, f"{pc.name} queue full ({depth} waiting)",
                self.estimated_service_s())

        # deadline-aware shed at enqueue: estimated wait = slots that must
        # free before this request runs, paced by the live service estimate
        ahead = sum(1 for w in self._waiters
                    if not w.shed and w.level <= pc.level)
        service = self.estimated_service_s()
        est_wait = service * (ahead + 1) / max(cfg.max_concurrent, 1)
        if est_wait > pc.max_wait_s:
            raise self._shed(
                pc,
                f"{pc.name} estimated queue wait {est_wait:.2f}s exceeds "
                f"deadline {pc.max_wait_s:.2f}s",
                est_wait)

        self._seq += 1
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        waiter = _Waiter(pc.level, self._seq, fut)
        heapq.heappush(self._waiters, waiter)
        try:
            await asyncio.wait_for(fut, timeout=pc.max_wait_s)
        except asyncio.TimeoutError:
            waiter.shed = True  # lazily discarded at dispatch/acquire
            if fut.done() and not fut.cancelled():
                # the slot was granted in the same instant — hand it back
                self._release(None)
            raise self._shed(
                pc, f"{pc.name} queue wait exceeded deadline "
                f"{pc.max_wait_s:.2f}s", service) from None
        except asyncio.CancelledError:
            waiter.shed = True
            if fut.done() and not fut.cancelled():
                # the slot was granted in the same instant — hand it back
                self._release(None)
            raise
        self.admitted_total += 1
        return Ticket(self, self.clock())

    def _release(self, started: Optional[float]) -> None:
        if started is not None:
            self.observe_service(max(0.0, self.clock() - started))
        while self._waiters:
            waiter = heapq.heappop(self._waiters)
            if waiter.shed or waiter.future.done():
                continue
            waiter.future.set_result(None)  # slot transfers, _running unchanged
            return
        self._running = max(0, self._running - 1)

    # --------------------------------------------------------------- insight
    def stats(self) -> dict:
        return {
            "running": self._running,
            "waiting": sum(1 for w in self._waiters if not w.shed),
            "admitted_total": self.admitted_total,
            "shed_total": dict(self.shed_total),
            "ttft_ewma_s": self.ttft_ewma,
            "service_ewma_s": self.service_ewma,
        }
