"""Planner loop: live metrics in, plans out, actuators apply them.

Reference parity: the Dynamo Planner component (docs/architecture.md:47)
— a control loop that subscribes to per-worker ForwardPassMetrics on the
event plane ({ns}.kv_metrics.*, the same subjects the KV router schedules
on), measures per-pool saturation, and re-plans worker allocation.

The loop is deliberately thin: every decision lives in the pure policy
(planner/policy.py), and every side effect lives in a pluggable actuator:

  * :class:`SupervisorActuator` — local process scaling through the sdk
    supervisor (sdk/serving.py ServeSupervisor.scale), including role
    flips (one pool scales down as the other scales up).
  * :class:`LogActuator` — dry-run: log the plan (the ``dynamo-tpu
    planner`` CLI default).
  * the k8s operator (deploy/operator.py) embeds the same policy
    functions directly rather than running this loop — cluster scaling
    actuates through spec reconcile, not a callback.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from typing import Callable, Optional, Protocol

from dynamo_tpu.llm.kv.persist import PrewarmActuator  # planner-facing re-export
from dynamo_tpu.llm.kv_router.publisher import metrics_subject
from dynamo_tpu.planner.policy import (
    MetricsSnapshot,
    Plan,
    PlannerConfig,
    PlannerPolicy,
    PoolSnapshot,
    WorkerSample,
)

log = logging.getLogger("dynamo_tpu.planner")

__all__ = ["PlannerLoop", "Actuator", "LogActuator", "SupervisorActuator",
           "PrewarmActuator"]


class Actuator(Protocol):
    async def apply(self, plan: Plan) -> None: ...


class LogActuator:
    """Dry-run actuation: log every plan, act on nothing."""

    def __init__(self) -> None:
        self.plans: list[Plan] = []

    async def apply(self, plan: Plan) -> None:
        self.plans.append(plan)
        log.info(
            "plan tick=%d prefill=%d decode=%d flip=%s (%s)",
            plan.tick, plan.prefill_replicas, plan.decode_replicas,
            plan.flip, plan.reason,
        )


class SupervisorActuator:
    """Scale sdk-supervised worker processes toward the plan.  A role
    flip needs no special casing: the plan's replica numbers already
    moved one worker between pools, so two scale() calls realize it.
    Downscales are graceful: the supervisor's SIGTERM triggers the
    worker's drain lifecycle (deregister → finish in-flight → exit), so
    a flip completes live streams instead of amputating them."""

    def __init__(self, supervisor, prefill_service: str, decode_service: str):
        self.supervisor = supervisor
        self.prefill_service = prefill_service
        self.decode_service = decode_service

    async def apply(self, plan: Plan) -> None:
        # scale the shrinking pool first so a flip frees its chips before
        # the growing pool's new worker asks the allocator for them
        down_first = plan.flip == "prefill_to_decode"
        order = (
            [(self.prefill_service, plan.prefill_replicas),
             (self.decode_service, plan.decode_replicas)]
        )
        if not down_first:
            order.reverse()
        for name, replicas in order:
            await self.supervisor.scale(name, replicas)


class PlannerLoop:
    """Subscribe → snapshot → plan → actuate, every ``interval_s``.

    Pool membership comes from live coordinator registrations under each
    pool's dyn:// endpoint prefix; freshness from the metrics plane
    subscription.  ``mix_source`` optionally supplies the observed
    (isl_mean, osl_mean) traffic mix (e.g. from the frontend's
    preprocessor stats) — the role-flip machine uses it to recognize the
    decode-heavy long-OSL regime.
    """

    def __init__(
        self,
        coordinator,
        namespace: str = "default",
        policy: Optional[PlannerPolicy] = None,
        config: Optional[PlannerConfig] = None,
        prefill_component: str = "prefill",
        prefill_endpoint: str = "generate",
        decode_component: str = "decode",
        decode_endpoint: str = "generate",
        prefill_queue: Optional[str] = None,
        interval_s: float = 2.0,
        stale_after_s: float = 15.0,
        actuators: tuple = (),
        mix_source: Optional[Callable[[], tuple[float, float]]] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.coord = coordinator
        # injectable clock: metric freshness (stale_after_s) works at
        # DetLoop virtual time under the load plane's macro-simulation
        self._clock = clock
        self.namespace = namespace
        self.policy = policy or PlannerPolicy(config)
        self.prefill_component = prefill_component
        self.prefill_endpoint = prefill_endpoint
        self.decode_component = decode_component
        self.decode_endpoint = decode_endpoint
        self.prefill_queue = prefill_queue or f"{namespace}_prefill_queue"
        self.interval_s = interval_s
        self.stale_after_s = stale_after_s
        self.actuators = list(actuators)
        self.mix_source = mix_source
        self.tick = 0
        self.last_plan: Optional[Plan] = None
        # desired replica counts carried tick-to-tick; initialized from
        # the first observation's registered counts
        self._replicas: dict[str, Optional[int]] = {"prefill": None, "decode": None}
        self._metrics: dict[int, dict] = {}
        self._sub: Optional[int] = None
        self._task: Optional[asyncio.Task] = None

    # ------------------------------------------------------------- ingestion
    def _on_metrics(self, subject: str, payload: bytes) -> None:
        try:
            d = json.loads(payload)
            d["_rx"] = self._clock()
            self._metrics[int(d["worker_id"])] = d
        except Exception:
            log.exception("bad kv_metrics payload on %s", subject)

    async def _pool_ids(self, component: str, endpoint: str) -> list[int]:
        prefix = (f"{self.namespace}/components/{component}"
                  f"/endpoints/{endpoint}/")
        insts = await self.coord.kv_get_prefix(prefix)
        ids = []
        for k in insts:
            try:
                ids.append(int(k.rsplit("/", 1)[-1], 16))
            except ValueError:
                continue
        return ids

    def _samples(self, ids: list[int]) -> tuple[WorkerSample, ...]:
        now = self._clock()
        out = []
        for wid in ids:
            m = self._metrics.get(wid)
            if not m or now - m.get("_rx", 0.0) > self.stale_after_s:
                continue
            out.append(WorkerSample(
                worker_id=wid,
                request_active_slots=int(m.get("request_active_slots", 0)),
                request_total_slots=int(m.get("request_total_slots", 1)),
                kv_active_blocks=int(m.get("kv_active_blocks", 0)),
                kv_total_blocks=int(m.get("kv_total_blocks", 1)),
                num_requests_waiting=int(m.get("num_requests_waiting", 0)),
            ))
        return tuple(out)

    # -------------------------------------------------------------- planning
    async def snapshot(self) -> MetricsSnapshot:
        pf_ids = await self._pool_ids(self.prefill_component, self.prefill_endpoint)
        dc_ids = await self._pool_ids(self.decode_component, self.decode_endpoint)
        try:
            depth = await self.coord.queue_len(self.prefill_queue)
        except Exception:
            depth = 0
        if self._replicas["prefill"] is None:
            self._replicas["prefill"] = max(1, len(pf_ids))
        if self._replicas["decode"] is None:
            self._replicas["decode"] = max(1, len(dc_ids))
        isl, osl = self.mix_source() if self.mix_source else (0.0, 0.0)
        return MetricsSnapshot(
            tick=self.tick,
            prefill=PoolSnapshot(
                replicas=self._replicas["prefill"],
                registered=len(pf_ids),
                samples=self._samples(pf_ids),
                queue_depth=depth,
            ),
            decode=PoolSnapshot(
                replicas=self._replicas["decode"],
                registered=len(dc_ids),
                samples=self._samples(dc_ids),
            ),
            isl_mean=isl,
            osl_mean=osl,
        )

    async def tick_once(self) -> Plan:
        snap = await self.snapshot()
        decided = self.policy.plan(snap)
        self._replicas["prefill"] = decided.prefill_replicas
        self._replicas["decode"] = decided.decode_replicas
        self.last_plan = decided
        self.tick += 1
        for actuator in self.actuators:
            try:
                await actuator.apply(decided)
            except Exception:
                log.exception("actuator %r failed for tick %d",
                              actuator, decided.tick)
        return decided

    # -------------------------------------------------------------- lifecycle
    async def attach(self) -> "PlannerLoop":
        """Subscribe to the metrics plane without starting the periodic
        task — callers that drive tick_once() themselves (tests, a host
        process with its own cadence) get deterministic tick counts."""
        if self._sub is None:
            self._sub = await self.coord.subscribe(
                metrics_subject(self.namespace), self._on_metrics)
        return self

    async def start(self) -> "PlannerLoop":
        await self.attach()
        self._task = asyncio.ensure_future(self._run())
        return self

    async def _run(self) -> None:
        while True:
            try:
                await self.tick_once()
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("planner tick failed; retrying")
            await asyncio.sleep(self.interval_s)

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        if self._sub is not None:
            try:
                await self.coord.unsubscribe(self._sub)
            except Exception:
                pass
            self._sub = None
