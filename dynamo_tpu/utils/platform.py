"""Backend/platform forcing.

This image's ``sitecustomize`` pre-imports jax and pins the platform to its
TPU PJRT plugin ("axon") through ``jax.config`` — plain env vars are too
late by the time user code runs.  This helper flips the platform back to an
n-device virtual CPU mesh (tests, multi-chip dry runs) before any backend
initialises.
"""

from __future__ import annotations

import os
import re


def force_cpu_devices(n: int) -> None:
    """Force an n-device CPU platform.  Must run before jax initialises a
    backend.  Raises the host-device-count flag if a smaller one is set."""
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
    if m is None:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
    elif int(m.group(1)) < n:
        os.environ["XLA_FLAGS"] = flags.replace(
            m.group(0), f"--xla_force_host_platform_device_count={n}"
        )
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        import jax._src.xla_bridge as _xb

        _xb._backend_factories.pop("axon", None)
    except Exception:
        pass
