"""Central mesh construction — ONE place that turns a topology into a
``jax.sharding.Mesh``.

Before this module, bench rigs, the engine CLI, the ring-attention
tests and the multinode configs each built meshes ad hoc (a
``np.array(jax.devices()[:n]).reshape(...)`` with hand-typed axis-name
tuples).  Each hand-typed ``("data", "model")`` is a chance for the
runtime and the sharding lint plane (``analysis/shardcheck.py``) to
disagree about what the mesh even is — and a renamed axis in a
PartitionSpec then *silently replicates* instead of sharding.  Every
mesh in the repo now comes from here, with the axis names imported
from ``obs/topology.py`` (the versioned hardware-constants table the
perf and shard planes already share):

- :func:`build_mesh` — a real device mesh, over ``jax.devices()`` by
  default (post-``multihost.bootstrap`` that is the GLOBAL device
  list, so the same call works single-host and multi-host).  Axis
  order follows ``jax.devices()`` ordering: one process's devices are
  contiguous, so the LAST axes land within a host — put
  ``AXIS_MODEL``/TP there (its collectives ride intra-host ICI) and
  let ``AXIS_DATA``/DP span hosts over DCN (the scaling-book layout).
- :func:`abstract_mesh` — the same topology as a
  ``jax.sharding.AbstractMesh``: axis *names and sizes* with no
  devices attached, what the lint planes use to reason about specs
  and trace ``shard_map`` bodies without owning hardware.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

from dynamo_tpu.obs.topology import (  # noqa: F401  (re-exported)
    AXIS_DATA,
    AXIS_MODEL,
    AXIS_SP,
    MESH_AXES,
)

__all__ = [
    "AXIS_DATA",
    "AXIS_MODEL",
    "AXIS_SP",
    "MESH_AXES",
    "abstract_mesh",
    "build_mesh",
]


def _shape(topology) -> tuple[int, ...]:
    if isinstance(topology, int):
        return (topology,)
    return tuple(int(n) for n in topology)


def build_mesh(topology, axes: Sequence[str] = MESH_AXES, *,
               devices: Optional[Sequence] = None):
    """Mesh of ``topology`` (an int or a tuple of per-axis sizes) over
    ``devices`` (default: the full ``jax.devices()`` list — global
    across hosts once ``multihost.bootstrap`` has run)."""
    import jax
    import numpy as np

    shape = _shape(topology)
    axes = tuple(axes)
    if len(shape) != len(axes):
        raise ValueError(
            f"mesh topology {shape} has {len(shape)} axes but "
            f"{len(axes)} names {axes}"
        )
    devs = list(devices) if devices is not None else jax.devices()
    need = math.prod(shape)
    if need > len(devs):
        raise ValueError(
            f"mesh {dict(zip(axes, shape))} needs {need} devices, "
            f"have {len(devs)}"
        )
    return jax.sharding.Mesh(np.array(devs[:need]).reshape(shape), axes)


def abstract_mesh(topology, axes: Sequence[str] = MESH_AXES):
    """The same topology as an ``AbstractMesh`` (axis names + sizes, no
    devices): enough to prune/evaluate PartitionSpecs and trace
    shard_map bodies shape-only — what the sharding and perf lint
    planes use so auditing a 4-chip layout never requires 4 chips."""
    import jax

    shape = _shape(topology)
    axes = tuple(axes)
    if len(shape) != len(axes):
        raise ValueError(
            f"mesh topology {shape} has {len(shape)} axes but "
            f"{len(axes)} names {axes}"
        )
    return jax.sharding.AbstractMesh(tuple(zip(axes, shape)))
