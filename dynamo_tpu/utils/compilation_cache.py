"""Persistent XLA compilation cache (VERDICT r5 next #1).

Every bench/serve entrypoint pays tens of seconds of XLA compiles on a
fresh process; the reference amortizes this over a long-lived vLLM worker
(lib/runtime/src/worker.rs), but a window-constrained or respawned run
cannot.  Pointing JAX's persistent compilation cache at a durable
directory makes the SECOND process start warm: compiles become disk hits.

Call :func:`enable_persistent_cache` once, before the first jit dispatch.
The directory comes from (in order) the explicit argument, the
``DYNAMO_XLA_CACHE_DIR`` env var, or ``~/.cache/dynamo_tpu/xla``.
Hit/miss logging: the relevant jax loggers are raised to DEBUG so a run's
transcript shows ``persistent compilation cache hit/miss`` lines — a warm
start is provable from the log, not inferred from timing.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

log = logging.getLogger("dynamo_tpu.compile_cache")

DEFAULT_CACHE_DIR = os.path.expanduser("~/.cache/dynamo_tpu/xla")

__all__ = ["enable_persistent_cache", "DEFAULT_CACHE_DIR"]


def enable_persistent_cache(path: Optional[str] = None) -> Optional[str]:
    """Configure jax's persistent compilation cache; returns the dir in
    use, or None when it could not be enabled (unwritable dir — the run
    proceeds cold rather than dying)."""
    path = path or os.environ.get("DYNAMO_XLA_CACHE_DIR") or DEFAULT_CACHE_DIR
    try:
        os.makedirs(path, exist_ok=True)
    except OSError:
        log.warning("cannot create XLA cache dir %s; compiles stay cold", path)
        return None
    import jax

    jax.config.update("jax_compilation_cache_dir", path)
    # cache EVERYTHING: the default thresholds skip sub-second compiles,
    # but a serving boot is death by dozens of small ones
    for knob, value in (
        ("jax_persistent_cache_min_compile_time_secs", 0.0),
        ("jax_persistent_cache_min_entry_size_bytes", -1),
    ):
        try:
            jax.config.update(knob, value)
        except Exception:  # knob renamed/absent on this jax version
            log.debug("jax knob %s unavailable", knob)
    # surface hit/miss lines in transcripts (jax logs them at DEBUG)
    for name in ("jax._src.compilation_cache", "jax._src.compiler"):
        logging.getLogger(name).setLevel(logging.DEBUG)
    log.info("persistent XLA compilation cache: %s", path)
    return path
