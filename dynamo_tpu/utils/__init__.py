"""Shared utilities (platform forcing, compilation cache, misc helpers)."""

from dynamo_tpu.utils.compilation_cache import enable_persistent_cache
from dynamo_tpu.utils.platform import force_cpu_devices

__all__ = ["force_cpu_devices", "enable_persistent_cache"]
