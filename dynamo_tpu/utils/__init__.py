"""Shared utilities (platform forcing, misc helpers)."""

from dynamo_tpu.utils.platform import force_cpu_devices

__all__ = ["force_cpu_devices"]
