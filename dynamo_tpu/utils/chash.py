"""Consistent-hash ring for control-plane ownership.

One ring, two consumers: the HTTP frontends map session keys to the
frontend that terminated the session's earlier turns (llm/http/
service.py SessionAffinity), and the sharded router maps index shards
to router replicas (llm/kv_router/shards).  Both need the same two
properties, which the tests pin quantitatively:

  * **uniformity** — with ``vnodes`` virtual points per node, key mass
    per node stays within a bounded factor of fair share;
  * **minimal movement** — adding or removing one node reassigns only
    the keys that land on that node's arcs (~1/n of the keyspace), so a
    frontend restart invalidates one frontend's sessions, not all of
    them.

Hashing is xxh3-64 (dynamo_tpu.tokens.compute_hash) over UTF-8 key
bytes — the same primitive the block index keys on, so the ring adds no
new hash dependency and stays deterministic across processes.
"""

from __future__ import annotations

import bisect
from typing import Iterable, Optional

from dynamo_tpu.tokens import compute_hash

__all__ = ["HashRing"]

# ring points are salted per vnode; 64 points/node keeps the max/mean
# node load under ~1.35 for the fleet sizes the control plane runs
# (2-16 frontends/replicas; ~1.5 by 64 nodes), measured by
# tests/test_chash.py's uniformity bound
_DEFAULT_VNODES = 64


class HashRing:
    """Deterministic consistent-hash ring over string node ids."""

    def __init__(self, nodes: Iterable[str] = (),
                 vnodes: int = _DEFAULT_VNODES):
        if vnodes <= 0:
            raise ValueError("vnodes must be positive")
        self.vnodes = vnodes
        self._nodes: set[str] = set()
        self._points: list[int] = []       # sorted ring positions
        self._owners: dict[int, str] = {}  # position -> node id
        for n in nodes:
            self.add(n)

    # ------------------------------------------------------------ membership
    def add(self, node: str) -> None:
        if node in self._nodes:
            return
        self._nodes.add(node)
        for v in range(self.vnodes):
            p = compute_hash(f"{node}#{v}".encode())
            # collisions resolve by smallest node id so two processes
            # building the same ring always agree on the owner
            cur = self._owners.get(p)
            if cur is None:
                bisect.insort(self._points, p)
                self._owners[p] = node
            elif node < cur:
                self._owners[p] = node

    def remove(self, node: str) -> None:
        if node not in self._nodes:
            return
        self._nodes.discard(node)
        for v in range(self.vnodes):
            p = compute_hash(f"{node}#{v}".encode())
            if self._owners.get(p) == node:
                del self._owners[p]
                i = bisect.bisect_left(self._points, p)
                if i < len(self._points) and self._points[i] == p:
                    self._points.pop(i)

    def nodes(self) -> set[str]:
        return set(self._nodes)

    def __len__(self) -> int:
        return len(self._nodes)

    def __contains__(self, node: str) -> bool:
        return node in self._nodes

    # ---------------------------------------------------------------- lookup
    def lookup(self, key: str) -> Optional[str]:
        """The node owning ``key`` — the first ring point clockwise from
        the key's hash (wrapping), or None on an empty ring."""
        if not self._points:
            return None
        h = compute_hash(key.encode() if isinstance(key, str) else key)
        i = bisect.bisect_right(self._points, h)
        if i == len(self._points):
            i = 0
        return self._owners[self._points[i]]
