from dynamo_tpu.cli import main

main()
