"""KV block manager: pool, prefix reuse, refcounts, LRU eviction, events.

Semantics carried over from the reference (lib/llm/src/kv/reuse.rs:16-50,
manager.rs:22, reserved.rs:66):

  * blocks preserve their contents when released — an unreferenced full
    block stays matchable by its sequence hash until evicted (LRU),
  * concurrent requests sharing a prefix dedupe onto the same blocks via
    refcounts (the reference's ReservedBlocks registry is folded into one
    hash→block table covering both active and idle blocks),
  * every registration/eviction emits a stored/removed event so the global
    router index stays truthful.

The manager is pure bookkeeping (no device memory) — the engine owns the
cache array; block ids here index its block axis.  Single-threaded by
design (called only from the engine loop), mirroring the reference's
actor-style single-writer discipline (SURVEY.md §5 race detection).
"""

from __future__ import annotations

from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Callable, Optional

from dynamo_tpu.llm.kv.events import KvCacheEvent, KvRemovedEvent, KvStoredEvent

__all__ = ["KvBlockManager", "BlockAllocation", "NoFreeBlocks"]


class NoFreeBlocks(Exception):
    """Pool exhausted (caller should finish/preempt a request)."""


@dataclass
class BlockAllocation:
    """Result of allocating blocks for a prompt."""

    block_ids: list[int]
    cached_tokens: int  # prefix tokens whose KV is already resident
    # tokens covered by ANOTHER request's in-flight (reserved, uncommitted)
    # prefill blocks immediately after the cached prefix: this request
    # references those blocks but must wait for the owner's commit instead
    # of recomputing them (ref lib/llm/src/kv/reserved.rs:66 registry)
    joined_tokens: int = 0


@dataclass
class _Block:
    ref_count: int = 0
    seq_hash: Optional[int] = None
    parent_hash: Optional[int] = None
    # content fully written (commit() ran for this block since its last
    # allocation) — what in-flight joiners poll before absorbing the block
    committed: bool = False


class KvBlockManager:
    def __init__(
        self,
        num_blocks: int,
        block_size: int,
        event_sink: Optional[Callable[[KvCacheEvent], None]] = None,
        enable_prefix_reuse: bool = True,
    ):
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.event_sink = event_sink
        # Called (bid, seq_hash, parent_hash) as a block's content is about
        # to be dropped — the engine's host-offload tier hooks in here.  The
        # device data is still intact at call time; the consumer must copy
        # it out before the next engine step overwrites the block.
        self.offload_sink: Optional[Callable[[int, int, Optional[int]], None]] = None
        self.enable_prefix_reuse = enable_prefix_reuse
        self._blocks = [_Block() for _ in range(num_blocks)]
        self._free: deque[int] = deque(range(num_blocks))
        # unreferenced-but-matchable blocks, oldest first (eviction order)
        self._lru: OrderedDict[int, None] = OrderedDict()
        # seq_hash -> block_id for every content-registered block
        self._table: dict[int, int] = {}
        # seq_hash -> block_id for blocks an in-flight prefill is WRITING:
        # later allocations with the same chain join these blocks and wait
        # on the owner's commit instead of prefilling duplicates (the
        # reference's ReservedBlocks registry, kv/reserved.rs:66 +
        # reuse.rs:16-50; this is what makes concurrent identical prompts —
        # and n>1 fan-out — run ONE prefill)
        self._reserved: dict[int, int] = {}

    # ----------------------------------------------------------------- stats
    @property
    def free_blocks(self) -> int:
        return len(self._free) + len(self._lru)

    @property
    def active_blocks(self) -> int:
        return self.num_blocks - self.free_blocks

    @property
    def usage(self) -> float:
        return self.active_blocks / self.num_blocks

    # ------------------------------------------------------------ allocation
    def match_prefix(self, seq_hashes: list[int], total_tokens: int) -> list[int]:
        """Longest cached-prefix match: block ids whose chained hashes match
        ``seq_hashes``, capped so >=1 token remains to run through the model.
        Read-only probe — shared by allocation and the disagg router's
        prefix_hit_length input (ref kv/manager.rs:31 + disagg_router.rs:236).
        """
        if not self.enable_prefix_reuse:
            return []
        max_match = min(len(seq_hashes), (total_tokens - 1) // self.block_size)
        matched: list[int] = []
        for i in range(max_match):
            bid = self._table.get(seq_hashes[i])
            if bid is None:
                break
            matched.append(bid)
        return matched

    def allocate(self, seq_hashes: list[int], total_tokens: int) -> BlockAllocation:
        """Allocate blocks to cover ``total_tokens``, reusing any cached
        prefix whose chained hashes match ``seq_hashes``.

        At least the final token is always left un-cached so the engine has
        a position to compute logits from.
        """
        n_blocks = -(-total_tokens // self.block_size)  # ceil
        block_ids: list[int] = []
        cached = 0
        for bid in self.match_prefix(seq_hashes, total_tokens):
            self._acquire(bid)
            block_ids.append(bid)
            cached += self.block_size
        # continue the chain through in-flight reservations: share the
        # owner's blocks rather than computing duplicates
        joined = 0
        max_match = min(len(seq_hashes), (total_tokens - 1) // self.block_size)
        while self.enable_prefix_reuse and len(block_ids) < max_match:
            bid = self._reserved.get(seq_hashes[len(block_ids)])
            if bid is None:
                break
            self._acquire(bid)
            block_ids.append(bid)
            joined += self.block_size
        try:
            while len(block_ids) < n_blocks:
                block_ids.append(self._alloc_fresh())
        except NoFreeBlocks:
            self.release(block_ids)
            raise
        return BlockAllocation(
            block_ids=block_ids, cached_tokens=cached, joined_tokens=joined
        )

    def allocate_raw(self, n: int) -> list[int]:
        """Allocate n fresh blocks (no prefix matching) — used by decode
        growth and by disaggregated decode pre-allocation."""
        out: list[int] = []
        try:
            for _ in range(n):
                out.append(self._alloc_fresh())
        except NoFreeBlocks:
            self.release(out)
            raise
        return out

    def _alloc_fresh(self) -> int:
        if self._free:
            bid = self._free.popleft()
        elif self._lru:
            bid, _ = self._lru.popitem(last=False)  # evict oldest
            self._unregister(bid)
        else:
            raise NoFreeBlocks
        blk = self._blocks[bid]
        blk.ref_count = 1
        blk.committed = False
        return bid

    def _acquire(self, bid: int) -> None:
        blk = self._blocks[bid]
        if blk.ref_count == 0:
            self._lru.pop(bid, None)
        blk.ref_count += 1

    # ------------------------------------------------ in-flight reservations
    def reserve(self, seq_hash: int, block_id: int) -> bool:
        """Claim responsibility for computing the block with this chain
        hash.  Fails (False) when the content already exists or another
        request is already computing it — the caller then joins/waits."""
        if not self.enable_prefix_reuse:
            return False
        if seq_hash in self._table or seq_hash in self._reserved:
            return False
        self._reserved[seq_hash] = block_id
        return True

    def unreserve(self, seq_hash: int, block_id: int) -> None:
        """Drop a reservation (owner aborted before committing).  No-op if
        the reservation was already resolved by commit or is held by a
        different block."""
        if self._reserved.get(seq_hash) == block_id:
            del self._reserved[seq_hash]

    def is_reserved(self, seq_hash: int) -> bool:
        return seq_hash in self._reserved

    def block_committed(self, block_id: int) -> bool:
        """Has this block's content been fully written since allocation?
        (What a joiner polls before absorbing a shared in-flight block.)"""
        return self._blocks[block_id].committed

    # ------------------------------------------------------------- lifecycle
    def commit(
        self,
        block_id: int,
        seq_hash: int,
        parent_hash: Optional[int],
        tokens: Optional[list[int]] = None,
    ) -> None:
        """A block filled with content — make it matchable and announce it.

        If the hash is already registered to another block (concurrent
        duplicate computation) the block stays private; dedupe happens at
        the next allocation.
        """
        if not self.enable_prefix_reuse:
            return
        blk = self._blocks[block_id]
        blk.committed = True
        self.unreserve(seq_hash, block_id)
        if seq_hash in self._table:
            return
        blk.seq_hash = seq_hash
        blk.parent_hash = parent_hash
        self._table[seq_hash] = block_id
        if self.event_sink:
            self.event_sink(
                KvStoredEvent(
                    block_hashes=[seq_hash],
                    parent_hash=parent_hash,
                    token_blocks=[list(tokens)] if tokens is not None else [],
                )
            )

    def release(self, block_ids: list[int]) -> None:
        """Drop one reference from each block; unreferenced blocks become
        evictable (content preserved) or free (never registered)."""
        for bid in block_ids:
            blk = self._blocks[bid]
            if blk.ref_count <= 0:
                raise ValueError(f"double free of block {bid}")
            blk.ref_count -= 1
            if blk.ref_count == 0:
                if blk.seq_hash is not None:
                    self._lru[bid] = None
                else:
                    self._free.append(bid)

    def _unregister(self, bid: int) -> None:
        blk = self._blocks[bid]
        if blk.seq_hash is not None:
            if self.offload_sink is not None:
                self.offload_sink(bid, blk.seq_hash, blk.parent_hash)
            self._table.pop(blk.seq_hash, None)
            if self.event_sink:
                self.event_sink(KvRemovedEvent(block_hashes=[blk.seq_hash]))
            blk.seq_hash = None
            blk.parent_hash = None

    def clear_reusable(self) -> None:
        """Evict all idle content blocks (cache flush)."""
        while self._lru:
            bid, _ = self._lru.popitem(last=False)
            self._unregister(bid)
            self._free.append(bid)

    def lookup(self, seq_hash: int) -> Optional[int]:
        return self._table.get(seq_hash)
