"""Streamed KV handoff plane — layer-wise KV streaming over the transfer wire.

The blocking disagg handoff (llm/kv/transfer.py ``write_blocks``) ships
the whole KV cache in one push *after* prefill completes, so at long ISL
the DCN transfer serializes behind compute (ROADMAP item 1).  The cache
is block-granular and layer-major, so each committed span's KV can
stream as soon as the engine commits it, overlapping transfer with the
remaining chunks' compute (FlowKV, arxiv 2504.03775).  This module owns
the whole streamed-handoff session:

  producer (prefill side)
    ``KvStreamProducer`` — drains the engine's per-commit hook
    (engine/core.py ``register_commit_hook``) into a bounded async
    queue, gathers each newly committed block span to host, and sends
    it as one ``WRITE_LAYER`` frame per layer through a
    ``KvStreamSession``.  Backpressure overflow or any transport error
    fails the session; the prefill worker then falls back to the
    blocking whole-cache push.

  session protocol (both sides)
    ``STREAM_BEGIN {v, session, request_id, num_layers}`` opens;
    ``WRITE_LAYER {session, seq, chunk, layer, block_ids, …}+payload``
    carries one layer of one committed chunk under a per-session
    monotonic ``seq``; ``STREAM_END {session, frames, sha}`` closes
    with a sha256 over every payload byte in seq order.  A missing,
    reordered or corrupted frame fails the sha/seq check at END — a
    torn stream is a MISS (the decode side assembles nothing), never
    wrong KV.  ``STREAM_ABORT`` is the producer's explicit give-up.

  assembler (decode side)
    ``KvStreamAssembler`` — stages arriving layers in host memory and
    applies the assembled ``[L, n, …]`` cache through the transfer
    server's ``write_sink`` (→ ``scatter_external``) only once the last
    layer landed AND the completion frame verified.  Partial sessions
    are discarded wholesale.

  routing
    ``choose_handoff_path`` — the NetKV-style transfer-cost term
    (arxiv 2606.03910): cost-compares streaming over DCN/ICI against a
    restore from the persist tier using the measured per-(src,dst,path)
    EWMA tables in obs/costs.py.

Granularity caveat: the prefill step is fully jitted per chunk, so a
true per-layer host callback inside the scan body is impossible — the
commit hook fires at CHUNK boundaries and the producer fans each chunk
out into per-layer frames.  With >=2 chunks the first chunk's layers
are on the wire while later chunks compute; a single-chunk prefill
degenerates to the blocking schedule (docs/kv_streaming.md).
"""

from __future__ import annotations

import asyncio
import hashlib
import itertools
import logging
import os
import time
from typing import Awaitable, Callable, Optional

import numpy as np

from dynamo_tpu.engine.counters import kv_stream_counters
from dynamo_tpu.obs import tracing
from dynamo_tpu.obs.costs import transfer_costs
from dynamo_tpu.runtime.transports.protocol import TransferOp

log = logging.getLogger("dynamo_tpu.kv_stream")

__all__ = [
    "STREAM_VERSION",
    "KvStreamAssembler",
    "KvStreamSession",
    "KvStreamProducer",
    "choose_handoff_path",
]

# Versioned session header: receivers reject sessions whose major
# version they don't speak (an explicit error reply, so the producer
# falls back to the version-free whole-cache push instead of feeding
# frames into a peer that mis-parses them).
STREAM_VERSION = 1

# Bound on concurrently-open assembler sessions: a flood of abandoned
# BEGINs (crashing producers) must not grow host staging without bound.
_MAX_SESSIONS = 64

_SESSION_IDS = itertools.count(1)


def new_session_id(request_id: str) -> str:
    """Process-unique session id; readable in traces and logs."""
    return f"{request_id}@{os.getpid()}#{next(_SESSION_IDS)}"


def _layer_of(arr, layer: int):
    """Slice one layer out of a layer-major block stack ``[L, n, ...]``
    (a (data, scale) tuple of such for the quantized cache)."""
    if isinstance(arr, (tuple, list)):
        return tuple(np.asarray(p)[layer] for p in arr)
    return np.asarray(arr)[layer]


def _num_layers_of(arr) -> int:
    part = arr[0] if isinstance(arr, (tuple, list)) else arr
    return int(np.asarray(part).shape[0])


# --------------------------------------------------------------- assembler


class _Assembly:
    """One in-flight inbound session's host staging state."""

    def __init__(self, header: dict):
        self.session = header["session"]
        self.request_id = header.get("request_id")
        self.num_layers = int(header["num_layers"])
        self.next_seq = 0
        self.sha = hashlib.sha256()
        # chunk index -> (block_ids, {layer: arr-or-parts})
        self.chunks: dict[int, tuple[list[int], dict]] = {}

    def stage(self, header: dict, arr) -> None:
        chunk = int(header["chunk"])
        layer = int(header["layer"])
        ids = [int(b) for b in header["block_ids"]]
        if not 0 <= layer < self.num_layers:
            raise ValueError(f"layer {layer} outside [0, {self.num_layers})")
        got = self.chunks.setdefault(chunk, (ids, {}))
        if got[0] != ids:
            raise ValueError(f"chunk {chunk} block_ids changed mid-session")
        if layer in got[1]:
            raise ValueError(f"duplicate layer {layer} for chunk {chunk}")
        got[1][layer] = arr

    def assemble(self) -> tuple[list[int], object]:
        """Stack the staged layers back into the transfer layout
        ``[L, n, ...]`` (tuple-of-stacks for quantized parts); raises on
        any gap, so a hole can never assemble."""
        if not self.chunks:
            raise ValueError("empty stream session")
        order = sorted(self.chunks)
        if order != list(range(len(order))):
            raise ValueError(f"non-contiguous chunk set {order}")
        ids: list[int] = []
        per_layer: list[list] = [[] for _ in range(self.num_layers)]
        quant = None
        for c in order:
            c_ids, layers = self.chunks[c]
            if set(layers) != set(range(self.num_layers)):
                raise ValueError(
                    f"chunk {c} incomplete: has layers {sorted(layers)}")
            ids.extend(c_ids)
            for layer in range(self.num_layers):
                arr = layers[layer]
                is_q = isinstance(arr, tuple)
                if quant is None:
                    quant = is_q
                elif quant != is_q:
                    raise ValueError("mixed quantized/plain layer frames")
                per_layer[layer].append(arr)
        if quant:
            nparts = len(per_layer[0][0])
            parts = tuple(
                np.stack([
                    np.concatenate([chunk[p] for chunk in layer_chunks],
                                   axis=0)
                    for layer_chunks in per_layer
                ])
                for p in range(nparts)
            )
            return ids, parts
        full = np.stack([
            np.concatenate(layer_chunks, axis=0)
            for layer_chunks in per_layer
        ])
        return ids, full


class KvStreamAssembler:
    """Decode-side assembler: stages layer frames per session in host
    memory; on a verified completion frame, applies the whole assembled
    cache through ``write_sink`` in ONE call — the existing
    scatter-at-step-boundary / request-ownership validation path.  Any
    protocol violation (bad seq, bad sha, version mismatch, hole)
    discards the session and raises — the reply wire turns that into an
    error the producer treats as "fall back", and the decode request is
    admitted only by a later whole-cache push or local prefill.  Never
    partial KV."""

    def __init__(
        self,
        write_sink: Callable[[list[int], object, Optional[str]], Awaitable[None]],
    ):
        self.write_sink = write_sink
        self._sessions: dict[str, _Assembly] = {}
        # observability: how sessions ended on this side
        self.completed = 0
        self.aborted = 0
        self.rejected = 0

    async def handle(self, header: dict, payload: bytes = b"") -> dict:
        """Uniform stream-op entry used by both the TCP server dispatch
        and the colocated client's direct path."""
        op = header.get("op")
        if op == TransferOp.STREAM_BEGIN:
            return self.begin(header)
        if op == TransferOp.WRITE_LAYER:
            return self.write_layer(header, payload)
        if op == TransferOp.STREAM_END:
            return await self.end(header)
        if op == TransferOp.STREAM_ABORT:
            return self.abort(header)
        raise ValueError(f"not a stream op: {op!r}")

    # ----------------------------------------------------------- handlers
    def begin(self, header: dict) -> dict:
        v = header.get("v")
        if v != STREAM_VERSION:
            self.rejected += 1
            raise ValueError(
                f"unsupported kv stream version {v!r} (speak {STREAM_VERSION})")
        sid = header["session"]
        if sid in self._sessions:
            raise ValueError(f"duplicate stream session {sid!r}")
        if len(self._sessions) >= _MAX_SESSIONS:
            self.rejected += 1
            raise ValueError("too many open stream sessions")
        self._sessions[sid] = _Assembly(header)
        return {"session": sid}

    def _session(self, header: dict) -> _Assembly:
        sess = self._sessions.get(header.get("session"))
        if sess is None:
            raise ValueError(f"unknown stream session {header.get('session')!r}")
        return sess

    def write_layer(self, header: dict, payload: bytes) -> dict:
        from dynamo_tpu.llm.kv.transfer import unpack_blocks

        sess = self._session(header)
        seq = int(header["seq"])
        if seq != sess.next_seq:
            # out-of-order / replayed frame: the session is torn; drop it
            # so END can only ever see a clean prefix
            self._sessions.pop(sess.session, None)
            self.rejected += 1
            raise ValueError(
                f"stream seq {seq} != expected {sess.next_seq} (torn)")
        try:
            sess.stage(header, unpack_blocks(header, payload))
        except Exception:
            self._sessions.pop(sess.session, None)
            self.rejected += 1
            raise
        sess.sha.update(payload)
        sess.next_seq += 1
        return {"seq": seq}

    async def end(self, header: dict) -> dict:
        sess = self._session(header)
        # completion verification: frame count, payload sha, then the
        # structural completeness check inside assemble().  Pop FIRST —
        # whatever the outcome, the session is over.
        self._sessions.pop(sess.session, None)
        frames = int(header.get("frames", -1))
        if frames != sess.next_seq:
            self.rejected += 1
            raise ValueError(
                f"completion frame count {frames} != received {sess.next_seq}")
        digest = sess.sha.hexdigest()
        if header.get("sha") != digest:
            self.rejected += 1
            raise ValueError("completion sha mismatch (torn stream = miss)")
        ids, arr = sess.assemble()
        await self.write_sink(ids, arr, sess.request_id)
        self.completed += 1
        return {"applied_blocks": len(ids)}

    def abort(self, header: dict) -> dict:
        if self._sessions.pop(header.get("session"), None) is not None:
            self.aborted += 1
        return {}


# ----------------------------------------------------------------- session


class KvStreamSession:
    """Producer-side session over EITHER transfer-client surface
    (``KvTransferClient`` on the wire, ``LocalKvTransferClient``
    in-process — the unified stream quartet).  Owns seq numbering, the
    rolling payload sha, and per-frame stream metrics."""

    def __init__(self, client, request_id: str, num_layers: int,
                 session_id: Optional[str] = None):
        self.client = client
        self.request_id = str(request_id)
        self.num_layers = int(num_layers)
        self.session_id = session_id or new_session_id(self.request_id)
        self.path = "ici" if getattr(client, "is_local", False) else "dcn"
        self._seq = 0
        self._chunk = 0
        self._sha = hashlib.sha256()
        self.bytes_sent = 0
        self.transfer_s = 0.0

    async def begin(self) -> None:
        kv_stream_counters.record_session()
        await self.client.stream_begin({
            "v": STREAM_VERSION,
            "session": self.session_id,
            "request_id": self.request_id,
            "num_layers": self.num_layers,
        })

    async def write_chunk(self, block_ids: list[int], arr,
                          compute_live: bool = True) -> None:
        """Send one committed block span as ``num_layers`` layer frames.
        ``arr`` is the span's layer-major stack ``[L, n, ...]`` (or the
        quantized (data, scale) pair).  ``compute_live=True`` means the
        producer's prefill is still computing — these frames' transfer
        time is HIDDEN under compute (the overlap_ratio numerator)."""
        from dynamo_tpu.llm.kv.transfer import pack_blocks

        if _num_layers_of(arr) != self.num_layers:
            raise ValueError(
                f"chunk has {_num_layers_of(arr)} layers, "
                f"session opened with {self.num_layers}")
        ids = [int(b) for b in block_ids]
        for layer in range(self.num_layers):
            meta, data = pack_blocks(_layer_of(arr, layer))
            header = {
                "session": self.session_id,
                "seq": self._seq,
                "chunk": self._chunk,
                "layer": layer,
                "block_ids": ids,
                **meta,
            }
            self._sha.update(data)
            t0 = time.perf_counter()
            await self.client.write_layer(header, data)
            dt = time.perf_counter() - t0
            self._seq += 1
            self.bytes_sent += len(data)
            self.transfer_s += dt
            kv_stream_counters.record_layer(len(data), dt,
                                            hidden=compute_live)
        self._chunk += 1

    async def end(self) -> dict:
        resp = await self.client.stream_end({
            "session": self.session_id,
            "frames": self._seq,
            "sha": self._sha.hexdigest(),
        })
        # one aggregate sample per session: the cost tables learn the
        # streamed path's effective throughput alongside write_blocks'
        dst = getattr(self.client, "url", "")
        if self.transfer_s > 0 and dst:
            transfer_costs.record(tracing.process_name(), dst, self.path,
                                  self.bytes_sent, self.transfer_s)
        return resp

    async def abort(self) -> None:
        """Best-effort: the transport may already be dead."""
        try:
            await self.client.stream_abort({"session": self.session_id})
        except (ConnectionError, RuntimeError, OSError,
                asyncio.TimeoutError):
            pass


# ---------------------------------------------------------------- producer


class KvStreamProducer:
    """Prefill-worker side: bridges the engine's commit hook (engine
    thread, fires per committed chunk) into an async drain that streams
    each newly committed span.  The queue is BOUNDED: if the wire falls
    so far behind compute that ``max_pending`` commit events pile up,
    the stream declares itself failed and the worker falls back to the
    whole-cache push — backpressure never stalls the engine thread."""

    def __init__(self, engine, client, request_id: str,
                 remote_block_ids: list[int], skip_blocks: int = 0,
                 max_pending: int = 32):
        self._engine = engine
        self._client = client
        self.request_id = request_id
        self._remote_ids = [int(b) for b in remote_block_ids]
        self._skip = int(skip_blocks)
        self._queue: asyncio.Queue = asyncio.Queue(maxsize=max_pending)
        self._loop = asyncio.get_running_loop()
        self._overflow = False
        self.completed = False
        self.failure: Optional[str] = None
        self.session: Optional[KvStreamSession] = None

    # ------------------------------------------------- engine-thread side
    def on_commit(self, local_ids: list[int], done: bool) -> None:
        """Engine commit hook (engine/core.py ``_fire_commit_hook``):
        ``local_ids`` is the cumulative list of this request's committed
        prefill-side block ids; ``done`` marks the final (held-blocks)
        event.  Engine thread — hop to the loop, never block."""
        ids = [int(b) for b in local_ids]
        try:
            self._loop.call_soon_threadsafe(self._offer, ids, done)
        except RuntimeError:
            pass  # loop closed mid-shutdown; the worker is gone anyway

    def _offer(self, ids: list[int], done: bool) -> None:
        try:
            self._queue.put_nowait((ids, done))
        except asyncio.QueueFull:
            self._overflow = True

    # --------------------------------------------------------- drain side
    async def run(self) -> bool:
        """Drain commit events into layer frames; returns True when the
        completion frame was acked (KV fully applied on the decode
        side), False on any failure — the caller then runs the fallback
        ladder.  Cancellation-safe: the worker cancels this task when
        prefill itself errors."""
        core = self._engine.core
        sent = self._skip
        span = tracing.start_span(
            "kv.stream.produce",
            attrs={"request_id": self.request_id,
                   "skip_blocks": self._skip})
        try:
            while True:
                ids, done = await self._queue.get()
                if self._overflow:
                    raise BufferError(
                        "stream backpressure bound exceeded "
                        "(wire too far behind compute)")
                if len(ids) > len(self._remote_ids):
                    raise ValueError(
                        f"prefill committed {len(ids)} blocks but decode "
                        f"allocated {len(self._remote_ids)}")
                if len(ids) > sent:
                    delta = ids[sent:]
                    arr = await self._engine.run_on_engine(
                        lambda d=delta: core.gather_blocks_np(d)
                    )
                    if self.session is None:
                        self.session = KvStreamSession(
                            self._client, self.request_id,
                            _num_layers_of(arr))
                        await self.session.begin()
                    await self.session.write_chunk(
                        self._remote_ids[sent:len(ids)], arr,
                        compute_live=not done,
                    )
                    sent = len(ids)
                if done:
                    if self.session is None:
                        # nothing beyond the skipped prefix ever committed
                        # — nothing to stream, nothing applied remotely
                        return False
                    if sent != len(self._remote_ids):
                        raise ValueError(
                            f"stream ended at {sent}/"
                            f"{len(self._remote_ids)} blocks")
                    resp = await self.session.end()
                    span.set(
                        applied_blocks=int(resp.get("applied_blocks", 0)))
                    self.completed = True
                    return True
        except asyncio.CancelledError:
            self.failure = "cancelled"
            raise
        except (ConnectionError, RuntimeError, OSError, ValueError,
                BufferError, asyncio.TimeoutError) as e:
            self.failure = f"{type(e).__name__}: {e}"
            log.warning("kv stream for %s failed (%s); falling back",
                        self.request_id, self.failure)
            if self.session is not None:
                await self.session.abort()
            return False
        finally:
            span.set(completed=self.completed)
            if self.failure:
                span.set(failure=self.failure)
            span.end()


# ----------------------------------------------------------------- routing


def choose_handoff_path(
    src: str,
    dst: str,
    nbytes: int,
    local: bool = False,
    persist_resident_blocks: int = 0,
    total_blocks: int = 1,
) -> tuple[str, float]:
    """Transfer-aware path choice for one (prefill, decode) pair.

    Returns ``(path, cost_s)`` with ``path`` one of ``"ici"``/``"dcn"``
    (stream the KV over the wire) or ``"persist"`` (skip the remote
    prefill's transfer: the persist index says the prefix is already
    resident, so restoring it costs a shared-store read instead).
    Costs come from the measured EWMA tables (``obs.costs.cost_s``),
    falling back to the dtperf topology priors for cold edges.  The
    persist path only competes for the fraction of blocks it actually
    holds — a partial persist hit still pays the wire for the rest.
    """
    wire = "ici" if local else "dcn"
    stream_cost = transfer_costs.cost_s(src, dst, wire, nbytes)
    blocks = max(1, int(total_blocks))
    hit = max(0, min(int(persist_resident_blocks), blocks))
    if hit == 0:
        return wire, stream_cost
    hit_bytes = nbytes * hit // blocks
    rest_bytes = nbytes - hit_bytes
    persist_cost = transfer_costs.cost_s(dst, dst, "persist", hit_bytes)
    if rest_bytes > 0:
        persist_cost += transfer_costs.cost_s(src, dst, wire, rest_bytes)
    if persist_cost < stream_cost:
        return "persist", persist_cost
    return wire, stream_cost
