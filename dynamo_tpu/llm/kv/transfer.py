"""KV-block transfer plane — the NIXL/RDMA replacement for TPU serving.

The reference moves KV blocks between engines with NIXL (UCX/RDMA) plus a
Triton kernel to re-arrange layouts across TP degrees (vllm patch:
``vllm/distributed/device_communicators/nixl.py``, ``kv_rearrange.py``;
SURVEY.md §2.9).  The TPU-native design replaces all of that with two paths:

  * **same slice (ICI)** — blocks are `jax.Array`s; gather/scatter over the
    block axis lets XLA route the copy over ICI when source and target
    shardings live on the same mesh (ops/block_copy.py).
  * **cross host (DCN)** — gather stages blocks to host RAM, this module
    ships the bytes over TCP with two-part framing, and the receiver
    scatters them into its pool.  Because the host staging buffer is a full
    (unsharded) ndarray, producer and consumer may run *different* TP
    degrees — resharding is free, where the reference needs a custom
    Triton kernel (kv_rearrange.py).

Wire protocol (two-part frames, framing.py):
  {op: "write_blocks", block_ids, dtype, shape, request_id?} + raw bytes -> {ok}
  {op: "read_blocks", block_ids}     -> {ok, dtype, shape} + raw bytes
  {op: "notify", request_id, first_token, error?}            -> {ok}

plus the streamed layer-wise handoff session (llm/kv/stream.py owns the
session semantics; this module only moves its frames):
  {op: "stream_begin", v, session, request_id, num_layers}       -> {ok}
  {op: "write_layer", session, seq, chunk, layer, block_ids, …}
                                                  + raw bytes    -> {ok}
  {op: "stream_end", session, frames, sha}                       -> {ok}
  {op: "stream_abort", session}                                  -> {ok}

The ``write_blocks`` reply is sent only after the receiving engine applied
the scatter at a step boundary — so ``notify`` ordered after it can never
race the KV into a decode step (the reference gets this ordering from
NIXL transfer-completion notifications).  The same holds for
``stream_end``: its reply means the assembled cache is applied, so the
producer's notify keeps the identical ordering contract on the streamed
path.

Both client surfaces — ``KvTransferClient`` (wire) and
``LocalKvTransferClient`` (colocated fast path) — implement ONE
protocol: identical method signatures, identical argument coercion
(block ids to int, request ids to str), identical notify semantics.
The local client used to hand its callers' objects straight to the
server callbacks, so a non-string request id round-tripped differently
than over JSON — the streaming assembler is tested against either
surface, which only works because the two now agree.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import os
import time
from typing import Awaitable, Callable, Optional

import numpy as np

from dynamo_tpu.obs import tracing
from dynamo_tpu.obs.costs import transfer_costs
from dynamo_tpu.runtime.transports.net import DEFAULT_NET
from dynamo_tpu.runtime.transports.protocol import TransferOp
from dynamo_tpu.runtime.transports.framing import (
    close_writer,
    read_frame,
    write_frame,
)

log = logging.getLogger("dynamo_tpu.kv_transfer")

# Bound on one write/read round-trip under the per-connection lock
# (DT005): a wedged-but-connected peer must surface as ConnectionError —
# otherwise every transfer caller queues forever behind its lock.
# Generous: a multi-hundred-MB block push over DCN is normal.
_TRANSFER_TIMEOUT_S = float(os.environ.get("DYN_KV_TRANSFER_TIMEOUT_S", "60"))

__all__ = [
    "pack_blocks",
    "unpack_blocks",
    "KvTransferServer",
    "KvTransferClient",
    "LocalKvTransferClient",
]

# process-local endpoint registry: when a prefill worker dials a transfer
# URL served from THIS process (colocated prefill/decode — one process
# driving one slice), the handoff short-circuits to the server's sinks with
# DEVICE arrays: gather → device_put/scatter rides ICI, no host staging,
# no TCP serialization.  Cross-process URLs fall through to TCP (the DCN
# path).  Ref: the reference's NIXL device-to-device block WRITE
# (vllm patch nixl.py +394) vs its network path.
_LOCAL_ENDPOINTS: dict[str, "KvTransferServer"] = {}

# live path counters (observability + tests): a colocated deployment can
# ASSERT its handoffs rode the device path, not host TCP staging —
# "transfers took the fast path" becomes checkable instead of assumed
stats = {
    "local_write_calls": 0, "local_blocks": 0,
    "tcp_write_calls": 0, "tcp_blocks": 0,
}


def _arr_nbytes(arr) -> int:
    """Total byte size of a block array or (data, scale) pair — works for
    both ndarray and jax.Array parts (both expose ``nbytes``)."""
    parts = arr if isinstance(arr, (tuple, list)) else [arr]
    return sum(int(getattr(p, "nbytes", 0)) for p in parts)


def _np_dtype(name: str):
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # jax dependency; covers bfloat16 / float8 variants

        return np.dtype(getattr(ml_dtypes, name))


def pack_blocks(arr) -> tuple[dict, bytes]:
    """Blocks -> (wire header fields, payload bytes).

    ``arr`` is either one ndarray (bf16 cache) or the quantized cache's
    (data, scale) pair — the multi-part header keeps the wire format
    self-describing so mixed-precision workers interoperate explicitly.
    """
    parts = list(arr) if isinstance(arr, (tuple, list)) else [arr]
    parts = [np.ascontiguousarray(np.asarray(p)) for p in parts]
    if len(parts) == 1:
        # keep the legacy single-array header so upgraded senders stay
        # readable by not-yet-upgraded receivers (bf16 transfers are the
        # mixed-version case; quantized pairs need upgraded peers anyway)
        p = parts[0]
        return {"dtype": p.dtype.name, "shape": list(p.shape)}, p.tobytes()
    header = {"parts": [{"dtype": p.dtype.name, "shape": list(p.shape)}
                        for p in parts]}
    return header, b"".join(p.tobytes() for p in parts)


def unpack_blocks(header: dict, payload: bytes):
    """Inverse of :func:`pack_blocks`; returns an ndarray, or a tuple of
    ndarrays for multi-part (quantized) payloads.  Accepts the legacy
    single-array header shape for mixed-version peers."""
    metas = header.get("parts")
    if metas is None:  # legacy single-array header
        return np.frombuffer(payload, dtype=_np_dtype(header["dtype"])).reshape(
            header["shape"]
        )
    out, off = [], 0
    for m in metas:
        dt = _np_dtype(m["dtype"])
        n = int(np.prod(m["shape"])) * dt.itemsize
        out.append(np.frombuffer(payload[off:off + n], dtype=dt).reshape(m["shape"]))
        off += n
    return out[0] if len(out) == 1 else tuple(out)


class KvTransferServer:
    """Per-worker ingest endpoint for KV blocks + prefill notifications.

    ``write_sink(block_ids, arr, request_id)`` must resolve once the blocks
    are applied to the engine cache; ``read_source(block_ids)`` returns
    staged blocks (for pull-mode transfer / offload);
    ``notify_cb(request_id, first_token, error)`` delivers the prefill-done
    signal.
    """

    def __init__(
        self,
        write_sink: Callable[[list[int], np.ndarray, Optional[str]], Awaitable[None]],
        notify_cb: Callable[[str, int, Optional[str]], Awaitable[None]],
        read_source: Optional[Callable[[list[int]], Awaitable[np.ndarray]]] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        net=None,
    ):
        from dynamo_tpu.llm.kv.stream import KvStreamAssembler

        self.write_sink = write_sink
        self.notify_cb = notify_cb
        self.read_source = read_source
        self.host, self.port = host, port
        self._net = net or DEFAULT_NET
        self._server = None
        # decode-side streamed-handoff assembler (llm/kv/stream.py):
        # stream-session ops route here; a verified completion applies
        # through the same write_sink as a whole-cache push
        self.assembler = KvStreamAssembler(self._apply_stream)
        # fault seam (fault/injector.py drop_frames / sever_after): called
        # per inbound frame with {"type": op, **header} before dispatch;
        # "drop" swallows the frame (no reply), "sever" cuts the conn —
        # the deterministic mid-stream kill for the fallback-ladder tests
        self.fault_hook: Optional[Callable[[dict], Optional[str]]] = None

    async def _apply_stream(self, block_ids, arr, request_id) -> None:
        await self.write_sink(block_ids, arr, request_id)

    async def start(self) -> "KvTransferServer":
        self._server, self.port = await self._net.start_server(
            self._handle, self.host, self.port
        )
        _LOCAL_ENDPOINTS[self.url] = self
        return self

    async def stop(self) -> None:
        _LOCAL_ENDPOINTS.pop(self.url, None)
        if self._server:
            self._server.close()
            await self._server.wait_closed()

    @property
    def url(self) -> str:
        return f"tcp://{self.host}:{self.port}"

    async def _handle(self, reader, writer) -> None:
        try:
            while True:
                frame = await read_frame(reader)
                if frame is None:
                    break
                h, payload = frame
                op, rid = h.get("op"), h.get("id")
                hook = self.fault_hook
                if hook is not None:
                    action = hook({"type": op, **h})
                    if action == "drop":
                        continue  # swallowed: no dispatch, no reply
                    if action == "sever":
                        break  # cut the transport mid-stream
                # dtspan: a traced sender's context continues through the
                # receive-side apply (scatter waits for a step boundary, so
                # this span measures the full transfer-visible latency)
                trace = tracing.extract(h)
                span = (
                    tracing.start_span(
                        f"kv.server.{op}", parent=trace,
                        attrs={"request_id": h.get("request_id", ""),
                               "bytes": len(payload)})
                    if trace is not None else tracing.NOP_SPAN
                )
                try:
                    if op == TransferOp.WRITE_BLOCKS:
                        await self.write_sink(
                            h["block_ids"],
                            unpack_blocks(h, payload),
                            h.get("request_id"),
                        )
                        write_frame(writer, {"id": rid, "ok": True})
                    elif op == TransferOp.READ_BLOCKS:
                        if self.read_source is None:
                            raise RuntimeError("read_blocks unsupported on this worker")
                        meta, data = pack_blocks(await self.read_source(h["block_ids"]))
                        write_frame(writer, {"id": rid, "ok": True, **meta}, data)
                    elif op == TransferOp.NOTIFY:
                        await self.notify_cb(
                            h["request_id"], h.get("first_token", -1), h.get("error")
                        )
                        write_frame(writer, {"id": rid, "ok": True})
                    elif op in (
                        TransferOp.STREAM_BEGIN,
                        TransferOp.WRITE_LAYER,
                        TransferOp.STREAM_END,
                        TransferOp.STREAM_ABORT,
                    ):
                        extra = await self.assembler.handle(h, payload)
                        write_frame(writer,
                                    {"id": rid, "ok": True, **(extra or {})})
                    else:
                        write_frame(writer, {"id": rid, "error": f"unknown op {op!r}"})
                except Exception as e:
                    log.exception("kv transfer op %s failed", op)
                    write_frame(writer, {"id": rid, "error": str(e)})
                finally:
                    span.end()
                await writer.drain()
        except (ConnectionResetError, asyncio.IncompleteReadError):
            pass
        finally:
            await close_writer(writer)


class LocalKvTransferClient:
    """Colocated fast path: same protocol surface as
    :class:`KvTransferClient` (identical signatures and coercions — the
    unified-client contract in the module docstring), but ops invoke the
    target server's sinks directly — block arrays stay ``jax.Array``s
    end to end, so the copy is device-to-device (ICI under a sharded
    mesh, on-chip otherwise) with zero host staging or wire
    serialization.  Stream-session ops route into the same
    :class:`~dynamo_tpu.llm.kv.stream.KvStreamAssembler` the wire path
    uses, so the streamed handoff is testable against either surface."""

    is_local = True

    def __init__(self, server: "KvTransferServer"):
        self._server = server

    @property
    def url(self) -> str:
        return self._server.url

    async def close(self) -> None:
        pass

    async def write_blocks(
        self,
        block_ids: list[int],
        arr: np.ndarray,
        request_id: Optional[str] = None,
    ) -> None:
        stats["local_write_calls"] += 1
        stats["local_blocks"] += len(block_ids)
        nbytes = _arr_nbytes(arr)
        rid = None if request_id is None else str(request_id)
        span = tracing.start_span(
            "kv.write_blocks",
            attrs={"path": "ici", "blocks": len(block_ids), "bytes": nbytes,
                   "request_id": rid or ""},
        )
        t0 = time.perf_counter()
        try:
            await self._server.write_sink(
                [int(b) for b in block_ids], arr, rid
            )
        finally:
            transfer_costs.record(
                tracing.process_name(), self._server.url, "ici",
                nbytes, time.perf_counter() - t0,
            )
            span.end()

    # ------------------------------------------- streamed handoff session
    # Same assembler, same header schema as the wire — only the framing
    # is skipped.  llm/kv/stream.py's KvStreamSession drives these.
    async def stream_begin(self, header: dict) -> dict:
        return await self._server.assembler.handle(
            {**header, "op": TransferOp.STREAM_BEGIN})

    async def write_layer(self, header: dict, payload: bytes) -> dict:
        return await self._server.assembler.handle(
            {**header, "op": TransferOp.WRITE_LAYER}, payload)

    async def stream_end(self, header: dict) -> dict:
        return await self._server.assembler.handle(
            {**header, "op": TransferOp.STREAM_END})

    async def stream_abort(self, header: dict) -> dict:
        return await self._server.assembler.handle(
            {**header, "op": TransferOp.STREAM_ABORT})

    async def read_blocks(self, block_ids):
        if self._server.read_source is None:
            raise RuntimeError("read_blocks unsupported on this worker")
        span = tracing.start_span(
            "kv.read_blocks", attrs={"path": "ici", "blocks": len(block_ids)})
        t0 = time.perf_counter()
        try:
            out = await self._server.read_source([int(b) for b in block_ids])
        finally:
            span.end()
        transfer_costs.record(
            self._server.url, tracing.process_name(), "ici",
            _arr_nbytes(out), time.perf_counter() - t0,
        )
        return out

    async def notify(
        self, request_id: str, first_token: int, error: Optional[str] = None
    ) -> None:
        # same coercions a JSON round trip imposes on the wire client, so
        # notify_cb sees one type signature regardless of surface
        await self._server.notify_cb(
            str(request_id), int(first_token),
            None if error is None else str(error),
        )


class KvTransferClient:
    """Dial a worker's transfer endpoint and push/pull blocks.

    ``connect`` returns the in-process :class:`LocalKvTransferClient` when
    the URL is served from this very process (colocated engines), and a
    TCP client otherwise."""

    is_local = False

    def __init__(self, url: str, net=None):
        hostport = url.split("//", 1)[-1]
        host, port = hostport.rsplit(":", 1)
        self.host, self.port = host, int(port)
        self._net = net or DEFAULT_NET
        self._reader = self._writer = None
        self._ids = itertools.count(1)
        self._lock = asyncio.Lock()

    @property
    def url(self) -> str:
        return f"tcp://{self.host}:{self.port}"

    @classmethod
    async def connect(cls, url: str, *, net=None, force_tcp: bool = False):
        # DYN_KV_TRANSFER_FORCE_TCP=1 / force_tcp=True disables the
        # colocated shortcut (tests exercising the wire path; protocheck
        # driving a MemNet server registered in _LOCAL_ENDPOINTS)
        local = (
            None
            if force_tcp or os.environ.get("DYN_KV_TRANSFER_FORCE_TCP")
            else _LOCAL_ENDPOINTS.get(url)
        )
        if local is not None:
            return LocalKvTransferClient(local)
        self = cls(url, net=net)
        self._reader, self._writer = await self._net.open_connection(
            self.host, self.port
        )
        return self

    async def close(self) -> None:
        # close AND await the transport teardown (bounded): stopping at
        # close() leaks a live TCP transport at loop shutdown (DT007);
        # null the reference so a repeated close() cannot double-close
        await close_writer(self._writer)
        self._writer = None

    async def _roundtrip(self, header: dict, payload: bytes):
        write_frame(self._writer, header, payload)
        await self._writer.drain()
        return await read_frame(self._reader)

    async def _call(self, header: dict, payload: bytes = b"") -> tuple[dict, bytes]:
        async with self._lock:  # strict request/reply per connection
            header["id"] = next(self._ids)
            tracing.inject(header)  # dtspan: carry the caller's trace
            # bounded (DT005): the reply wait under the lock must not
            # wedge other transfers behind a dead-but-connected peer
            try:
                frame = await asyncio.wait_for(
                    self._roundtrip(header, payload), _TRANSFER_TIMEOUT_S
                )
            except asyncio.TimeoutError:
                raise ConnectionError(
                    f"kv transfer to {self.host}:{self.port} timed out "
                    f"after {_TRANSFER_TIMEOUT_S}s"
                ) from None
        if frame is None:
            raise ConnectionError("kv transfer peer closed")
        resp, data = frame
        if "error" in resp:
            raise RuntimeError(f"kv transfer error: {resp['error']}")
        return resp, data

    async def write_blocks(
        self,
        block_ids: list[int],
        arr: np.ndarray,
        request_id: Optional[str] = None,
    ) -> None:
        """Push blocks into the peer's cache at ``block_ids`` (NIXL WRITE).
        ``request_id`` lets the receiver validate block ownership (a late
        write for an aborted request is dropped, not applied)."""
        stats["tcp_write_calls"] += 1
        stats["tcp_blocks"] += len(block_ids)
        meta, data = pack_blocks(arr)
        dst = f"{self.host}:{self.port}"
        span = tracing.start_span(
            "kv.write_blocks",
            attrs={"path": "dcn", "dst": dst, "blocks": len(block_ids),
                   "bytes": len(data), "request_id": request_id or ""},
        )
        t0 = time.perf_counter()
        try:
            await self._call(
                {
                    "op": TransferOp.WRITE_BLOCKS,
                    "block_ids": list(map(int, block_ids)),
                    "request_id": request_id,
                    **meta,
                },
                data,
            )
        finally:
            # the round-trip completes only after the receiver applied the
            # scatter, so this measures effective (not raw-socket) bandwidth
            transfer_costs.record(
                tracing.process_name(), dst, "dcn",
                len(data), time.perf_counter() - t0,
            )
            span.end()

    async def read_blocks(self, block_ids: list[int]) -> np.ndarray:
        """Pull blocks out of the peer's cache (NIXL READ)."""
        src = f"{self.host}:{self.port}"
        span = tracing.start_span(
            "kv.read_blocks",
            attrs={"path": "dcn", "src": src, "blocks": len(block_ids)},
        )
        t0 = time.perf_counter()
        try:
            resp, data = await self._call(
                {"op": TransferOp.READ_BLOCKS,
                 "block_ids": list(map(int, block_ids))}
            )
        finally:
            span.end()
        transfer_costs.record(
            src, tracing.process_name(), "dcn",
            len(data), time.perf_counter() - t0,
        )
        return unpack_blocks(resp, data)

    async def notify(
        self, request_id: str, first_token: int, error: Optional[str] = None
    ) -> None:
        await self._call(
            {
                "op": TransferOp.NOTIFY,
                "request_id": str(request_id),
                "first_token": int(first_token),
                "error": error,
            }
        )

    # ------------------------------------------- streamed handoff session
    # Thin framed carriers for llm/kv/stream.py's KvStreamSession: every
    # op is a request/reply under the connection lock, so a rejected
    # frame (torn seq, unknown session) surfaces to the producer
    # immediately as RuntimeError and the fallback ladder engages before
    # more layers are wasted on a dead session.
    async def stream_begin(self, header: dict) -> dict:
        resp, _ = await self._call({**header, "op": TransferOp.STREAM_BEGIN})
        return resp

    async def write_layer(self, header: dict, payload: bytes) -> dict:
        resp, _ = await self._call(
            {**header, "op": TransferOp.WRITE_LAYER}, payload)
        return resp

    async def stream_end(self, header: dict) -> dict:
        resp, _ = await self._call({**header, "op": TransferOp.STREAM_END})
        return resp

    async def stream_abort(self, header: dict) -> dict:
        resp, _ = await self._call({**header, "op": TransferOp.STREAM_ABORT})
        return resp
