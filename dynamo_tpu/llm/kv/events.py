"""KV cache events — how workers tell the router what their caches hold.

Reference parity: KvCacheEvent{Stored{parent_hash, blocks}, Removed{hashes}}
(lib/llm/src/kv_router/protocols.rs:60-120 region), published per worker on
the event plane and consumed by the router's radix-tree indexer.

The ``tier``/``kind`` string constants below are the single source of
truth for the event plane's discriminators (wirecheck rule WR003):
producers (engine/core.py, persist.py spill paths) and consumers
(kv_router/indexer.py) both import them instead of re-spelling the
literals.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Optional, Union

log = logging.getLogger("dynamo_tpu.kv_router")

# cache tiers a block can be resident in (wire field "tier")
TIER_DEVICE = "device"
TIER_PERSIST = "persist"
# event kinds (wire field "kind")
KIND_STORED = "stored"
KIND_REMOVED = "removed"

# every key current producers may put on the wire; event_from_wire drops
# anything else (forward compat: a newer worker may tag events with
# fields this router build does not know yet)
_WIRE_KEYS = frozenset({
    "event_id", "worker_id", "kind", "parent_hash", "block_hashes",
    "token_blocks", "tier",
})


@dataclass
class KvStoredEvent:
    """Blocks became resident (and reusable) on a worker.

    ``block_hashes`` are chained sequence hashes (dynamo_tpu.tokens), in
    order; ``parent_hash`` is the sequence hash of the block preceding the
    first one (None at sequence root).
    """

    block_hashes: list[int]
    parent_hash: Optional[int] = None
    token_blocks: list[list[int]] = field(default_factory=list)  # optional token payload
    # which cache tier holds the blocks: "device" (HBM radix hit, free to
    # reuse) or "persist" (disk tier — reusable after a host-side restore,
    # so the router scores it at a discount)
    tier: str = TIER_DEVICE

    kind = KIND_STORED


@dataclass
class KvRemovedEvent:
    """Blocks were evicted from a worker's cache."""

    block_hashes: list[int]
    tier: str = TIER_DEVICE

    kind = KIND_REMOVED


KvCacheEvent = Union[KvStoredEvent, KvRemovedEvent]


def event_to_wire(event_id: int, worker_id: int, ev: KvCacheEvent) -> dict:
    """JSON-serialisable router event (ref RouterEvent, indexer.rs)."""
    out = {"event_id": event_id, "worker_id": worker_id, "kind": ev.kind}
    if isinstance(ev, KvStoredEvent):
        out["parent_hash"] = ev.parent_hash
        out["block_hashes"] = ev.block_hashes
        if ev.token_blocks:
            out["token_blocks"] = ev.token_blocks
    else:
        out["block_hashes"] = ev.block_hashes
    if ev.tier != TIER_DEVICE:  # wire-compat: old consumers never see the key
        out["tier"] = ev.tier
    return out


def event_from_wire(d: dict) -> tuple[int, int, KvCacheEvent]:
    unknown = set(d) - _WIRE_KEYS
    if unknown:
        # tolerate-and-drop, never raise: a newer producer must be able
        # to add fields (e.g. the streamed-handoff layer tags) without
        # breaking older routers mid-rollout
        log.debug("kv event: dropping unknown wire fields %s",
                  sorted(unknown))
    tier = d.get("tier", TIER_DEVICE)
    if d["kind"] == KIND_STORED:
        ev: KvCacheEvent = KvStoredEvent(
            block_hashes=list(d["block_hashes"]),
            parent_hash=d.get("parent_hash"),
            token_blocks=[list(t) for t in d.get("token_blocks", [])],
            tier=tier,
        )
    else:
        ev = KvRemovedEvent(block_hashes=list(d["block_hashes"]), tier=tier)
    return d["event_id"], d["worker_id"], ev
