"""KV cache events — how workers tell the router what their caches hold.

Reference parity: KvCacheEvent{Stored{parent_hash, blocks}, Removed{hashes}}
(lib/llm/src/kv_router/protocols.rs:60-120 region), published per worker on
the event plane and consumed by the router's radix-tree indexer.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Union


@dataclass
class KvStoredEvent:
    """Blocks became resident (and reusable) on a worker.

    ``block_hashes`` are chained sequence hashes (dynamo_tpu.tokens), in
    order; ``parent_hash`` is the sequence hash of the block preceding the
    first one (None at sequence root).
    """

    block_hashes: list[int]
    parent_hash: Optional[int] = None
    token_blocks: list[list[int]] = field(default_factory=list)  # optional token payload
    # which cache tier holds the blocks: "device" (HBM radix hit, free to
    # reuse) or "persist" (disk tier — reusable after a host-side restore,
    # so the router scores it at a discount)
    tier: str = "device"

    kind = "stored"


@dataclass
class KvRemovedEvent:
    """Blocks were evicted from a worker's cache."""

    block_hashes: list[int]
    tier: str = "device"

    kind = "removed"


KvCacheEvent = Union[KvStoredEvent, KvRemovedEvent]


def event_to_wire(event_id: int, worker_id: int, ev: KvCacheEvent) -> dict:
    """JSON-serialisable router event (ref RouterEvent, indexer.rs)."""
    out = {"event_id": event_id, "worker_id": worker_id, "kind": ev.kind}
    if isinstance(ev, KvStoredEvent):
        out["parent_hash"] = ev.parent_hash
        out["block_hashes"] = ev.block_hashes
        if ev.token_blocks:
            out["token_blocks"] = ev.token_blocks
    else:
        out["block_hashes"] = ev.block_hashes
    if ev.tier != "device":  # wire-compat: old consumers never see the key
        out["tier"] = ev.tier
    return out


def event_from_wire(d: dict) -> tuple[int, int, KvCacheEvent]:
    tier = d.get("tier", "device")
    if d["kind"] == "stored":
        ev: KvCacheEvent = KvStoredEvent(
            block_hashes=list(d["block_hashes"]),
            parent_hash=d.get("parent_hash"),
            token_blocks=[list(t) for t in d.get("token_blocks", [])],
            tier=tier,
        )
    else:
        ev = KvRemovedEvent(block_hashes=list(d["block_hashes"]), tier=tier)
    return d["event_id"], d["worker_id"], ev
