"""Worker-side paged-KV bookkeeping: block pool, prefix reuse, events,
transfer.  Reference parity: lib/llm/src/kv/{manager,reuse,reserved}.rs and
the KV event types in lib/llm/src/kv_router/protocols.rs."""

from dynamo_tpu.llm.kv.events import KvCacheEvent, KvStoredEvent, KvRemovedEvent
from dynamo_tpu.llm.kv.block_manager import KvBlockManager, BlockAllocation

__all__ = [
    "KvCacheEvent",
    "KvStoredEvent",
    "KvRemovedEvent",
    "KvBlockManager",
    "BlockAllocation",
]
