"""Persistent prefix-cache tier: content-addressed KV block store on disk.

The fourth KV tier, below device HBM (KvBlockManager), host RAM
(HostKvPool) and the DCN transfer plane (kv/transfer.py).  Blocks the
host pool publishes spill here asynchronously as block-group files;
``EngineCore._restore_from_host`` falls through to this index when the
host pool misses, so a worker restart — or a replica that never
prefilled the prompt — re-enters the prefix as ``cached_tokens``
exactly like a warm radix hit (docs/kv_persistence.md).

Key scheme: the chained xxh3-64 sequence hashes (dynamo_tpu.tokens,
seed 1337) already commit to their entire prefix, so a flat
hash → (file, row) index gives true prefix-match semantics with no tree.
A *generation tag* (hash of the model/cache identity, computed by the
engine) namespaces the store directory: a model or cache-layout change
opens a fresh generation and deletes the stale ones.

File format (one file per spilled block group)::

    magic   b"DTKVP1\\n"
    u64 LE  header length
    JSON    {version, generation, hashes, structure, leaves:
             [{dtype, shape}], payload_sha256, created}
    bytes   leaf payloads, concatenated in leaf order (C-order rows)

``structure`` records how to rebuild the block pytree without JAX:
``leaf`` (one bf16/f32 array), ``quant`` (QuantKvCache data+scales), or
``tuple``.  Payload integrity is the same sha256 helper model pulls use
(model_store.file_sha256 over bytes here); a corrupt file is deleted and
reported as a miss, never served.

Eviction: LRU by last-touch at file granularity under a byte size cap,
plus an optional TTL.  last_touch is mirrored to the file mtime so the
LRU order survives restarts.

Concurrency: internally locked (the engine thread matches/loads while
the kv-offload thread spills).  All file writes fsync off the event
loop — the engine threads are plain threads, and the async replicator
crosses into file I/O only via ``asyncio.to_thread``.
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import logging
import os
import shutil
import struct
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass
from pathlib import Path
from typing import Callable, Optional, Sequence

import numpy as np

from dynamo_tpu.engine.counters import persist_counters
from dynamo_tpu.obs import tracing
from dynamo_tpu.obs.costs import transfer_costs

log = logging.getLogger("dynamo_tpu.kv.persist")

__all__ = [
    "PersistentKvStore",
    "PersistReplicator",
    "PrewarmActuator",
    "prewarm_key",
]

MAGIC = b"DTKVP1\n"
FORMAT_VERSION = 1
SUFFIX = ".dtkv"


def _np_dtype(name: str) -> np.dtype:
    """dtype from its header name; bfloat16 and friends resolve through
    ml_dtypes when plain numpy doesn't know them."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _flatten(blocks) -> tuple[str, list[np.ndarray]]:
    """Block pytree → (structure tag, numpy leaves).  Deliberately not
    jax.tree: the store must rebuild the structure in a fresh process
    where no treedef object exists yet."""
    try:
        from dynamo_tpu.ops.kv_quant import QuantKvCache
    except ImportError:  # pragma: no cover - kv_quant always present
        QuantKvCache = None
    if QuantKvCache is not None and isinstance(blocks, QuantKvCache):
        return "quant", [np.asarray(blocks.data), np.asarray(blocks.scales)]
    if isinstance(blocks, np.ndarray):
        return "leaf", [blocks]
    if isinstance(blocks, (tuple, list)):
        return "tuple", [np.asarray(a) for a in blocks]
    return "leaf", [np.asarray(blocks)]


def _unflatten(structure: str, leaves: list[np.ndarray]):
    if structure == "leaf":
        return leaves[0]
    if structure == "quant":
        from dynamo_tpu.ops.kv_quant import QuantKvCache

        return QuantKvCache(*leaves)
    return tuple(leaves)


@dataclass
class _GroupFile:
    path: Path
    size: int
    last_touch: float
    hashes: list[int]
    verified: bool = False  # payload sha checked at least once this run


class _StoreCorrupt(Exception):
    """A block-group file failed its integrity/format check."""


def _parse(data: bytes, generation: Optional[str] = None) -> tuple[dict, bytes]:
    """Split a block-group file into (header, payload), verifying magic,
    version, optional generation, and the payload sha256."""
    if not data.startswith(MAGIC):
        raise _StoreCorrupt("bad magic")
    off = len(MAGIC)
    if len(data) < off + 8:
        raise _StoreCorrupt("truncated header length")
    (hlen,) = struct.unpack("<Q", data[off:off + 8])
    off += 8
    if len(data) < off + hlen:
        raise _StoreCorrupt("truncated header")
    try:
        header = json.loads(data[off:off + hlen].decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise _StoreCorrupt(f"header not JSON: {e}") from e
    if header.get("version") != FORMAT_VERSION:
        raise _StoreCorrupt(f"version {header.get('version')}")
    if generation is not None and header.get("generation") != generation:
        raise _StoreCorrupt(
            f"generation {header.get('generation')!r} != {generation!r}")
    payload = data[off + hlen:]
    if hashlib.sha256(payload).hexdigest() != header.get("payload_sha256"):
        raise _StoreCorrupt("payload sha256 mismatch")
    return header, payload


def _read_header(path: Path) -> dict:
    """Header only (cheap index rebuild at open; payload stays unread)."""
    with open(path, "rb") as f:
        magic = f.read(len(MAGIC))
        if magic != MAGIC:
            raise _StoreCorrupt("bad magic")
        raw = f.read(8)
        if len(raw) < 8:
            raise _StoreCorrupt("truncated header length")
        (hlen,) = struct.unpack("<Q", raw)
        blob = f.read(hlen)
        if len(blob) < hlen:
            raise _StoreCorrupt("truncated header")
    try:
        return json.loads(blob.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise _StoreCorrupt(f"header not JSON: {e}") from e


def _payload_leaves(header: dict, payload: bytes) -> list[np.ndarray]:
    leaves = []
    off = 0
    for spec in header["leaves"]:
        dt = _np_dtype(spec["dtype"])
        shape = tuple(spec["shape"])
        n = dt.itemsize * int(np.prod(shape, dtype=np.int64))
        if off + n > len(payload):
            raise _StoreCorrupt("payload shorter than leaf specs")
        leaves.append(
            np.frombuffer(payload, dtype=dt, count=n // dt.itemsize,
                          offset=off).reshape(shape))
        off += n
    return leaves


class PersistentKvStore:
    """Content-addressed persistent block store keyed by sequence hash.

    ``max_bytes=0`` disables the size cap; ``ttl_s=0`` disables TTL.
    ``clock`` is injectable for eviction tests.
    """

    def __init__(self, root_dir: str | Path, generation: str, *,
                 max_bytes: int = 0, ttl_s: float = 0.0,
                 clock: Callable[[], float] = time.time):
        self.generation = str(generation)
        self.root = Path(root_dir)
        self.dir = self.root / self.generation
        self.max_bytes = int(max_bytes)
        self.ttl_s = float(ttl_s)
        self._clock = clock
        self._lock = threading.Lock()
        # seq_hash -> (stem, row); stem -> file info, LRU order (oldest
        # last_touch first)
        self._index: dict[int, tuple[str, int]] = {}
        self._files: "OrderedDict[str, _GroupFile]" = OrderedDict()
        self._removed: deque[int] = deque()  # evicted hashes → router events
        # stats
        self.hits = 0
        self.misses = 0
        self.spilled_bytes = 0
        self.evicted_files = 0
        self.evicted_blocks = 0
        self.invalid_files = 0
        self._open()

    # ------------------------------------------------------------------ open
    def _open(self) -> None:
        self.dir.mkdir(parents=True, exist_ok=True)
        # generation invalidation: a model/config change must not serve
        # stale blocks, and must not leak the old generation's disk
        for sib in self.root.iterdir():
            if sib.is_dir() and sib.name != self.generation:
                log.info("persist: invalidating stale generation %s", sib.name)
                shutil.rmtree(sib, ignore_errors=True)
        for path in sorted(self.dir.glob(f"*{SUFFIX}")):
            try:
                header = _read_header(path)
                if header.get("version") != FORMAT_VERSION:
                    raise _StoreCorrupt("version")
                if header.get("generation") != self.generation:
                    raise _StoreCorrupt("generation")
                hashes = [int(h) for h in header["hashes"]]
            except (_StoreCorrupt, OSError, KeyError, ValueError) as e:
                log.warning("persist: dropping unreadable %s (%s)", path, e)
                self.invalid_files += 1
                path.unlink(missing_ok=True)
                continue
            st = path.stat()
            self._register(path.name[:-len(SUFFIX)], path, st.st_size,
                           st.st_mtime, hashes)
        self._files = OrderedDict(
            sorted(self._files.items(), key=lambda kv: kv[1].last_touch))
        with self._lock:
            self._sweep_locked()
        persist_counters.set_resident(self.resident_bytes)

    def _register(self, stem: str, path: Path, size: int, touch: float,
                  hashes: list[int]) -> None:
        self._files[stem] = _GroupFile(path=path, size=size,
                                       last_touch=touch, hashes=hashes)
        for row, h in enumerate(hashes):
            self._index.setdefault(h, (stem, row))

    # ----------------------------------------------------------------- state
    @property
    def resident_bytes(self) -> int:
        return sum(f.size for f in self._files.values())

    @property
    def resident_blocks(self) -> int:
        return len(self._index)

    def __contains__(self, seq_hash: int) -> bool:
        with self._lock:
            return seq_hash in self._index

    def has_file(self, stem: str) -> bool:
        with self._lock:
            return stem in self._files

    def resident_hashes(self) -> list[int]:
        """Snapshot of every resident sequence hash (restart announce)."""
        with self._lock:
            return list(self._index)

    # ----------------------------------------------------------------- spill
    def spill(self, seq_hashes: Sequence[int], blocks) -> int:
        """Persist the blocks not already resident; returns bytes written.

        ``blocks`` is block-major (``blocks[i]`` ↔ ``seq_hashes[i]``) in
        the same pytree structure HostKvPool stores.  Runs on the
        kv-offload thread — never the event loop.
        """
        with self._lock:
            seen: set[int] = set()
            rows = [i for i, h in enumerate(seq_hashes)
                    if h not in self._index and not (h in seen or seen.add(h))]
        if not rows:
            return 0
        fresh = [int(seq_hashes[i]) for i in rows]
        structure, leaves = _flatten(blocks)
        subs = [np.ascontiguousarray(leaf[rows]) for leaf in leaves]
        payload = b"".join(s.tobytes() for s in subs)
        header = {
            "version": FORMAT_VERSION,
            "generation": self.generation,
            "hashes": fresh,
            "structure": structure,
            "leaves": [{"dtype": str(s.dtype), "shape": list(s.shape)}
                       for s in subs],
            "payload_sha256": hashlib.sha256(payload).hexdigest(),
            "created": self._clock(),
        }
        stem = (f"{fresh[0] & 0xFFFFFFFFFFFFFFFF:016x}"
                f"-{len(fresh)}-{header['payload_sha256'][:8]}")
        path = self.dir / f"{stem}{SUFFIX}"
        blob = self._encode(header, payload)
        self._write_atomic(path, blob)
        now = self._clock()
        os.utime(path, (now, now))
        with self._lock:
            if stem not in self._files:
                self._register(stem, path, len(blob), now, fresh)
                self._files.move_to_end(stem)
            self.spilled_bytes += len(blob)
            persist_counters.record_spill(len(blob))
            self._sweep_locked()
            persist_counters.set_resident(self.resident_bytes)
        return len(blob)

    @staticmethod
    def _encode(header: dict, payload: bytes) -> bytes:
        hj = json.dumps(header, separators=(",", ":")).encode("utf-8")
        return MAGIC + struct.pack("<Q", len(hj)) + hj + payload

    def _write_atomic(self, path: Path, blob: bytes) -> None:
        tmp = path.with_name(f".tmp-{path.name}")
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    # ----------------------------------------------------------------- fetch
    def match_prefix(self, seq_hashes: Sequence[int]) -> list[int]:
        """Longest resident prefix (chained hashes: element-wise walk is a
        true prefix match).  Expired (TTL) entries count as misses and
        are reclaimed in place."""
        out: list[int] = []
        with self._lock:
            now = self._clock()
            for h in seq_hashes:
                ent = self._index.get(h)
                if ent is None:
                    break
                info = self._files.get(ent[0])
                if info is None:
                    break
                if self.ttl_s and now - info.last_touch > self.ttl_s:
                    self._remove_locked(ent[0])
                    break
                out.append(h)
            self.hits += len(out)
            if seq_hashes and not out:
                self.misses += 1
        return out

    def load(self, seq_hashes: Sequence[int]):
        """Blocks for ``seq_hashes`` (block-major, original structure).
        Raises KeyError if any is not resident or its file is corrupt —
        callers treat that as a miss."""
        if not seq_hashes:
            raise KeyError("empty load")
        t0 = time.perf_counter()
        span = tracing.start_span(
            "kv.persist_restore", attrs={"blocks": len(seq_hashes)})
        with self._lock:
            now = self._clock()
            per_file: "OrderedDict[str, list[tuple[int, int]]]" = OrderedDict()
            for pos, h in enumerate(seq_hashes):
                ent = self._index.get(h)
                if ent is None:
                    raise KeyError(f"block {h:#x} not resident in persist tier")
                per_file.setdefault(ent[0], []).append((pos, ent[1]))
            structure = None
            out_leaves: Optional[list[np.ndarray]] = None
            for stem, pairs in per_file.items():
                info = self._files[stem]
                try:
                    data = info.path.read_bytes()
                    header, payload = _parse(data, self.generation)
                    leaves = _payload_leaves(header, payload)
                except (OSError, _StoreCorrupt) as e:
                    log.warning("persist: corrupt %s on load (%s); dropping",
                                info.path, e)
                    self.invalid_files += 1
                    self._remove_locked(stem)
                    raise KeyError(f"persist file {stem} corrupt") from e
                info.verified = True
                info.last_touch = now
                self._files.move_to_end(stem)
                try:
                    os.utime(info.path, (now, now))
                except OSError:
                    pass
                if out_leaves is None:
                    structure = header["structure"]
                    out_leaves = [
                        np.empty((len(seq_hashes),) + leaf.shape[1:],
                                 dtype=leaf.dtype)
                        for leaf in leaves
                    ]
                for pos, row in pairs:
                    for out, leaf in zip(out_leaves, leaves):
                        out[pos] = leaf[row]
        assert out_leaves is not None and structure is not None
        nbytes = sum(leaf.nbytes for leaf in out_leaves)
        # measured restore cost: disk → this worker's host pool ("persist"
        # path in the per-(src,dst) table alongside ici/dcn transfers)
        transfer_costs.record(
            "disk", tracing.process_name(), "persist",
            nbytes, time.perf_counter() - t0,
        )
        span.set(bytes=nbytes).end()
        return _unflatten(structure, out_leaves)

    # -------------------------------------------------------------- eviction
    def _remove_locked(self, stem: str) -> None:
        info = self._files.pop(stem, None)
        if info is None:
            return
        for h in info.hashes:
            if self._index.get(h, (None,))[0] == stem:
                del self._index[h]
                self._removed.append(h)
        self.evicted_files += 1
        self.evicted_blocks += len(info.hashes)
        info.path.unlink(missing_ok=True)

    def _sweep_locked(self) -> None:
        now = self._clock()
        if self.ttl_s:
            expired = [s for s, f in self._files.items()
                       if now - f.last_touch > self.ttl_s]
            for stem in expired:
                self._remove_locked(stem)
        if self.max_bytes:
            while self._files and self.resident_bytes > self.max_bytes:
                oldest = next(iter(self._files))
                self._remove_locked(oldest)

    def sweep(self) -> None:
        with self._lock:
            self._sweep_locked()
        persist_counters.set_resident(self.resident_bytes)

    def drain_removed(self) -> list[int]:
        """Hashes evicted since the last drain — the engine forwards them
        as tier="persist" KvRemovedEvents so the router index stays true."""
        with self._lock:
            out = list(self._removed)
            self._removed.clear()
        return out

    # ------------------------------------------------------------ replication
    def export_files(self) -> list[tuple[str, Path, list[int], int]]:
        """Snapshot of (stem, path, hashes, size) for the replicator."""
        with self._lock:
            return [(s, f.path, list(f.hashes), f.size)
                    for s, f in self._files.items()]

    def import_file(self, data: bytes) -> int:
        """Adopt a block-group file fetched from the coordinator blob
        store.  Verifies format/generation/payload integrity; returns how
        many blocks became newly resident (0 for dup/mismatch)."""
        try:
            header, _payload = _parse(data, self.generation)
            hashes = [int(h) for h in header["hashes"]]
        except _StoreCorrupt as e:
            log.warning("persist: rejecting imported file (%s)", e)
            self.invalid_files += 1
            return 0
        with self._lock:
            fresh = [h for h in hashes if h not in self._index]
        if not fresh:
            return 0
        stem = (f"{hashes[0] & 0xFFFFFFFFFFFFFFFF:016x}"
                f"-{len(hashes)}-{header['payload_sha256'][:8]}")
        path = self.dir / f"{stem}{SUFFIX}"
        self._write_atomic(path, data)
        now = self._clock()
        os.utime(path, (now, now))
        with self._lock:
            if stem not in self._files:
                self._register(stem, path, len(data), now, hashes)
                self._files.move_to_end(stem)
            self._sweep_locked()
            persist_counters.set_resident(self.resident_bytes)
        return len(fresh)

    # ----------------------------------------------------------------- stats
    def stats(self) -> dict:
        with self._lock:
            return {
                "persist_files": len(self._files),
                "persist_blocks": len(self._index),
                "persist_resident_bytes": self.resident_bytes,
                "persist_spilled_bytes": self.spilled_bytes,
                "persist_hits": self.hits,
                "persist_misses": self.misses,
                "persist_evicted_files": self.evicted_files,
                "persist_evicted_blocks": self.evicted_blocks,
                "persist_invalid_files": self.invalid_files,
            }

    def close(self) -> None:
        persist_counters.set_resident(self.resident_bytes)


# --------------------------------------------------------------------- remote
def prewarm_key(namespace: str) -> str:
    return f"{namespace}/kvpersist/prewarm"


class PersistReplicator:
    """Replicated persist index over the coordinator (model_store idiom).

    Layout::

      KV   {ns}/kvpersist/{generation}/{stem} -> {hashes, size, sha256}
      blob kvpersist/{ns}/{generation}/{stem} -> block-group file bytes

    ``publish_once`` uploads local block-group files the index doesn't
    know; ``pull_once`` adopts remote files this store lacks (replica B
    restores prefixes replica A prefilled).  ``start()`` runs an
    immediate sync — the planner scale-up pre-warm — then keeps syncing
    on ``interval_s``.  All disk I/O crosses into threads via
    ``asyncio.to_thread`` (lint rule DT009 guards exactly this).
    """

    def __init__(self, coordinator, store: PersistentKvStore,
                 namespace: str = "default", interval_s: float = 5.0):
        self.coord = coordinator
        self.store = store
        self.namespace = namespace
        self.interval_s = interval_s
        self._known: set[str] = set()  # stems already on the coordinator
        self._task: Optional[asyncio.Task] = None
        self._boot: Optional[asyncio.Task] = None
        self.published_files = 0
        self.pulled_blocks = 0

    def _kv_prefix(self) -> str:
        return f"{self.namespace}/kvpersist/{self.store.generation}/"

    def _kv_key(self, stem: str) -> str:
        return f"{self._kv_prefix()}{stem}"

    def _blob_key(self, stem: str) -> str:
        from urllib.parse import quote

        return (f"kvpersist/{quote(self.namespace, safe='')}"
                f"/{self.store.generation}/{stem}")

    async def publish_once(self) -> int:
        """Upload local block-group files absent from the remote index."""
        n = 0
        for stem, path, hashes, _size in self.store.export_files():
            if stem in self._known:
                continue
            if await self.coord.kv_get(self._kv_key(stem)) is not None:
                self._known.add(stem)
                continue
            try:
                data = await asyncio.to_thread(path.read_bytes)
            except OSError:
                continue  # evicted between snapshot and read
            info = await self.coord.blob_put(self._blob_key(stem), data)
            await self.coord.kv_put(self._kv_key(stem), {
                "stem": stem,
                "hashes": hashes,
                "size": len(data),
                "sha256": info["sha256"],
            })
            self._known.add(stem)
            self.published_files += 1
            n += 1
        return n

    async def pull_once(self) -> int:
        """Adopt remote block-group files this store lacks; returns how
        many blocks became newly resident."""
        entries = await self.coord.kv_get_prefix(self._kv_prefix())
        n = 0
        for key, meta in entries.items():
            stem = key.rsplit("/", 1)[-1]
            if stem in self._known or self.store.has_file(stem):
                self._known.add(stem)
                continue
            try:
                data = await self.coord.blob_get(self._blob_key(stem))
            except KeyError:
                continue  # index ahead of blob (publish in flight)
            want = (meta or {}).get("sha256")
            if want and hashlib.sha256(data).hexdigest() != want:
                log.warning("persist: remote blob %s failed sha256; skipping",
                            stem)
                continue
            got = await asyncio.to_thread(self.store.import_file, data)
            self._known.add(stem)
            self.pulled_blocks += got
            n += got
        return n

    async def sync_once(self) -> tuple[int, int]:
        pulled = await self.pull_once()
        published = await self.publish_once()
        return pulled, published

    async def _run(self) -> None:
        while True:
            await asyncio.sleep(self.interval_s)
            try:
                await self.sync_once()
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("persist replication sync failed; retrying")

    async def start(self) -> "PersistReplicator":
        # immediate boot-time sync: a planner scale-up's fresh worker
        # pre-warms from the shared store before it takes traffic
        try:
            await self.sync_once()
        except Exception:
            log.exception("persist pre-warm sync failed; continuing cold")
        self._task = asyncio.ensure_future(self._run())
        return self

    def start_soon(self) -> "PersistReplicator":
        """Sync-context start (worker attach hooks): schedule start()
        and retain the handle so stop() drains a boot still in flight."""
        self._boot = asyncio.ensure_future(self.start())
        return self

    async def stop(self) -> None:
        if self._boot:
            self._boot.cancel()
            try:
                await self._boot
            except asyncio.CancelledError:
                pass
            self._boot = None
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None


class PrewarmActuator:
    """Planner actuator: on a scale-up plan, publish a pre-warm hint so
    replicas know a persist sync is expected.  The freshly-started
    worker's PersistReplicator performs the actual pull at boot; the
    hint records which tick asked for it (observability + a future
    watch-based trigger)."""

    def __init__(self, coordinator, namespace: str = "default"):
        self.coord = coordinator
        self.namespace = namespace
        self._last: Optional[tuple[int, int]] = None
        self.epoch = 0

    async def apply(self, plan) -> None:
        cur = (plan.prefill_replicas, plan.decode_replicas)
        last, self._last = self._last, cur
        if last is None or (cur[0] <= last[0] and cur[1] <= last[1]):
            return
        self.epoch += 1
        await self.coord.kv_put(prewarm_key(self.namespace), {
            "epoch": self.epoch,
            "tick": plan.tick,
            "prefill_replicas": plan.prefill_replicas,
            "decode_replicas": plan.decode_replicas,
            "reason": getattr(plan, "reason", ""),
        })
