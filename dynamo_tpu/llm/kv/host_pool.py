"""Host-RAM KV offload tier: evicted device blocks stay reusable.

Reference parity: the HBM→CPU KV offload tier (lib/llm/src/kv/reuse.rs
state-preserving pool + kv/layer.rs:619 CopyStream device↔pinned-host copy
orchestration; docs/architecture.md:87-93 claims +40% TTFT from it).

TPU translation: the device side is XLA gather/scatter over the paged
cache's block axis (dynamo_tpu/ops/block_copy.py); this module owns the
host side — one big numpy pool (block-major, so a block is one contiguous
row) moved with the native threaded memcpy (native/src/block_copy.cpp),
plus the hash→block bookkeeping: LRU eviction, chained-sequence-hash
prefix matching, content-addressed dedupe.

Concurrency: NOT internally synchronized.  The engine's kv-offload
thread calls ``store`` while the engine loop calls
``match_prefix``/``gather``/``touch`` — every call site must hold
``EngineCore._offload_lock``.
"""

from __future__ import annotations

import logging
from collections import OrderedDict, deque
from typing import Optional, Sequence

import numpy as np

from dynamo_tpu import native

log = logging.getLogger("dynamo_tpu.kv.host_pool")

__all__ = ["HostKvPool"]


class HostKvPool:
    """Fixed-capacity host pool of KV blocks keyed by sequence hash.

    The backing array is allocated lazily on the first ``store`` (the
    engine knows a block's host-side shape only after the first gather).
    """

    def __init__(self, num_blocks: int):
        if num_blocks <= 0:
            raise ValueError("num_blocks must be positive")
        self.num_blocks = num_blocks
        # one pool array per block-pytree leaf: [arr] for the bf16 cache,
        # [data, scale] for the quantized cache (ops/kv_quant.py) — the
        # pool is structure-generic; the treedef captured at first store
        # lets gather() return exactly the structure store() received
        self._arrs: Optional[list[np.ndarray]] = None
        self._treedef = None
        self._free: deque[int] = deque(range(num_blocks))
        self._lru: "OrderedDict[int, None]" = OrderedDict()  # hid -> (order)
        self._hash_of: list[Optional[int]] = [None] * num_blocks
        self._table: dict[int, int] = {}  # seq_hash -> hid
        # stats
        self.stored_blocks = 0
        self.restored_blocks = 0
        self.evicted_blocks = 0
        self.dropped_blocks = 0  # capacity-cap truncations (see reserve)
        # lookup counters (block granularity): how much of each probed
        # prefix was resident vs not — the tier's effective hit rate
        self.hit_blocks = 0
        self.miss_blocks = 0

    # ------------------------------------------------------------------ state
    @property
    def resident(self) -> int:
        return len(self._table)

    @property
    def block_nbytes(self) -> int:
        if self._arrs is None:
            return 0
        return sum(a[0].nbytes for a in self._arrs)

    def __contains__(self, seq_hash: int) -> bool:
        return seq_hash in self._table

    # ------------------------------------------------------------------ store
    def _ensure_arrs(self, parts: list[np.ndarray], treedef) -> None:
        if self._arrs is None:
            self._treedef = treedef
            self._arrs = [
                np.empty((self.num_blocks,) + p.shape[1:], dtype=p.dtype)
                for p in parts
            ]
            return
        if treedef != self._treedef:
            raise ValueError(
                f"block structure changed: pool holds {self._treedef},"
                f" incoming {treedef}"
            )
        for a, p in zip(self._arrs, parts):
            if a.shape[1:] != p.shape[1:] or a.dtype != p.dtype:
                raise ValueError(
                    f"block shape changed: pool {a.shape[1:]}/{a.dtype}"
                    f" vs incoming {p.shape[1:]}/{p.dtype}"
                )

    def _alloc(self) -> int:
        if self._free:
            return self._free.popleft()
        hid, _ = self._lru.popitem(last=False)  # oldest
        old = self._hash_of[hid]
        if old is not None:
            del self._table[old]
            self._hash_of[hid] = None
            self.evicted_blocks += 1
        return hid

    def reserve(self, seq_hashes: Sequence[int], blocks) -> tuple[list[int], list[int]]:
        """Store phase 1 (hold the caller's lock): LRU-refresh resident
        hashes, allocate pool rows for the fresh ones.

        Reserved rows sit in neither ``_table`` nor ``_lru``, so readers
        cannot observe them and eviction cannot reclaim them until
        :meth:`publish`.  Returns ``(hids, rows)``: the pool row for each
        fresh hash and its index into ``seq_hashes``/``blocks``.
        """
        import jax

        parts, treedef = jax.tree.flatten(blocks)
        if any(len(seq_hashes) != len(p) for p in parts):
            raise ValueError(
                f"{len(seq_hashes)} hashes vs {[len(p) for p in parts]} blocks"
            )
        self._ensure_arrs(parts, treedef)
        hids: list[int] = []
        rows: list[int] = []
        seen: set[int] = set()  # intra-batch dedupe (one row per hash)
        # reserved rows leave the free list AND the LRU, so a batch can
        # claim at most free+evictable rows — capping here (instead of
        # letting _alloc raise on an empty LRU) keeps the pool sane when
        # one eviction batch exceeds capacity.  Prefix matching walks
        # from the sequence start, so the EARLIEST blocks are the useful
        # ones to keep when something must be dropped.
        cap = len(self._free) + len(self._lru)
        for i, h in enumerate(seq_hashes):
            hid = self._table.get(h)
            if hid is not None:
                self._lru.move_to_end(hid)
                continue
            if h in seen:
                continue
            if len(hids) >= cap:
                # keeping the drop visible: an undersized num_host_blocks
                # otherwise shows up only as a mysteriously low hit rate
                dropped = len(
                    {x for x in seq_hashes[i:]
                     if x not in seen and x not in self._table})
                self.dropped_blocks += dropped
                log.warning(
                    "host pool full: dropped %d of %d blocks from a store "
                    "batch (num_host_blocks=%d undersized?)",
                    dropped, len(seq_hashes), self.num_blocks)
                break
            seen.add(h)
            hids.append(self._alloc())
            rows.append(i)
        return hids, rows

    def abort(self, hids: list[int]) -> None:
        """Return reserved-but-unpublished rows to the free list (the
        write failed); without this a failed store leaks capacity."""
        self._free.extend(hids)

    def write_rows(self, hids: list[int], blocks, rows: list[int]) -> None:
        """Store phase 2 (NO lock needed — the rows are reserved, hence
        invisible and un-evictable): bulk memcpy into the pool.  This is
        the expensive part; keeping it outside the lock means a store
        never stalls the engine thread's drain/restore."""
        import jax

        parts, _ = jax.tree.flatten(blocks)
        for arr, p in zip(self._arrs, parts):
            # fancy indexing already yields a fresh contiguous array
            native.blocks_scatter(arr, hids, p[rows])

    def publish(self, hids: list[int], seq_hashes: list[int]) -> int:
        """Store phase 3 (hold the lock): make written rows visible.  A
        hash a concurrent store landed first frees its row instead."""
        n = 0
        for hid, h in zip(hids, seq_hashes):
            if h in self._table:
                self._free.append(hid)
                continue
            self._table[h] = hid
            self._hash_of[hid] = h
            self._lru[hid] = None
            n += 1
        self.stored_blocks += n
        return n

    def store(self, seq_hashes: Sequence[int], blocks) -> int:
        """Offload blocks (block-major: blocks[i] belongs to seq_hashes[i];
        a tuple of block-major arrays for the quantized cache).

        Already-resident hashes are refreshed in LRU order but not
        re-copied.  Returns how many new blocks were written.  This is
        the single-caller convenience form of reserve/write_rows/publish.
        """
        hids, rows = self.reserve(seq_hashes, blocks)
        if not hids:
            return 0
        self.write_rows(hids, blocks, rows)
        return self.publish(hids, [seq_hashes[r] for r in rows])

    def touch(self, seq_hashes: Sequence[int]) -> None:
        """Refresh LRU order for resident hashes (no copy)."""
        for h in seq_hashes:
            hid = self._table.get(h)
            if hid is not None:
                self._lru.move_to_end(hid)

    # ------------------------------------------------------------------ fetch
    def match_prefix(self, seq_hashes: Sequence[int]) -> list[int]:
        """Longest resident prefix of ``seq_hashes`` (chained hashes commit
        to their prefix, so element-wise probing is a true prefix match)."""
        out: list[int] = []
        for h in seq_hashes:
            if h not in self._table:
                break
            out.append(h)
        self.hit_blocks += len(out)
        self.miss_blocks += len(seq_hashes) - len(out)
        return out

    def gather(self, seq_hashes: Sequence[int]):
        """Fetch resident blocks (block-major) for upload back to device,
        in exactly the pytree structure ``store`` received."""
        hids = []
        for h in seq_hashes:
            hid = self._table.get(h)
            if hid is None:
                raise KeyError(f"block {h:#x} not resident in host pool")
            self._lru.move_to_end(hid)
            hids.append(hid)
        import jax

        self.restored_blocks += len(hids)
        out = [native.blocks_gather(a, hids) for a in self._arrs]
        return jax.tree.unflatten(self._treedef, out)

    def clear(self) -> None:
        self._table.clear()
        self._lru.clear()
        self._hash_of = [None] * self.num_blocks
        self._free = deque(range(self.num_blocks))

    def stats(self) -> dict:
        return {
            "host_blocks_resident": self.resident,
            "host_blocks_total": self.num_blocks,
            "host_blocks_stored": self.stored_blocks,
            "host_blocks_restored": self.restored_blocks,
            "host_blocks_evicted": self.evicted_blocks,
            "host_blocks_dropped": self.dropped_blocks,
            "host_blocks_hits": self.hit_blocks,
            "host_blocks_misses": self.miss_blocks,
        }
