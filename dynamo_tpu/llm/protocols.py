"""Engine-agnostic internal request/response protocol.

The preprocessor turns OpenAI-level requests into a BackendInput (token ids
+ sampling + stop conditions); engines emit LLMEngineOutput deltas; the
backend detokenizes them into text deltas.

Reference parity: lib/llm/src/protocols/common/llm_backend.rs:1-126
(BackendInput, LLMEngineOutput, FinishReason) and protocols/common/
(SamplingOptions, StopConditions).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Optional


class FinishReason(str, enum.Enum):
    EOS = "eos"          # hit an end-of-sequence token
    STOP = "stop"        # hit a stop sequence / stop token
    LENGTH = "length"    # max_tokens or model context limit
    CANCELLED = "cancelled"
    ERROR = "error"

    def as_openai(self) -> str:
        """Map to OpenAI finish_reason strings."""
        if self in (FinishReason.EOS, FinishReason.STOP):
            return "stop"
        if self is FinishReason.LENGTH:
            return "length"
        return "stop" if self is FinishReason.CANCELLED else "error"


@dataclass
class SamplingOptions:
    temperature: float = 1.0
    top_k: int = 0          # 0 = disabled
    top_p: float = 1.0
    # min-p nucleus floor (vLLM extension; ref protocols/common.rs:293):
    # drop candidates with prob < min_p * max_prob.  0 = disabled
    min_p: float = 0.0
    # OpenAI logit_bias: token id -> additive bias in [-100, 100]
    logit_bias: Optional[dict[int, float]] = None
    seed: Optional[int] = None
    # OpenAI penalties over generated tokens (engine/sampling.py applies
    # them by scatter-add on device; vLLM-compatible semantics)
    frequency_penalty: float = 0.0
    presence_penalty: float = 0.0
    # logprob reporting: chosen-token logprob and top-N alternatives
    logprobs: bool = False
    top_logprobs: int = 0
    # response_format JSON mode: grammar-constrained decoding (the engine
    # masks invalid-next-token logits inside the decode scan; engine/grammar.py)
    json_mode: bool = False
    # guided_choice (vLLM-compatible extension): the output is exactly one
    # of these strings — enforced by a choice-trie grammar in the same scan
    guided_choice: Optional[list[str]] = None
    # guided_regex (vLLM-compatible extension): the output fullmatches this
    # pattern (bounded regex subset compiled to a byte DFA)
    guided_regex: Optional[str] = None

    @property
    def greedy(self) -> bool:
        return self.temperature <= 0.0


@dataclass
class StopConditions:
    max_tokens: Optional[int] = None
    stop: list[str] = field(default_factory=list)          # stop strings (detok layer)
    stop_token_ids: list[int] = field(default_factory=list)
    ignore_eos: bool = False
    min_tokens: int = 0


@dataclass
class BackendInput:
    """What an engine consumes: tokens in, sampling+stop config."""

    token_ids: list[int] = field(default_factory=list)
    sampling: SamplingOptions = field(default_factory=SamplingOptions)
    stops: StopConditions = field(default_factory=StopConditions)
    model: str = ""
    annotations: dict[str, Any] = field(default_factory=dict)


@dataclass
class LLMEngineOutput:
    """A streamed engine delta: newly generated token ids (usually one)."""

    token_ids: list[int] = field(default_factory=list)
    finish_reason: Optional[FinishReason] = None
    # engine-side bookkeeping surfaced for metrics/tests
    cached_tokens: int = 0      # prefix-cache hit length for this request
    # filled by the detokenizing backend:
    text: Optional[str] = None
    # per-token logprob data (aligned with token_ids), when requested:
    logprobs: Optional[list[float]] = None
    # per-token top-N candidates as (token_id, logprob) pairs
    top_logprobs: Optional[list[list[tuple]]] = None
    # display-form logprobs (token strings + bytes), filled by the Backend:
    # [{token, logprob, bytes, top_logprobs: [{token, logprob, bytes}]}]
    logprob_content: Optional[list[dict]] = None

    def __post_init__(self):
        # tolerate wire-decoded plain strings (runtime/serde.py)
        if isinstance(self.finish_reason, str):
            self.finish_reason = FinishReason(self.finish_reason)

    @property
    def finished(self) -> bool:
        return self.finish_reason is not None
