"""GGUF checkpoint support: metadata, tokenizer, and weight extraction.

Reference parity: lib/llm/src/gguf/{content,gguf_metadata,gguf_tokenizer}.rs
(~1030 LoC) — the reference reads GGUF only to build a ModelDeploymentCard
for llama.cpp models.  Here GGUF is a first-class checkpoint format: the
native JAX engine can serve a GGUF file directly (metadata → ModelConfig,
tensors → params pytree, vocab → tokenizer), including dequantising
Q8_0/Q4_0 blocks to the compute dtype.

Format (spec v3): magic "GGUF", little-endian; u32 version, u64 tensor
count, u64 metadata-kv count; metadata KVs; tensor infos (name, dims,
ggml type, data offset); alignment padding; tensor data.  ggml dims are
fastest-varying-first, so a [out, in] torch weight appears as dims
[in, out] and reads back via reshape(dims[::-1]).

Q/K attention weights are stored rope-permuted by llama.cpp's converter
(rows reordered for interleaved rotary); ``unpermute_qk`` restores the HF
rotate-half layout our model uses.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from pathlib import Path
from typing import Any, BinaryIO, Optional

import numpy as np

__all__ = ["GGUFFile", "GGUFTensorInfo", "write_gguf", "load_gguf_model"]

GGUF_MAGIC = b"GGUF"
GGUF_VERSION = 3
ALIGNMENT = 32

# metadata value types
(
    T_U8, T_I8, T_U16, T_I16, T_U32, T_I32, T_F32, T_BOOL, T_STRING, T_ARRAY,
    T_U64, T_I64, T_F64,
) = range(13)

_SCALAR_FMT = {
    T_U8: "<B", T_I8: "<b", T_U16: "<H", T_I16: "<h", T_U32: "<I",
    T_I32: "<i", T_F32: "<f", T_U64: "<Q", T_I64: "<q", T_F64: "<d",
}

# ggml tensor dtypes we understand
GGML_F32, GGML_F16 = 0, 1
GGML_Q4_0, GGML_Q8_0 = 2, 8
GGML_Q4_K, GGML_Q5_K, GGML_Q6_K = 12, 13, 14
GGML_BF16 = 30

_Q4_BLOCK, _Q8_BLOCK = 32, 32
_QK_K = 256  # K-quant super-block size
# K-quant super-block byte sizes (ggml block_q{4,5,6}_K layouts)
_Q4K_BYTES, _Q5K_BYTES, _Q6K_BYTES = 144, 176, 210


@dataclass
class GGUFTensorInfo:
    name: str
    shape: tuple[int, ...]  # numpy order (reversed ggml dims)
    ggml_type: int
    offset: int  # relative to data section start

    @property
    def n_elements(self) -> int:
        return int(np.prod(self.shape, dtype=np.int64)) if self.shape else 1


# --------------------------------------------------------------------- read --


def _read_string(f: BinaryIO) -> str:
    (n,) = struct.unpack("<Q", f.read(8))
    return f.read(n).decode("utf-8")


def _read_value(f: BinaryIO, vtype: int) -> Any:
    if vtype == T_STRING:
        return _read_string(f)
    if vtype == T_BOOL:
        return bool(f.read(1)[0])
    if vtype == T_ARRAY:
        (etype,) = struct.unpack("<I", f.read(4))
        (count,) = struct.unpack("<Q", f.read(8))
        return [_read_value(f, etype) for _ in range(count)]
    fmt = _SCALAR_FMT[vtype]
    (v,) = struct.unpack(fmt, f.read(struct.calcsize(fmt)))
    return v


class GGUFFile:
    """Parsed GGUF container: metadata dict + lazy tensor access."""

    def __init__(self, path: str | Path):
        self.path = Path(path)
        self.metadata: dict[str, Any] = {}
        self.tensors: dict[str, GGUFTensorInfo] = {}
        with open(self.path, "rb") as f:
            if f.read(4) != GGUF_MAGIC:
                raise ValueError(f"{path}: not a GGUF file")
            (version,) = struct.unpack("<I", f.read(4))
            if version not in (2, 3):
                raise ValueError(f"{path}: unsupported GGUF version {version}")
            n_tensors, n_kv = struct.unpack("<QQ", f.read(16))
            for _ in range(n_kv):
                key = _read_string(f)
                (vtype,) = struct.unpack("<I", f.read(4))
                self.metadata[key] = _read_value(f, vtype)
            for _ in range(n_tensors):
                name = _read_string(f)
                (n_dims,) = struct.unpack("<I", f.read(4))
                dims = struct.unpack(f"<{n_dims}Q", f.read(8 * n_dims))
                ggml_type, offset = struct.unpack("<IQ", f.read(12))
                self.tensors[name] = GGUFTensorInfo(
                    name, tuple(reversed(dims)), ggml_type, offset
                )
            align = self.metadata.get("general.alignment", ALIGNMENT)
            pos = f.tell()
            self._data_start = (pos + align - 1) // align * align

    # ------------------------------------------------------------- tensor io
    def _raw(self, info: GGUFTensorInfo) -> bytes:
        nbytes = _tensor_nbytes(info)
        with open(self.path, "rb") as f:
            f.seek(self._data_start + info.offset)
            return f.read(nbytes)

    def load_tensor(self, name: str, dtype=np.float32) -> np.ndarray:
        """Read + dequantise one tensor to ``dtype`` in numpy layout."""
        info = self.tensors[name]
        raw = self._raw(info)
        t = info.ggml_type
        if t == GGML_F32:
            arr = np.frombuffer(raw, np.float32)
        elif t == GGML_F16:
            arr = np.frombuffer(raw, np.float16).astype(np.float32)
        elif t == GGML_BF16:
            import ml_dtypes

            arr = np.frombuffer(raw, ml_dtypes.bfloat16).astype(np.float32)
        elif t == GGML_Q8_0:
            arr = _dequant_q8_0(raw, info.n_elements)
        elif t == GGML_Q4_0:
            arr = _dequant_q4_0(raw, info.n_elements)
        elif t == GGML_Q4_K:
            arr = _dequant_q4_k(raw, info.n_elements)
        elif t == GGML_Q5_K:
            arr = _dequant_q5_k(raw, info.n_elements)
        elif t == GGML_Q6_K:
            arr = _dequant_q6_k(raw, info.n_elements)
        else:
            raise NotImplementedError(f"ggml tensor type {t} ({name})")
        return arr.reshape(info.shape).astype(dtype)

    # ------------------------------------------------------------- metadata
    @property
    def architecture(self) -> str:
        return self.metadata.get("general.architecture", "llama")

    def field(self, suffix: str, default=None):
        """Architecture-scoped metadata: field("block_count") →
        metadata["llama.block_count"]."""
        return self.metadata.get(f"{self.architecture}.{suffix}", default)

    def model_config_dict(self) -> dict:
        """HF-config-shaped dict (feeds ModelConfig.from_hf_config)."""
        n_heads = self.field("attention.head_count")
        vocab = self.metadata.get("tokenizer.ggml.tokens")
        return {
            "architectures": ["LlamaForCausalLM"],
            "vocab_size": self.field("vocab_size", len(vocab) if vocab else None),
            "hidden_size": self.field("embedding_length"),
            "intermediate_size": self.field("feed_forward_length"),
            "num_hidden_layers": self.field("block_count"),
            "num_attention_heads": n_heads,
            "num_key_value_heads": self.field("attention.head_count_kv", n_heads),
            "rope_theta": self.field("rope.freq_base", 10000.0),
            "rms_norm_eps": self.field("attention.layer_norm_rms_epsilon", 1e-5),
            "max_position_embeddings": self.field("context_length", 4096),
            "tie_word_embeddings": "output.weight" not in self.tensors,
        }

    # ------------------------------------------------------------- tokenizer
    def tokenizer_vocab(self) -> tuple[str, list[str], list[float]]:
        """(model kind, tokens, scores) from tokenizer.ggml.* metadata."""
        kind = self.metadata.get("tokenizer.ggml.model", "llama")
        tokens = self.metadata.get("tokenizer.ggml.tokens", [])
        scores = self.metadata.get("tokenizer.ggml.scores", [0.0] * len(tokens))
        return kind, tokens, scores

    def build_hf_tokenizer(self):
        """Construct a `tokenizers.Tokenizer` from the embedded vocab
        (gguf_tokenizer.rs parity).  BPE ("gpt2") uses the stored merges;
        SentencePiece ("llama") becomes a Unigram model with byte fallback.
        """
        from tokenizers import Tokenizer, decoders, models, pre_tokenizers

        kind, tokens, scores = self.tokenizer_vocab()
        if not tokens:
            raise ValueError("no tokenizer vocabulary embedded in GGUF")
        if kind == "gpt2":
            vocab = {t: i for i, t in enumerate(tokens)}
            merges = [
                tuple(m.split(" ", 1))
                for m in self.metadata.get("tokenizer.ggml.merges", [])
            ]
            tok = Tokenizer(models.BPE(vocab=vocab, merges=merges))
            tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
            tok.decoder = decoders.ByteLevel()
        else:  # sentencepiece-style
            tok = Tokenizer(
                models.Unigram([(t, float(s)) for t, s in zip(tokens, scores)], 0, True)
            )
            tok.decoder = decoders.Replace("▁", " ")
        return tok

    def eos_token_ids(self) -> list[int]:
        eos = self.metadata.get("tokenizer.ggml.eos_token_id")
        return [int(eos)] if eos is not None else []


def _tensor_nbytes(info: GGUFTensorInfo) -> int:
    n = info.n_elements
    t = info.ggml_type
    if t == GGML_F32:
        return n * 4
    if t in (GGML_F16, GGML_BF16):
        return n * 2
    if t == GGML_Q8_0:
        return n // _Q8_BLOCK * 34  # f16 scale + 32×i8
    if t == GGML_Q4_0:
        return n // _Q4_BLOCK * 18  # f16 scale + 16 nibble bytes
    if t == GGML_Q4_K:
        return n // _QK_K * _Q4K_BYTES
    if t == GGML_Q5_K:
        return n // _QK_K * _Q5K_BYTES
    if t == GGML_Q6_K:
        return n // _QK_K * _Q6K_BYTES
    raise NotImplementedError(f"ggml tensor type {t}")


def _dequant_q8_0(raw: bytes, n: int) -> np.ndarray:
    blocks = n // _Q8_BLOCK
    rec = np.frombuffer(raw, dtype=np.dtype([("d", "<f2"), ("qs", "i1", _Q8_BLOCK)]),
                        count=blocks)
    return (rec["d"].astype(np.float32)[:, None] * rec["qs"].astype(np.float32)).reshape(-1)


def _dequant_q4_0(raw: bytes, n: int) -> np.ndarray:
    blocks = n // _Q4_BLOCK
    rec = np.frombuffer(raw, dtype=np.dtype([("d", "<f2"), ("qs", "u1", 16)]),
                        count=blocks)
    lo = (rec["qs"] & 0x0F).astype(np.int8) - 8
    hi = (rec["qs"] >> 4).astype(np.int8) - 8
    q = np.concatenate([lo, hi], axis=1).astype(np.float32)  # [blocks, 32]
    return (rec["d"].astype(np.float32)[:, None] * q).reshape(-1)


def _k_scale_min(scales: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Unpack the shared K-quant 6-bit (scale, min) encoding: 12 bytes →
    8 sub-block scales + 8 mins per super-block (ggml get_scale_min_k4).
    ``scales`` [B, 12] uint8 → (sc [B, 8], mn [B, 8]) float32."""
    q = scales.astype(np.uint8)
    sc = np.empty(q.shape[:-1] + (8,), np.uint8)
    mn = np.empty_like(sc)
    sc[..., :4] = q[..., 0:4] & 63
    mn[..., :4] = q[..., 4:8] & 63
    sc[..., 4:] = (q[..., 8:12] & 0x0F) | ((q[..., 0:4] >> 6) << 4)
    mn[..., 4:] = (q[..., 8:12] >> 4) | ((q[..., 4:8] >> 6) << 4)
    return sc.astype(np.float32), mn.astype(np.float32)


def _dequant_q4_k(raw: bytes, n: int) -> np.ndarray:
    """block_q4_K: {f16 d, f16 dmin, u8 scales[12], u8 qs[128]} per 256
    values — 8 sub-blocks of 32, value = d·sc·q − dmin·mn, with each
    32-byte qs chunk holding sub-block 2j in low nibbles and 2j+1 in
    high nibbles."""
    blocks = n // _QK_K
    rec = np.frombuffer(raw, dtype=np.dtype(
        [("d", "<f2"), ("dmin", "<f2"), ("scales", "u1", 12),
         ("qs", "u1", 128)]), count=blocks)
    sc, mn = _k_scale_min(rec["scales"])               # [B, 8]
    d = rec["d"].astype(np.float32)[:, None, None]     # [B, 1, 1]
    dmin = rec["dmin"].astype(np.float32)[:, None, None]
    qs = rec["qs"].reshape(blocks, 4, 32)              # 4 chunks of 32B
    lo = (qs & 0x0F).astype(np.float32)                # sub-blocks 0,2,4,6
    hi = (qs >> 4).astype(np.float32)                  # sub-blocks 1,3,5,7
    q = np.stack([lo, hi], axis=2).reshape(blocks, 8, 32)
    out = d * sc[:, :, None] * q - dmin * mn[:, :, None]
    return out.reshape(-1)


def _dequant_q5_k(raw: bytes, n: int) -> np.ndarray:
    """block_q5_K: Q4_K plus qh[32] carrying each value's 5th bit — the
    bit for sub-block j lives at qh bit j (shifting mask per 64-value
    chunk in the scalar code = bit index per sub-block here)."""
    blocks = n // _QK_K
    rec = np.frombuffer(raw, dtype=np.dtype(
        [("d", "<f2"), ("dmin", "<f2"), ("scales", "u1", 12),
         ("qh", "u1", 32), ("qs", "u1", 128)]), count=blocks)
    sc, mn = _k_scale_min(rec["scales"])
    d = rec["d"].astype(np.float32)[:, None, None]
    dmin = rec["dmin"].astype(np.float32)[:, None, None]
    qs = rec["qs"].reshape(blocks, 4, 32)
    lo = (qs & 0x0F).astype(np.float32)
    hi = (qs >> 4).astype(np.float32)
    q = np.stack([lo, hi], axis=2).reshape(blocks, 8, 32)
    qh = rec["qh"]                                     # [B, 32]
    bits = (qh[:, None, :] >> np.arange(8, dtype=np.uint8)[None, :, None]) & 1
    out = d * sc[:, :, None] * (q + bits.astype(np.float32) * 16.0) \
        - dmin * mn[:, :, None]
    return out.reshape(-1)


def _dequant_q6_k(raw: bytes, n: int) -> np.ndarray:
    """block_q6_K: {u8 ql[128], u8 qh[64], i8 scales[16], f16 d} per 256
    values — 16 sub-blocks of 16, q = ((ql nibble) | (qh 2 bits << 4))
    − 32, value = d·scales[sub]·q.  Laid out in two 128-value halves;
    within a half, position l∈[0,32) of quarter k reads ql[l + 32·(k&1)]
    nibble (k<2 low, k≥2 high) and qh[l] bits (2k, 2k+1)."""
    blocks = n // _QK_K
    rec = np.frombuffer(raw, dtype=np.dtype(
        [("ql", "u1", 128), ("qh", "u1", 64), ("scales", "i1", 16),
         ("d", "<f2")]), count=blocks)
    d = rec["d"].astype(np.float32)
    scales = rec["scales"].astype(np.float32)          # [B, 16]
    ql = rec["ql"].reshape(blocks, 2, 2, 32)           # [B, half, lohalf, 32]
    qh = rec["qh"].reshape(blocks, 2, 32)              # [B, half, 32]
    out = np.empty((blocks, 2, 4, 32), np.float32)     # [B, half, quarter, 32]
    for k in range(4):                                 # quarter within a half
        nib = ql[:, :, k & 1]                          # [B, half, 32]
        nib = (nib & 0x0F) if k < 2 else (nib >> 4)
        high = (qh >> (2 * k)) & 3
        out[:, :, k] = (nib | (high << 4)).astype(np.float32) - 32.0
    # scale index: sub-block of 16 → scales[(half·128 + quarter·32 + l)//16]
    idx = (np.arange(_QK_K) // 16).reshape(2, 4, 32)
    out *= scales[:, idx]
    out *= d[:, None, None, None]
    return out.reshape(-1)


# ----------------------------------------------------------- HF weight maps --


def unpermute_qk(w: np.ndarray, n_heads: int) -> np.ndarray:
    """Invert llama.cpp's rope permutation on a [out, in] Q/K weight
    (convert_hf_to_gguf permute: reshape(h, 2, dh/2, in).swapaxes(1, 2))."""
    out, rest = w.shape[0], w.shape[1:]
    dh = out // n_heads
    return (
        w.reshape(n_heads, dh // 2, 2, *rest)
        .swapaxes(1, 2)
        .reshape(w.shape)
    )


def permute_qk(w: np.ndarray, n_heads: int) -> np.ndarray:
    """llama.cpp's converter permutation (used by write_gguf/tests)."""
    out, rest = w.shape[0], w.shape[1:]
    dh = out // n_heads
    return (
        w.reshape(n_heads, 2, dh // 2, *rest)
        .swapaxes(1, 2)
        .reshape(w.shape)
    )


class _GGUFStateDict:
    """Adapts GGUF tensor names to the HF state-dict names our loader
    expects, unpermuting Q/K on the fly."""

    _MAP = {
        "model.embed_tokens.weight": "token_embd.weight",
        "model.norm.weight": "output_norm.weight",
        "lm_head.weight": "output.weight",
    }
    _LAYER_MAP = {
        "input_layernorm.weight": "attn_norm.weight",
        "self_attn.q_proj.weight": "attn_q.weight",
        "self_attn.k_proj.weight": "attn_k.weight",
        "self_attn.v_proj.weight": "attn_v.weight",
        "self_attn.o_proj.weight": "attn_output.weight",
        "post_attention_layernorm.weight": "ffn_norm.weight",
        "mlp.gate_proj.weight": "ffn_gate.weight",
        "mlp.up_proj.weight": "ffn_up.weight",
        "mlp.down_proj.weight": "ffn_down.weight",
    }

    def __init__(self, gf: GGUFFile, n_heads: int, n_kv_heads: int):
        self.gf = gf
        self.n_heads = n_heads
        self.n_kv_heads = n_kv_heads

    def _gguf_name(self, hf_name: str) -> str:
        if hf_name in self._MAP:
            return self._MAP[hf_name]
        if hf_name.startswith("model.layers."):
            _, _, i, rest = hf_name.split(".", 3)
            return f"blk.{i}.{self._LAYER_MAP[rest]}"
        raise KeyError(hf_name)

    def __getitem__(self, hf_name: str) -> np.ndarray:
        arr = self.gf.load_tensor(self._gguf_name(hf_name))
        if "q_proj" in hf_name:
            arr = unpermute_qk(arr, self.n_heads)
        elif "k_proj" in hf_name:
            arr = unpermute_qk(arr, self.n_kv_heads)
        return arr

    def __contains__(self, hf_name: str) -> bool:
        try:
            return self._gguf_name(hf_name) in self.gf.tensors
        except KeyError:
            return False


def load_gguf_model(path: str | Path, dtype: str = "bfloat16"):
    """(ModelConfig, params) straight from a GGUF file — the llama.cpp-model
    entry point the reference routes to an external engine."""
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.models.loader import load_params_from_state_dict

    gf = GGUFFile(path)
    cfg = ModelConfig.from_hf_config(gf.model_config_dict(), dtype=dtype)
    state = _GGUFStateDict(gf, cfg.num_heads, cfg.num_kv_heads)
    params = load_params_from_state_dict(cfg, state)
    return cfg, params


# -------------------------------------------------------------------- write --


def _write_string(f: BinaryIO, s: str) -> None:
    b = s.encode("utf-8")
    f.write(struct.pack("<Q", len(b)) + b)


def _value_type(v: Any) -> int:
    if isinstance(v, bool):
        return T_BOOL
    if isinstance(v, int):
        return T_U32 if 0 <= v < 2**32 else T_I64
    if isinstance(v, float):
        return T_F32
    if isinstance(v, str):
        return T_STRING
    raise TypeError(type(v))


def _write_value(f: BinaryIO, v: Any) -> None:
    if isinstance(v, bool):
        f.write(struct.pack("<B", int(v)))
    elif isinstance(v, int):
        f.write(struct.pack("<I" if 0 <= v < 2**32 else "<q", v))
    elif isinstance(v, float):
        f.write(struct.pack("<f", v))
    elif isinstance(v, str):
        _write_string(f, v)
    else:
        raise TypeError(type(v))


def _quant_q8_0(arr: np.ndarray) -> bytes:
    flat = arr.reshape(-1, _Q8_BLOCK).astype(np.float32)
    d = np.abs(flat).max(axis=1) / 127.0
    d_safe = np.where(d == 0, 1.0, d)
    qs = np.clip(np.round(flat / d_safe[:, None]), -127, 127).astype(np.int8)
    rec = np.zeros(len(flat), dtype=np.dtype([("d", "<f2"), ("qs", "i1", _Q8_BLOCK)]))
    rec["d"] = d.astype(np.float16)
    rec["qs"] = qs
    return rec.tobytes()


def write_gguf(
    path: str | Path,
    metadata: dict[str, Any],
    tensors: dict[str, np.ndarray],
    quantize: Optional[dict[str, int]] = None,
    raw: Optional[dict[str, tuple[int, tuple[int, ...], bytes]]] = None,
) -> None:
    """Minimal GGUF v3 writer (tests + export).  ``quantize`` maps tensor
    name → ggml type (default F32).  ``raw`` carries PRE-QUANTIZED
    tensors verbatim as name → (ggml_type, shape, payload bytes) —
    repacking K-quant tensors this writer cannot produce itself."""
    quantize = quantize or {}
    raw = raw or {}
    overlap = set(tensors) & set(raw)
    if overlap:
        # strict readers (llama.cpp) reject duplicate tensor names —
        # fail at write time, not at someone else's load time
        raise ValueError(f"tensor names in both tensors and raw: {overlap}")
    with open(path, "wb") as f:
        f.write(GGUF_MAGIC)
        f.write(struct.pack("<I", GGUF_VERSION))
        f.write(struct.pack("<QQ", len(tensors) + len(raw), len(metadata)))
        for k, v in metadata.items():
            _write_string(f, k)
            if isinstance(v, list):
                f.write(struct.pack("<I", T_ARRAY))
                etype = _value_type(v[0]) if v else T_U32
                f.write(struct.pack("<IQ", etype, len(v)))
                for item in v:
                    _write_value(f, item)
            else:
                f.write(struct.pack("<I", _value_type(v)))
                _write_value(f, v)

        payloads: list[bytes] = []
        offset = 0

        def emit_info(name: str, shape: tuple[int, ...], t: int,
                      data: bytes) -> None:
            nonlocal offset
            _write_string(f, name)
            dims = tuple(reversed(shape))
            f.write(struct.pack("<I", len(dims)))
            f.write(struct.pack(f"<{len(dims)}Q", *dims))
            f.write(struct.pack("<IQ", t, offset))
            payloads.append(data)
            offset += (len(data) + ALIGNMENT - 1) // ALIGNMENT * ALIGNMENT

        for name, arr in tensors.items():
            t = quantize.get(name, GGML_F32)
            if t == GGML_F32:
                data = np.ascontiguousarray(arr, np.float32).tobytes()
            elif t == GGML_F16:
                data = np.ascontiguousarray(arr, np.float16).tobytes()
            elif t == GGML_Q8_0:
                data = _quant_q8_0(arr)
            else:
                raise NotImplementedError(f"write type {t}")
            emit_info(name, arr.shape, t, data)
        for name, (t, shape, data) in raw.items():
            emit_info(name, tuple(shape), t, bytes(data))

        pos = f.tell()
        f.write(b"\x00" * ((pos + ALIGNMENT - 1) // ALIGNMENT * ALIGNMENT - pos))
        for data in payloads:
            f.write(data)
            pad = (len(data) + ALIGNMENT - 1) // ALIGNMENT * ALIGNMENT - len(data)
            f.write(b"\x00" * pad)
