"""Worker-side publishers: KV cache events + forward-pass metrics.

Reference parity: lib/llm/src/kv_router/publisher.rs (KvEventPublisher:33,
KvMetricsPublisher:76).  Workers publish two things the router needs:

  * **KV events** (`{ns}.kv_events.{worker_id}`): Stored/Removed block
    events, consumed by the router's KvIndexer to keep the global prefix
    index fresh (SURVEY §3.4).
  * **ForwardPassMetrics** (`{ns}.kv_metrics.{worker_id}`): load snapshot
    (active slots, kv blocks, queue depth) scraped into the scheduler's
    cost model — NATS $SRV.STATS parity on the coordinator's pub/sub plane.

Both publishers also accept a native C++ event source
(dynamo_tpu.native.NativeEventQueue — the C-bindings parity surface,
lib/bindings/c/src/lib.rs) and drain it on the same cadence.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import logging
from typing import Callable, Optional

from dynamo_tpu.llm.kv.events import (
    KvCacheEvent,
    KvRemovedEvent,
    KvStoredEvent,
    event_to_wire,
)
from dynamo_tpu.llm.kv_router.scheduler import WorkerMetrics

log = logging.getLogger("dynamo_tpu.kv_router")

__all__ = ["KvEventPublisher", "KvMetricsPublisher", "metrics_subject", "events_subject"]


def events_subject(namespace: str, worker_id: int | str = "") -> str:
    base = f"{namespace}.kv_events"
    return f"{base}.{worker_id}" if worker_id != "" else f"{base}.>"


def metrics_subject(namespace: str, worker_id: int | str = "") -> str:
    base = f"{namespace}.kv_metrics"
    return f"{base}.{worker_id}" if worker_id != "" else f"{base}.>"


class KvEventPublisher:
    """Buffers engine KV events and flushes them to the event plane.

    Hook `publisher.sink` up as the KvBlockManager's ``event_sink``; call
    ``start()`` to flush on a cadence, or ``flush()`` manually (tests).
    Event ids are monotonically increasing per worker so the indexer can
    spot gaps (ref RouterEvent ordering).
    """

    def __init__(
        self,
        coordinator,
        worker_id: int,
        namespace: str = "default",
        flush_interval_s: float = 0.05,
        native_queue=None,  # Optional[dynamo_tpu.native.NativeEventQueue]
    ):
        self.coord = coordinator
        self.worker_id = worker_id
        self.namespace = namespace
        self.flush_interval_s = flush_interval_s
        self.native_queue = native_queue
        self._buf: list[KvCacheEvent] = []
        self._next_event_id = 0
        self._task: Optional[asyncio.Task] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None

    # The engine thread calls this synchronously from the block manager.
    def sink(self, ev: KvCacheEvent) -> None:
        self._buf.append(ev)

    def _drain_native(self) -> None:
        if self.native_queue is None:
            return
        from dynamo_tpu import native as native_mod

        for kind, parent, hashes in self.native_queue.drain():
            if kind == native_mod.EVENT_STORED:
                self._buf.append(
                    KvStoredEvent(block_hashes=hashes, parent_hash=parent or None)
                )
            else:
                self._buf.append(KvRemovedEvent(block_hashes=hashes))

    async def flush(self) -> int:
        """Publish all buffered events; returns how many went out."""
        self._drain_native()
        if not self._buf:
            return 0
        batch, self._buf = self._buf, []
        subject = events_subject(self.namespace, self.worker_id)
        for ev in batch:
            wire = event_to_wire(self._next_event_id, self.worker_id, ev)
            self._next_event_id += 1
            await self.coord.publish(subject, wire)
        return len(batch)

    async def _run(self) -> None:
        while True:
            await asyncio.sleep(self.flush_interval_s)
            try:
                await self.flush()
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("kv event flush failed; retrying")

    def start(self) -> "KvEventPublisher":
        self._task = asyncio.ensure_future(self._run())
        return self

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        await self.flush()


class KvMetricsPublisher:
    """Periodically publishes a worker's ForwardPassMetrics snapshot.

    ``source()`` returns the raw dict (EngineCore.metrics() shape); extra
    identity fields are attached here.  Reference: publisher.rs:76 +
    ForwardPassMetrics (kv_router/protocols.rs:30-47).
    """

    def __init__(
        self,
        coordinator,
        worker_id: int,
        source: Callable[[], dict],
        namespace: str = "default",
        interval_s: float = 1.0,
    ):
        self.coord = coordinator
        self.worker_id = worker_id
        self.source = source
        self.namespace = namespace
        self.interval_s = interval_s
        self._task: Optional[asyncio.Task] = None

    def snapshot(self) -> WorkerMetrics:
        raw = dict(self.source())
        known = {f.name for f in dataclasses.fields(WorkerMetrics)}
        return WorkerMetrics(
            worker_id=self.worker_id,
            **{k: v for k, v in raw.items() if k in known and k != "worker_id"},
        )

    async def publish_once(self) -> None:
        m = self.snapshot()
        payload = dataclasses.asdict(m)
        payload.pop("updated_at", None)
        await self.coord.publish(
            metrics_subject(self.namespace, self.worker_id),
            json.dumps(payload).encode(),
        )

    async def _run(self) -> None:
        while True:
            try:
                await self.publish_once()
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("metrics publish failed; retrying")
            await asyncio.sleep(self.interval_s)

    def start(self) -> "KvMetricsPublisher":
        self._task = asyncio.ensure_future(self._run())
        return self

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
