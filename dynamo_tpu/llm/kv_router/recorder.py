"""JSONL recorder/replayer of router events.

Distributed routing behavior is testable offline: record each worker's KV
events to JSONL, replay them into a fresh indexer, and assert routing
decisions — no cluster needed (reference: lib/llm/src/recorder.rs:38,
kv_router/recorder.rs, replay fixtures in lib/llm/tests/data/replays/).
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import Iterator, Optional, TextIO

from dynamo_tpu.llm.kv.events import KvCacheEvent, event_from_wire, event_to_wire
from dynamo_tpu.llm.kv_router.indexer import KvIndexer

__all__ = ["KvRecorder", "replay_into"]


RECORDING_VERSION = 1


class KvRecorder:
    def __init__(self, path: str | Path):
        self._path = Path(path)
        self._fh: Optional[TextIO] = None
        self._count = 0

    def __enter__(self) -> "KvRecorder":
        self._fh = self._path.open("a")
        return self

    def __exit__(self, *exc) -> None:
        if self._fh:
            self._fh.close()
            self._fh = None

    def record(self, event_id: int, worker_id: int, event: KvCacheEvent) -> None:
        if self._fh is None:
            self._fh = self._path.open("a")
        line = event_to_wire(event_id, worker_id, event)
        line["ts"] = time.time()
        # recordings outlive the process: tag the format so a future
        # replayer can detect old captures (event_from_wire drops both
        # "ts" and "v" as unknown keys on replay) — wirecheck WR004
        line["v"] = RECORDING_VERSION
        self._fh.write(json.dumps(line) + "\n")
        self._fh.flush()
        self._count += 1

    @property
    def count(self) -> int:
        return self._count


def iter_events(path: str | Path) -> Iterator[tuple[int, int, KvCacheEvent]]:
    with Path(path).open() as fh:
        for line in fh:
            line = line.strip()
            if line:
                yield event_from_wire(json.loads(line))


def replay_into(path: str | Path, indexer: KvIndexer) -> int:
    """Feed a recorded JSONL stream into an indexer; returns event count."""
    n = 0
    for event_id, worker_id, ev in iter_events(path):
        indexer.apply_event(worker_id, ev, event_id)
        n += 1
    return n
