"""KvScheduler — pick the decode worker for a request.

Cost model carried over from the reference (kv_router/scheduler.rs:236-330,
DefaultWorkerSelector):

    logit = 2.0 * overlap − kv_usage − normalized_active_slots

where overlap is the prefix-hit fraction of the request's blocks, kv_usage
is the worker's cache occupancy [0,1], and normalized_active_slots its
request-slot occupancy [0,1].  Highest logit wins; ties break randomly.
The selector is pluggable (ref WorkerSelector trait, kv_router.rs:57).
"""

from __future__ import annotations

import random
import statistics
import time
from dataclasses import dataclass, field
from typing import Optional, Protocol

__all__ = ["WorkerMetrics", "KvScheduler", "DefaultWorkerSelector", "KVHitRateEvent"]


@dataclass
class WorkerMetrics:
    """A worker's published load (ref ForwardPassMetrics,
    kv_router/protocols.rs:30-47)."""

    worker_id: int
    request_active_slots: int = 0
    request_total_slots: int = 1
    kv_active_blocks: int = 0
    kv_total_blocks: int = 1
    num_requests_waiting: int = 0
    cache_hit_rate: float = 0.0
    updated_at: float = field(default_factory=time.monotonic)

    @property
    def kv_usage(self) -> float:
        return self.kv_active_blocks / max(self.kv_total_blocks, 1)

    @property
    def slot_usage(self) -> float:
        return self.request_active_slots / max(self.request_total_slots, 1)


@dataclass
class KVHitRateEvent:
    """Emitted per routing decision for the metrics plane
    (ref kv_router/scheduler.rs:31)."""

    worker_id: int
    isl_blocks: int       # request length in blocks
    overlap_blocks: int   # blocks already resident on the chosen worker


class WorkerSelector(Protocol):
    def select(
        self,
        workers: dict[int, WorkerMetrics],
        overlaps: dict[int, int],
        request_blocks: int,
    ) -> Optional[int]: ...


class DefaultWorkerSelector:
    def __init__(self, rng: Optional[random.Random] = None):
        self._rng = rng or random.Random()

    def select(
        self,
        workers: dict[int, WorkerMetrics],
        overlaps: dict[int, int],
        request_blocks: int,
    ) -> Optional[int]:
        if not workers:
            return None
        best_logit = None
        best: list[int] = []
        for wid, m in workers.items():
            overlap = overlaps.get(wid, 0) / max(request_blocks, 1)
            logit = 2.0 * overlap - m.kv_usage - m.slot_usage
            if best_logit is None or logit > best_logit + 1e-9:
                best_logit, best = logit, [wid]
            elif abs(logit - best_logit) <= 1e-9:
                best.append(wid)
        return self._rng.choice(best)


class AllWorkersBusy(Exception):
    """No worker has spare slots (ref scheduler.rs:146-160 waits on capacity)."""


class KvScheduler:
    """Combines worker metrics + overlap scores into routing decisions."""

    def __init__(self, selector: Optional[WorkerSelector] = None, block_size: int = 16,
                 persist_weight: float = 1.0, transfer_weight: float = 0.0):
        self.selector = selector or DefaultWorkerSelector()
        self.block_size = block_size
        # relative worth of a persistent-tier prefix block vs a device-
        # resident one (device term weighs 2.0 in the selector logit):
        # restoring from disk beats re-prefilling but costs a host-side
        # load + scatter, so it scores at persist_weight/2.0 of a warm
        # hit.  0 disables persist-aware routing.
        self.persist_weight = persist_weight
        # NetKV transfer-cost term (logit −= transfer_weight * cost_s per
        # candidate, cost from obs/costs.py via the caller): a decode
        # worker that is cheap to reach over ICI/DCN beats an equally
        # loaded one behind an expensive hop.  0 (default) disables it.
        self.transfer_weight = transfer_weight
        self._workers: dict[int, WorkerMetrics] = {}
        self._suspects: set[int] = set()
        self._hit_events: list[KVHitRateEvent] = []

    # ------------------------------------------------------------ worker set
    def update_worker(self, metrics: WorkerMetrics) -> None:
        self._workers[metrics.worker_id] = metrics

    def remove_worker(self, worker_id: int) -> None:
        self._workers.pop(worker_id, None)
        self._suspects.discard(worker_id)

    def workers(self) -> dict[int, WorkerMetrics]:
        return dict(self._workers)

    # ---------------------------------------------------------- suspect state
    # fed by the fault plane's HealthMonitor (fault/health.py): a suspect
    # worker stops attracting prefix-hit routing seconds before its lease
    # would expire, but is NOT forgotten — a recovered probe restores it.
    def mark_suspect(self, worker_id: int) -> None:
        self._suspects.add(worker_id)

    def clear_suspect(self, worker_id: int) -> None:
        self._suspects.discard(worker_id)

    def suspects(self) -> set[int]:
        return set(self._suspects)

    # -------------------------------------------------------------- schedule
    def _fold_overlaps(self, overlaps: dict[int, int], request_blocks: int,
                       persist_overlaps: Optional[dict[int, int]],
                       transfer_costs_s: Optional[dict[int, float]],
                       ) -> dict[int, float]:
        """Fold persist-tier and transfer-cost terms into effective
        overlap counts so the WorkerSelector protocol (and custom
        selectors) stays unchanged.

        Persistent-tier matches enter as a DISCOUNTED overlap term: only
        the blocks persist offers beyond the device prefix count, scaled
        so the selector's 2.0*overlap weight nets out to persist_weight
        per persist block.  Transfer costs are scaled so the selector's
        2.0/request_blocks overlap normalization nets out to a logit
        delta of −transfer_weight * cost_s per candidate (llm/kv/
        stream.py choose_handoff_path supplies the per-worker predicted
        seconds)."""
        eff: dict[int, float] = dict(overlaps)
        if persist_overlaps and self.persist_weight > 0:
            for w, p in persist_overlaps.items():
                extra = p - overlaps.get(w, 0)
                if extra > 0:
                    eff[w] = (overlaps.get(w, 0)
                              + (self.persist_weight / 2.0) * extra)
        if transfer_costs_s and self.transfer_weight > 0:
            for w, cost in transfer_costs_s.items():
                if cost > 0:
                    eff[w] = (eff.get(w, 0)
                              - (self.transfer_weight / 2.0) * cost
                              * request_blocks)
        return eff

    def score_candidates(self, overlaps: dict[int, int], request_tokens: int,
                         persist_overlaps: Optional[dict[int, int]] = None,
                         transfer_costs_s: Optional[dict[int, float]] = None,
                         ) -> list[tuple[int, float, dict]]:
        """Pure scoring seam: every non-suspect worker's logit with the
        terms itemized, best first (ties broken by worker id — no RNG,
        no state mutation, no hit events).

        Returns ``[(worker_id, logit, breakdown)]`` where ``breakdown``
        holds the additive terms {overlap, persist, transfer, kv_usage,
        slot_usage} and ``logit == sum(breakdown.values())``, matching
        the DefaultWorkerSelector cost model over folded overlaps
        exactly.  The load plane asserts router-decision quality per
        scenario on this surface, and a future global scheduler
        (ROADMAP item 4) inherits it as its explainability contract."""
        request_blocks = max(1, request_tokens // self.block_size)
        scored: list[tuple[int, float, dict]] = []
        for wid, m in self._workers.items():
            if wid in self._suspects:
                continue
            overlap_term = 2.0 * overlaps.get(wid, 0) / request_blocks
            persist_term = 0.0
            if persist_overlaps and self.persist_weight > 0:
                extra = persist_overlaps.get(wid, 0) - overlaps.get(wid, 0)
                if extra > 0:
                    persist_term = (self.persist_weight * extra
                                    / request_blocks)
            transfer_term = 0.0
            if transfer_costs_s and self.transfer_weight > 0:
                cost = transfer_costs_s.get(wid, 0.0)
                if cost > 0:
                    transfer_term = -self.transfer_weight * cost
            breakdown = {
                "overlap": overlap_term,
                "persist": persist_term,
                "transfer": transfer_term,
                "kv_usage": -m.kv_usage,
                "slot_usage": -m.slot_usage,
            }
            scored.append((wid, sum(breakdown.values()), breakdown))
        scored.sort(key=lambda t: (-t[1], t[0]))
        return scored

    def schedule(self, overlaps: dict[int, int], request_tokens: int,
                 persist_overlaps: Optional[dict[int, int]] = None,
                 transfer_costs_s: Optional[dict[int, float]] = None) -> int:
        request_blocks = max(1, request_tokens // self.block_size)
        candidates = {w: m for w, m in self._workers.items()
                      if w not in self._suspects}
        device_overlaps = overlaps
        overlaps = self._fold_overlaps(overlaps, request_blocks,
                                       persist_overlaps, transfer_costs_s)
        # every worker suspect = probes failing cluster-wide (or the probe
        # plane itself broke): routing somewhere beats routing nowhere
        wid = self.selector.select(candidates or self._workers, overlaps,
                                   request_blocks)
        if wid is None:
            raise AllWorkersBusy("no live workers")
        self._hit_events.append(
            KVHitRateEvent(wid, request_blocks, device_overlaps.get(wid, 0))
        )
        # optimistic local update so burst arrivals spread before the next
        # metrics scrape lands
        m = self._workers.get(wid)
        if m is not None:
            m.request_active_slots += 1
        return wid

    def drain_hit_events(self) -> list[KVHitRateEvent]:
        out, self._hit_events = self._hit_events, []
        return out

    # --------------------------------------------------------------- summary
    def load_summary(self) -> dict:
        """load avg/std across workers (ref scoring.rs:22-52 ProcessedEndpoints)."""
        if not self._workers:
            return {"load_avg": 0.0, "load_std": 0.0, "workers": 0}
        loads = [m.request_active_slots for m in self._workers.values()]
        return {
            "load_avg": statistics.fmean(loads),
            "load_std": statistics.pstdev(loads) if len(loads) > 1 else 0.0,
            "workers": len(loads),
        }
