"""KvRouter — the composed smart router.

Given a tokenized request, hash its blocks, look up prefix overlap per
worker in the indexer, and let the scheduler pick a worker.  Exposed both
as a plain `schedule()` call and as an AsyncEngine that emits the decision
(reference kv_router.rs:66-169 wraps it the same way so it can serve a
`generate` endpoint; components/router/src/main.rs).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import AsyncIterator, Optional, Sequence

from dynamo_tpu.llm.kv_router.indexer import KvIndexer
from dynamo_tpu.llm.kv_router.scheduler import KvScheduler, WorkerSelector
from dynamo_tpu.runtime.engine import AsyncEngine, Context
from dynamo_tpu.tokens import sequence_hashes

__all__ = ["KvRouter", "RoutingDecision"]


@dataclass
class RoutingDecision:
    worker_id: int
    overlap_blocks: int     # prefix blocks already on that worker
    overlap_tokens: int


class KvRouter(AsyncEngine):
    def __init__(
        self,
        block_size: int = 16,
        selector: Optional[WorkerSelector] = None,
        indexer: Optional[KvIndexer] = None,
        scheduler: Optional[KvScheduler] = None,
    ):
        self.block_size = block_size
        self.indexer = indexer or KvIndexer()
        self.scheduler = scheduler or KvScheduler(selector, block_size=block_size)

    def schedule(self, token_ids: Sequence[int]) -> RoutingDecision:
        hashes = sequence_hashes(token_ids, self.block_size)
        overlaps = self.indexer.find_matches(hashes).scores
        wid = self.scheduler.schedule(overlaps, len(token_ids))
        blocks = overlaps.get(wid, 0)
        return RoutingDecision(
            worker_id=wid, overlap_blocks=blocks, overlap_tokens=blocks * self.block_size
        )

    def remove_worker(self, worker_id: int) -> None:
        self.indexer.remove_worker(worker_id)
        self.scheduler.remove_worker(worker_id)

    # AsyncEngine surface: request payload = token id list → single decision
    def generate(self, request: Context) -> AsyncIterator[RoutingDecision]:
        return self._run(request)

    async def _run(self, request: Context) -> AsyncIterator[RoutingDecision]:
        token_ids = request.data
        if hasattr(token_ids, "token_ids"):  # BackendInput passthrough
            token_ids = token_ids.token_ids
        yield self.schedule(token_ids)
