"""KvRouter — the composed smart router.

Given a tokenized request, hash its blocks, look up prefix overlap per
worker in the indexer, and let the scheduler pick a worker.  Exposed both
as a plain `schedule()` call and as an AsyncEngine that emits the decision
(reference kv_router.rs:66-169 wraps it the same way so it can serve a
`generate` endpoint; components/router/src/main.rs).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import AsyncIterator, Optional, Sequence

from dynamo_tpu.llm.kv_router.indexer import KvIndexer
from dynamo_tpu.llm.kv_router.scheduler import KvScheduler, WorkerSelector
from dynamo_tpu.runtime.engine import AsyncEngine, Context
from dynamo_tpu.tokens import sequence_hashes

__all__ = ["KvRouter", "RoutingDecision"]


@dataclass
class RoutingDecision:
    worker_id: int
    overlap_blocks: int     # prefix blocks already on that worker (device)
    overlap_tokens: int
    persist_blocks: int = 0  # prefix blocks restorable from its persist tier


class KvRouter(AsyncEngine):
    def __init__(
        self,
        block_size: int = 16,
        selector: Optional[WorkerSelector] = None,
        indexer: Optional[KvIndexer] = None,
        scheduler: Optional[KvScheduler] = None,
    ):
        self.block_size = block_size
        self.indexer = indexer or KvIndexer()
        self.scheduler = scheduler or KvScheduler(selector, block_size=block_size)

    def schedule(self, token_ids: Sequence[int]) -> RoutingDecision:
        hashes = sequence_hashes(token_ids, self.block_size)
        match = self.indexer.find_matches(hashes)
        overlaps = match.scores
        wid = self.scheduler.schedule(overlaps, len(token_ids),
                                      persist_overlaps=match.persist_scores)
        blocks = overlaps.get(wid, 0)
        return RoutingDecision(
            worker_id=wid, overlap_blocks=blocks,
            overlap_tokens=blocks * self.block_size,
            persist_blocks=match.persist_scores.get(wid, 0),
        )

    def remove_worker(self, worker_id: int) -> None:
        self.indexer.remove_worker(worker_id)
        self.scheduler.remove_worker(worker_id)

    # AsyncEngine surface (what the standalone router service serves over
    # dyn://{ns}.router.generate): payload = token id list, a BackendInput,
    # or a {token_ids} dict → ONE wire-serializable decision dict.
    # {"worker_id": None} = no live workers; caller falls back to its own
    # load balancing.  In-process callers wanting the dataclass use
    # schedule() directly.
    def generate(self, request: Context) -> AsyncIterator[dict]:
        return self._run(request)

    async def _run(self, request: Context) -> AsyncIterator[dict]:
        from dynamo_tpu.llm.kv_router.scheduler import AllWorkersBusy

        token_ids = request.data
        if hasattr(token_ids, "token_ids"):  # BackendInput passthrough
            token_ids = token_ids.token_ids
        elif isinstance(token_ids, dict):
            token_ids = token_ids["token_ids"]
        try:
            d = self.schedule(token_ids)
        except AllWorkersBusy:
            yield {"worker_id": None}
            return
        yield {
            "worker_id": d.worker_id,
            "overlap_blocks": d.overlap_blocks,
            "overlap_tokens": d.overlap_tokens,
        }
