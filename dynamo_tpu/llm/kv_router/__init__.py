"""KV-cache-aware smart routing (reference lib/llm/src/kv_router/).

Workers publish cache events (stored/removed block hashes) and load metrics;
the router keeps a global index of which worker holds which prefix blocks
and scores workers by overlap vs load for each incoming request.
"""

from dynamo_tpu.llm.kv_router.indexer import KvIndexer, OverlapScores
from dynamo_tpu.llm.kv_router.scheduler import (
    DefaultWorkerSelector,
    KvScheduler,
    WorkerMetrics,
)
from dynamo_tpu.llm.kv_router.router import KvRouter

__all__ = [
    "KvIndexer",
    "OverlapScores",
    "KvScheduler",
    "DefaultWorkerSelector",
    "WorkerMetrics",
    "KvRouter",
]
