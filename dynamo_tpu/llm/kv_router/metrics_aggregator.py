"""Router-side aggregation: worker metrics + KV events off the event plane.

Reference parity: lib/llm/src/kv_router/metrics_aggregator.rs:26-82
(KvMetricsAggregator / collect_endpoints_task) and the KvRouter event
subscription loop (kv_router.rs:97-118 → indexer apply_event).

`KvRouterSubscriber` is the one-call wiring that makes a KvRouter live on a
coordinator: it subscribes to kv_events (feeding the indexer), kv_metrics
(feeding the scheduler's cost model), and prunes workers whose metrics went
stale (lease-expiry analogue for the metrics plane).
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from typing import Callable, Optional

from dynamo_tpu.llm.kv.events import event_from_wire
from dynamo_tpu.llm.kv_router.publisher import events_subject, metrics_subject
from dynamo_tpu.llm.kv_router.router import KvRouter
from dynamo_tpu.llm.kv_router.scheduler import KvScheduler, WorkerMetrics

log = logging.getLogger("dynamo_tpu.kv_router")

__all__ = ["KvMetricsAggregator", "KvRouterSubscriber"]


class KvMetricsAggregator:
    """Collects per-worker ForwardPassMetrics into a scheduler."""

    def __init__(
        self,
        coordinator,
        scheduler: KvScheduler,
        namespace: str = "default",
        stale_after_s: float = 10.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.coord = coordinator
        self.scheduler = scheduler
        self.namespace = namespace
        self.stale_after_s = stale_after_s
        # injectable clock: staleness reaping runs at DetLoop virtual
        # time under the load plane's macro-simulation
        self._clock = clock
        self._sub_id: Optional[int] = None
        self._reaper: Optional[asyncio.Task] = None

    def _on_metrics(self, subject: str, payload: bytes) -> None:
        try:
            d = json.loads(payload)
            m = WorkerMetrics(**d)
            m.updated_at = self._clock()   # receipt time, aggregator clock
            self.scheduler.update_worker(m)
        except Exception:
            log.exception("bad metrics payload on %s", subject)

    async def _reap_stale(self) -> None:
        while True:
            await asyncio.sleep(self.stale_after_s / 2)
            now = self._clock()
            for wid, m in list(self.scheduler.workers().items()):
                if now - m.updated_at > self.stale_after_s:
                    log.warning("worker %s metrics stale; dropping from scheduler", wid)
                    self.scheduler.remove_worker(wid)

    async def start(self) -> "KvMetricsAggregator":
        self._sub_id = await self.coord.subscribe(
            metrics_subject(self.namespace), self._on_metrics
        )
        self._reaper = asyncio.ensure_future(self._reap_stale())
        return self

    async def stop(self) -> None:
        if self._reaper:
            self._reaper.cancel()
            try:
                await self._reaper
            except asyncio.CancelledError:
                pass
            self._reaper = None
        if self._sub_id is not None:
            await self.coord.unsubscribe(self._sub_id)
            self._sub_id = None


class KvRouterSubscriber:
    """Makes a KvRouter live: events → indexer, metrics → scheduler,
    hit-rate decisions → `{ns}.kv_hit_rate` for the metrics component,
    and (``workers_prefix``) discovery deletes → worker teardown, so a
    dead worker stops attracting prefix-hit routing the moment its lease
    expires instead of lingering until its metrics go stale."""

    def __init__(
        self,
        router: KvRouter,
        coordinator,
        namespace: str = "default",
        hit_rate_flush_s: float = 1.0,
        workers_prefix: Optional[str] = None,
    ):
        self.router = router
        self.coord = coordinator
        self.namespace = namespace
        self.hit_rate_flush_s = hit_rate_flush_s
        self.workers_prefix = workers_prefix
        self.aggregator = KvMetricsAggregator(coordinator, router.scheduler, namespace)
        self._ev_sub: Optional[int] = None
        self._watch_id: Optional[int] = None
        self._hit_task: Optional[asyncio.Task] = None

    def _on_discovery(self, event: str, key: str, value) -> None:
        if event != "delete":
            return
        try:
            wid = int(key.rsplit("/", 1)[-1], 16)
        except ValueError:
            return
        log.info("worker %x left discovery; removing from router", wid)
        self.router.remove_worker(wid)

    def _on_event(self, subject: str, payload: bytes) -> None:
        try:
            event_id, worker_id, ev = event_from_wire(json.loads(payload))
            self.router.indexer.apply_event(worker_id, ev, event_id=event_id)
        except Exception:
            log.exception("bad kv event on %s", subject)

    async def _flush_hit_events(self) -> None:
        while True:
            await asyncio.sleep(self.hit_rate_flush_s)
            try:
                for ev in self.router.scheduler.drain_hit_events():
                    await self.coord.publish(
                        f"{self.namespace}.kv_hit_rate",
                        json.dumps(
                            {
                                "worker_id": ev.worker_id,
                                "isl_blocks": ev.isl_blocks,
                                "overlap_blocks": ev.overlap_blocks,
                            }
                        ).encode(),
                    )
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("hit-rate flush failed; retrying")

    async def start(self) -> "KvRouterSubscriber":
        self._ev_sub = await self.coord.subscribe(
            events_subject(self.namespace), self._on_event
        )
        if self.workers_prefix:
            self._watch_id, _ = await self.coord.watch(
                self.workers_prefix, self._on_discovery
            )
        await self.aggregator.start()
        self._hit_task = asyncio.ensure_future(self._flush_hit_events())
        return self

    async def stop(self) -> None:
        if self._watch_id is not None:
            try:
                await self.coord.unwatch(self._watch_id)
            except (ConnectionError, RuntimeError):
                pass
            self._watch_id = None
        if self._hit_task:
            self._hit_task.cancel()
            try:
                await self._hit_task
            except asyncio.CancelledError:
                pass
            self._hit_task = None
        await self.aggregator.stop()
        if self._ev_sub is not None:
            await self.coord.unsubscribe(self._ev_sub)
            self._ev_sub = None
