"""KvIndexer — the global prefix index: block hash → workers holding it.

Reference parity: lib/llm/src/kv_router/indexer.rs:187-499 (RadixTree,
find_matches, apply_event, KvIndexer).  The reference builds an explicit
radix tree; here the chained sequence hashes (dynamo_tpu.tokens) make the
trie redundant — a block hash already commits to its entire prefix, so a
flat hash→workers map gives identical match semantics with O(1) lookups,
plus per-worker hash sets for O(worker's blocks) teardown on failure.

Like the reference (indexer.rs:36 doc), the index is single-writer: apply
events from one task/thread; find_matches is read-only.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Sequence

from dynamo_tpu.llm.kv.events import (
    TIER_DEVICE,
    TIER_PERSIST,
    KvCacheEvent,
    KvRemovedEvent,
    KvStoredEvent,
)

log = logging.getLogger("dynamo_tpu.kv_router")

__all__ = ["KvIndexer", "OverlapScores"]


@dataclass
class OverlapScores:
    """worker_id → number of consecutive prefix blocks resident there
    (ref indexer.rs OverlapScores).  ``persist_scores`` is the same
    longest-prefix walk over each worker's PERSISTENT tier (llm/kv/
    persist.py): blocks a worker can restore host-side before prefill
    rather than already holding in HBM — the scheduler scores them at a
    discount."""

    scores: dict[int, int] = field(default_factory=dict)
    persist_scores: dict[int, int] = field(default_factory=dict)

    def best(self) -> tuple[int, int] | None:
        if not self.scores:
            return None
        wid = max(self.scores, key=lambda w: self.scores[w])
        return wid, self.scores[wid]


class KvIndexer:
    def __init__(self, use_native: bool | None = None) -> None:
        # Prefer the C++ index (native/src/kv_index.cpp) — same semantics,
        # O(1) probes without Python set churn on the per-request hot path.
        self._native = None
        if use_native is not False:
            try:
                from dynamo_tpu import native

                if native.available():
                    self._native = native.NativeKvIndex()
                elif use_native:
                    raise RuntimeError("native KV index requested but unavailable")
            except ImportError:  # toolchain absent → pure-Python fallback
                if use_native:
                    raise
        # block sequence-hash → set of worker ids holding it
        self._holders: dict[int, set[int]] = {}
        # worker id → hashes it holds (for teardown)
        self._worker_blocks: dict[int, set[int]] = {}
        # persistent tier (tier="persist" events) — always Python-side:
        # the native index only models the device tier
        self._persist_holders: dict[int, set[int]] = {}
        self._persist_worker_blocks: dict[int, set[int]] = {}
        # per-worker last event id (gap/ordering diagnostics)
        self._last_event_id: dict[int, int] = {}

    @property
    def is_native(self) -> bool:
        return self._native is not None

    # ---------------------------------------------------------------- queries
    def find_matches(self, seq_hashes: Sequence[int]) -> OverlapScores:
        """Longest-prefix match per worker over the request's block hashes."""
        persist = self._persist_matches(seq_hashes)
        if self._native is not None:
            return OverlapScores(self._native.find_matches(seq_hashes),
                                 persist)
        scores: dict[int, int] = {}
        live: set[int] | None = None  # workers matching every block so far
        for i, h in enumerate(seq_hashes):
            holders = self._holders.get(h)
            if not holders:
                break
            live = set(holders) if live is None else (live & holders)
            if not live:
                break
            for w in live:  # workers that dropped out keep their shorter score
                scores[w] = i + 1
        return OverlapScores(scores, persist)

    def _persist_matches(self, seq_hashes: Sequence[int]) -> dict[int, int]:
        """Longest prefix per worker over the persistent tier alone —
        what each worker could restore host-side starting from a cold
        device cache.  Conservative: the walk starts at the sequence
        root, so persist blocks that merely CONTINUE a device-resident
        prefix (device holds 0..k, persist holds k+1..) score 0 here;
        the scheduler only adds the persist term where it EXCEEDS the
        device score, so undercounting can never double-pay."""
        if not self._persist_holders:
            return {}
        scores: dict[int, int] = {}
        live: set[int] | None = None
        for i, h in enumerate(seq_hashes):
            holders = self._persist_holders.get(h)
            if not holders:
                break
            live = set(holders) if live is None else (live & holders)
            if not live:
                break
            for w in live:
                scores[w] = i + 1
        return scores

    # Per-position probes for the sharded control plane (shards/): a
    # gather walk asks the shard owning position i for exactly that
    # hash's holder set instead of running a full find_matches.  Python
    # path only — shard replicas are built with use_native=False, and
    # the native index exposes no single-hash probe.
    def holders_of(self, h: int) -> frozenset[int]:
        """Device-tier workers holding block hash ``h``."""
        if self._native is not None:
            raise RuntimeError("holders_of: native index has no probe path")
        return frozenset(self._holders.get(h, ()))

    def persist_holders_of(self, h: int) -> frozenset[int]:
        """Persist-tier workers holding block hash ``h``."""
        return frozenset(self._persist_holders.get(h, ()))

    @property
    def resident_keys(self) -> int:
        """Distinct block hashes resident across both tiers — the
        /metrics per-shard gauge (persist keys that also exist on device
        count once per tier; the gauge tracks index memory, not bytes)."""
        return self.num_blocks + len(self._persist_holders)

    @property
    def num_blocks(self) -> int:
        if self._native is not None:
            return self._native.num_blocks
        return len(self._holders)

    def workers(self) -> list[int]:
        return sorted(self._worker_blocks)

    # ----------------------------------------------------------------- events
    def apply_event(self, worker_id: int, event: KvCacheEvent, event_id: int | None = None) -> None:
        if event_id is not None:
            last = self._last_event_id.get(worker_id)
            if last is not None and event_id != last + 1:
                log.debug(
                    "worker %s event id gap: %s -> %s", worker_id, last, event_id
                )
            self._last_event_id[worker_id] = event_id

        if getattr(event, "tier", TIER_DEVICE) == TIER_PERSIST:
            # persist-tier events bypass the native index (device-only)
            if isinstance(event, KvStoredEvent):
                blocks = self._persist_worker_blocks.setdefault(worker_id, set())
                for h in event.block_hashes:
                    self._persist_holders.setdefault(h, set()).add(worker_id)
                    blocks.add(h)
            elif isinstance(event, KvRemovedEvent):
                blocks = self._persist_worker_blocks.get(worker_id, set())
                for h in event.block_hashes:
                    holders = self._persist_holders.get(h)
                    if holders:
                        holders.discard(worker_id)
                        if not holders:
                            del self._persist_holders[h]
                    blocks.discard(h)
            return

        if self._native is not None:
            if isinstance(event, KvStoredEvent):
                # workers() listing tracks Stored only (matches Python path)
                self._worker_blocks.setdefault(worker_id, set())
                self._native.store(worker_id, event.block_hashes)
            elif isinstance(event, KvRemovedEvent):
                self._native.remove(worker_id, event.block_hashes)
            return

        if isinstance(event, KvStoredEvent):
            blocks = self._worker_blocks.setdefault(worker_id, set())
            for h in event.block_hashes:
                self._holders.setdefault(h, set()).add(worker_id)
                blocks.add(h)
        elif isinstance(event, KvRemovedEvent):
            blocks = self._worker_blocks.get(worker_id, set())
            for h in event.block_hashes:
                holders = self._holders.get(h)
                if holders:
                    holders.discard(worker_id)
                    if not holders:
                        del self._holders[h]
                blocks.discard(h)

    def remove_worker(self, worker_id: int) -> None:
        """Worker died/left: drop all its blocks (ref: client watcher delete
        path, component/client.rs:145-154 → router stops picking it)."""
        for h in self._persist_worker_blocks.pop(worker_id, set()):
            holders = self._persist_holders.get(h)
            if holders:
                holders.discard(worker_id)
                if not holders:
                    del self._persist_holders[h]
        if self._native is not None:
            self._native.remove_worker(worker_id)
            self._worker_blocks.pop(worker_id, None)
            self._last_event_id.pop(worker_id, None)
            return
        for h in self._worker_blocks.pop(worker_id, set()):
            holders = self._holders.get(h)
            if holders:
                holders.discard(worker_id)
                if not holders:
                    del self._holders[h]
        self._last_event_id.pop(worker_id, None)

    def clear(self) -> None:
        if self._native is not None:
            self._native.clear()
        self._holders.clear()
        self._worker_blocks.clear()
        self._persist_holders.clear()
        self._persist_worker_blocks.clear()
        self._last_event_id.clear()
