"""ShardedKvIndexer — the prefix index split across N real KvIndexers.

Each shard is a plain `KvIndexer` (Python path — the native index has no
per-hash probe) fed only its key range: `apply_event` splits every
worker KV event with `partition.split_event` and forwards each piece to
its owning shard, so a replica process hosting one shard sees exactly
the event stream it would receive from a range-filtered subscription.

`find_matches` keeps the singleton signature by running a complete
in-process scatter-gather (shards/scatter.py `probe_shard` +
`gather_overlaps`), which makes it the reference answer the degraded
network path is tested against — equivalence with a singleton
`KvIndexer` fed the same events is pinned by tests/test_kv_router_shards.
"""

from __future__ import annotations

import time
from typing import Sequence

from dynamo_tpu.engine.counters import kv_shard_counters
from dynamo_tpu.llm.kv.events import KvCacheEvent
from dynamo_tpu.llm.kv_router.indexer import KvIndexer, OverlapScores
from dynamo_tpu.llm.kv_router.shards.partition import split_event
from dynamo_tpu.llm.kv_router.shards.scatter import gather_overlaps, probe_shard

__all__ = ["ShardedKvIndexer"]


class ShardedKvIndexer:
    def __init__(self, n_shards: int, generation: int = 0) -> None:
        if n_shards < 1:
            raise ValueError("n_shards must be >= 1")
        self.n_shards = n_shards
        self.generation = generation
        self._shards = [KvIndexer(use_native=False) for _ in range(n_shards)]
        # gap diagnostics live here: sub-events reach shards without ids
        self._last_event_id: dict[int, int] = {}

    def shard(self, shard_id: int) -> KvIndexer:
        return self._shards[shard_id]

    # ----------------------------------------------------------------- events
    def apply_event(self, worker_id: int, event: KvCacheEvent,
                    event_id: int | None = None) -> None:
        if event_id is not None:
            self._last_event_id[worker_id] = event_id
        for shard_id, sub in split_event(event, self.n_shards).items():
            self._shards[shard_id].apply_event(worker_id, sub)

    def remove_worker(self, worker_id: int) -> None:
        for s in self._shards:
            s.remove_worker(worker_id)
        self._last_event_id.pop(worker_id, None)

    def clear(self) -> None:
        for s in self._shards:
            s.clear()
        self._last_event_id.clear()

    # ---------------------------------------------------------------- queries
    def find_matches(self, seq_hashes: Sequence[int]) -> OverlapScores:
        t0 = time.perf_counter()
        replies = {
            s: probe_shard(self._shards[s], s, self.n_shards, seq_hashes,
                           self.generation)
            for s in range(self.n_shards)
        }
        scores, _ = gather_overlaps(seq_hashes, self.n_shards, replies,
                                    self.generation)
        kv_shard_counters.record_scatter(
            (time.perf_counter() - t0) * 1e3, fan_out=self.n_shards)
        return scores

    def workers(self) -> list[int]:
        out: set[int] = set()
        for s in self._shards:
            out.update(s.workers())
        return sorted(out)

    @property
    def num_blocks(self) -> int:
        return sum(s.num_blocks for s in self._shards)

    @property
    def resident_keys(self) -> int:
        return sum(s.resident_keys for s in self._shards)

    def shard_sizes(self) -> list[tuple[int, int]]:
        """Per-shard (device blocks, resident keys) — the /metrics
        gauges; also pushes them into the process-global counters so a
        scrape needs no reference to this object."""
        sizes = [(s.num_blocks, s.resident_keys) for s in self._shards]
        for shard_id, (blocks, keys) in enumerate(sizes):
            kv_shard_counters.set_shard_size(shard_id, blocks, keys)
        return sizes

    # --------------------------------------------------------------- handoff
    def export_shard(self, shard_id: int) -> tuple[dict[int, list[int]],
                                                   dict[int, list[int]]]:
        """Snapshot one shard's (device, persist) holder maps for an
        index handoff, in wire shape: hash -> sorted worker ids."""
        src = self._shards[shard_id]
        device = {h: sorted(src.holders_of(h))
                  for h in sorted(src._holders)}
        persist = {h: sorted(src.persist_holders_of(h))
                   for h in sorted(src._persist_holders)}
        return device, persist

    def import_shard(self, shard_id: int, device: dict[int, list[int]],
                     persist: dict[int, list[int]]) -> None:
        """Install a handed-off shard snapshot, replacing the local
        range.  The caller is responsible for the generation fence —
        an import only happens after the membership change that bumped
        it (lifecycle.py)."""
        from dynamo_tpu.llm.kv.events import (  # local: avoid cycle at import
            TIER_PERSIST,
            KvStoredEvent,
        )
        fresh = KvIndexer(use_native=False)
        by_worker: dict[int, list[int]] = {}
        for h, wids in device.items():
            for w in wids:
                by_worker.setdefault(w, []).append(h)
        for w, hashes in sorted(by_worker.items()):
            fresh.apply_event(w, KvStoredEvent(block_hashes=sorted(hashes)))
        by_worker.clear()
        for h, wids in persist.items():
            for w in wids:
                by_worker.setdefault(w, []).append(h)
        for w, hashes in sorted(by_worker.items()):
            fresh.apply_event(
                w, KvStoredEvent(block_hashes=sorted(hashes),
                                 tier=TIER_PERSIST))
        self._shards[shard_id] = fresh
