"""Sharded control plane: hash-partitioned prefix index + scatter-gather.

Scales the router out of its singleton box (ROADMAP item 1): the prefix
index is partitioned by hash prefix of the chained block keys across N
replicas (`ShardedKvIndexer`), overlap scoring scatters to the owning
shards and merges with bounded deadlines (`ScatterGatherScheduler` —
a missing shard degrades scores, never blocks placement), and replica
membership/handoff rides the existing discovery-delete idiom with a
generation fence (`lifecycle.ShardReplica`).  See docs/router_sharding.md.
"""

from dynamo_tpu.llm.kv_router.shards.indexer import ShardedKvIndexer
from dynamo_tpu.llm.kv_router.shards.lifecycle import (
    PubSubShardClient,
    ShardReplica,
)
from dynamo_tpu.llm.kv_router.shards.partition import (
    ShardMap,
    membership_generation,
    shard_of,
    split_event,
    split_hashes,
)
from dynamo_tpu.llm.kv_router.shards.scatter import (
    LocalShardClient,
    ScatterGatherScheduler,
    ShardReply,
    gather_overlaps,
    probe_shard,
)

__all__ = [
    "ShardedKvIndexer",
    "ScatterGatherScheduler",
    "ShardReplica",
    "PubSubShardClient",
    "LocalShardClient",
    "ShardMap",
    "ShardReply",
    "shard_of",
    "split_event",
    "split_hashes",
    "membership_generation",
    "gather_overlaps",
    "probe_shard",
]
