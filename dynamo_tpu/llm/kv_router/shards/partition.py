"""Key partitioning for the sharded prefix index.

The unit of ownership is a single chained-xxh3 block key (dynamo_tpu.
tokens.sequence_hashes).  Because a block hash already commits to its
entire prefix, any position of any sequence can be scored by whichever
shard holds that one key — there is no tree to co-locate.  The partition
function takes the top 16 bits of the 64-bit key ("hash prefix", mirrors
the flat-map-as-radix-tree argument in kv_router/indexer.py) modulo the
shard count, so consecutive blocks of one sequence spray across shards
and no shard inherits a hot tenant's whole prefix.

Shards are a fixed keyspace partition; *replicas* are processes that own
shards.  `ShardMap` binds the two under a generation number derived from
the membership itself (`membership_generation`): every observer of the
same live replica set computes the same generation with no leader and no
shared counter, and scatter replies carrying a different generation are
rejected by the gather merge (shards/scatter.py) — the fence that keeps
a replica which missed a membership change from serving a range it no
longer owns.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from dynamo_tpu.llm.kv.events import (
    KvCacheEvent,
    KvRemovedEvent,
    KvStoredEvent,
)
from dynamo_tpu.tokens import compute_hash
from dynamo_tpu.utils.chash import HashRing

__all__ = ["shard_of", "split_hashes", "split_event", "ShardMap",
           "membership_generation"]

# bits of hash prefix the partition keys on; 16 bits ≫ any plausible
# shard count, so ownership is stable under modulo for small N
SHARD_PREFIX_BITS = 16


def shard_of(block_hash: int, n_shards: int) -> int:
    """Owning shard of one chained block key."""
    if n_shards <= 1:
        return 0
    return ((block_hash & 0xFFFFFFFFFFFFFFFF) >> (64 - SHARD_PREFIX_BITS)) % n_shards


def split_hashes(block_hashes, n_shards: int) -> dict[int, list[int]]:
    """Group block keys by owning shard, preserving order within each."""
    out: dict[int, list[int]] = {}
    for h in block_hashes:
        out.setdefault(shard_of(h, n_shards), []).append(h)
    return out


def split_event(event: KvCacheEvent, n_shards: int) -> dict[int, KvCacheEvent]:
    """Split one worker KV event into per-shard sub-events covering only
    each shard's keys.  Parent hashes are dropped: the flat index never
    reads them, and a sub-event's first block's parent usually lives on
    another shard anyway."""
    if n_shards <= 1:
        return {0: event}
    parts = split_hashes(event.block_hashes, n_shards)
    out: dict[int, KvCacheEvent] = {}
    if isinstance(event, KvStoredEvent):
        tokens_by_hash = {}
        if event.token_blocks and len(event.token_blocks) == len(event.block_hashes):
            tokens_by_hash = dict(zip(event.block_hashes, event.token_blocks))
        for s, hashes in parts.items():
            out[s] = KvStoredEvent(
                block_hashes=hashes,
                parent_hash=None,
                token_blocks=[tokens_by_hash[h] for h in hashes] if tokens_by_hash else [],
                tier=event.tier,
            )
    else:
        for s, hashes in parts.items():
            out[s] = KvRemovedEvent(block_hashes=hashes, tier=event.tier)
    return out


def membership_generation(replicas, n_shards: int) -> int:
    """Content-addressed generation of one membership view: the xxh3 of
    the sorted replica set (plus the shard count).  Two replicas — or a
    replica and a gatherer — that observed the same membership agree on
    the fence value without ever talking to each other; one that missed
    a change disagrees and gets fenced.  ABA (membership returning to an
    exact prior composition) resurrects that composition's generation,
    which is benign for ownership (same set, same map) and bounds the
    staleness of a resurrected snapshot by the live event stream."""
    blob = "|".join(sorted(replicas)) + f"#{n_shards}"
    return compute_hash(blob.encode())


@dataclass
class ShardMap:
    """Which replica owns which shard, fenced by a generation.

    Built deterministically from the live replica set with the same
    consistent-hash ring the frontends use, so every observer of the
    same membership computes the same map — no leader election needed
    for read-path ownership."""

    n_shards: int
    generation: int = 0
    owners: dict[int, str] = field(default_factory=dict)  # shard -> replica id

    @classmethod
    def from_replicas(cls, replicas, n_shards: int,
                      generation: Optional[int] = None) -> "ShardMap":
        ring = HashRing(replicas)
        owners = {s: ring.lookup(f"shard/{s}") for s in range(n_shards)}
        if generation is None:
            generation = membership_generation(replicas, n_shards)
        return cls(n_shards=n_shards, generation=generation,
                   owners={s: o for s, o in owners.items() if o is not None})

    def owner(self, shard_id: int):
        return self.owners.get(shard_id)

    def shards_of(self, replica: str) -> list[int]:
        return sorted(s for s, o in self.owners.items() if o == replica)

    def rebind(self, replicas) -> "ShardMap":
        """Membership changed: recompute ownership and the fence."""
        return ShardMap.from_replicas(replicas, self.n_shards)

    def moved_shards(self, new: "ShardMap") -> list[int]:
        """Shards whose owner differs between two maps — exactly the
        ranges that need an index handoff."""
        return sorted(
            s for s in range(self.n_shards)
            if self.owners.get(s) != new.owners.get(s)
        )
