"""Shard replica lifecycle: discovery, membership, handoff, fencing.

Registration reuses the worker discovery-delete idiom exactly
(kv_router/metrics_aggregator.py KvRouterSubscriber): each router
replica `kv_put`s itself under ``routers_prefix`` bound to a lease, and
every participant — replicas and frontends alike — `watch`es the prefix.
A put means a replica joined; a lease-expiry delete means it died.
Either way every observer independently rebinds the ShardMap from the
same sorted replica set with the same consistent-hash ring, deriving the
generation from the membership itself (partition.membership_generation):
no coordinator-side logic, no leader, and no counter to disagree on.

Two races the protocol plane (analysis/protocheck.py router.shard)
pins: a joining replica subscribes to EVERY handoff subject before it
announces itself, so the frames its own join triggers cannot outrun the
subscription; and a handoff frame that arrives before the local rebind
that justifies it is stashed and re-judged after the rebind instead of
being dropped on the floor.

On a rebind, the OLD owner of each moved shard (if still alive) ships
its range snapshot as a handoff frame; the new owner imports it only if
the frame's generation matches its current map — a replica that was
partitioned away and comes back with pre-handoff state fails this fence
and its frames (and scatter replies) are dropped rather than merged.
If the old owner died there is nothing to ship and the new owner serves
the range cold, repopulating from the live event stream; the gather
side sees that only as temporarily lower overlap scores.
"""

from __future__ import annotations

import asyncio
import itertools
import json
import logging
from typing import Optional, Sequence

from dynamo_tpu.engine.counters import kv_shard_counters
from dynamo_tpu.llm.kv.events import event_from_wire
from dynamo_tpu.llm.kv_router.shards.indexer import ShardedKvIndexer
from dynamo_tpu.llm.kv_router.shards.partition import ShardMap
from dynamo_tpu.llm.kv_router.shards.scatter import ShardReply, probe_shard
from dynamo_tpu.llm.kv_router.shards.wire import (
    decode_scatter_reply,
    decode_scatter_request,
    decode_shard_handoff,
    encode_scatter_reply,
    encode_scatter_request,
    encode_shard_announce,
    encode_shard_handoff,
    shard_announce_subject,
    shard_handoff_subject,
    shard_scatter_subject,
)

log = logging.getLogger("dynamo_tpu.kv_router")

__all__ = ["ShardReplica", "PubSubShardClient", "DEFAULT_ROUTERS_PREFIX"]

DEFAULT_ROUTERS_PREFIX = "/kv_routers"


class ShardReplica:
    """One router replica: hosts its owned shards' index ranges, serves
    scatter probes, and participates in membership + handoff."""

    def __init__(self, coordinator, replica_id: str, n_shards: int,
                 namespace: str = "default",
                 routers_prefix: str = DEFAULT_ROUTERS_PREFIX,
                 lease_ttl_s: float = 10.0):
        self.coord = coordinator
        self.replica_id = replica_id
        self.namespace = namespace
        self.routers_prefix = routers_prefix
        self.lease_ttl_s = lease_ttl_s
        self.index = ShardedKvIndexer(n_shards)
        self.map = ShardMap(n_shards)
        self._replicas: set[str] = set()
        self._lease: Optional[int] = None
        self._watch_id: Optional[int] = None
        self._subs: dict[int, int] = {}     # shard -> scatter sub id
        self._handoff_subs: dict[int, int] = {}
        self._ev_sub: Optional[int] = None
        # handoff frames that raced ahead of our own rebind, re-judged
        # after every membership change (shard -> latest frame)
        self._pending_handoffs: dict[int, tuple[int, str, dict, dict]] = {}
        # rebinds and scatter replies spawned from sync callbacks:
        # retained so failures are logged, drained on stop()
        self._bg_tasks: set[asyncio.Task] = set()

    def _spawn(self, coro) -> asyncio.Task:
        task = asyncio.ensure_future(coro)
        self._bg_tasks.add(task)
        task.add_done_callback(self._bg_done)
        return task

    def _bg_done(self, task: asyncio.Task) -> None:
        self._bg_tasks.discard(task)
        if not task.cancelled() and task.exception() is not None:
            log.error("shard replica %s background task failed",
                      self.replica_id, exc_info=task.exception())

    # -------------------------------------------------------------- lifecycle
    async def start(self) -> "ShardReplica":
        # subscribe to every handoff subject BEFORE announcing: the old
        # owners ship the moment they see our membership put, and a
        # frame published before our subscription lands is lost forever
        for s in range(self.index.n_shards):
            self._handoff_subs[s] = await self.coord.subscribe(
                shard_handoff_subject(self.namespace, s), self._on_handoff)
        self._lease = await self.coord.lease_create(ttl=self.lease_ttl_s)
        await self.coord.kv_put(
            f"{self.routers_prefix}/{self.replica_id}",
            {"replica": self.replica_id, "n_shards": self.index.n_shards},
            lease_id=self._lease,
        )
        self._watch_id, existing = await self.coord.watch(
            self.routers_prefix, self._on_membership)
        replicas = {k.rsplit("/", 1)[-1] for k in (existing or {})}
        replicas.add(self.replica_id)
        await self._rebind(replicas, ship_handoffs=False)
        return self

    async def stop(self) -> None:
        for t in list(self._bg_tasks):
            t.cancel()
        if self._bg_tasks:
            await asyncio.gather(*self._bg_tasks, return_exceptions=True)
        for sub in list(self._subs.values()) + list(self._handoff_subs.values()):
            try:
                await self.coord.unsubscribe(sub)
            except (ConnectionError, RuntimeError):
                pass
        self._subs.clear()
        self._handoff_subs.clear()
        if self._ev_sub is not None:
            try:
                await self.coord.unsubscribe(self._ev_sub)
            except (ConnectionError, RuntimeError):
                pass
            self._ev_sub = None
        if self._watch_id is not None:
            try:
                await self.coord.unwatch(self._watch_id)
            except (ConnectionError, RuntimeError):
                pass
            self._watch_id = None
        if self._lease is not None:
            try:
                await self.coord.lease_revoke(self._lease)
            except (ConnectionError, RuntimeError):
                pass
            self._lease = None

    # ------------------------------------------------------------- membership
    def _on_membership(self, event: str, key: str, value) -> None:
        rid = key.rsplit("/", 1)[-1]
        replicas = set(self._replicas)
        if event == "put":
            replicas.add(rid)
        elif event == "delete":
            replicas.discard(rid)
        if replicas != self._replicas:
            self._spawn(self._rebind(replicas, ship_handoffs=True))

    async def _rebind(self, replicas: set[str], ship_handoffs: bool) -> None:
        old = self.map
        self._replicas = set(replicas)
        self.map = old.rebind(sorted(replicas))
        self.index.generation = self.map.generation
        kv_shard_counters.set_generation(self.map.generation)
        moved = old.moved_shards(self.map)
        await self._resubscribe()
        await self.coord.publish(
            shard_announce_subject(self.namespace),
            encode_shard_announce(self.replica_id,
                                  self.map.shards_of(self.replica_id),
                                  self.map.generation))
        if ship_handoffs:
            for s in moved:
                if (old.owner(s) == self.replica_id
                        and self.map.owner(s) != self.replica_id):
                    device, persist = self.index.export_shard(s)
                    await self.coord.publish(
                        shard_handoff_subject(self.namespace, s),
                        encode_shard_handoff(s, self.map.generation,
                                             self.replica_id, device, persist))
        # re-judge frames that arrived before this rebind
        for s in sorted(self._pending_handoffs):
            generation, source, device, persist = self._pending_handoffs[s]
            if generation != self.map.generation:
                continue
            del self._pending_handoffs[s]
            if (source != self.replica_id
                    and self.map.owner(s) == self.replica_id):
                self.index.import_shard(s, device, persist)

    async def _resubscribe(self) -> None:
        owned = set(self.map.shards_of(self.replica_id))
        for s in list(self._subs):
            if s not in owned:
                await self.coord.unsubscribe(self._subs.pop(s))
        for s in sorted(owned - set(self._subs)):
            self._subs[s] = await self.coord.subscribe(
                shard_scatter_subject(self.namespace, s), self._on_scatter)
        for s in list(self._handoff_subs):
            if s not in owned:
                await self.coord.unsubscribe(self._handoff_subs.pop(s))
        for s in sorted(owned - set(self._handoff_subs)):
            self._handoff_subs[s] = await self.coord.subscribe(
                shard_handoff_subject(self.namespace, s), self._on_handoff)

    # ---------------------------------------------------------------- serving
    def _on_scatter(self, subject: str, payload: bytes) -> None:
        try:
            request_id, shard_id, seq_hashes, _gen, reply_subject = (
                decode_scatter_request(payload))
        except Exception:
            log.exception("bad scatter request on %s", subject)
            return
        # reply with OUR generation — the gatherer's fence decides; a
        # replica that lags a membership change must not forge currency
        reply = probe_shard(self.index.shard(shard_id), shard_id,
                            self.index.n_shards, seq_hashes,
                            self.map.generation)
        self._spawn(self.coord.publish(
            reply_subject, encode_scatter_reply(request_id, reply)))

    def _on_handoff(self, subject: str, payload: bytes) -> None:
        try:
            shard_id, generation, source, device, persist = (
                decode_shard_handoff(payload))
        except Exception:
            log.exception("bad handoff frame on %s", subject)
            return
        if source == self.replica_id:
            return
        if generation != self.map.generation:
            # either stale (will never match — bounded stash, one frame
            # per shard) or ahead of our own rebind (re-judged there)
            self._pending_handoffs[shard_id] = (
                generation, source, device, persist)
            return
        if self.map.owner(shard_id) != self.replica_id:
            return
        self.index.import_shard(shard_id, device, persist)

    # ------------------------------------------------------------ event plane
    async def subscribe_events(self, events_subject: str) -> None:
        """Feed this replica from the worker KV event plane; the sharded
        indexer's split keeps only owned ranges hot (a replica also
        indexes ranges it may inherit later — memory is bounded by the
        same eviction events workers publish)."""
        def _on_event(subject: str, payload: bytes) -> None:
            try:
                event_id, worker_id, ev = event_from_wire(json.loads(payload))
                self.index.apply_event(worker_id, ev, event_id=event_id)
            except Exception:
                log.exception("bad kv event on %s", subject)

        self._ev_sub = await self.coord.subscribe(events_subject, _on_event)


class PubSubShardClient:
    """ShardClient over the coordinator's pub/sub plane: publishes a
    scatter request on the shard's subject and waits for the reply on a
    private inbox subject.  Request ids are a per-client counter —
    deterministic under the analysis planes' virtual clock."""

    _ids = itertools.count(1)

    def __init__(self, coordinator, namespace: str, shard_id: int,
                 client_id: str):
        self.coord = coordinator
        self.namespace = namespace
        self.shard_id = shard_id
        self.client_id = client_id
        self._inbox = f"{namespace}.kv_shards.inbox.{client_id}.{shard_id}"
        self._sub: Optional[int] = None
        self._pending: dict[str, asyncio.Future] = {}

    def _on_reply(self, subject: str, payload: bytes) -> None:
        try:
            request_id, reply = decode_scatter_reply(payload)
        except Exception:
            log.exception("bad scatter reply on %s", subject)
            return
        fut = self._pending.pop(request_id, None)
        if fut is not None and not fut.done():
            fut.set_result(reply)

    async def start(self) -> "PubSubShardClient":
        self._sub = await self.coord.subscribe(self._inbox, self._on_reply)
        return self

    async def stop(self) -> None:
        if self._sub is not None:
            try:
                await self.coord.unsubscribe(self._sub)
            except (ConnectionError, RuntimeError):
                pass
            self._sub = None
        for fut in self._pending.values():
            if not fut.done():
                fut.cancel()
        self._pending.clear()

    async def probe(self, seq_hashes: Sequence[int],
                    generation: int) -> ShardReply:
        request_id = f"{self.client_id}:{self.shard_id}:{next(self._ids)}"
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._pending[request_id] = fut
        try:
            await self.coord.publish(
                shard_scatter_subject(self.namespace, self.shard_id),
                encode_scatter_request(request_id, self.shard_id,
                                       seq_hashes, generation, self._inbox))
            return await fut
        finally:
            self._pending.pop(request_id, None)
