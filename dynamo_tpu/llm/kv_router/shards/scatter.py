"""Scatter-gather overlap scoring across index shards.

One routing decision needs the holder set of every prefix position of
the request.  Position i's key is owned by exactly one shard, so the
scatter sends the full hash list to every owning replica, each replica
answers with holders for just the positions it owns (`probe_shard`),
and the gather (`gather_overlaps`) re-runs the singleton KvIndexer's
longest-prefix intersection walk over the merged per-position sets.
With every shard present the result is bit-identical to a singleton
`KvIndexer.find_matches` fed the same events — tests pin this.

Failure semantics: a reply that is missing (deadline miss, replica
death mid-scatter) or fenced (stale generation) truncates the walk at
that shard's first owned position.  Scores degrade monotonically —
overlap can only be under-counted, never invented — and placement
proceeds on whatever survived; a missing shard never blocks the
decision.  `gather_partial_total` (engine/counters.py) counts how often
that happened.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import Optional, Protocol, Sequence

from dynamo_tpu.engine.counters import kv_shard_counters
from dynamo_tpu.llm.kv_router.indexer import KvIndexer, OverlapScores
from dynamo_tpu.llm.kv_router.scheduler import KvScheduler
from dynamo_tpu.llm.kv_router.shards.partition import shard_of

__all__ = [
    "ShardReply",
    "probe_shard",
    "gather_overlaps",
    "LocalShardClient",
    "ScatterGatherScheduler",
]


@dataclass
class ShardReply:
    """One shard's answer to a scatter: holder sets for the request
    positions it owns, fenced by the replica's view of the generation."""

    shard_id: int
    generation: int
    # request position -> worker ids holding that position's block
    holders: dict[int, frozenset[int]] = field(default_factory=dict)
    persist_holders: dict[int, frozenset[int]] = field(default_factory=dict)


def probe_shard(index: KvIndexer, shard_id: int, n_shards: int,
                seq_hashes: Sequence[int], generation: int) -> ShardReply:
    """Serve a scatter request from one shard's index: probe every
    position this shard owns.  Pure read — safe to serve concurrently
    with the replica's event-apply task only under the same
    single-writer rule the singleton index documents."""
    holders: dict[int, frozenset[int]] = {}
    persist: dict[int, frozenset[int]] = {}
    for i, h in enumerate(seq_hashes):
        if shard_of(h, n_shards) != shard_id:
            continue
        hs = index.holders_of(h)
        if hs:
            holders[i] = hs
        ps = index.persist_holders_of(h)
        if ps:
            persist[i] = ps
    return ShardReply(shard_id=shard_id, generation=generation,
                      holders=holders, persist_holders=persist)


def _walk(seq_hashes: Sequence[int], n_shards: int,
          replies: dict[int, Optional[ShardReply]], generation: int,
          persist_tier: bool) -> dict[int, int]:
    scores: dict[int, int] = {}
    live: Optional[set[int]] = None
    for i, h in enumerate(seq_hashes):
        rep = replies.get(shard_of(h, n_shards))
        if rep is None or rep.generation != generation:
            break  # degraded: truncate at the missing/fenced shard
        holders = (rep.persist_holders if persist_tier else rep.holders).get(i)
        if not holders:
            break
        live = set(holders) if live is None else (live & holders)
        if not live:
            break
        for w in live:
            scores[w] = i + 1
    return scores


def gather_overlaps(seq_hashes: Sequence[int], n_shards: int,
                    replies: dict[int, Optional[ShardReply]],
                    generation: int) -> tuple[OverlapScores, bool]:
    """Merge scatter replies into OverlapScores.  Returns the scores
    plus a ``partial`` flag: True when any shard owning at least one
    request position was missing or answered with a stale generation.
    Identical to the singleton longest-prefix walk when complete."""
    owned = {shard_of(h, n_shards) for h in seq_hashes}
    partial = any(
        replies.get(s) is None or replies[s].generation != generation
        for s in owned
    )
    scores = _walk(seq_hashes, n_shards, replies, generation, persist_tier=False)
    persist = _walk(seq_hashes, n_shards, replies, generation, persist_tier=True)
    return OverlapScores(scores, persist), partial


class ShardClient(Protocol):
    """Transport seam for one replica: in-process (LocalShardClient),
    wire round-trip (lifecycle.WireShardClient), or a real socket."""

    shard_id: int

    async def probe(self, seq_hashes: Sequence[int],
                    generation: int) -> ShardReply: ...


class LocalShardClient:
    """In-process client over a replica's KvIndexer — the load plane's
    macro-simulation and single-process deployments use this."""

    def __init__(self, shard_id: int, n_shards: int, index: KvIndexer,
                 generation_fn=None, delay_s: float = 0.0):
        self.shard_id = shard_id
        self.n_shards = n_shards
        self.index = index
        # replica's OWN view of the generation (may lag the gatherer's —
        # that is the point of the fence); defaults to the gatherer's
        self._generation_fn = generation_fn
        # test hook: simulated probe latency, to force deadline misses
        self.delay_s = delay_s

    async def probe(self, seq_hashes: Sequence[int],
                    generation: int) -> ShardReply:
        if self.delay_s > 0:
            await asyncio.sleep(self.delay_s)
        gen = self._generation_fn() if self._generation_fn else generation
        return probe_shard(self.index, self.shard_id, self.n_shards,
                           seq_hashes, gen)


class ScatterGatherScheduler:
    """Fans overlap scoring out to shard replicas and folds the merged
    scores through the singleton KvScheduler's pure `score_candidates`
    seam, so the itemized logit breakdown contract from PR 16 survives
    sharding unchanged."""

    def __init__(self, scheduler: KvScheduler, clients: Sequence[ShardClient],
                 n_shards: int, deadline_s: float = 0.050,
                 generation: int = 0, clock=time.perf_counter):
        self.scheduler = scheduler
        self.clients = list(clients)
        self.n_shards = n_shards
        # per-shard gather deadline: a replica that cannot answer within
        # this bound is treated as absent for THIS decision only
        self.deadline_s = deadline_s
        self.generation = generation
        self._clock = clock

    def set_generation(self, generation: int) -> None:
        self.generation = generation

    async def _scatter(self, seq_hashes: Sequence[int]
                       ) -> dict[int, Optional[ShardReply]]:
        async def one(c: ShardClient):
            try:
                return await asyncio.wait_for(
                    c.probe(seq_hashes, self.generation), self.deadline_s)
            except (asyncio.TimeoutError, ConnectionError, OSError):
                return None

        t0 = self._clock()
        results = await asyncio.gather(*(one(c) for c in self.clients))
        replies: dict[int, Optional[ShardReply]] = {}
        for c, r in zip(self.clients, results):
            replies[c.shard_id] = r
        kv_shard_counters.record_scatter((self._clock() - t0) * 1e3,
                                         fan_out=len(self.clients))
        return replies

    async def overlaps(self, seq_hashes: Sequence[int]
                       ) -> tuple[OverlapScores, bool]:
        replies = await self._scatter(seq_hashes)
        scores, partial = gather_overlaps(seq_hashes, self.n_shards,
                                          replies, self.generation)
        if partial:
            kv_shard_counters.record_partial_gather()
        return scores, partial

    async def score_candidates(self, seq_hashes: Sequence[int],
                               request_tokens: int,
                               transfer_costs_s: Optional[dict[int, float]] = None,
                               ) -> list[tuple[int, float, dict]]:
        ov, _ = await self.overlaps(seq_hashes)
        return self.scheduler.score_candidates(
            ov.scores, request_tokens, persist_overlaps=ov.persist_scores,
            transfer_costs_s=transfer_costs_s)

    async def schedule(self, seq_hashes: Sequence[int], request_tokens: int,
                       transfer_costs_s: Optional[dict[int, float]] = None) -> int:
        ov, _ = await self.overlaps(seq_hashes)
        return self.scheduler.schedule(
            ov.scores, request_tokens, persist_overlaps=ov.persist_scores,
            transfer_costs_s=transfer_costs_s)
