"""Wire contracts for the sharded control plane.

Four payloads ride the coordinator's pub/sub plane, all JSON, all
discriminated by ``op`` and all carrying ``generation`` — the membership
fence doubles as the payload's version tag (dtwire WR004): a frame from
before a membership change is by definition from an older protocol
epoch and the receiver drops it.

  * **shard_announce** (`{ns}.kv_shards.announce`) — a replica declares
    which shards it serves at which generation; frontends and peers
    rebuild their ShardMap from the latest announce per replica.
  * **shard_scatter** (`{ns}.kv_shards.scatter.{shard}`) — overlap probe
    for one routing decision: the full hash list plus the subject the
    reply should land on.
  * **shard_reply** (reply subject from the request) — per-position
    holder sets for the shard's owned positions, both tiers.
  * **shard_handoff** (`{ns}.kv_shards.handoff.{shard}`) — a departing
    or re-balanced owner ships its range snapshot to the new owner.

Holder maps serialize as sorted ``[key, [worker_ids]]`` pairs rather
than JSON objects so integer keys survive the round trip and the bytes
are deterministic — tests/wire_golden pins the scatter reply encoding.
"""

from __future__ import annotations

import json
from typing import Mapping, Sequence

from dynamo_tpu.llm.kv_router.shards.scatter import ShardReply

__all__ = [
    "shard_announce_subject", "shard_scatter_subject", "shard_handoff_subject",
    "encode_shard_announce", "decode_shard_announce",
    "encode_scatter_request", "decode_scatter_request",
    "encode_scatter_reply", "decode_scatter_reply",
    "encode_shard_handoff", "decode_shard_handoff",
]

OP_ANNOUNCE = "shard_announce"
OP_SCATTER = "shard_scatter"
OP_REPLY = "shard_reply"
OP_HANDOFF = "shard_handoff"


def shard_announce_subject(namespace: str) -> str:
    return f"{namespace}.kv_shards.announce"


def shard_scatter_subject(namespace: str, shard_id: int | str = "") -> str:
    base = f"{namespace}.kv_shards.scatter"
    return f"{base}.{shard_id}" if shard_id != "" else f"{base}.>"


def shard_handoff_subject(namespace: str, shard_id: int | str = "") -> str:
    base = f"{namespace}.kv_shards.handoff"
    return f"{base}.{shard_id}" if shard_id != "" else f"{base}.>"


def _pairs(m: Mapping[int, Sequence[int]]) -> list[list]:
    return [[int(k), sorted(int(w) for w in v)] for k, v in sorted(m.items())]


def _unpairs(pairs) -> dict[int, frozenset[int]]:
    return {int(k): frozenset(int(w) for w in v) for k, v in pairs}


# ------------------------------------------------------------------ announce
def encode_shard_announce(replica: str, shards: Sequence[int],
                          generation: int) -> bytes:
    return json.dumps({
        "op": "shard_announce",
        "replica": replica,
        "shards": sorted(shards),
        "generation": generation,
    }, sort_keys=True).encode()


def decode_shard_announce(payload: bytes) -> tuple[str, list[int], int]:
    d = json.loads(payload)
    if d["op"] == OP_ANNOUNCE:
        return d["replica"], list(d["shards"]), d["generation"]
    raise ValueError(f"expected {OP_ANNOUNCE}, got {d['op']!r}")


# ------------------------------------------------------------------- scatter
def encode_scatter_request(request_id: str, shard_id: int,
                           seq_hashes: Sequence[int], generation: int,
                           reply_subject: str) -> bytes:
    return json.dumps({
        "op": "shard_scatter",
        "request_id": request_id,
        "shard": shard_id,
        "seq_hashes": list(seq_hashes),
        "generation": generation,
        "reply_subject": reply_subject,
    }, sort_keys=True).encode()


def decode_scatter_request(payload: bytes) -> tuple[str, int, list[int], int, str]:
    d = json.loads(payload)
    if d["op"] == OP_SCATTER:
        return (d["request_id"], d["shard"], list(d["seq_hashes"]),
                d["generation"], d["reply_subject"])
    raise ValueError(f"expected {OP_SCATTER}, got {d['op']!r}")


def encode_scatter_reply(request_id: str, reply: ShardReply) -> bytes:
    return json.dumps({
        "op": "shard_reply",
        "request_id": request_id,
        "shard": reply.shard_id,
        "generation": reply.generation,
        "holders": _pairs(reply.holders),
        "persist_holders": _pairs(reply.persist_holders),
    }, sort_keys=True).encode()


def decode_scatter_reply(payload: bytes) -> tuple[str, ShardReply]:
    d = json.loads(payload)
    if d["op"] == OP_REPLY:
        return d["request_id"], ShardReply(
            shard_id=d["shard"],
            generation=d["generation"],
            holders=_unpairs(d["holders"]),
            persist_holders=_unpairs(d["persist_holders"]),
        )
    raise ValueError(f"expected {OP_REPLY}, got {d['op']!r}")


# ------------------------------------------------------------------- handoff
def encode_shard_handoff(shard_id: int, generation: int, source: str,
                         device: Mapping[int, Sequence[int]],
                         persist: Mapping[int, Sequence[int]]) -> bytes:
    return json.dumps({
        "op": "shard_handoff",
        "shard": shard_id,
        "generation": generation,
        "source": source,
        "device": _pairs(device),
        "persist": _pairs(persist),
    }, sort_keys=True).encode()


def decode_shard_handoff(payload: bytes
                         ) -> tuple[int, int, str, dict, dict]:
    d = json.loads(payload)
    if d["op"] == OP_HANDOFF:
        device = {int(k): [int(w) for w in v] for k, v in d["device"]}
        persist = {int(k): [int(w) for w in v] for k, v in d["persist"]}
        return d["shard"], d["generation"], d["source"], device, persist
    raise ValueError(f"expected {OP_HANDOFF}, got {d['op']!r}")
