"""LLM serving library: protocols, preprocessing, KV management, routing,
HTTP service — the lib/llm equivalent (SURVEY.md §2.2), minus the engine
itself which lives in dynamo_tpu/engine (in-process JAX, not external)."""
