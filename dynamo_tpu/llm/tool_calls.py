"""Tool calling: prompt-side rendering + output-side parsing.

Reference parity: lib/llm/src/preprocessor/tools.rs (tool schema injection)
and the per-model-family call formats its prompt templates target.  Three
wire formats cover the served model zoo (llama/qwen/mistral/hermes):

  hermes       <tool_call>{"name": ..., "arguments": {...}}</tool_call>
               (Qwen2, Hermes, most chat-template models)
  llama3_json  {"name": ..., "parameters": {...}} as the whole message,
               optionally behind <|python_tag|>, ';'-separated for multiple
  mistral      [TOOL_CALLS] [{...}, ...]

Streaming uses a stop-string-style jail: text is released to the client
until a suffix could begin a tool-call marker, then held until the call
is complete or disproven — so normal content streams, and tool calls are
emitted as a single `tool_calls` delta at the end (what OpenAI clients
handle today).
"""

from __future__ import annotations

import json
import uuid
from typing import Optional

__all__ = ["ToolCallParser", "render_tools_system", "validate_tools"]

HERMES_OPEN = "<tool_call>"
HERMES_CLOSE = "</tool_call>"
MISTRAL_TAG = "[TOOL_CALLS]"
PYTHON_TAG = "<|python_tag|>"

# streaming jail triggers: any of these starting in the pending tail holds
# back emission until resolved
_MARKERS = (HERMES_OPEN, MISTRAL_TAG, PYTHON_TAG)


def validate_tools(tools, tool_choice) -> None:
    """Raise ValueError on malformed tools/tool_choice (caller wraps in
    OpenAIError)."""
    if not isinstance(tools, list) or not tools:
        raise ValueError("'tools' must be a non-empty array")
    for t in tools:
        if not isinstance(t, dict) or t.get("type") != "function":
            raise ValueError("each tool must be {'type': 'function', ...}")
        fn = t.get("function")
        if not isinstance(fn, dict) or not fn.get("name"):
            raise ValueError("each tool needs function.name")
    if tool_choice is not None:
        if isinstance(tool_choice, str):
            if tool_choice not in ("none", "auto", "required"):
                raise ValueError(
                    "'tool_choice' must be none|auto|required or a function ref"
                )
        elif not (
            isinstance(tool_choice, dict)
            and tool_choice.get("type") == "function"
            and isinstance(tool_choice.get("function"), dict)
            and tool_choice["function"].get("name")
        ):
            raise ValueError("'tool_choice' object must name a function")


def render_tools_system(tools: list[dict], tool_choice=None) -> str:
    """System-prompt block teaching a template-less model the hermes
    format — used when the model card's chat template has no native tools
    support (ref preprocessor/prompt: template-side tool injection).

    tool_choice 'required' / a named function is enforced prompt-side (MUST
    instructions); there is no grammar-level constraint yet, so a
    non-compliant model can still answer in prose."""
    lines = [
        "You have access to the following tools. To call a tool, reply with",
        '<tool_call>{"name": <tool-name>, "arguments": <args-json>}</tool_call>',
        "Available tools:",
    ]
    for t in tools:
        fn = t.get("function", {})
        lines.append(json.dumps(
            {
                "name": fn.get("name"),
                "description": fn.get("description", ""),
                "parameters": fn.get("parameters", {}),
            },
            separators=(",", ":"),
        ))
    if tool_choice == "required":
        lines.append("You MUST call at least one tool before answering.")
    elif isinstance(tool_choice, dict):
        name = tool_choice.get("function", {}).get("name")
        lines.append(
            f"You MUST respond with a call to the tool '{name}' and nothing else."
        )
    return "\n".join(lines)


def _call_id() -> str:
    return f"call_{uuid.uuid4().hex[:24]}"


def _mk_call(name: str, arguments) -> dict:
    if not isinstance(arguments, str):
        arguments = json.dumps(arguments or {}, separators=(",", ":"))
    return {
        "id": _call_id(),
        "type": "function",
        "function": {"name": name, "arguments": arguments},
    }


def _parse_obj(obj) -> Optional[dict]:
    """One tool-call JSON object → OpenAI tool_call dict (None if not one)."""
    if not isinstance(obj, dict) or not obj.get("name"):
        return None
    args = obj.get("arguments", obj.get("parameters", {}))
    return _mk_call(str(obj["name"]), args)


def _parse_json_calls(text: str) -> list[dict]:
    """Parse raw JSON tool calls: a single object, an array of objects, or
    ';'-separated objects (llama3 multi-call)."""
    text = text.strip()
    try:
        data = json.loads(text)
        objs = data if isinstance(data, list) else [data]
        calls = [c for c in (_parse_obj(o) for o in objs) if c]
        return calls
    except json.JSONDecodeError:
        pass
    calls = []
    for part in text.split(";"):
        part = part.strip()
        if not part:
            continue
        try:
            c = _parse_obj(json.loads(part))
        except json.JSONDecodeError:
            return []
        if c is None:
            return []
        calls.append(c)
    return calls


class ToolCallParser:
    """Incremental tool-call extractor over a streamed text channel.

    feed(delta) -> text safe to emit now (may be "");
    finish() -> (remaining_text, tool_calls).

    ``only`` (from a named tool_choice) keeps just calls to that function.
    """

    def __init__(self, fmt: str = "auto", only: Optional[str] = None):
        self.fmt = fmt
        self.only = only
        self._pending = ""       # text withheld from the client
        self._emitted_any = False
        self._jailed = False     # a marker matched: hold everything

    # ------------------------------------------------------------- streaming
    def feed(self, delta: str) -> str:
        self._pending += delta
        if self._jailed:
            return ""
        p = self._pending
        # the whole MESSAGE may be a bare JSON call (llama3): jail only when
        # the message-initial non-space char is '{' or '[' — a brace after
        # emitted prose is ordinary content (JSON-shaped answers must
        # stream, not be eaten as fake tool calls)
        lead = p.lstrip()
        if not self._emitted_any and lead[:1] in ("{", "["):
            self._jailed = True
            return ""
        # full marker anywhere → jail from its start
        for m in _MARKERS:
            at = p.find(m)
            if at >= 0:
                out, self._pending = p[:at], p[at:]
                self._jailed = True
                self._emitted_any = self._emitted_any or bool(out.strip())
                return out
        # hold back a tail that could still become a marker
        hold = 0
        for m in _MARKERS:
            for k in range(min(len(m) - 1, len(p)), 0, -1):
                if p.endswith(m[:k]):
                    hold = max(hold, k)
                    break
        out, self._pending = p[: len(p) - hold], p[len(p) - hold:]
        # whitespace-only output must NOT count as emitted prose: a leading
        # "\n" delta before a bare-JSON llama3 call would otherwise disarm
        # the message-initial jail and stream the call out as content
        self._emitted_any = self._emitted_any or bool(out.strip())
        return out

    # --------------------------------------------------------------- parsing
    def finish(self) -> tuple[str, list[dict]]:
        """Parse whatever is withheld; returns (text_to_flush, tool_calls).

        Text outside the call markup (e.g. prose after the last
        ``</tool_call>``) flushes as content alongside the calls.  When a
        named tool_choice filters every parsed call out, the raw markup is
        dropped — never leaked to the client as content."""
        text = self._pending
        self._pending = ""
        calls, remainder = self._parse(text)
        if calls and self.only:
            calls = [c for c in calls if c["function"]["name"] == self.only]
            return remainder, calls  # markup never leaks, even if all filtered
        if calls:
            return remainder, calls
        return text, []

    def _parse(self, text: str) -> tuple[list[dict], str]:
        """Returns (calls, non-call remainder text)."""
        stripped = text.strip()
        if not stripped:
            return [], ""
        fmt = self.fmt
        if fmt in ("auto", "hermes") and HERMES_OPEN in stripped:
            return self._parse_hermes(text)
        if fmt in ("auto", "mistral") and stripped.startswith(MISTRAL_TAG):
            return _parse_json_calls(stripped[len(MISTRAL_TAG):]), ""
        if fmt in ("auto", "llama3_json"):
            if stripped.startswith(PYTHON_TAG):
                stripped = stripped[len(PYTHON_TAG):].strip()
            if stripped[:1] in ("{", "["):
                return _parse_json_calls(stripped), ""
        return [], ""

    @staticmethod
    def _parse_hermes(text: str) -> tuple[list[dict], str]:
        calls = []
        outside: list[str] = []
        pos = 0
        while True:
            start = text.find(HERMES_OPEN, pos)
            if start < 0:
                outside.append(text[pos:])
                break
            outside.append(text[pos:start])
            end = text.find(HERMES_CLOSE, start)
            body = text[start + len(HERMES_OPEN): end if end >= 0 else None]
            try:
                c = _parse_obj(json.loads(body.strip()))
            except json.JSONDecodeError:
                c = None
            if c:
                calls.append(c)
            if end < 0:
                break
            pos = end + len(HERMES_CLOSE)
        return calls, "".join(outside).strip(" \n") if calls else ""
