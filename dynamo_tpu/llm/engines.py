"""Engine stubs for tests and wiring rehearsals.

Reference parity: lib/llm/src/engines.rs (EchoEngineCore/EchoEngineFull with
DYN_TOKEN_ECHO_DELAY_MS) — every serving-stack feature must be testable with
no model and no TPU (SURVEY.md §4 test strategy).
"""

from __future__ import annotations

import asyncio
import os
from typing import AsyncIterator

from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor
from dynamo_tpu.llm.backend import Backend
from dynamo_tpu.llm.protocols import BackendInput, FinishReason, LLMEngineOutput
from dynamo_tpu.llm.tokenizer import TokenizerWrapper
from dynamo_tpu.runtime.engine import AsyncEngine, Context
from dynamo_tpu.runtime.pipeline import build_pipeline

__all__ = ["EchoEngineCore", "ScriptedEngine", "build_serving_pipeline"]


class ScriptedEngine(AsyncEngine):
    """Emits a fixed sequence of text deltas, ignoring the input — lets
    protocol-surface tests (tool-call parsing, stop jail, SSE framing)
    script exact model output without a model."""

    def __init__(self, deltas: list[str]):
        self.deltas = list(deltas)

    def generate(self, request) -> AsyncIterator[LLMEngineOutput]:
        return self._run(request)

    async def _run(self, request) -> AsyncIterator[LLMEngineOutput]:
        for i, d in enumerate(self.deltas):
            if getattr(request, "is_stopped", False):
                yield LLMEngineOutput(finish_reason=FinishReason.CANCELLED)
                return
            yield LLMEngineOutput(
                token_ids=[i],
                text=d,
                finish_reason=(
                    FinishReason.STOP if i + 1 == len(self.deltas) else None
                ),
            )


class EchoEngineCore(AsyncEngine):
    """Echoes the prompt's token ids back, one per step (ref engines.rs:40)."""

    def __init__(self, delay_s: float | None = None):
        if delay_s is None:
            delay_s = float(os.environ.get("DYNTPU_TOKEN_ECHO_DELAY_MS", "0")) / 1e3
        self.delay_s = delay_s

    def generate(self, request: Context[BackendInput]) -> AsyncIterator[LLMEngineOutput]:
        return self._run(request)

    async def _run(self, request: Context[BackendInput]) -> AsyncIterator[LLMEngineOutput]:
        inp = request.data
        max_tokens = inp.stops.max_tokens or len(inp.token_ids)
        for i, tid in enumerate(inp.token_ids):
            if request.is_stopped:
                yield LLMEngineOutput(token_ids=[], finish_reason=FinishReason.CANCELLED)
                return
            if self.delay_s:
                await asyncio.sleep(self.delay_s)
            last = i + 1 >= max_tokens or i + 1 >= len(inp.token_ids)
            out = LLMEngineOutput(
                token_ids=[tid],
                finish_reason=FinishReason.LENGTH if last else None,
            )
            if inp.sampling.logprobs or inp.sampling.top_logprobs:
                # deterministic fake logprobs so the protocol surface is
                # testable without a model (real values come from the engine)
                out.logprobs = [-0.5]
                if inp.sampling.top_logprobs > 0:
                    out.top_logprobs = [[(tid, -0.5)]]
            yield out
            if last:
                return


def build_serving_pipeline(
    engine: AsyncEngine, card: ModelDeploymentCard, tokenizer: TokenizerWrapper | None = None
) -> AsyncEngine:
    """frontend-ready pipeline: ParsedRequest → preprocess → engine → detok.

    Mirrors the reference's local pipeline assembly
    (launch/dynamo-run/src/input/common.rs:78-96).
    """
    pre = OpenAIPreprocessor(card, tokenizer)
    back = Backend(pre.tokenizer)
    # JSON mode (response_format): the core compiles grammar tables from
    # this tokenizer lazily on the first json_mode request
    core = getattr(engine, "core", None)
    if core is not None and hasattr(core, "attach_grammar_tokenizer"):
        core.attach_grammar_tokenizer(pre.tokenizer, card.eos_token_ids)
    return build_pipeline(engine, pre, back)
