"""Conditional disaggregation router — local vs remote prefill decision.

Reference parity: lib/llm/src/disagg_router.rs (DisaggregatedRouter,
decision `prefill_length − prefix_hit_length > max_local_prefill_length`
at :236-244) and examples/llm/components/disagg_router.py (queue-size
guard).  The config hot-reloads from a coordinator watch, mirroring
DisaggRouterConf::from_etcd_with_watcher (disagg_router.rs:37-140).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Optional

log = logging.getLogger("dynamo_tpu.disagg_router")

__all__ = ["DisaggRouterConf", "DisaggregatedRouter", "CONF_KEY"]

CONF_KEY = "disagg_router_conf"  # under {namespace}/


@dataclass
class DisaggRouterConf:
    # prompts whose non-cached remainder exceeds this go to a prefill worker
    max_local_prefill_length: int = 512
    # but never when the prefill queue is already this deep (backpressure)
    max_prefill_queue_size: int = 16
    # ... nor when the KV handoff itself would cost more wall-clock than
    # this (NetKV-style transfer-cost term: the predicted cost_s() of
    # moving the request's KV from prefill to decode — obs/costs.py EWMA,
    # topology prior on cold edges).  inf = transfer cost never vetoes.
    max_transfer_cost_s: float = float("inf")


class DisaggregatedRouter:
    def __init__(self, conf: Optional[DisaggRouterConf] = None, namespace: str = "default"):
        self.conf = conf or DisaggRouterConf()
        self.namespace = namespace
        self._watch_id: Optional[int] = None

    def prefill_remote(
        self,
        prefill_length: int,
        prefix_hit_length: int,
        queue_size: int = 0,
        transfer_cost_s: float = 0.0,
    ) -> bool:
        """True = enqueue remote prefill; False = prefill locally.

        ``transfer_cost_s`` is the predicted seconds to move this
        request's KV from the prefill worker into this decode engine
        over the CHEAPEST handoff path (stream over ICI/DCN vs
        persist-tier restore — llm/kv/stream.py ``choose_handoff_path``);
        a remote prefill whose handoff costs more than
        ``max_transfer_cost_s`` stays local, because the transfer would
        eat the TTFT the remote prefill was supposed to save."""
        return (
            prefill_length - prefix_hit_length > self.conf.max_local_prefill_length
            and queue_size < self.conf.max_prefill_queue_size
            and transfer_cost_s <= self.conf.max_transfer_cost_s
        )

    # ------------------------------------------------------ dynamic config
    def _key(self) -> str:
        return f"{self.namespace}/{CONF_KEY}"

    async def watch(self, coordinator) -> None:
        """Hot-reload the thresholds from the coordinator KV plane."""

        def on_event(event: str, key: str, value) -> None:
            if event == "put" and isinstance(value, dict):
                self.conf = DisaggRouterConf(
                    max_local_prefill_length=int(
                        value.get("max_local_prefill_length", self.conf.max_local_prefill_length)
                    ),
                    max_prefill_queue_size=int(
                        value.get("max_prefill_queue_size", self.conf.max_prefill_queue_size)
                    ),
                    max_transfer_cost_s=float(
                        value.get("max_transfer_cost_s", self.conf.max_transfer_cost_s)
                    ),
                )
                log.info("disagg router conf updated: %s", self.conf)

        self._watch_id, snapshot = await coordinator.watch(self._key(), on_event)
        if self._key() in snapshot:
            on_event("put", self._key(), snapshot[self._key()])

    async def publish(self, coordinator, conf: DisaggRouterConf) -> None:
        """Write new thresholds for every watching worker to pick up."""
        await coordinator.kv_put(
            self._key(),
            {
                "max_local_prefill_length": conf.max_local_prefill_length,
                "max_prefill_queue_size": conf.max_prefill_queue_size,
                "max_transfer_cost_s": conf.max_transfer_cost_s,
            },
        )
