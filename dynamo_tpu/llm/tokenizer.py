"""Tokenizer wrapper with incremental (streaming) detokenization.

Wraps a HuggingFace ``tokenizer.json`` (tokenizers crate via its Python
binding — same underlying Rust library the reference uses).  The streaming
decoder keeps prefix/read offsets so multi-token glyphs and sentencepiece
space markers render correctly as tokens trickle in.

Reference parity: lib/llm/src/tokenizers.rs (HF wrapper, Encoding,
DecodeStream) and the decode-stream jail in backend.rs.
"""

from __future__ import annotations

from pathlib import Path
from typing import Optional, Sequence

__all__ = ["TokenizerWrapper", "DecodeStream"]


class TokenizerWrapper:
    def __init__(self, tokenizer):
        self._tk = tokenizer

    @classmethod
    def from_file(cls, path: str | Path) -> "TokenizerWrapper":
        from tokenizers import Tokenizer

        p = Path(path)
        if p.is_dir():
            if not (p / "tokenizer.json").exists() \
                    and (p / "tokenizer.model").exists():
                # sentencepiece-only checkpoint: materialise an equivalent
                # tokenizer.json once (llm/sentencepiece.py)
                from dynamo_tpu.llm.sentencepiece import materialize_tokenizer

                p = materialize_tokenizer(p / "tokenizer.model")
            else:
                p = p / "tokenizer.json"
        elif p.suffix == ".model":
            from dynamo_tpu.llm.sentencepiece import materialize_tokenizer

            p = materialize_tokenizer(p)
        return cls(Tokenizer.from_file(str(p)))

    def encode(self, text: str, add_special_tokens: bool = True) -> list[int]:
        return self._tk.encode(text, add_special_tokens=add_special_tokens).ids

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        return self._tk.decode(list(ids), skip_special_tokens=skip_special_tokens)

    def token_to_id(self, token: str) -> Optional[int]:
        return self._tk.token_to_id(token)

    def id_to_token(self, token_id: int) -> Optional[str]:
        return self._tk.id_to_token(token_id)

    @property
    def vocab_size(self) -> int:
        return self._tk.get_vocab_size()

    def decode_stream(self, skip_special_tokens: bool = True) -> "DecodeStream":
        return DecodeStream(self, skip_special_tokens)


class DecodeStream:
    """Incremental detokenizer (vLLM-style prefix/read offsets).

    ``step(token_id)`` returns the new text produced by this token, or ""
    while the tokenizer is mid-glyph (e.g. partial UTF-8 from BPE bytes).
    """

    def __init__(self, tokenizer: TokenizerWrapper, skip_special_tokens: bool = True):
        self._tk = tokenizer
        self._skip = skip_special_tokens
        self._ids: list[int] = []
        self._prefix_offset = 0
        self._read_offset = 0

    def step(self, token_id: int) -> str:
        self._ids.append(token_id)
        prefix_text = self._tk.decode(
            self._ids[self._prefix_offset : self._read_offset], self._skip
        )
        full_text = self._tk.decode(self._ids[self._prefix_offset :], self._skip)
        if full_text.endswith("�"):
            return ""  # mid-glyph; wait for more tokens
        new_text = full_text[len(prefix_text) :]
        self._prefix_offset = self._read_offset
        self._read_offset = len(self._ids)
        return new_text
