"""Backend — the detokenizing postprocessor operator.

Wraps the engine: on the response path it incrementally detokenizes token
deltas into text, holds back text that might be the start of a stop
sequence (the "jail"), and maps finish reasons.

Reference parity: lib/llm/src/backend.rs:63 (Backend operator with
DecodeStream + hidden-stop-token jail).
"""

from __future__ import annotations

from typing import AsyncIterator

from dynamo_tpu.llm.protocols import BackendInput, FinishReason, LLMEngineOutput
from dynamo_tpu.llm.tokenizer import TokenizerWrapper
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.pipeline import Operator

__all__ = ["Backend"]


class Backend(Operator):
    def __init__(self, tokenizer: TokenizerWrapper):
        self.tokenizer = tokenizer

    async def forward(self, request: Context[BackendInput]) -> Context[BackendInput]:
        return request

    def backward(
        self, stream: AsyncIterator[LLMEngineOutput], request: Context[BackendInput]
    ) -> AsyncIterator[LLMEngineOutput]:
        return self._detokenize(stream, request)

    def _logprob_content(self, out: LLMEngineOutput) -> list[dict]:
        """Map engine logprob data (token ids) to OpenAI display form
        (token strings + UTF-8 bytes), one entry per emitted token."""
        entries = []
        tops = out.top_logprobs or [None] * len(out.token_ids)
        for tid, lp, top in zip(out.token_ids, out.logprobs, tops):
            s = self.tokenizer.decode([tid], skip_special_tokens=False)
            e = {"token": s, "logprob": lp, "bytes": list(s.encode())}
            if top:
                e["top_logprobs"] = [
                    {
                        "token": (ts := self.tokenizer.decode([int(i)], skip_special_tokens=False)),
                        "logprob": float(l),
                        "bytes": list(ts.encode()),
                    }
                    for i, l in top
                ]
            else:
                e["top_logprobs"] = []
            entries.append(e)
        return entries

    async def _detokenize(
        self, stream: AsyncIterator[LLMEngineOutput], request: Context[BackendInput]
    ) -> AsyncIterator[LLMEngineOutput]:
        decoder = self.tokenizer.decode_stream()
        stop_strings = request.data.stops.stop
        max_stop = max((len(s) for s in stop_strings), default=0)
        held = ""  # jail: text that may be a stop-string prefix

        async for out in stream:
            text = ""
            for tid in out.token_ids:
                text += decoder.step(tid)
            held += text
            if out.logprobs is not None:
                out.logprob_content = self._logprob_content(out)

            if stop_strings:
                hit = None
                for s in stop_strings:
                    i = held.find(s)
                    if i >= 0 and (hit is None or i < hit[0]):
                        hit = (i, s)
                if hit is not None:
                    out.text = held[: hit[0]]
                    out.finish_reason = FinishReason.STOP
                    yield out
                    request.stop_generating()
                    return
                # release everything that can no longer start a stop string
                safe = len(held) - (max_stop - 1)
                if out.finished:
                    out.text = held
                    held = ""
                elif safe > 0:
                    out.text = held[:safe]
                    held = held[safe:]
                else:
                    out.text = ""
            else:
                out.text = held
                held = ""
            yield out
            if out.finished:
                return
