"""OpenAI-compatible protocol: request parsing, response building, SSE.

Covers /v1/chat/completions and /v1/completions (streaming and unary),
including the reference's `nvext` extension fields (ignore_eos,
annotations; lib/llm/src/protocols/openai/nvext.rs) which are accepted
under both "nvext" and "ext" keys.

Parsing is dict-based with explicit validation (no heavyweight schema
dependency); the aggregator turns a streamed sequence of deltas back into
a full response for non-streaming callers (reference: protocols/openai/
chat_completions/aggregator.rs).
"""

from __future__ import annotations

import json
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Optional

from dynamo_tpu.llm.protocols import BackendInput, SamplingOptions, StopConditions


class OpenAIError(Exception):
    def __init__(self, message: str, status: int = 400, err_type: str = "invalid_request_error"):
        super().__init__(message)
        self.status = status
        self.err_type = err_type

    def body(self) -> dict:
        return {"error": {"message": str(self), "type": self.err_type, "code": self.status}}


@dataclass
class ParsedRequest:
    """A validated OpenAI request, engine-ready except for tokenization."""

    model: str
    messages: Optional[list[dict]] = None   # chat mode
    prompt: Optional[str] = None            # completions mode
    prompt_token_ids: Optional[list[int]] = None
    stream: bool = False
    n: int = 1
    sampling: SamplingOptions = field(default_factory=SamplingOptions)
    stops: StopConditions = field(default_factory=StopConditions)
    echo: bool = False
    annotations: list[str] = field(default_factory=list)
    raw: dict = field(default_factory=dict)

    @property
    def is_chat(self) -> bool:
        return self.messages is not None


def _require(cond: bool, msg: str) -> None:
    if not cond:
        raise OpenAIError(msg)


def parse_request(body: dict, chat: bool) -> ParsedRequest:
    _require(isinstance(body, dict), "request body must be a JSON object")
    model = body.get("model")
    _require(isinstance(model, str) and model, "'model' is required")

    req = ParsedRequest(model=model, raw=body, stream=bool(body.get("stream", False)))

    if chat:
        messages = body.get("messages")
        _require(isinstance(messages, list) and messages, "'messages' must be a non-empty array")
        for m in messages:
            _require(isinstance(m, dict) and "role" in m, "each message needs a 'role'")
        req.messages = messages
    else:
        prompt = body.get("prompt")
        _require(prompt is not None, "'prompt' is required")
        if isinstance(prompt, list) and prompt and isinstance(prompt[0], int):
            req.prompt_token_ids = prompt
        elif isinstance(prompt, list):
            _require(len(prompt) == 1, "batched prompts not yet supported")
            req.prompt = prompt[0]
        else:
            _require(isinstance(prompt, str), "'prompt' must be a string or token array")
            req.prompt = prompt
        req.echo = bool(body.get("echo", False))

    temperature = body.get("temperature")
    top_p = body.get("top_p")
    top_k = body.get("top_k")  # extension (vLLM-compatible)
    seed = body.get("seed")
    req.sampling = SamplingOptions(
        temperature=1.0 if temperature is None else float(temperature),
        top_p=1.0 if top_p is None else float(top_p),
        top_k=0 if top_k is None else int(top_k),
        seed=seed,
    )

    max_tokens = body.get("max_completion_tokens", body.get("max_tokens"))
    stop = body.get("stop") or []
    if isinstance(stop, str):
        stop = [stop]
    _require(isinstance(stop, list), "'stop' must be a string or array")
    req.stops = StopConditions(
        max_tokens=int(max_tokens) if max_tokens is not None else 16 if not chat else None,
        stop=[s for s in stop if s],
        min_tokens=int(body.get("min_tokens", 0)),
    )

    ext = body.get("nvext") or body.get("ext") or {}
    if isinstance(ext, dict):
        req.stops.ignore_eos = bool(ext.get("ignore_eos", body.get("ignore_eos", False)))
        ann = ext.get("annotations", [])
        if isinstance(ann, list):
            req.annotations = ann

    n = int(body.get("n", 1))
    _require(n == 1, "'n' > 1 not yet supported")
    return req


# --------------------------------------------------------------------- builders

def _now() -> int:
    return int(time.time())


def new_id(prefix: str) -> str:
    return f"{prefix}-{uuid.uuid4().hex}"


def chat_chunk(
    rid: str, model: str, *, role: Optional[str] = None, content: Optional[str] = None,
    finish_reason: Optional[str] = None, usage: Optional[dict] = None,
) -> dict:
    delta: dict[str, Any] = {}
    if role is not None:
        delta["role"] = role
    if content:
        delta["content"] = content
    out = {
        "id": rid,
        "object": "chat.completion.chunk",
        "created": _now(),
        "model": model,
        "choices": [{"index": 0, "delta": delta, "finish_reason": finish_reason}],
    }
    if usage is not None:
        out["usage"] = usage
    return out


def chat_response(rid: str, model: str, content: str, finish_reason: str, usage: dict) -> dict:
    return {
        "id": rid,
        "object": "chat.completion",
        "created": _now(),
        "model": model,
        "choices": [
            {
                "index": 0,
                "message": {"role": "assistant", "content": content},
                "finish_reason": finish_reason,
            }
        ],
        "usage": usage,
    }


def completion_chunk(
    rid: str, model: str, text: str, finish_reason: Optional[str] = None,
    usage: Optional[dict] = None,
) -> dict:
    out = {
        "id": rid,
        "object": "text_completion",
        "created": _now(),
        "model": model,
        "choices": [{"index": 0, "text": text, "finish_reason": finish_reason}],
    }
    if usage is not None:
        out["usage"] = usage
    return out


def completion_response(rid: str, model: str, text: str, finish_reason: str, usage: dict) -> dict:
    return {
        "id": rid,
        "object": "text_completion",
        "created": _now(),
        "model": model,
        "choices": [{"index": 0, "text": text, "finish_reason": finish_reason}],
        "usage": usage,
    }


def usage_dict(prompt_tokens: int, completion_tokens: int) -> dict:
    return {
        "prompt_tokens": prompt_tokens,
        "completion_tokens": completion_tokens,
        "total_tokens": prompt_tokens + completion_tokens,
    }


def sse_encode(data: dict | str) -> bytes:
    if isinstance(data, dict):
        data = json.dumps(data, separators=(",", ":"))
    return f"data: {data}\n\n".encode()


SSE_DONE = b"data: [DONE]\n\n"


def aggregate_stream(chunks: list[dict], chat: bool) -> dict:
    """Fold streamed chunks into a full response (ref aggregator.rs)."""
    text = []
    finish = "stop"
    usage = None
    rid = chunks[0]["id"] if chunks else new_id("cmpl")
    model = chunks[0]["model"] if chunks else ""
    for c in chunks:
        ch = c["choices"][0]
        if chat:
            text.append(ch["delta"].get("content", "") or "")
        else:
            text.append(ch.get("text", "") or "")
        if ch.get("finish_reason"):
            finish = ch["finish_reason"]
        if c.get("usage"):
            usage = c["usage"]
    usage = usage or usage_dict(0, 0)
    if chat:
        return chat_response(rid, model, "".join(text), finish, usage)
    return completion_response(rid, model, "".join(text), finish, usage)
