"""OpenAI-compatible protocol: request parsing, response building, SSE.

Covers /v1/chat/completions and /v1/completions (streaming and unary),
including the reference's `nvext` extension fields (ignore_eos,
annotations; lib/llm/src/protocols/openai/nvext.rs) which are accepted
under both "nvext" and "ext" keys.

Parsing is dict-based with explicit validation (no heavyweight schema
dependency); the aggregator turns a streamed sequence of deltas back into
a full response for non-streaming callers (reference: protocols/openai/
chat_completions/aggregator.rs).
"""

from __future__ import annotations

import json
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Optional

from dynamo_tpu.llm.protocols import BackendInput, SamplingOptions, StopConditions


class OpenAIError(Exception):
    def __init__(self, message: str, status: int = 400, err_type: str = "invalid_request_error"):
        super().__init__(message)
        self.status = status
        self.err_type = err_type

    def body(self) -> dict:
        return {"error": {"message": str(self), "type": self.err_type, "code": self.status}}


@dataclass
class ParsedRequest:
    """A validated OpenAI request, engine-ready except for tokenization."""

    model: str
    messages: Optional[list[dict]] = None   # chat mode
    prompt: Optional[str] = None            # completions mode
    prompt_token_ids: Optional[list[int]] = None
    stream: bool = False
    n: int = 1
    sampling: SamplingOptions = field(default_factory=SamplingOptions)
    stops: StopConditions = field(default_factory=StopConditions)
    echo: bool = False
    annotations: list[str] = field(default_factory=list)
    # tool calling (chat mode): validated OpenAI tool schemas + choice
    tools: Optional[list[dict]] = None
    tool_choice: Any = None  # "none"|"auto"|"required"|{function ref}|None
    # response_format: None | "json_object" | "json_schema"; schema kept
    # for prompt injection; enforcement = schema-shaped regex when the
    # schema translates (schema_regex), else the generic JSON grammar
    response_format: Optional[str] = None
    json_schema: Optional[dict] = None
    schema_regex: Optional[str] = None
    raw: dict = field(default_factory=dict)

    @property
    def is_chat(self) -> bool:
        return self.messages is not None

    @property
    def wants_tools(self) -> bool:
        return bool(self.tools) and self.tool_choice != "none"


def _require(cond: bool, msg: str) -> None:
    if not cond:
        raise OpenAIError(msg)


def parse_request(body: dict, chat: bool) -> ParsedRequest:
    _require(isinstance(body, dict), "request body must be a JSON object")
    model = body.get("model")
    _require(isinstance(model, str) and model, "'model' is required")

    req = ParsedRequest(model=model, raw=body, stream=bool(body.get("stream", False)))

    if chat:
        messages = body.get("messages")
        _require(isinstance(messages, list) and messages, "'messages' must be a non-empty array")
        for m in messages:
            _require(isinstance(m, dict) and "role" in m, "each message needs a 'role'")
            if m["role"] == "tool":
                _require("tool_call_id" in m, "tool messages need 'tool_call_id'")
        req.messages = messages
        tools = body.get("tools")
        if tools is not None:
            from dynamo_tpu.llm.tool_calls import validate_tools

            try:
                validate_tools(tools, body.get("tool_choice"))
            except ValueError as e:
                raise OpenAIError(str(e))
            req.tools = tools
            req.tool_choice = body.get("tool_choice", "auto")
    else:
        prompt = body.get("prompt")
        _require(prompt is not None, "'prompt' is required")
        if isinstance(prompt, list) and prompt and isinstance(prompt[0], int):
            req.prompt_token_ids = prompt
        elif isinstance(prompt, list):
            _require(len(prompt) == 1, "batched prompts not yet supported")
            req.prompt = prompt[0]
        else:
            _require(isinstance(prompt, str), "'prompt' must be a string or token array")
            req.prompt = prompt
        req.echo = bool(body.get("echo", False))

    temperature = body.get("temperature")
    top_p = body.get("top_p")
    top_k = body.get("top_k")  # extension (vLLM-compatible)
    try:  # extension (vLLM-compatible)
        min_p = float(body.get("min_p") or 0.0)
    except (TypeError, ValueError):
        raise OpenAIError("'min_p' must be a number")
    _require(0.0 <= min_p <= 1.0, "'min_p' must be in [0, 1]")
    seed = body.get("seed")
    if seed is not None:
        _require(isinstance(seed, int) and not isinstance(seed, bool)
                 and -(2 ** 63) <= seed < 2 ** 63,
                 "'seed' must be an integer")
    logit_bias = body.get("logit_bias")
    if logit_bias is not None:
        _require(isinstance(logit_bias, dict), "'logit_bias' must be an object")
        _require(len(logit_bias) <= 300, "'logit_bias' supports at most 300 tokens")
        try:
            logit_bias = {int(k): float(v) for k, v in logit_bias.items()}
        except (TypeError, ValueError):
            raise OpenAIError("'logit_bias' keys must be token ids, values numbers")
        _require(all(-100.0 <= v <= 100.0 for v in logit_bias.values()),
                 "'logit_bias' values must be in [-100, 100]")
    freq_pen = float(body.get("frequency_penalty") or 0.0)
    pres_pen = float(body.get("presence_penalty") or 0.0)
    _require(-2.0 <= freq_pen <= 2.0, "'frequency_penalty' must be in [-2, 2]")
    _require(-2.0 <= pres_pen <= 2.0, "'presence_penalty' must be in [-2, 2]")

    # logprobs: chat = bool 'logprobs' + int 'top_logprobs' (0-20);
    # completions = int-or-null 'logprobs' meaning top-N
    if chat:
        want_lp = bool(body.get("logprobs", False))
        top_lp = int(body.get("top_logprobs") or 0)
        _require(0 <= top_lp <= 20, "'top_logprobs' must be in [0, 20]")
        _require(top_lp == 0 or want_lp,
                 "'top_logprobs' requires 'logprobs': true")
    else:
        lp = body.get("logprobs")
        want_lp = lp is not None and lp is not False
        top_lp = int(lp) if isinstance(lp, int) and not isinstance(lp, bool) else 0
        _require(0 <= top_lp <= 20, "'logprobs' must be in [0, 20]")

    # response_format: json_object / json_schema switch the engine to
    # grammar-constrained decoding (engine/grammar.py).  json_object is
    # endpoint-agnostic; json_schema needs a chat transcript to inject the
    # schema instruction into, so it is chat-only.
    rf = body.get("response_format")
    if rf is not None:
        _require(isinstance(rf, dict) and "type" in rf,
                 "'response_format' must be an object with a 'type'")
        rft = rf["type"]
        _require(rft in ("text", "json_object", "json_schema"),
                 "'response_format.type' must be 'text', 'json_object' or "
                 "'json_schema'")
        _require(rft != "json_schema" or chat,
                 "'json_schema' response_format is only supported on chat "
                 "completions")
        if rft == "json_schema":
            js = rf.get("json_schema")
            _require(isinstance(js, dict) and isinstance(js.get("schema"), dict),
                     "'response_format.json_schema.schema' is required")
            req.response_format = rft
            req.json_schema = js
            # enforce the schema's SHAPE when it translates to the bounded
            # regex engine (objects with required scalar/array/enum props);
            # otherwise the generic JSON grammar + prompt injection applies
            from dynamo_tpu.engine.grammar import json_schema_to_regex

            req.schema_regex = json_schema_to_regex(js["schema"])
            if req.schema_regex and len(req.schema_regex) > 4096:
                req.schema_regex = None  # generic JSON grammar instead
        elif rft == "json_object":
            req.response_format = rft

    # guided_choice (vLLM-compatible extension): output constrained to
    # exactly one of the given strings (engine/grammar.py choice trie)
    guided_choice = body.get("guided_choice")
    if guided_choice is not None:
        _require(isinstance(guided_choice, list) and guided_choice
                 and all(isinstance(c, str) and c for c in guided_choice),
                 "'guided_choice' must be a non-empty array of strings")
        _require(len(guided_choice) <= 256,
                 "'guided_choice' supports at most 256 choices")
        _require(sum(len(c.encode("utf-8")) for c in guided_choice) <= 4096,
                 "'guided_choice' total length exceeds 4096 bytes")
        _require(rf is None,
                 "'guided_choice' cannot be combined with 'response_format'")

    # guided_regex (vLLM-compatible extension): bounded regex subset,
    # validated up front so syntax errors are 400s, not engine errors
    guided_regex = body.get("guided_regex")
    if guided_regex is not None:
        _require(isinstance(guided_regex, str) and guided_regex,
                 "'guided_regex' must be a non-empty string")
        _require(len(guided_regex) <= 1024,
                 "'guided_regex' exceeds 1024 chars")
        _require(rf is None and guided_choice is None,
                 "'guided_regex' cannot be combined with 'response_format' "
                 "or 'guided_choice'")
        from dynamo_tpu.engine.grammar import RegexError, _parse_regex

        try:
            _parse_regex(guided_regex)
        except RegexError as e:
            raise OpenAIError(f"'guided_regex': {e}")

    req.sampling = SamplingOptions(
        temperature=1.0 if temperature is None else float(temperature),
        top_p=1.0 if top_p is None else float(top_p),
        top_k=0 if top_k is None else int(top_k),
        min_p=min_p,
        logit_bias=logit_bias or None,
        guided_choice=guided_choice,
        guided_regex=guided_regex or req.schema_regex,
        seed=seed,
        frequency_penalty=freq_pen,
        presence_penalty=pres_pen,
        logprobs=want_lp,
        top_logprobs=top_lp,
        # json_mode stays set alongside a schema regex: the engine prefers
        # the regex grammar and falls back to generic JSON if its DFA
        # exceeds the cap (schema requests must never hard-fail on size)
        json_mode=req.response_format is not None,
    )

    max_tokens = body.get("max_completion_tokens", body.get("max_tokens"))
    stop = body.get("stop") or []
    if isinstance(stop, str):
        stop = [stop]
    _require(isinstance(stop, list), "'stop' must be a string or array")
    req.stops = StopConditions(
        max_tokens=int(max_tokens) if max_tokens is not None else 16 if not chat else None,
        stop=[s for s in stop if s],
        min_tokens=int(body.get("min_tokens", 0)),
    )

    ext = body.get("nvext") or body.get("ext") or {}
    if isinstance(ext, dict):
        req.stops.ignore_eos = bool(ext.get("ignore_eos", body.get("ignore_eos", False)))
        ann = ext.get("annotations", [])
        if isinstance(ann, list):
            req.annotations = ann

    n = int(body.get("n", 1))
    _require(1 <= n <= 16, "'n' must be in [1, 16]")
    req.n = n
    return req


# --------------------------------------------------------------------- builders

def _now() -> int:
    return int(time.time())


def new_id(prefix: str) -> str:
    return f"{prefix}-{uuid.uuid4().hex}"


def chat_chunk(
    rid: str, model: str, *, role: Optional[str] = None, content: Optional[str] = None,
    finish_reason: Optional[str] = None, usage: Optional[dict] = None,
    index: int = 0, logprobs: Optional[dict] = None,
    tool_calls: Optional[list[dict]] = None,
) -> dict:
    delta: dict[str, Any] = {}
    if role is not None:
        delta["role"] = role
    if content:
        delta["content"] = content
    if tool_calls:
        delta["tool_calls"] = [
            {"index": i, **c} for i, c in enumerate(tool_calls)
        ]
    choice: dict[str, Any] = {
        "index": index, "delta": delta, "finish_reason": finish_reason,
    }
    if logprobs is not None:
        choice["logprobs"] = logprobs
    out = {
        "id": rid,
        "object": "chat.completion.chunk",
        "created": _now(),
        "model": model,
        "choices": [choice],
    }
    if usage is not None:
        out["usage"] = usage
    return out


def chat_response(
    rid: str, model: str, content: str, finish_reason: str, usage: dict,
    *, index: int = 0, logprobs: Optional[dict] = None,
    tool_calls: Optional[list[dict]] = None,
) -> dict:
    message: dict[str, Any] = {"role": "assistant", "content": content}
    if tool_calls:
        message["content"] = content or None  # OpenAI: null content on calls
        message["tool_calls"] = tool_calls
    choice: dict[str, Any] = {
        "index": index,
        "message": message,
        "finish_reason": finish_reason,
    }
    if logprobs is not None:
        choice["logprobs"] = logprobs
    return {
        "id": rid,
        "object": "chat.completion",
        "created": _now(),
        "model": model,
        "choices": [choice],
        "usage": usage,
    }


def completion_chunk(
    rid: str, model: str, text: str, finish_reason: Optional[str] = None,
    usage: Optional[dict] = None, *, index: int = 0,
    logprobs: Optional[dict] = None,
) -> dict:
    choice: dict[str, Any] = {
        "index": index, "text": text, "finish_reason": finish_reason,
    }
    if logprobs is not None:
        choice["logprobs"] = logprobs
    out = {
        "id": rid,
        "object": "text_completion",
        "created": _now(),
        "model": model,
        "choices": [choice],
    }
    if usage is not None:
        out["usage"] = usage
    return out


def completion_response(
    rid: str, model: str, text: str, finish_reason: str, usage: dict,
    *, index: int = 0, logprobs: Optional[dict] = None,
) -> dict:
    choice: dict[str, Any] = {
        "index": index, "text": text, "finish_reason": finish_reason,
    }
    if logprobs is not None:
        choice["logprobs"] = logprobs
    return {
        "id": rid,
        "object": "text_completion",
        "created": _now(),
        "model": model,
        "choices": [choice],
        "usage": usage,
    }


def chat_logprobs_block(content: list[dict]) -> dict:
    """Chat-format logprobs: {"content": [{token, logprob, bytes,
    top_logprobs: [...]}]} — entries come from Backend detokenization."""
    return {"content": content}


def completion_logprobs_block(
    content: list[dict], text_offset_base: int = 0
) -> dict:
    """Completions-format logprobs: parallel arrays (tokens, token_logprobs,
    top_logprobs, text_offset) built from the same Backend entries."""
    tokens, lps, tops, offsets = [], [], [], []
    off = text_offset_base
    for e in content:
        tokens.append(e["token"])
        lps.append(e["logprob"])
        tops.append({t["token"]: t["logprob"] for t in e.get("top_logprobs", [])} or None)
        offsets.append(off)
        off += len(e["token"])
    return {
        "tokens": tokens,
        "token_logprobs": lps,
        "top_logprobs": tops,
        "text_offset": offsets,
    }


def usage_dict(prompt_tokens: int, completion_tokens: int) -> dict:
    return {
        "prompt_tokens": prompt_tokens,
        "completion_tokens": completion_tokens,
        "total_tokens": prompt_tokens + completion_tokens,
    }


def sse_encode(data: dict | str) -> bytes:
    if isinstance(data, dict):
        data = json.dumps(data, separators=(",", ":"))
    return f"data: {data}\n\n".encode()


SSE_DONE = b"data: [DONE]\n\n"
