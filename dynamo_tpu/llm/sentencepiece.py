"""SentencePiece ``tokenizer.model`` support — no sentencepiece package.

Reference parity: lib/llm/src/tokenizers.rs wraps BOTH HF tokenizer.json
and SentencePiece models.  This repo standardises on the ``tokenizers``
runtime (same Rust core the reference uses); a checkpoint that ships only
``tokenizer.model`` gets its model PARSED here (the file is a small
protobuf — pieces, scores, trainer/normalizer specs) and MATERIALISED as
an equivalent ``tokenizer.json`` (Unigram + byte-fallback + the model's
own precompiled normalizer charsmap), exactly like the GGUF path
materialises its embedded vocab (llm/gguf.py:build_hf_tokenizer).

The conversion mirrors transformers' SpmConverter/LlamaConverter
pipeline: Precompiled(charsmap) → Prepend("▁") (dummy prefix) →
Replace(" ","▁") normalizers; Unigram(vocab, unk_id, byte_fallback);
Replace/ByteFallback/Fuse/Strip decoders.  SP-BPE models (model_type=2)
are rejected loudly — their merges are not recoverable from scores.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from pathlib import Path
from typing import Optional

__all__ = ["SpModel", "parse_model_proto", "build_hf_tokenizer",
           "materialize_tokenizer", "is_sentencepiece_model"]

# SentencePiece piece types (sentencepiece_model.proto)
NORMAL, UNKNOWN, CONTROL, USER_DEFINED, UNUSED, BYTE = 1, 2, 3, 4, 5, 6
UNIGRAM, BPE = 1, 2


@dataclass
class SpModel:
    pieces: list[tuple[str, float, int]] = field(default_factory=list)
    model_type: int = UNIGRAM
    unk_id: int = 0
    bos_id: int = 1
    eos_id: int = 2
    pad_id: int = -1
    add_dummy_prefix: bool = True
    remove_extra_whitespaces: bool = True
    precompiled_charsmap: bytes = b""


def _varint(data: bytes, i: int) -> tuple[int, int]:
    shift = v = 0
    while True:
        b = data[i]
        v |= (b & 0x7F) << shift
        i += 1
        if not b & 0x80:
            return v, i
        shift += 7


def _signed(v: int) -> int:
    """Protobuf int32/int64 varints are two's-complement 64-bit."""
    return v - (1 << 64) if v >= 1 << 63 else v


def _fields(data: bytes):
    """Iterate (field_number, wire_type, value) over a protobuf message;
    value is int for varint/fixed, bytes for length-delimited."""
    i, n = 0, len(data)
    while i < n:
        key, i = _varint(data, i)
        fnum, wt = key >> 3, key & 7
        if wt == 0:
            v, i = _varint(data, i)
        elif wt == 1:
            v, i = int.from_bytes(data[i:i + 8], "little"), i + 8
        elif wt == 2:
            ln, i = _varint(data, i)
            v, i = data[i:i + ln], i + ln
        elif wt == 5:
            v, i = int.from_bytes(data[i:i + 4], "little"), i + 4
        else:
            raise ValueError(f"unsupported protobuf wire type {wt}")
        yield fnum, wt, v


def _f32(v: int) -> float:
    import struct

    return struct.unpack("<f", v.to_bytes(4, "little"))[0]


def parse_model_proto(data: bytes) -> SpModel:
    """Parse a sentencepiece ModelProto (the ``tokenizer.model`` bytes)."""
    sp = SpModel()
    for fnum, wt, v in _fields(data):
        if fnum == 1 and wt == 2:  # repeated SentencePiece
            piece, score, ptype = "", 0.0, NORMAL
            for f2, w2, v2 in _fields(v):
                if f2 == 1 and w2 == 2:
                    piece = v2.decode("utf-8", errors="replace")
                elif f2 == 2 and w2 == 5:
                    score = _f32(v2)
                elif f2 == 3 and w2 == 0:
                    ptype = v2
            sp.pieces.append((piece, score, ptype))
        elif fnum == 2 and wt == 2:  # TrainerSpec
            for f2, w2, v2 in _fields(v):
                if w2 != 0:
                    continue
                if f2 == 3:
                    sp.model_type = v2
                elif f2 == 40:
                    sp.unk_id = _signed(v2)
                elif f2 == 41:
                    sp.bos_id = _signed(v2)
                elif f2 == 42:
                    sp.eos_id = _signed(v2)
                elif f2 == 43:
                    sp.pad_id = _signed(v2)
        elif fnum == 3 and wt == 2:  # NormalizerSpec
            for f2, w2, v2 in _fields(v):
                if f2 == 2 and w2 == 2:
                    sp.precompiled_charsmap = v2
                elif f2 == 3 and w2 == 0:
                    sp.add_dummy_prefix = bool(v2)
                elif f2 == 4 and w2 == 0:
                    sp.remove_extra_whitespaces = bool(v2)
    if not sp.pieces:
        raise ValueError("no pieces in sentencepiece model (not a ModelProto?)")
    return sp


def build_hf_tokenizer(sp: SpModel):
    """SpModel → ``tokenizers.Tokenizer`` (Unigram pipeline)."""
    from tokenizers import AddedToken, Tokenizer, decoders, models, normalizers

    if sp.model_type != UNIGRAM:
        raise NotImplementedError(
            f"sentencepiece model_type {sp.model_type} (only unigram "
            "models materialise; SP-BPE merges are not stored)"
        )
    byte_fallback = any(t == BYTE for _, _, t in sp.pieces)
    vocab = [(p, s) for p, s, _ in sp.pieces]
    unk = sp.unk_id if 0 <= sp.unk_id < len(vocab) else 0
    tok = Tokenizer(models.Unigram(vocab, unk, byte_fallback))

    norms = []
    if sp.precompiled_charsmap:
        # the model's own NFKC-ish charsmap applies verbatim — the
        # tokenizers crate executes it natively
        norms.append(normalizers.Precompiled(sp.precompiled_charsmap))
    if sp.remove_extra_whitespaces:
        # sentencepiece default: collapse whitespace runs BEFORE the
        # space→▁ mapping (transformers SpmConverter does the same)
        from tokenizers import Regex

        norms.append(normalizers.Replace(Regex(" {2,}"), " "))
    if sp.add_dummy_prefix:
        norms.append(normalizers.Prepend("▁"))
    norms.append(normalizers.Replace(" ", "▁"))
    tok.normalizer = normalizers.Sequence(norms)

    decs = [decoders.Replace("▁", " "), decoders.ByteFallback(),
            decoders.Fuse()]
    if sp.add_dummy_prefix:
        decs.append(decoders.Strip(" ", 1, 0))
    tok.decoder = decoders.Sequence(decs)

    specials = [
        AddedToken(p, special=True, normalized=False)
        for p, _, t in sp.pieces if t == CONTROL
    ]
    if specials:
        tok.add_special_tokens(specials)
    return tok


def is_sentencepiece_model(path: str | Path) -> bool:
    p = Path(path)
    return p.is_file() and p.suffix == ".model"


def materialize_tokenizer(model_file: str | Path,
                          out: Optional[str | Path] = None) -> Path:
    """Parse ``tokenizer.model`` and write the equivalent
    ``tokenizer.json`` (default: next to it; falls back to the model
    cache when the directory is read-only).

    Concurrency/staleness: the write is temp-file + atomic rename (two
    workers racing never expose a half-written JSON to a third), and an
    existing materialisation is reused only when at least as new as the
    source .model (a replaced checkpoint re-materialises)."""
    import os

    src = Path(model_file)
    dst = Path(out) if out else src.parent / "tokenizer.json"
    if dst.exists() and dst.stat().st_mtime >= src.stat().st_mtime:
        return dst
    tok = build_hf_tokenizer(parse_model_proto(src.read_bytes()))

    def atomic_save(path: Path) -> None:
        tmp = path.with_name(f".{path.name}.tmp-{os.getpid()}")
        tok.save(str(tmp))
        os.replace(tmp, path)

    try:
        atomic_save(dst)
    except Exception:
        from dynamo_tpu.llm.model_store import DEFAULT_CACHE

        alt = DEFAULT_CACHE / "sp-materialized"
        alt.mkdir(parents=True, exist_ok=True)
        import hashlib

        h = hashlib.sha256(src.read_bytes()).hexdigest()[:12]
        dst = alt / f"{src.stem}-{h}.tokenizer.json"
        if not dst.exists():
            atomic_save(dst)
    return dst
