"""ModelDeploymentCard — the model manifest.

Everything a frontend/router/worker needs to know about a served model
without loading its weights: tokenizer, chat template, context length,
special tokens, checksum.  Published to the control plane so remote
components can preprocess for a model they don't host.

Reference parity: lib/llm/src/model_card/model.rs:97-199 (ModelDeploymentCard,
mdcsum checksum, load-from-HF-repo) and create.rs.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Optional


@dataclass
class ModelDeploymentCard:
    name: str
    model_path: Optional[str] = None        # local HF dir (workers only)
    tokenizer_path: Optional[str] = None    # tokenizer.json
    context_length: int = 4096
    eos_token_ids: list[int] = field(default_factory=list)
    bos_token_id: Optional[int] = None
    # token STRINGS for chat-template rendering: real templates (Llama-3,
    # Mistral) reference {{ bos_token }}/{{ eos_token }} — without these
    # every chat prompt silently loses its BOS marker
    bos_token: Optional[str] = None
    eos_token: Optional[str] = None
    chat_template: Optional[str] = None     # jinja source
    extra: dict[str, Any] = field(default_factory=dict)

    @property
    def mdcsum(self) -> str:
        """Stable checksum of the card (ref model.rs mdcsum)."""
        payload = json.dumps(self.to_dict(), sort_keys=True).encode()
        return hashlib.blake2s(payload, digest_size=8).hexdigest()

    # ------------------------------------------------------------- serde
    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "model_path": self.model_path,
            "tokenizer_path": self.tokenizer_path,
            "context_length": self.context_length,
            "eos_token_ids": self.eos_token_ids,
            "bos_token_id": self.bos_token_id,
            "bos_token": self.bos_token,
            "eos_token": self.eos_token,
            "chat_template": self.chat_template,
            "extra": self.extra,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ModelDeploymentCard":
        return cls(**d)

    # -------------------------------------------------------------- loading
    @classmethod
    def from_hf_dir(cls, model_dir: str | Path, name: Optional[str] = None) -> "ModelDeploymentCard":
        """Build a card from a local HuggingFace model directory."""
        d = Path(model_dir)
        cfg = json.loads((d / "config.json").read_text()) if (d / "config.json").exists() else {}

        eos = cfg.get("eos_token_id", [])
        if isinstance(eos, int):
            eos = [eos]
        bos = cfg.get("bos_token_id")

        def _tok_str(v) -> Optional[str]:
            # tokenizer_config.json stores special tokens as plain strings
            # or AddedToken dicts ({"content": "<s>", ...})
            if isinstance(v, str):
                return v
            if isinstance(v, dict) and isinstance(v.get("content"), str):
                return v["content"]
            return None

        chat_template = None
        bos_str = eos_str = None
        gen_cfg_path = d / "tokenizer_config.json"
        if gen_cfg_path.exists():
            tk_cfg = json.loads(gen_cfg_path.read_text())
            chat_template = tk_cfg.get("chat_template")
            bos_str = _tok_str(tk_cfg.get("bos_token"))
            eos_str = _tok_str(tk_cfg.get("eos_token"))
        sep = d / "chat_template.jinja"
        if chat_template is None and sep.exists():
            chat_template = sep.read_text()

        tok = d / "tokenizer.json"
        if not tok.exists() and (d / "tokenizer.model").exists():
            # sentencepiece-only checkpoint (older Llama/Mistral exports):
            # materialise an equivalent tokenizer.json once
            from dynamo_tpu.llm.sentencepiece import materialize_tokenizer

            try:
                tok = materialize_tokenizer(d / "tokenizer.model")
            except Exception:
                pass  # unparseable/SP-BPE: card carries no tokenizer
        if not eos and eos_str and tok.exists():
            # config.json had no eos_token_id but tokenizer_config names
            # the token: resolve it here or the engine never receives an
            # EOS stop id (every generation would run to max_tokens)
            try:
                from tokenizers import Tokenizer

                tid = Tokenizer.from_file(str(tok)).token_to_id(eos_str)
                if tid is not None:
                    eos = [tid]
            except Exception:
                pass
        return cls(
            name=name or d.name,
            model_path=str(d),
            tokenizer_path=str(tok) if tok.exists() else None,
            context_length=cfg.get("max_position_embeddings", 4096),
            eos_token_ids=list(eos),
            bos_token_id=bos,
            bos_token=bos_str,
            eos_token=eos_str,
            chat_template=chat_template,
        )

    @classmethod
    def from_gguf(cls, gguf_path: str | Path, name: Optional[str] = None) -> "ModelDeploymentCard":
        """Build a card from GGUF metadata, materialising the embedded
        tokenizer as a tokenizer.json next to the checkpoint (reference:
        gguf_metadata.rs + gguf_tokenizer.rs feed MDC creation)."""
        from dynamo_tpu.llm.gguf import GGUFFile

        p = Path(gguf_path)
        gf = GGUFFile(p)
        tok_path = p.with_suffix(".tokenizer.json")
        if not tok_path.exists():
            try:
                gf.build_hf_tokenizer().save(str(tok_path))
            except ValueError:
                tok_path = None  # no embedded vocab
        chat_template = gf.metadata.get("tokenizer.chat_template")
        bos = gf.metadata.get("tokenizer.ggml.bos_token_id")
        return cls(
            name=name or gf.metadata.get("general.name", p.stem),
            model_path=str(p),
            tokenizer_path=str(tok_path) if tok_path else None,
            context_length=int(gf.field("context_length", 4096)),
            eos_token_ids=gf.eos_token_ids(),
            bos_token_id=int(bos) if bos is not None else None,
            chat_template=chat_template,
        )
