"""Model artifact distribution over the coordinator blob store.

Reference parity: the reference publishes the model card + tokenizer to
the NATS object store so remote workers self-serve their artifacts
(lib/llm/src/model_card/model.rs:150-199 move_to_nats/move_from_nats).
Here the coordinator's blob plane (transports/coordinator.py plane 4)
carries the WHOLE model directory — config, tokenizer, safetensors or
native orbax checkpoint — so a multi-host graph needs the weights on one
host only: every other worker boots from a ``dyn://models/<name>`` ref,
pulls once, and caches under a content-addressed local directory.

Layout on the coordinator:

  KV   models/<name>            -> manifest {files: {rel: {size, sha256}},
                                   digest, pushed_at}
  blob models/<name>/<relpath>  -> file bytes (content-addressed on disk)

Pulls are concurrency-safe per host (download to a temp dir, atomic
rename into the cache; a lost race simply reuses the winner's copy) and
idempotent across restarts (the cache key is the manifest digest, so a
re-push with different bytes lands in a fresh directory).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import tempfile
import time
from pathlib import Path
from typing import Optional

log = logging.getLogger("dynamo_tpu.model_store")

__all__ = ["push_model", "pull_model", "resolve_model", "manifest_key",
           "is_model_ref", "DEFAULT_CACHE", "file_sha256", "verify_files"]

DEFAULT_CACHE = Path(os.environ.get(
    "DYNAMO_MODEL_CACHE", os.path.expanduser("~/.cache/dynamo_tpu/models")
))
_REF_PREFIX = "dyn://models/"
# never shipped: transient HF artifacts and lock/cache noise
_SKIP_PARTS = {".locks", "__pycache__", ".git"}


def file_sha256(path: str | Path, chunk_size: int = 1 << 20) -> str:
    """Streaming sha256 of a file on disk.  Shared integrity primitive:
    model pulls verify manifest hashes with it, and the persistent KV
    tier (llm/kv/persist.py) verifies block-group files against their
    header digest with the same helper."""
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            chunk = f.read(chunk_size)
            if not chunk:
                break
            h.update(chunk)
    return h.hexdigest()


def verify_files(root: str | Path, files: dict) -> list[str]:
    """Check every manifest entry under ``root`` against its recorded
    sha256.  Returns the rel paths that are missing or corrupt (size
    mismatch short-circuits the hash)."""
    root = Path(root)
    bad: list[str] = []
    for rel, info in files.items():
        p = root / rel
        if not p.is_file():
            bad.append(rel)
            continue
        size = info.get("size")
        if size is not None and p.stat().st_size != size:
            bad.append(rel)
            continue
        if file_sha256(p) != info["sha256"]:
            bad.append(rel)
    return bad


def _check_rel(name: str, rel: str) -> None:
    """The manifest is UNTRUSTED (any coordinator client can write it): a
    '..' segment or absolute path must never escape the cache directory."""
    relp = Path(rel)
    if (not rel or relp.is_absolute()
            or any(part in ("..", "") for part in relp.parts)):
        raise IOError(
            f"model {name!r}: manifest entry {rel!r} is not a "
            "safe relative path"
        )


def manifest_key(name: str) -> str:
    return f"models/{name}"


def _blob_key(name: str, rel: str) -> str:
    """Blob key with the model name slash-quoted: 'meta/llama' +
    'config.json' must never collide with model 'meta' + file
    'llama/config.json'."""
    from urllib.parse import quote

    return f"models/{quote(name, safe='')}/{rel}"


def is_model_ref(ref: str) -> bool:
    return isinstance(ref, str) and ref.startswith(_REF_PREFIX)


def _ref_name(ref: str) -> str:
    name = ref[len(_REF_PREFIX):].strip("/")
    if not name:
        raise ValueError(f"empty model name in ref {ref!r}")
    return name


def _iter_files(root: Path):
    for p in sorted(root.rglob("*")):
        if not p.is_file():
            continue
        if any(part in _SKIP_PARTS for part in p.relative_to(root).parts):
            continue
        yield p


async def push_model(coordinator, name: str, model_dir: str | Path) -> dict:
    """Upload every file under ``model_dir`` and publish the manifest.
    Returns the manifest."""
    root = Path(model_dir)
    if not root.is_dir():
        raise FileNotFoundError(f"model dir {root} does not exist")
    files: dict[str, dict] = {}
    for p in _iter_files(root):
        rel = p.relative_to(root).as_posix()
        info = await coordinator.blob_put(
            _blob_key(name, rel), p, meta={"model": name, "rel": rel}
        )
        files[rel] = info
        log.info("pushed %s/%s (%d bytes)", name, rel, info["size"])
    if not files:
        raise FileNotFoundError(f"model dir {root} is empty")
    digest = hashlib.sha256(json.dumps(
        {r: f["sha256"] for r, f in sorted(files.items())},
        sort_keys=True, separators=(",", ":"),
    ).encode()).hexdigest()
    manifest = {"name": name, "files": files, "digest": digest,
                "pushed_at": time.time()}
    await coordinator.kv_put(manifest_key(name), manifest)
    return manifest


async def pull_model(coordinator, name: str,
                     cache_dir: Optional[str | Path] = None) -> Path:
    """Materialise model ``name`` locally; returns the directory.  A
    cache hit (same manifest digest) downloads nothing."""
    manifest = await coordinator.kv_get(manifest_key(name))
    if manifest is None:
        raise FileNotFoundError(
            f"model {name!r} not found in the coordinator store "
            f"(push it with `dynamo-tpu models push {name} <dir>`)"
        )
    cache = Path(cache_dir) if cache_dir else DEFAULT_CACHE
    cache.mkdir(parents=True, exist_ok=True)
    target = cache / f"{name.replace('/', '--')}-{manifest['digest'][:12]}"
    if target.exists():
        # the cache directory is content-addressed by manifest digest, but
        # the FILES inside are not self-verifying: a torn write or disk
        # fault leaves a directory that exists yet serves corrupt weights.
        # Verify per-file hashes against the manifest and re-pull only the
        # corrupt/missing ones.  Hashing runs in a worker thread — this
        # coroutine may share its loop with live serving.
        import asyncio

        for rel in manifest["files"]:
            _check_rel(name, rel)
        bad = await asyncio.to_thread(verify_files, target, manifest["files"])
        for rel in bad:
            log.warning("model %s: cached file %s corrupt/missing; re-pulling",
                        name, rel)
            dest = target / rel
            dest.parent.mkdir(parents=True, exist_ok=True)
            try:
                await coordinator.blob_get(_blob_key(name, rel), dest)
            except KeyError:
                legacy = f"models/{name}/{rel}"
                if legacy == _blob_key(name, rel):
                    raise
                await coordinator.blob_get(legacy, dest)
        if bad:
            still = await asyncio.to_thread(
                verify_files, target,
                {r: manifest["files"][r] for r in bad})
            if still:
                raise IOError(
                    f"model {name!r}: files {still} still corrupt after "
                    "re-pull (store itself damaged?)")
        return target
    tmp = Path(tempfile.mkdtemp(dir=cache, prefix=".pull-"))
    try:
        for rel, info in manifest["files"].items():
            _check_rel(name, rel)
            dest = tmp / rel
            dest.parent.mkdir(parents=True, exist_ok=True)
            try:
                got = await coordinator.blob_get(_blob_key(name, rel), dest)
            except KeyError:
                # stores written before name-quoting used the raw name
                legacy = f"models/{name}/{rel}"
                if legacy == _blob_key(name, rel):
                    raise
                got = await coordinator.blob_get(legacy, dest)
            if got["sha256"] != info["sha256"]:
                raise IOError(
                    f"blob models/{name}/{rel}: digest mismatch "
                    f"(store re-pushed mid-pull?) — retry the pull"
                )
        try:
            tmp.rename(target)  # atomic publish of the complete dir
        except OSError:
            if not target.exists():  # a real failure, not a lost race
                raise
        return target
    finally:
        if tmp.exists():
            import shutil

            shutil.rmtree(tmp, ignore_errors=True)


async def resolve_model(ref: str, coordinator=None,
                        cache_dir: Optional[str | Path] = None) -> str:
    """``dyn://models/<name>`` -> local cached path (pulling if needed);
    anything else passes through unchanged."""
    if not is_model_ref(ref):
        return ref
    if coordinator is None:
        raise ValueError(
            f"model ref {ref!r} needs a coordinator connection "
            "(--coordinator) to pull from"
        )
    return str(await pull_model(coordinator, _ref_name(ref), cache_dir))


def resolve_model_sync(ref: str, coordinator_url: Optional[str],
                       cache_dir: Optional[str | Path] = None) -> str:
    """Blocking :func:`resolve_model` for synchronous callers (the engine
    builders): the pull runs on a private event loop in a worker thread,
    safe whether or not a loop is already running in this thread.

    Caveat: this BLOCKS the calling thread — do not call it from the very
    event loop that serves the target coordinator (an in-process server
    could never answer the pull; production coordinators are separate
    processes, so worker engine builders are fine)."""
    if not is_model_ref(ref):
        return ref
    if not coordinator_url:
        raise ValueError(
            f"model ref {ref!r} needs a coordinator URL (--coordinator / "
            "DYNTPU_COORDINATOR) to pull from"
        )

    async def go() -> str:
        from dynamo_tpu.runtime.transports.coordinator import CoordinatorClient

        c = await CoordinatorClient(coordinator_url).connect()
        try:
            return await resolve_model(ref, c, cache_dir)
        finally:
            await c.close()

    import asyncio
    import concurrent.futures

    with concurrent.futures.ThreadPoolExecutor(1) as ex:
        return ex.submit(lambda: asyncio.run(go())).result()
