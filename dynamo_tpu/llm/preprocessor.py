"""OpenAIPreprocessor — OpenAI request → BackendInput (tokens + config).

Renders the model's chat template (jinja), tokenizes with the model card's
tokenizer, applies stop-condition and sampling defaults, and records
annotations (formatted_prompt, token_ids) on the request context.

Reference parity: lib/llm/src/preprocessor.rs:63-106 (OpenAIPreprocessor,
minijinja prompt formatting, annotations) and preprocessor/prompt/.
"""

from __future__ import annotations

from typing import Optional

from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.llm.openai import OpenAIError, ParsedRequest
from dynamo_tpu.llm.protocols import BackendInput
from dynamo_tpu.llm.tokenizer import TokenizerWrapper
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.pipeline import Operator

__all__ = ["OpenAIPreprocessor", "PromptFormatter"]

# a minimal fallback template for models that ship none (role-tagged lines)
DEFAULT_CHAT_TEMPLATE = (
    "{% for message in messages %}"
    "<|{{ message['role'] }}|> {{ message['content'] }}\n"
    "{% endfor %}"
    "<|assistant|>"
)


class PromptFormatter:
    """Jinja chat-template renderer (ref preprocessor/prompt/template/*)."""

    def __init__(self, template: Optional[str], bos_token: str = "", eos_token: str = ""):
        import jinja2
        from jinja2 import meta

        env = jinja2.Environment(trim_blocks=True, lstrip_blocks=True)
        env.globals["raise_exception"] = self._raise
        src = template or DEFAULT_CHAT_TEMPLATE
        self._template = env.from_string(src)
        # does the template actually consume a `tools` variable?  (A
        # substring probe misfires on templates merely mentioning the word;
        # the AST check is exact.)
        try:
            free = meta.find_undeclared_variables(env.parse(src))
            self.supports_tools = "tools" in free
        except Exception:
            self.supports_tools = False
        self._bos = bos_token
        self._eos = eos_token
        # Templates that emit BOS themselves must not ALSO get the
        # tokenizer's special-token insertion (double-BOS corrupts real
        # models).  Decided by a probe RENDER, not source inspection — a
        # substring test would misfire on '<s>' inside a hardcoded
        # '</s>', and a bare variable reference with an EMPTY bos string
        # renders nothing (the tokenizer must then keep inserting BOS).
        self.renders_bos = False
        if bos_token:
            sentinel = "\x00BOS\x00"
            try:
                probe = self._template.render(
                    messages=[{"role": "user", "content": "x"}],
                    add_generation_prompt=True,
                    bos_token=sentinel, eos_token=eos_token, tools=None,
                )
                self.renders_bos = (sentinel in probe
                                    or probe.startswith(bos_token))
            except Exception:
                pass  # template needs richer inputs: keep tokenizer BOS

    @staticmethod
    def _raise(msg: str):
        raise OpenAIError(f"chat template error: {msg}")

    def render(
        self,
        messages: list[dict],
        add_generation_prompt: bool = True,
        tools: Optional[list[dict]] = None,
    ) -> str:
        return self._template.render(
            messages=messages,
            add_generation_prompt=add_generation_prompt,
            bos_token=self._bos,
            eos_token=self._eos,
            tools=tools,
        )


class OpenAIPreprocessor(Operator):
    """Pipeline operator: Context[ParsedRequest] → Context[BackendInput]."""

    def __init__(self, card: ModelDeploymentCard, tokenizer: Optional[TokenizerWrapper] = None):
        self.card = card
        if tokenizer is None:
            if card.tokenizer_path is None:
                raise ValueError(f"model card {card.name} has no tokenizer")
            tokenizer = TokenizerWrapper.from_file(card.tokenizer_path)
        self.tokenizer = tokenizer
        # token STRINGS reach the template: real templates interpolate
        # {{ bos_token }}/{{ eos_token }}.  Card strings (from
        # tokenizer_config.json) win; ids resolve through the tokenizer
        # as fallback (GGUF cards carry only ids)
        bos = card.bos_token
        if bos is None and card.bos_token_id is not None:
            bos = self.tokenizer.id_to_token(card.bos_token_id)
        eos = card.eos_token
        if eos is None and card.eos_token_ids:
            eos = self.tokenizer.id_to_token(card.eos_token_ids[0])
        self.formatter = PromptFormatter(
            card.chat_template, bos_token=bos or "", eos_token=eos or "")

    async def forward(self, request: Context[ParsedRequest]) -> Context[BackendInput]:
        parsed = request.data
        if parsed.is_chat:
            messages = parsed.messages
            tools = parsed.tools if parsed.wants_tools else None
            if tools and not self.formatter.supports_tools:
                # template has no native tools support: inject a hermes-
                # format instruction block as a leading system message
                # (ref lib/llm/src/preprocessor/tools.rs schema injection)
                from dynamo_tpu.llm.tool_calls import render_tools_system

                messages = [
                    {
                        "role": "system",
                        "content": render_tools_system(
                            tools, parsed.tool_choice
                        ),
                    }
                ] + list(messages)
                tools = None
            if parsed.response_format == "json_schema" and parsed.json_schema:
                # the grammar guarantees *syntactic* JSON; steer the model
                # toward the schema's shape via an injected instruction
                # (same split as vLLM json_object vs outlines schema modes)
                import json as _json

                schema = parsed.json_schema.get("schema", {})
                messages = [
                    {
                        "role": "system",
                        "content": "Respond ONLY with a JSON value matching "
                        "this JSON Schema:\n"
                        + _json.dumps(schema, indent=2),
                    }
                ] + list(messages)
            prompt = self.formatter.render(messages, tools=tools)
            # a template that already emitted BOS must not get a second
            # one from the tokenizer's special-token post-processor
            token_ids = self.tokenizer.encode(
                prompt,
                add_special_tokens=not self.formatter.renders_bos,
            )
        elif parsed.prompt_token_ids is not None:
            prompt = None
            token_ids = list(parsed.prompt_token_ids)
        else:
            prompt = parsed.prompt
            token_ids = self.tokenizer.encode(prompt)

        if len(token_ids) >= self.card.context_length:
            raise OpenAIError(
                f"prompt ({len(token_ids)} tokens) exceeds model context length "
                f"({self.card.context_length})",
            )

        stops = parsed.stops
        # resolve stop strings that are single tokens into token-level stops
        for s in stops.stop:
            tid = self.tokenizer.token_to_id(s)
            if tid is not None and tid not in stops.stop_token_ids:
                stops.stop_token_ids.append(tid)

        inp = BackendInput(
            token_ids=token_ids,
            sampling=parsed.sampling,
            stops=stops,
            model=parsed.model,
        )
        request.annotations["prompt_tokens"] = len(token_ids)
        if "formatted_prompt" in parsed.annotations and prompt is not None:
            request.annotations["formatted_prompt"] = prompt
        if "token_ids" in parsed.annotations:
            request.annotations["token_ids"] = token_ids
        return request.map(inp)
