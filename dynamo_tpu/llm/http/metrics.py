"""HTTP service metrics in Prometheus text exposition format.

Reference parity: lib/llm/src/http/service/metrics.rs:36-46 (request
counters by model/endpoint/status, inflight gauge with RAII guard).
No prometheus client dependency — the text format is trivial to emit.

Every metric name comes from the committed registry
(``obs/metric_names.py``); the dtmet lint plane
(``analysis/metcheck.py``) statically extracts each ``# TYPE`` and
sample line below and audits the producer -> renderer -> scraper
chain, so a renamed or dropped series fails ``lint --metrics`` instead
of silently zeroing a bench column.
"""

from __future__ import annotations

import bisect
import time
from collections import defaultdict
from typing import Iterator

from dynamo_tpu.engine.counters import counters as prefill_counters
from dynamo_tpu.engine.counters import (kv_shard_counters, kv_stream_counters,
                                        lookahead_counters, persist_counters)
from dynamo_tpu.fault.counters import counters as fault_counters
from dynamo_tpu.obs.costs import transfer_costs
from dynamo_tpu.obs.metric_names import EngineMetric as EM
from dynamo_tpu.obs.metric_names import FaultMetric as FM
from dynamo_tpu.obs.metric_names import HttpMetric as HM
from dynamo_tpu.obs.metric_names import KvShardMetric as SHM
from dynamo_tpu.obs.metric_names import KvStreamMetric as STM
from dynamo_tpu.obs.metric_names import KvTransferMetric as KM
from dynamo_tpu.obs.metric_names import PerfMetric as PM
from dynamo_tpu.obs.perfmodel import perf_model
from dynamo_tpu.obs.timeline import PHASES, step_timeline

# seconds; TTFT and whole-request durations share one ladder
_BUCKETS = (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)
# finer ladder for per-token gaps — ITL sits well under the request
# ladder's first bound on warm decode
_ITL_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                0.25, 0.5, 1.0, 2.5)


class Histogram:
    """Minimal Prometheus histogram (cumulative buckets + sum + count)."""

    def __init__(self, buckets: tuple = _BUCKETS) -> None:
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)  # last = +Inf
        self.total = 0.0
        self.n = 0

    def observe(self, v: float) -> None:
        # first bucket with bound >= v; past the ladder = the +Inf slot
        self.counts[bisect.bisect_left(self.buckets, v)] += 1
        self.total += v
        self.n += 1

    def render(self, name: str, labels: str) -> Iterator[str]:
        cum = 0
        for b, c in zip(self.buckets, self.counts):
            cum += c
            yield f'{name}_bucket{{{labels},le="{b}"}} {cum}'
        yield f'{name}_bucket{{{labels},le="+Inf"}} {self.n}'
        yield f'{name}_sum{{{labels}}} {round(self.total, 6)}'
        yield f'{name}_count{{{labels}}} {self.n}'


class Metrics:
    def __init__(self) -> None:
        # (model, endpoint, status) -> count
        self.requests: dict[tuple[str, str, str], int] = defaultdict(int)
        # model -> inflight
        self.inflight: dict[str, int] = defaultdict(int)
        self.tokens_out: dict[str, int] = defaultdict(int)
        self.ttft: dict[str, Histogram] = defaultdict(Histogram)
        # per-token gap after the first token (the streaming-latency SLO
        # metric TTFT says nothing about); multi-token emissions spread
        # the emission gap evenly across their tokens
        self.itl: dict[str, Histogram] = defaultdict(
            lambda: Histogram(_ITL_BUCKETS))
        # submit -> slot admission wait inside the engine (from
        # EngineRequest.queue_wait_s via Context annotations)
        self.queue_wait: dict[str, Histogram] = defaultdict(Histogram)
        # duration keyed by (model, status): near-zero error/disconnect
        # requests must not pull the success series' percentiles down
        self.duration: dict[tuple[str, str], Histogram] = defaultdict(Histogram)
        # (model, priority) -> requests shed by admission control (429)
        self.shed: dict[tuple[str, str], int] = defaultdict(int)
        # live TTFT taps (seconds) — the admission controller subscribes
        # here so its deadline estimates track the serving latency plane
        self.ttft_listeners: list = []

    def guard(self, model: str, endpoint: str) -> "InflightGuard":
        return InflightGuard(self, model, endpoint)

    def render(self) -> str:
        lines: list[str] = []
        lines.append(f"# TYPE {HM.REQUESTS_TOTAL} counter")
        for (model, endpoint, status), n in sorted(self.requests.items()):
            lines.append(
                f'{HM.REQUESTS_TOTAL}{{model="{model}",endpoint="{endpoint}",status="{status}"}} {n}'
            )
        lines.append(f"# TYPE {HM.INFLIGHT_REQUESTS} gauge")
        for model, n in sorted(self.inflight.items()):
            lines.append(f'{HM.INFLIGHT_REQUESTS}{{model="{model}"}} {n}')
        lines.append(f"# TYPE {HM.OUTPUT_TOKENS_TOTAL} counter")
        for model, n in sorted(self.tokens_out.items()):
            lines.append(f'{HM.OUTPUT_TOKENS_TOTAL}{{model="{model}"}} {n}')
        lines.append(f"# TYPE {HM.ADMISSION_SHED_TOTAL} counter")
        for (model, priority), n in sorted(self.shed.items()):
            lines.append(
                f'{HM.ADMISSION_SHED_TOTAL}{{model="{model}",priority="{priority}"}} {n}'
            )
        lines.append(f"# TYPE {HM.TTFT_SECONDS} histogram")
        for model, h in sorted(self.ttft.items()):
            lines.extend(h.render(HM.TTFT_SECONDS, f'model="{model}"'))
        lines.append(f"# TYPE {HM.INTER_TOKEN_SECONDS} histogram")
        for model, h in sorted(self.itl.items()):
            lines.extend(h.render(HM.INTER_TOKEN_SECONDS,
                                  f'model="{model}"'))
        lines.append(f"# TYPE {HM.QUEUE_WAIT_SECONDS} histogram")
        for model, h in sorted(self.queue_wait.items()):
            lines.extend(h.render(HM.QUEUE_WAIT_SECONDS,
                                  f'model="{model}"'))
        lines.append(f"# TYPE {HM.REQUEST_SECONDS} histogram")
        for (model, status), h in sorted(self.duration.items()):
            lines.extend(h.render(
                HM.REQUEST_SECONDS,
                f'model="{model}",status="{status}"'))
        # fault plane (process-global): migrations performed, drains live,
        # instances currently suspect per the health probes
        lines.append(f"# TYPE {FM.MIGRATIONS_TOTAL} counter")
        lines.append(f"{FM.MIGRATIONS_TOTAL} "
                     f"{fault_counters.migrations_total}")
        lines.append(f"# TYPE {FM.DRAINS_IN_PROGRESS} gauge")
        lines.append(f"{FM.DRAINS_IN_PROGRESS} "
                     f"{fault_counters.drains_in_progress}")
        lines.append(f"# TYPE {FM.SUSPECT_INSTANCES} gauge")
        lines.append(f"{FM.SUSPECT_INSTANCES} "
                     f"{fault_counters.suspect_instances()}")
        # prefill batching (process-global, like the fault plane): how
        # well the token-budget ragged prefill packs the device
        lines.append(f"# TYPE {EM.PREFILL_DISPATCHES_TOTAL} counter")
        lines.append(f"{EM.PREFILL_DISPATCHES_TOTAL} "
                     f"{prefill_counters.dispatches_total}")
        lines.append(f"# TYPE {EM.PREFILL_TOKENS_TOTAL} counter")
        lines.append(f"{EM.PREFILL_TOKENS_TOTAL} "
                     f"{prefill_counters.tokens_total}")
        lines.append(f"# TYPE {EM.PREFILL_BATCH_OCCUPANCY} gauge")
        lines.append(f"{EM.PREFILL_BATCH_OCCUPANCY} "
                     f"{round(prefill_counters.batch_occupancy, 6)}")
        lines.append(f"# TYPE {EM.PREFILL_BUDGET_UTILIZATION} gauge")
        lines.append(f"{EM.PREFILL_BUDGET_UTILIZATION} "
                     f"{round(prefill_counters.budget_utilization, 6)}")
        # unified mixed prefill+decode dispatch: how many turns collapsed
        # the two-dispatch interleave into one, and what shared the axis
        lines.append(f"# TYPE {EM.UNIFIED_DISPATCHES_TOTAL} counter")
        lines.append(f"{EM.UNIFIED_DISPATCHES_TOTAL} "
                     f"{prefill_counters.unified_dispatches_total}")
        lines.append(f"# TYPE {EM.UNIFIED_DECODE_ROWS_TOTAL} counter")
        lines.append(f"{EM.UNIFIED_DECODE_ROWS_TOTAL} "
                     f"{prefill_counters.unified_decode_rows_total}")
        lines.append(f"# TYPE {EM.UNIFIED_PREFILL_TOKENS_TOTAL} counter")
        lines.append(f"{EM.UNIFIED_PREFILL_TOKENS_TOTAL} "
                     f"{prefill_counters.unified_prefill_tokens_total}")
        lines.append(f"# TYPE {EM.UNIFIED_BUDGET_UTILIZATION} gauge")
        lines.append(f"{EM.UNIFIED_BUDGET_UTILIZATION} "
                     f"{round(prefill_counters.unified_budget_utilization, 6)}")
        # double-buffered dispatch (lookahead scheduler): fused bursts,
        # per-row prediction hit/mispredict split, speculative next-turn
        # prebuild commits/flushes, and the depth of the last burst
        lc = lookahead_counters
        lines.append(f"# TYPE {EM.LOOKAHEAD_BURSTS_TOTAL} counter")
        lines.append(f"{EM.LOOKAHEAD_BURSTS_TOTAL} {lc.bursts_total}")
        lines.append(f"# TYPE {EM.LOOKAHEAD_HITS_TOTAL} counter")
        lines.append(f"{EM.LOOKAHEAD_HITS_TOTAL} {lc.hits_total}")
        lines.append(f"# TYPE {EM.LOOKAHEAD_MISPREDICTS_TOTAL} counter")
        lines.append(f"{EM.LOOKAHEAD_MISPREDICTS_TOTAL} "
                     f"{lc.mispredicts_total}")
        lines.append(f"# TYPE {EM.LOOKAHEAD_COMMITS_TOTAL} counter")
        lines.append(f"{EM.LOOKAHEAD_COMMITS_TOTAL} {lc.commits_total}")
        lines.append(f"# TYPE {EM.LOOKAHEAD_FLUSHES_TOTAL} counter")
        lines.append(f"{EM.LOOKAHEAD_FLUSHES_TOTAL} {lc.flushes_total}")
        lines.append(f"# TYPE {EM.LOOKAHEAD_DISPATCH_DEPTH} gauge")
        lines.append(f"{EM.LOOKAHEAD_DISPATCH_DEPTH} {lc.dispatch_depth}")
        # persistent prefix-cache tier (llm/kv/persist.py): blocks/tokens
        # restored from disk instead of re-prefilled, spill volume, and
        # the store's current footprint
        lines.append(f"# TYPE {EM.PERSIST_HITS_TOTAL} counter")
        lines.append(f"{EM.PERSIST_HITS_TOTAL} "
                     f"{persist_counters.hits_total}")
        lines.append(f"# TYPE {EM.PERSIST_MISSES_TOTAL} counter")
        lines.append(f"{EM.PERSIST_MISSES_TOTAL} "
                     f"{persist_counters.misses_total}")
        lines.append(f"# TYPE {EM.PERSIST_RESTORED_TOKENS_TOTAL} counter")
        lines.append(f"{EM.PERSIST_RESTORED_TOKENS_TOTAL} "
                     f"{persist_counters.restored_tokens_total}")
        lines.append(f"# TYPE {EM.PERSIST_SPILL_BYTES_TOTAL} counter")
        lines.append(f"{EM.PERSIST_SPILL_BYTES_TOTAL} "
                     f"{persist_counters.spill_bytes_total}")
        lines.append(f"# TYPE {EM.PERSIST_RESIDENT_BYTES} gauge")
        lines.append(f"{EM.PERSIST_RESIDENT_BYTES} "
                     f"{persist_counters.resident_bytes}")
        # streamed KV handoff (llm/kv/stream.py): layer frames shipped
        # while prefill still computed, and how often the stream fell
        # back to the blocking whole-cache push
        lines.append(f"# TYPE {STM.SESSIONS_TOTAL} counter")
        lines.append(f"{STM.SESSIONS_TOTAL} "
                     f"{kv_stream_counters.sessions_total}")
        lines.append(f"# TYPE {STM.LAYERS_SENT_TOTAL} counter")
        lines.append(f"{STM.LAYERS_SENT_TOTAL} "
                     f"{kv_stream_counters.layers_sent_total}")
        lines.append(f"# TYPE {STM.BYTES_TOTAL} counter")
        lines.append(f"{STM.BYTES_TOTAL} "
                     f"{kv_stream_counters.bytes_total}")
        lines.append(f"# TYPE {STM.FALLBACKS_TOTAL} counter")
        lines.append(f"{STM.FALLBACKS_TOTAL} "
                     f"{kv_stream_counters.fallbacks_total}")
        lines.append(f"# TYPE {STM.OVERLAP_RATIO} gauge")
        lines.append(f"{STM.OVERLAP_RATIO} "
                     f"{round(kv_stream_counters.overlap_ratio, 6)}")
        # sharded control plane (llm/kv_router/shards/): scatter rounds,
        # partial gathers (a shard missed its deadline or answered behind
        # the generation fence), fan-out latency, per-shard index gauges
        sc = kv_shard_counters
        lines.append(f"# TYPE {SHM.SCATTERS_TOTAL} counter")
        lines.append(f"{SHM.SCATTERS_TOTAL} {sc.scatters_total}")
        lines.append(f"# TYPE {SHM.GATHER_PARTIAL_TOTAL} counter")
        lines.append(f"{SHM.GATHER_PARTIAL_TOTAL} "
                     f"{sc.gather_partial_total}")
        lines.append(f"# TYPE {SHM.GENERATION} gauge")
        lines.append(f"{SHM.GENERATION} {sc.generation}")
        lines.append(f"# TYPE {SHM.LAST_FAN_OUT} gauge")
        lines.append(f"{SHM.LAST_FAN_OUT} {sc.last_fan_out}")
        lines.append(f"# TYPE {SHM.FANOUT_LATENCY_MS} histogram")
        for edge, count in zip(sc.FANOUT_BUCKETS_MS,
                               sc.fanout_bucket_counts):
            lines.append(
                f'{SHM.FANOUT_LATENCY_MS}_bucket{{le="{edge}"}} {count}')
        lines.append(f'{SHM.FANOUT_LATENCY_MS}_bucket{{le="+Inf"}} '
                     f"{sc.scatters_total}")
        lines.append(f"{SHM.FANOUT_LATENCY_MS}_sum "
                     f"{round(sc.fanout_ms_sum, 6)}")
        lines.append(f"{SHM.FANOUT_LATENCY_MS}_count "
                     f"{sc.scatters_total}")
        if sc.index_blocks:
            lines.append(f"# TYPE {SHM.INDEX_BLOCKS} gauge")
            for shard_id, blocks in sorted(sc.index_blocks.items()):
                lines.append(
                    f'{SHM.INDEX_BLOCKS}{{shard="{shard_id}"}} {blocks}')
            lines.append(f"# TYPE {SHM.RESIDENT_KEYS} gauge")
            for shard_id, keys in sorted(sc.resident_keys.items()):
                lines.append(
                    f'{SHM.RESIDENT_KEYS}{{shard="{shard_id}"}} {keys}')
        # dtspan engine step timeline: per-phase wall attribution plus the
        # headline host bubble (ROADMAP item 3's committed before-number)
        tl = step_timeline.snapshot()
        lines.append(f"# TYPE {EM.STEPS_TOTAL} counter")
        lines.append(f"{EM.STEPS_TOTAL} {tl['steps_total']}")
        lines.append(f"# TYPE {EM.BUSY_STEPS_TOTAL} counter")
        lines.append(f"{EM.BUSY_STEPS_TOTAL} {tl['busy_steps_total']}")
        lines.append(f"# TYPE {EM.STEP_WALL_SECONDS_TOTAL} counter")
        lines.append(f"{EM.STEP_WALL_SECONDS_TOTAL} "
                     f"{round(tl['wall_seconds_total'], 6)}")
        lines.append(f"# TYPE {EM.STEP_PHASE_SECONDS_TOTAL} counter")
        for p in PHASES:
            lines.append(
                f'{EM.STEP_PHASE_SECONDS_TOTAL}{{phase="{p}"}} '
                f"{round(tl['phases'][p], 6)}")
        lines.append(f"# TYPE {EM.HOST_GAP_MS_PER_TURN} gauge")
        lines.append(f"{EM.HOST_GAP_MS_PER_TURN} "
                     f"{round(tl['host_gap_ms_per_turn'], 6)}")
        # smoothed per-step companions to the lifetime means above — the
        # signal a live dashboard watches while a run warms up
        lines.append(f"# TYPE {EM.STEP_WALL_MS_EWMA} gauge")
        lines.append(f"{EM.STEP_WALL_MS_EWMA} "
                     f"{round(tl['ewma_wall_ms'], 6)}")
        lines.append(f"# TYPE {EM.HOST_GAP_MS_EWMA} gauge")
        lines.append(f"{EM.HOST_GAP_MS_EWMA} "
                     f"{round(tl['ewma_host_gap_ms'], 6)}")
        # measured KV-transfer costs per (src, dst, path) edge
        costs = transfer_costs.snapshot()
        if costs:
            for name, typ in ((KM.CALLS_TOTAL, "counter"),
                              (KM.BYTES_TOTAL, "counter"),
                              (KM.SECONDS_TOTAL, "counter"),
                              (KM.MBPS, "gauge"),
                              (KM.LATENCY_MS, "gauge")):
                lines.append(f"# TYPE {name} {typ}")
                for (src, dst, path), e in sorted(costs.items()):
                    labels = f'src="{src}",dst="{dst}",path="{path}"'
                    val = {
                        KM.CALLS_TOTAL: e["calls"],
                        KM.BYTES_TOTAL: e["bytes"],
                        KM.SECONDS_TOTAL: round(e["seconds"], 6),
                        KM.MBPS: round(e["ewma_mbps"], 6),
                        KM.LATENCY_MS: round(e["ewma_latency_s"] * 1e3, 6),
                    }[name]
                    lines.append(f"{name}{{{labels}}} {val}")
        # dtperf plane: roofline-predicted step latency per (entrypoint,
        # config) from the committed perf manifest (JSON-only read — no
        # tracing happens here), plus the runtime predicted-vs-measured
        # reconciliation per live dispatch kind
        try:
            from dynamo_tpu.analysis.perfcheck import manifest_predictions

            rows = manifest_predictions()
        except Exception:
            rows = []
        if rows:
            lines.append(f"# TYPE {PM.PREDICTED_STEP_MS} gauge")
            for r in rows:
                labels = (f'entrypoint="{r["entrypoint"]}",'
                          f'config="{r["config"]}",'
                          f'signature="{r["signature"]}",'
                          f'bound="{r["bound"]}"')
                lines.append(
                    f"{PM.PREDICTED_STEP_MS}{{{labels}}} "
                    f"{r['predicted_ms']}")
        recon = perf_model.reconcile()
        if recon:
            for name, field, typ in (
                    (PM.PREDICTED_DISPATCH_MS, "predicted_ms", "gauge"),
                    (PM.MEASURED_DISPATCH_MS, "measured_ms", "gauge"),
                    (PM.DISPATCHES_TOTAL, "dispatches", "counter"),
                    (PM.MODEL_ERROR_RATIO, "error_ratio", "gauge")):
                rendered = [r for r in recon if r.get(field) is not None]
                if not rendered:
                    continue
                lines.append(f"# TYPE {name} {typ}")
                for r in rendered:
                    lines.append(
                        f'{name}{{kind="{r["kind"]}"}} {r[field]}')
        return "\n".join(lines) + "\n"


class InflightGuard:
    """Counts a request as inflight until closed; records final status."""

    def __init__(self, metrics: Metrics, model: str, endpoint: str):
        self._m = metrics
        self.model = model
        self.endpoint = endpoint
        self._status = "error"
        self._t0 = time.monotonic()
        self._saw_first = False
        self._last_tok = 0.0
        self._m.inflight[model] += 1

    def first_token(self) -> None:
        """Record TTFT once, at the first generated-token emission."""
        if not self._saw_first:
            self._saw_first = True
            now = time.monotonic()
            self._last_tok = now
            dt = now - self._t0
            self._m.ttft[self.model].observe(dt)
            for listener in self._m.ttft_listeners:
                listener(dt)

    def tokens(self, k: int) -> None:
        """Record a k-token emission: TTFT on the first, then the
        emission gap spread as k equal inter-token observations (so the
        histogram count tracks tokens, and multi-step decode bursts
        don't read as one slow token)."""
        if k <= 0:
            return
        if not self._saw_first:
            self.first_token()
            k -= 1
            if k <= 0:
                return
        now = time.monotonic()
        per = (now - self._last_tok) / k
        h = self._m.itl[self.model]
        for _ in range(k):
            h.observe(per)
        self._last_tok = now

    def ok(self) -> None:
        self._status = "success"

    def status(self, s: str) -> None:
        self._status = s

    def close(self) -> None:
        self._m.inflight[self.model] -= 1
        self._m.requests[(self.model, self.endpoint, self._status)] += 1
        self._m.duration[(self.model, self._status)].observe(
            time.monotonic() - self._t0)
