"""HTTP service metrics in Prometheus text exposition format.

Reference parity: lib/llm/src/http/service/metrics.rs:36-46 (request
counters by model/endpoint/status, inflight gauge with RAII guard).
No prometheus client dependency — the text format is trivial to emit.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Iterator

PREFIX = "dynamo_tpu_http_service"


class Metrics:
    def __init__(self) -> None:
        # (model, endpoint, status) -> count
        self.requests: dict[tuple[str, str, str], int] = defaultdict(int)
        # model -> inflight
        self.inflight: dict[str, int] = defaultdict(int)
        self.tokens_out: dict[str, int] = defaultdict(int)

    def guard(self, model: str, endpoint: str) -> "InflightGuard":
        return InflightGuard(self, model, endpoint)

    def render(self) -> str:
        lines = [
            f"# TYPE {PREFIX}_requests_total counter",
        ]
        for (model, endpoint, status), n in sorted(self.requests.items()):
            lines.append(
                f'{PREFIX}_requests_total{{model="{model}",endpoint="{endpoint}",status="{status}"}} {n}'
            )
        lines.append(f"# TYPE {PREFIX}_inflight_requests gauge")
        for model, n in sorted(self.inflight.items()):
            lines.append(f'{PREFIX}_inflight_requests{{model="{model}"}} {n}')
        lines.append(f"# TYPE {PREFIX}_output_tokens_total counter")
        for model, n in sorted(self.tokens_out.items()):
            lines.append(f'{PREFIX}_output_tokens_total{{model="{model}"}} {n}')
        return "\n".join(lines) + "\n"


class InflightGuard:
    """Counts a request as inflight until closed; records final status."""

    def __init__(self, metrics: Metrics, model: str, endpoint: str):
        self._m = metrics
        self.model = model
        self.endpoint = endpoint
        self._status = "error"
        self._m.inflight[model] += 1

    def ok(self) -> None:
        self._status = "success"

    def status(self, s: str) -> None:
        self._status = s

    def close(self) -> None:
        self._m.inflight[self.model] -= 1
        self._m.requests[(self.model, self.endpoint, self._status)] += 1
