"""HTTP service metrics in Prometheus text exposition format.

Reference parity: lib/llm/src/http/service/metrics.rs:36-46 (request
counters by model/endpoint/status, inflight gauge with RAII guard).
No prometheus client dependency — the text format is trivial to emit.
"""

from __future__ import annotations

import bisect
import time
from collections import defaultdict
from typing import Iterator

from dynamo_tpu.engine.counters import counters as prefill_counters
from dynamo_tpu.engine.counters import (kv_shard_counters, kv_stream_counters,
                                        lookahead_counters, persist_counters)
from dynamo_tpu.fault.counters import counters as fault_counters
from dynamo_tpu.obs.costs import transfer_costs
from dynamo_tpu.obs.perfmodel import perf_model
from dynamo_tpu.obs.timeline import PHASES, step_timeline

PREFIX = "dynamo_tpu_http_service"
FAULT_PREFIX = "dynamo_tpu_fault"
ENGINE_PREFIX = "dynamo_tpu_engine"
KV_PREFIX = "dynamo_tpu_kv_transfer"
STREAM_PREFIX = "dynamo_tpu_kv_stream"
SHARD_PREFIX = "dynamo_tpu_kv_shard"
PERF_PREFIX = "dynamo_tpu_perf"

# seconds; TTFT and whole-request durations share one ladder
_BUCKETS = (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)
# finer ladder for per-token gaps — ITL sits well under the request
# ladder's first bound on warm decode
_ITL_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                0.25, 0.5, 1.0, 2.5)


class Histogram:
    """Minimal Prometheus histogram (cumulative buckets + sum + count)."""

    def __init__(self, buckets: tuple = _BUCKETS) -> None:
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)  # last = +Inf
        self.total = 0.0
        self.n = 0

    def observe(self, v: float) -> None:
        # first bucket with bound >= v; past the ladder = the +Inf slot
        self.counts[bisect.bisect_left(self.buckets, v)] += 1
        self.total += v
        self.n += 1

    def render(self, name: str, labels: str) -> Iterator[str]:
        cum = 0
        for b, c in zip(self.buckets, self.counts):
            cum += c
            yield f'{name}_bucket{{{labels},le="{b}"}} {cum}'
        yield f'{name}_bucket{{{labels},le="+Inf"}} {self.n}'
        yield f'{name}_sum{{{labels}}} {round(self.total, 6)}'
        yield f'{name}_count{{{labels}}} {self.n}'


class Metrics:
    def __init__(self) -> None:
        # (model, endpoint, status) -> count
        self.requests: dict[tuple[str, str, str], int] = defaultdict(int)
        # model -> inflight
        self.inflight: dict[str, int] = defaultdict(int)
        self.tokens_out: dict[str, int] = defaultdict(int)
        self.ttft: dict[str, Histogram] = defaultdict(Histogram)
        # per-token gap after the first token (the streaming-latency SLO
        # metric TTFT says nothing about); multi-token emissions spread
        # the emission gap evenly across their tokens
        self.itl: dict[str, Histogram] = defaultdict(
            lambda: Histogram(_ITL_BUCKETS))
        # submit -> slot admission wait inside the engine (from
        # EngineRequest.queue_wait_s via Context annotations)
        self.queue_wait: dict[str, Histogram] = defaultdict(Histogram)
        # duration keyed by (model, status): near-zero error/disconnect
        # requests must not pull the success series' percentiles down
        self.duration: dict[tuple[str, str], Histogram] = defaultdict(Histogram)
        # (model, priority) -> requests shed by admission control (429)
        self.shed: dict[tuple[str, str], int] = defaultdict(int)
        # live TTFT taps (seconds) — the admission controller subscribes
        # here so its deadline estimates track the serving latency plane
        self.ttft_listeners: list = []

    def guard(self, model: str, endpoint: str) -> "InflightGuard":
        return InflightGuard(self, model, endpoint)

    def render(self) -> str:
        lines = [
            f"# TYPE {PREFIX}_requests_total counter",
        ]
        for (model, endpoint, status), n in sorted(self.requests.items()):
            lines.append(
                f'{PREFIX}_requests_total{{model="{model}",endpoint="{endpoint}",status="{status}"}} {n}'
            )
        lines.append(f"# TYPE {PREFIX}_inflight_requests gauge")
        for model, n in sorted(self.inflight.items()):
            lines.append(f'{PREFIX}_inflight_requests{{model="{model}"}} {n}')
        lines.append(f"# TYPE {PREFIX}_output_tokens_total counter")
        for model, n in sorted(self.tokens_out.items()):
            lines.append(f'{PREFIX}_output_tokens_total{{model="{model}"}} {n}')
        lines.append(f"# TYPE {PREFIX}_admission_shed_total counter")
        for (model, priority), n in sorted(self.shed.items()):
            lines.append(
                f'{PREFIX}_admission_shed_total{{model="{model}",priority="{priority}"}} {n}'
            )
        lines.append(f"# TYPE {PREFIX}_ttft_seconds histogram")
        for model, h in sorted(self.ttft.items()):
            lines.extend(h.render(f"{PREFIX}_ttft_seconds",
                                  f'model="{model}"'))
        lines.append(f"# TYPE {PREFIX}_inter_token_seconds histogram")
        for model, h in sorted(self.itl.items()):
            lines.extend(h.render(f"{PREFIX}_inter_token_seconds",
                                  f'model="{model}"'))
        lines.append(f"# TYPE {PREFIX}_queue_wait_seconds histogram")
        for model, h in sorted(self.queue_wait.items()):
            lines.extend(h.render(f"{PREFIX}_queue_wait_seconds",
                                  f'model="{model}"'))
        lines.append(f"# TYPE {PREFIX}_request_seconds histogram")
        for (model, status), h in sorted(self.duration.items()):
            lines.extend(h.render(
                f"{PREFIX}_request_seconds",
                f'model="{model}",status="{status}"'))
        # fault plane (process-global): migrations performed, drains live,
        # instances currently suspect per the health probes
        lines.append(f"# TYPE {FAULT_PREFIX}_migrations_total counter")
        lines.append(f"{FAULT_PREFIX}_migrations_total "
                     f"{fault_counters.migrations_total}")
        lines.append(f"# TYPE {FAULT_PREFIX}_drains_in_progress gauge")
        lines.append(f"{FAULT_PREFIX}_drains_in_progress "
                     f"{fault_counters.drains_in_progress}")
        lines.append(f"# TYPE {FAULT_PREFIX}_suspect_instances gauge")
        lines.append(f"{FAULT_PREFIX}_suspect_instances "
                     f"{fault_counters.suspect_instances()}")
        # prefill batching (process-global, like the fault plane): how
        # well the token-budget ragged prefill packs the device
        lines.append(f"# TYPE {ENGINE_PREFIX}_prefill_dispatches_total counter")
        lines.append(f"{ENGINE_PREFIX}_prefill_dispatches_total "
                     f"{prefill_counters.dispatches_total}")
        lines.append(f"# TYPE {ENGINE_PREFIX}_prefill_tokens_total counter")
        lines.append(f"{ENGINE_PREFIX}_prefill_tokens_total "
                     f"{prefill_counters.tokens_total}")
        lines.append(f"# TYPE {ENGINE_PREFIX}_prefill_batch_occupancy gauge")
        lines.append(f"{ENGINE_PREFIX}_prefill_batch_occupancy "
                     f"{round(prefill_counters.batch_occupancy, 6)}")
        lines.append(f"# TYPE {ENGINE_PREFIX}_prefill_budget_utilization gauge")
        lines.append(f"{ENGINE_PREFIX}_prefill_budget_utilization "
                     f"{round(prefill_counters.budget_utilization, 6)}")
        # unified mixed prefill+decode dispatch: how many turns collapsed
        # the two-dispatch interleave into one, and what shared the axis
        lines.append(f"# TYPE {ENGINE_PREFIX}_unified_dispatches_total counter")
        lines.append(f"{ENGINE_PREFIX}_unified_dispatches_total "
                     f"{prefill_counters.unified_dispatches_total}")
        lines.append(f"# TYPE {ENGINE_PREFIX}_unified_decode_rows counter")
        lines.append(f"{ENGINE_PREFIX}_unified_decode_rows "
                     f"{prefill_counters.unified_decode_rows_total}")
        lines.append(f"# TYPE {ENGINE_PREFIX}_unified_prefill_tokens counter")
        lines.append(f"{ENGINE_PREFIX}_unified_prefill_tokens "
                     f"{prefill_counters.unified_prefill_tokens_total}")
        lines.append(f"# TYPE {ENGINE_PREFIX}_unified_budget_utilization gauge")
        lines.append(f"{ENGINE_PREFIX}_unified_budget_utilization "
                     f"{round(prefill_counters.unified_budget_utilization, 6)}")
        # double-buffered dispatch (lookahead scheduler): fused bursts,
        # per-row prediction hit/mispredict split, speculative next-turn
        # prebuild commits/flushes, and the depth of the last burst
        lc = lookahead_counters
        lines.append(f"# TYPE {ENGINE_PREFIX}_lookahead_bursts_total counter")
        lines.append(f"{ENGINE_PREFIX}_lookahead_bursts_total "
                     f"{lc.bursts_total}")
        lines.append(f"# TYPE {ENGINE_PREFIX}_lookahead_hits_total counter")
        lines.append(f"{ENGINE_PREFIX}_lookahead_hits_total "
                     f"{lc.hits_total}")
        lines.append(f"# TYPE {ENGINE_PREFIX}_lookahead_mispredicts_total "
                     f"counter")
        lines.append(f"{ENGINE_PREFIX}_lookahead_mispredicts_total "
                     f"{lc.mispredicts_total}")
        lines.append(f"# TYPE {ENGINE_PREFIX}_lookahead_commits_total counter")
        lines.append(f"{ENGINE_PREFIX}_lookahead_commits_total "
                     f"{lc.commits_total}")
        lines.append(f"# TYPE {ENGINE_PREFIX}_lookahead_flushes_total counter")
        lines.append(f"{ENGINE_PREFIX}_lookahead_flushes_total "
                     f"{lc.flushes_total}")
        lines.append(f"# TYPE {ENGINE_PREFIX}_lookahead_dispatch_depth gauge")
        lines.append(f"{ENGINE_PREFIX}_lookahead_dispatch_depth "
                     f"{lc.dispatch_depth}")
        # persistent prefix-cache tier (llm/kv/persist.py): blocks/tokens
        # restored from disk instead of re-prefilled, spill volume, and
        # the store's current footprint
        lines.append(f"# TYPE {ENGINE_PREFIX}_persist_hits_total counter")
        lines.append(f"{ENGINE_PREFIX}_persist_hits_total "
                     f"{persist_counters.hits_total}")
        lines.append(f"# TYPE {ENGINE_PREFIX}_persist_misses_total counter")
        lines.append(f"{ENGINE_PREFIX}_persist_misses_total "
                     f"{persist_counters.misses_total}")
        lines.append(f"# TYPE {ENGINE_PREFIX}_persist_restored_tokens_total counter")
        lines.append(f"{ENGINE_PREFIX}_persist_restored_tokens_total "
                     f"{persist_counters.restored_tokens_total}")
        lines.append(f"# TYPE {ENGINE_PREFIX}_persist_spill_bytes_total counter")
        lines.append(f"{ENGINE_PREFIX}_persist_spill_bytes_total "
                     f"{persist_counters.spill_bytes_total}")
        lines.append(f"# TYPE {ENGINE_PREFIX}_persist_resident_bytes gauge")
        lines.append(f"{ENGINE_PREFIX}_persist_resident_bytes "
                     f"{persist_counters.resident_bytes}")
        # streamed KV handoff (llm/kv/stream.py): layer frames shipped
        # while prefill still computed, and how often the stream fell
        # back to the blocking whole-cache push
        lines.append(f"# TYPE {STREAM_PREFIX}_sessions_total counter")
        lines.append(f"{STREAM_PREFIX}_sessions_total "
                     f"{kv_stream_counters.sessions_total}")
        lines.append(f"# TYPE {STREAM_PREFIX}_layers_sent_total counter")
        lines.append(f"{STREAM_PREFIX}_layers_sent_total "
                     f"{kv_stream_counters.layers_sent_total}")
        lines.append(f"# TYPE {STREAM_PREFIX}_bytes_total counter")
        lines.append(f"{STREAM_PREFIX}_bytes_total "
                     f"{kv_stream_counters.bytes_total}")
        lines.append(f"# TYPE {STREAM_PREFIX}_fallbacks_total counter")
        lines.append(f"{STREAM_PREFIX}_fallbacks_total "
                     f"{kv_stream_counters.fallbacks_total}")
        lines.append(f"# TYPE {STREAM_PREFIX}_overlap_ratio gauge")
        lines.append(f"{STREAM_PREFIX}_overlap_ratio "
                     f"{round(kv_stream_counters.overlap_ratio, 6)}")
        # sharded control plane (llm/kv_router/shards/): scatter rounds,
        # partial gathers (a shard missed its deadline or answered behind
        # the generation fence), fan-out latency, per-shard index gauges
        sc = kv_shard_counters
        lines.append(f"# TYPE {SHARD_PREFIX}_scatters_total counter")
        lines.append(f"{SHARD_PREFIX}_scatters_total {sc.scatters_total}")
        lines.append(f"# TYPE {SHARD_PREFIX}_gather_partial_total counter")
        lines.append(f"{SHARD_PREFIX}_gather_partial_total "
                     f"{sc.gather_partial_total}")
        lines.append(f"# TYPE {SHARD_PREFIX}_generation gauge")
        lines.append(f"{SHARD_PREFIX}_generation {sc.generation}")
        lines.append(f"# TYPE {SHARD_PREFIX}_fanout_latency_ms histogram")
        for edge, count in zip(sc.FANOUT_BUCKETS_MS,
                               sc.fanout_bucket_counts):
            lines.append(
                f'{SHARD_PREFIX}_fanout_latency_ms_bucket{{le="{edge}"}} '
                f"{count}")
        lines.append(f'{SHARD_PREFIX}_fanout_latency_ms_bucket{{le="+Inf"}} '
                     f"{sc.scatters_total}")
        lines.append(f"{SHARD_PREFIX}_fanout_latency_ms_sum "
                     f"{round(sc.fanout_ms_sum, 6)}")
        lines.append(f"{SHARD_PREFIX}_fanout_latency_ms_count "
                     f"{sc.scatters_total}")
        if sc.index_blocks:
            lines.append(f"# TYPE {SHARD_PREFIX}_index_blocks gauge")
            for shard_id, blocks in sorted(sc.index_blocks.items()):
                lines.append(
                    f'{SHARD_PREFIX}_index_blocks{{shard="{shard_id}"}} '
                    f"{blocks}")
            lines.append(f"# TYPE {SHARD_PREFIX}_resident_keys gauge")
            for shard_id, keys in sorted(sc.resident_keys.items()):
                lines.append(
                    f'{SHARD_PREFIX}_resident_keys{{shard="{shard_id}"}} '
                    f"{keys}")
        # dtspan engine step timeline: per-phase wall attribution plus the
        # headline host bubble (ROADMAP item 3's committed before-number)
        tl = step_timeline.snapshot()
        lines.append(f"# TYPE {ENGINE_PREFIX}_steps_total counter")
        lines.append(f"{ENGINE_PREFIX}_steps_total {tl['steps_total']}")
        lines.append(f"# TYPE {ENGINE_PREFIX}_busy_steps_total counter")
        lines.append(f"{ENGINE_PREFIX}_busy_steps_total "
                     f"{tl['busy_steps_total']}")
        lines.append(f"# TYPE {ENGINE_PREFIX}_step_wall_seconds_total counter")
        lines.append(f"{ENGINE_PREFIX}_step_wall_seconds_total "
                     f"{round(tl['wall_seconds_total'], 6)}")
        lines.append(f"# TYPE {ENGINE_PREFIX}_step_phase_seconds_total counter")
        for p in PHASES:
            lines.append(
                f'{ENGINE_PREFIX}_step_phase_seconds_total{{phase="{p}"}} '
                f"{round(tl['phases'][p], 6)}")
        lines.append(f"# TYPE {ENGINE_PREFIX}_host_gap_ms_per_turn gauge")
        lines.append(f"{ENGINE_PREFIX}_host_gap_ms_per_turn "
                     f"{round(tl['host_gap_ms_per_turn'], 6)}")
        # measured KV-transfer costs per (src, dst, path) edge
        costs = transfer_costs.snapshot()
        if costs:
            for metric, typ in (("calls_total", "counter"),
                                ("bytes_total", "counter"),
                                ("seconds_total", "counter"),
                                ("mbps", "gauge"),
                                ("latency_ms", "gauge")):
                lines.append(f"# TYPE {KV_PREFIX}_{metric} {typ}")
                for (src, dst, path), e in sorted(costs.items()):
                    labels = f'src="{src}",dst="{dst}",path="{path}"'
                    val = {
                        "calls_total": e["calls"],
                        "bytes_total": e["bytes"],
                        "seconds_total": round(e["seconds"], 6),
                        "mbps": round(e["ewma_mbps"], 6),
                        "latency_ms": round(e["ewma_latency_s"] * 1e3, 6),
                    }[metric]
                    lines.append(f"{KV_PREFIX}_{metric}{{{labels}}} {val}")
        # dtperf plane: roofline-predicted step latency per (entrypoint,
        # config) from the committed perf manifest (JSON-only read — no
        # tracing happens here), plus the runtime predicted-vs-measured
        # reconciliation per live dispatch kind
        try:
            from dynamo_tpu.analysis.perfcheck import manifest_predictions

            rows = manifest_predictions()
        except Exception:
            rows = []
        if rows:
            lines.append(f"# TYPE {PERF_PREFIX}_predicted_step_ms gauge")
            for r in rows:
                labels = (f'entrypoint="{r["entrypoint"]}",'
                          f'config="{r["config"]}",'
                          f'signature="{r["signature"]}",'
                          f'bound="{r["bound"]}"')
                lines.append(
                    f"{PERF_PREFIX}_predicted_step_ms{{{labels}}} "
                    f"{r['predicted_ms']}")
        recon = perf_model.reconcile()
        if recon:
            for metric, field, typ in (
                    ("predicted_dispatch_ms", "predicted_ms", "gauge"),
                    ("measured_dispatch_ms", "measured_ms", "gauge"),
                    ("dispatches_total", "dispatches", "counter"),
                    ("model_error_ratio", "error_ratio", "gauge")):
                rendered = [r for r in recon if r.get(field) is not None]
                if not rendered:
                    continue
                lines.append(f"# TYPE {PERF_PREFIX}_{metric} {typ}")
                for r in rendered:
                    lines.append(
                        f'{PERF_PREFIX}_{metric}{{kind="{r["kind"]}"}} '
                        f"{r[field]}")
        return "\n".join(lines) + "\n"


class InflightGuard:
    """Counts a request as inflight until closed; records final status."""

    def __init__(self, metrics: Metrics, model: str, endpoint: str):
        self._m = metrics
        self.model = model
        self.endpoint = endpoint
        self._status = "error"
        self._t0 = time.monotonic()
        self._saw_first = False
        self._last_tok = 0.0
        self._m.inflight[model] += 1

    def first_token(self) -> None:
        """Record TTFT once, at the first generated-token emission."""
        if not self._saw_first:
            self._saw_first = True
            now = time.monotonic()
            self._last_tok = now
            dt = now - self._t0
            self._m.ttft[self.model].observe(dt)
            for listener in self._m.ttft_listeners:
                listener(dt)

    def tokens(self, k: int) -> None:
        """Record a k-token emission: TTFT on the first, then the
        emission gap spread as k equal inter-token observations (so the
        histogram count tracks tokens, and multi-step decode bursts
        don't read as one slow token)."""
        if k <= 0:
            return
        if not self._saw_first:
            self.first_token()
            k -= 1
            if k <= 0:
                return
        now = time.monotonic()
        per = (now - self._last_tok) / k
        h = self._m.itl[self.model]
        for _ in range(k):
            h.observe(per)
        self._last_tok = now

    def ok(self) -> None:
        self._status = "success"

    def status(self, s: str) -> None:
        self._status = s

    def close(self) -> None:
        self._m.inflight[self.model] -= 1
        self._m.requests[(self.model, self.endpoint, self._status)] += 1
        self._m.duration[(self.model, self._status)].observe(
            time.monotonic() - self._t0)
