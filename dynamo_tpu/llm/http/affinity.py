"""Consistent-hash session affinity across stateless HTTP frontends.

N frontends terminate streams; a multi-turn session is cheapest on the
frontend/router pair whose persist tier already holds the session's
prefix blocks.  The ring (utils/chash.py) maps a session key to its
owning frontend deterministically — every frontend computes the same
answer, so no shared state is needed on the hot path, and one frontend
restart moves only the ~1/N of sessions that hashed to it.

On an **affinity miss** — the ring's owner is not the frontend whose
persist tier is warm (typical after a membership change re-mapped the
session) — the content-addressed persist index is the cross-replica
source of truth: every frontend records "I served this session prefix"
under the xxh3 digest of the session key, and the resolver prefers that
recorded holder over the ring's cold answer.  `CoordAffinityIndex`
stores the records in the coordinator KV plane; `LocalAffinityIndex`
is the in-process equivalent for tests and single-host runs.

The decision surfaces as headers — ``x-affinity-owner`` on every
response carrying a session, plus an optional 307 redirect to the
owner's base URL when ``redirect=True`` — so dumb load balancers can
learn the mapping without a config push.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, Mapping, Optional

from dynamo_tpu.tokens import compute_hash
from dynamo_tpu.utils.chash import HashRing

__all__ = ["AffinityDecision", "SessionAffinity",
           "LocalAffinityIndex", "CoordAffinityIndex"]


@dataclass
class AffinityDecision:
    session_key: str
    owner: Optional[str]       # frontend id that should serve this session
    is_local: bool             # owner == this frontend
    source: str                # "ring" | "persist" | "none"
    redirect_url: Optional[str] = None


class LocalAffinityIndex:
    """In-process persist-affinity records; share one instance across
    frontends to model the cross-replica index in tests."""

    def __init__(self) -> None:
        self._holders: dict[int, str] = {}

    async def note(self, digest: int, frontend: str) -> None:
        self._holders[digest] = frontend

    async def lookup(self, digest: int) -> Optional[str]:
        return self._holders.get(digest)


class CoordAffinityIndex:
    """Persist-affinity records on the coordinator KV plane, keyed by
    content digest under ``prefix`` — the deployment-grade source of
    truth (same plane the persist replicator already uses)."""

    def __init__(self, coordinator, prefix: str = "/persist_affinity"):
        self.coord = coordinator
        self.prefix = prefix

    def _key(self, digest: int) -> str:
        return f"{self.prefix}/{digest:016x}"

    async def note(self, digest: int, frontend: str) -> None:
        await self.coord.kv_put(self._key(digest), frontend)

    async def lookup(self, digest: int) -> Optional[str]:
        return await self.coord.kv_get(self._key(digest))


class SessionAffinity:
    def __init__(self, self_id: str,
                 frontends: Mapping[str, str] | Iterable[str] = (),
                 persist_index=None, redirect: bool = False):
        self.self_id = self_id
        self.persist_index = persist_index
        self.redirect = redirect
        self._urls: dict[str, str] = {}
        self.ring = HashRing()
        if isinstance(frontends, Mapping):
            for fid, url in frontends.items():
                self.add_frontend(fid, url)
        else:
            for fid in frontends:
                self.add_frontend(fid)
        if self_id not in self.ring:
            self.add_frontend(self_id)

    # ------------------------------------------------------------- membership
    def add_frontend(self, frontend_id: str, base_url: str = "") -> None:
        self.ring.add(frontend_id)
        if base_url:
            self._urls[frontend_id] = base_url

    def remove_frontend(self, frontend_id: str) -> None:
        self.ring.remove(frontend_id)
        self._urls.pop(frontend_id, None)

    # -------------------------------------------------------------- decisions
    @staticmethod
    def digest(session_key: str) -> int:
        return compute_hash(session_key.encode())

    async def resolve(self, session_key: str) -> AffinityDecision:
        owner = self.ring.lookup(session_key)
        source = "ring" if owner else "none"
        if owner != self.self_id and self.persist_index is not None:
            # affinity miss: the ring's answer may be cold (membership
            # changed since the session started) — the recorded warm
            # holder wins if it is still a live frontend
            warm = await self.persist_index.lookup(self.digest(session_key))
            if warm is not None and warm in self.ring:
                owner, source = warm, "persist"
        return AffinityDecision(
            session_key=session_key,
            owner=owner,
            is_local=(owner is None or owner == self.self_id),
            source=source,
            redirect_url=self._urls.get(owner) if owner else None,
        )

    async def note_served(self, session_key: str) -> None:
        """We terminated a turn of this session — our persist tier is
        now the warm one; record it for everyone else's misses."""
        if self.persist_index is not None:
            await self.persist_index.note(self.digest(session_key),
                                          self.self_id)
