"""OpenAI-compatible HTTP frontend (aiohttp) — reference lib/llm/src/http/."""

from dynamo_tpu.llm.http.service import HttpService, ModelManager

__all__ = ["HttpService", "ModelManager"]
