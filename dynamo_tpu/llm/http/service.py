"""The OpenAI-compatible HTTP service (aiohttp).

Routes (reference lib/llm/src/http/service/openai.rs:132,218 and
service_v2.rs):

  POST /v1/chat/completions   — streaming (SSE) and unary
  POST /v1/completions        — streaming (SSE) and unary
  GET  /v1/models
  GET  /metrics               — Prometheus text format
  GET  /health, /live, /ready

Models are served through a ModelManager registry; entries can be added and
removed at runtime (the distributed frontend watches the control plane and
registers remote models dynamically, ref http/service/discovery.rs:58).
Client disconnects kill the request context so engines stop generating.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from dataclasses import dataclass
from typing import AsyncIterator, Optional

from aiohttp import web

from dynamo_tpu.llm.http.affinity import SessionAffinity
from dynamo_tpu.llm.http.metrics import Metrics
from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.llm.openai import (
    SSE_DONE,
    OpenAIError,
    chat_chunk,
    chat_logprobs_block,
    chat_response,
    completion_chunk,
    completion_logprobs_block,
    completion_response,
    new_id,
    parse_request,
    sse_encode,
    usage_dict,
)
from dynamo_tpu.llm.protocols import FinishReason, LLMEngineOutput
from dynamo_tpu.llm.tool_calls import ToolCallParser
from dynamo_tpu.obs import tracing
from dynamo_tpu.obs.export import trace_for_request
from dynamo_tpu.runtime.engine import AsyncEngine, Context

log = logging.getLogger("dynamo_tpu.http")

__all__ = ["ModelManager", "HttpService"]


def _tool_parser(parsed) -> ToolCallParser:
    """Parser honoring a named tool_choice (only that function's calls)."""
    only = None
    if isinstance(parsed.tool_choice, dict):
        only = parsed.tool_choice.get("function", {}).get("name")
    return ToolCallParser(only=only)


@dataclass
class ModelEntry:
    card: ModelDeploymentCard
    engine: AsyncEngine  # full pipeline: Context[ParsedRequest] → LLMEngineOutput(text)


class ModelManager:
    """Registry of served models (ref http/service.rs:59 ModelManager)."""

    def __init__(self) -> None:
        self._models: dict[str, ModelEntry] = {}

    def add_model(self, name: str, engine: AsyncEngine, card: Optional[ModelDeploymentCard] = None) -> None:
        self._models[name] = ModelEntry(card or ModelDeploymentCard(name=name), engine)

    def remove_model(self, name: str) -> None:
        self._models.pop(name, None)

    def get(self, name: str) -> ModelEntry:
        entry = self._models.get(name)
        if entry is None:
            raise OpenAIError(f"model '{name}' not found", status=404, err_type="model_not_found")
        return entry

    def list_models(self) -> list[str]:
        return sorted(self._models)


class HttpService:
    def __init__(self, manager: Optional[ModelManager] = None, host: str = "127.0.0.1", port: int = 8080,
                 admission=None, affinity: Optional[SessionAffinity] = None):
        self.manager = manager or ModelManager()
        self.metrics = Metrics()
        # consistent-hash session affinity (llm/http/affinity.py): with N
        # stateless frontends, route a multi-turn session to the replica
        # whose persist tier is warm.  None = singleton frontend, no-op.
        self.affinity = affinity
        # optional planner AdmissionController: per-tenant rate limits,
        # priority classes, deadline-aware shedding (429 + Retry-After).
        # Its wait estimates feed off this service's live TTFT plane.
        self.admission = admission
        if admission is not None:
            self.metrics.ttft_listeners.append(admission.observe_ttft)
        self.host = host
        self.port = port
        self._runner: Optional[web.AppRunner] = None
        self.app = web.Application()
        self.app.router.add_post("/v1/chat/completions", self._chat)
        self.app.router.add_post("/v1/completions", self._completions)
        self.app.router.add_get("/v1/models", self._models)
        self.app.router.add_get("/metrics", self._metrics)
        self.app.router.add_get("/debug/traces/{request_id}", self._debug_trace)
        for p in ("/health", "/live", "/ready"):
            self.app.router.add_get(p, self._health)

    # ------------------------------------------------------------- lifecycle
    async def start(self) -> None:
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        # resolve ephemeral port
        for s in self._runner.sites:
            server = getattr(s, "_server", None)
            if server and server.sockets:
                self.port = server.sockets[0].getsockname()[1]
        log.info("http service listening on %s:%s", self.host, self.port)

    async def stop(self) -> None:
        if self._runner:
            await self._runner.cleanup()

    # --------------------------------------------------------------- handlers
    async def _health(self, request: web.Request) -> web.Response:
        return web.json_response({"status": "ok", "models": self.manager.list_models()})

    async def _models(self, request: web.Request) -> web.Response:
        return web.json_response(
            {
                "object": "list",
                "data": [
                    {"id": m, "object": "model", "owned_by": "dynamo_tpu"}
                    for m in self.manager.list_models()
                ],
            }
        )

    async def _metrics(self, request: web.Request) -> web.Response:
        return web.Response(text=self.metrics.render(), content_type="text/plain")

    async def _debug_trace(self, request: web.Request) -> web.Response:
        """Chrome trace-event JSON for one request id (the response id,
        or the caller's ``x-request-id`` when it sent one).  Load the
        body in chrome://tracing or ui.perfetto.dev."""
        rid = request.match_info["request_id"]
        doc = trace_for_request(rid)
        if doc is None:
            return web.json_response(
                {"error": f"no trace recorded for {rid!r}"
                          " (is DYNAMO_TRACE=1 set?)"},
                status=404)
        return web.json_response(doc)

    async def _chat(self, request: web.Request) -> web.StreamResponse:
        return await self._serve(request, chat=True)

    async def _completions(self, request: web.Request) -> web.StreamResponse:
        return await self._serve(request, chat=False)

    async def _serve(self, request: web.Request, chat: bool) -> web.StreamResponse:
        endpoint = "chat_completions" if chat else "completions"
        try:
            body = await request.json()
        except json.JSONDecodeError:
            err = OpenAIError("invalid JSON body")
            return web.json_response(err.body(), status=err.status)

        guard = None
        ticket = None
        # client-supplied correlation id: accepted, propagated as the
        # engine-side request id, and echoed back on every response
        xrid = request.headers.get("x-request-id") or ""
        # session affinity: multi-turn callers tag their session so all
        # turns land where the persist tier is warm
        session = (request.headers.get("x-session-id")
                   or body.get("session_id") or "")
        affinity = None
        if self.affinity is not None and session:
            affinity = await self.affinity.resolve(session)
            if not affinity.is_local and self.affinity.redirect \
                    and affinity.redirect_url:
                return web.json_response(
                    {"redirect": "session affinity"},
                    status=307,
                    headers={"Location": affinity.redirect_url,
                             "x-affinity-owner": affinity.owner,
                             "x-affinity-source": affinity.source})
        # dtspan root: every downstream span (engine, coordinator hop,
        # remote prefill, KV transfer) parents under this one trace
        span = tracing.start_span(
            "http.request",
            attrs={"endpoint": endpoint, "request_id": xrid})
        try:
            parsed = parse_request(body, chat=chat)
            entry = self.manager.get(parsed.model)
            if self.admission is not None:
                priority = (request.headers.get("x-priority")
                            or body.get("priority"))
                tenant = (request.headers.get("x-tenant")
                          or request.headers.get("authorization")
                          or "default")
                from dynamo_tpu.planner.admission import AdmissionRejected

                try:
                    ticket = await self.admission.acquire(tenant, priority)
                except AdmissionRejected as e:
                    # shed: the SLA-preserving no.  Retry-After tells the
                    # client when capacity is likely (ref 429 semantics)
                    self.metrics.shed[(parsed.model, priority or "normal")] += 1
                    self.metrics.requests[(parsed.model, endpoint, "shed")] += 1
                    err = OpenAIError(str(e), status=429, err_type="overloaded")
                    return web.json_response(
                        err.body(), status=429,
                        headers={"Retry-After": str(e.retry_after_s)})
            guard = self.metrics.guard(parsed.model, endpoint)
            rid = new_id("chatcmpl" if chat else "cmpl")
            if tracing.enabled():
                # findable under both the response id and the caller's id
                tracing.collector.bind_request(rid, span.trace_id)
                if xrid:
                    tracing.collector.bind_request(xrid, span.trace_id)
            # n>1: fan out independent generations of the same prompt; the
            # engine's reserved-block registry (kv/block_manager.py) makes
            # them share ONE prefill — later admissions join the first
            # request's in-flight blocks and wait on its commits
            # (tests/test_inflight_dedupe.py covers the n=4 case)
            if parsed.n > 1 and parsed.sampling.seed is not None:
                # per-choice seeds: one seed would make all n choices
                # identical (seeded noise is position-deterministic)
                import dataclasses as _dc

                variants = [
                    _dc.replace(parsed, sampling=_dc.replace(
                        parsed.sampling, seed=parsed.sampling.seed + i))
                    for i in range(parsed.n)
                ]
                ctxs = [Context(v) for v in variants]
            else:
                ctxs = [Context(parsed) for _ in range(parsed.n)]
            if xrid:
                # the caller's id becomes the engine-visible request id
                # (choice-suffixed for n>1 so ids stay unique)
                for i, c in enumerate(ctxs):
                    c.id = xrid if parsed.n == 1 else f"{xrid}-{i}"
            # per-request migration budget (fault plane): "x-migration-limit:
            # 0" opts a request out of mid-stream migration entirely
            mig_limit = request.headers.get("x-migration-limit")
            if mig_limit is not None:
                try:
                    for c in ctxs:
                        c.annotations["migration_limit"] = max(0, int(mig_limit))
                except ValueError:
                    pass
            streams = [entry.engine.generate(c) for c in ctxs]
            if parsed.stream:
                resp = await self._stream_response(
                    request, ctxs, streams, rid, parsed, chat, guard,
                    xrid=xrid, affinity=affinity)
            else:
                resp = await self._unary_response(
                    ctxs, streams, rid, parsed, chat, guard, xrid=xrid,
                    affinity=affinity)
            if self.affinity is not None and session:
                # our persist tier is warm for this session now — record
                # it so peers resolve future turns here on affinity miss
                await self.affinity.note_served(session)
            return resp
        except OpenAIError as e:
            if guard:
                guard.status("error")
            return web.json_response(e.body(), status=e.status)
        except Exception:
            log.exception("request failed")
            err = OpenAIError("internal error", status=500, err_type="internal_error")
            return web.json_response(err.body(), status=err.status)
        finally:
            if ticket is not None:
                ticket.release()
            if guard:
                guard.close()
            span.end()

    # ------------------------------------------------------------- responders
    def _chunk(
        self, rid: str, parsed, chat: bool, out: LLMEngineOutput, index: int,
        text_off: int, finish_override: Optional[str] = None,
    ) -> list[dict]:
        finish = finish_override or (
            out.finish_reason.as_openai() if out.finish_reason else None
        )
        # logprob entries must flow even when the stop-string jail withholds
        # text (the entry's token was still produced this delta)
        if not (out.text or finish or out.logprob_content):
            return []
        lp_block = None
        if out.logprob_content:
            lp_block = (
                chat_logprobs_block(out.logprob_content)
                if chat
                else completion_logprobs_block(out.logprob_content, text_off)
            )
        if chat:
            return [chat_chunk(rid, parsed.model, content=out.text or "",
                               finish_reason=finish, index=index,
                               logprobs=lp_block)]
        return [completion_chunk(rid, parsed.model, out.text or "",
                                 finish_reason=finish, index=index,
                                 logprobs=lp_block)]

    async def _stream_response(
        self, request: web.Request, ctxs: list[Context],
        streams: list[AsyncIterator[LLMEngineOutput]],
        rid: str, parsed, chat: bool, guard, xrid: str = "",
        affinity=None,
    ) -> web.StreamResponse:
        headers = {
            "Content-Type": "text/event-stream",
            "Cache-Control": "no-cache",
            "Connection": "keep-alive",
        }
        if xrid:
            headers["x-request-id"] = xrid
        if affinity is not None and affinity.owner:
            headers["x-affinity-owner"] = affinity.owner
            headers["x-affinity-source"] = affinity.source
        resp = web.StreamResponse(headers=headers)
        await resp.prepare(request)
        n = len(streams)
        n_out = 0
        text_off = [0] * n
        # bounded (DT006): the pumps' `await put()` applies backpressure
        # to the engine streams when the SSE writer (the client's socket)
        # is slow, instead of buffering the whole generation in memory
        merged: asyncio.Queue = asyncio.Queue(maxsize=max(16, 4 * n))

        async def pump(i: int, s: AsyncIterator[LLMEngineOutput]) -> None:
            try:
                async for out in s:
                    await merged.put((i, out))
                    if out.finished:
                        break
            except Exception as e:  # surface engine errors as a finish
                log.exception("choice %d stream failed", i)
                await merged.put(
                    (i, LLMEngineOutput(finish_reason=FinishReason.ERROR))
                )
            finally:
                await merged.put((i, None))

        tasks = [asyncio.ensure_future(pump(i, s)) for i, s in enumerate(streams)]
        # tool-call extraction per choice: stream content through the jail,
        # emit parsed calls as one tool_calls delta at finish
        parsers = [
            _tool_parser(parsed) if chat and parsed.wants_tools else None
            for _ in range(n)
        ]
        try:
            if chat:
                for i in range(n):
                    await resp.write(sse_encode(
                        chat_chunk(rid, parsed.model, role="assistant",
                                   content="", index=i)
                    ))
            live = n
            while live:
                i, out = await merged.get()
                if out is None:
                    live -= 1
                    continue
                if out.token_ids:
                    guard.tokens(len(out.token_ids))
                n_out += len(out.token_ids)
                finish_override = None
                if parsers[i] is not None:
                    visible = parsers[i].feed(out.text or "")
                    if out.finish_reason is not None:
                        leftover, calls = parsers[i].finish()
                        # leftover = non-call prose (flushed either way)
                        out.text = visible + leftover
                        if calls:
                            finish_override = "tool_calls"
                            await resp.write(sse_encode(chat_chunk(
                                rid, parsed.model, tool_calls=calls, index=i
                            )))
                    else:
                        out.text = visible
                for chunk in self._chunk(rid, parsed, chat, out, i,
                                         text_off[i], finish_override):
                    await resp.write(sse_encode(chunk))
                text_off[i] += len(out.text or "")
            usage = usage_dict(ctxs[0].annotations.get("prompt_tokens", 0), n_out)
            if chat:
                await resp.write(sse_encode(chat_chunk(rid, parsed.model, usage=usage)))
            # headers are long gone on a stream, so the migration marker
            # rides an SSE comment (spec-legal, ignored by parsers)
            migrated = max((c.annotations.get("migrations", 0) for c in ctxs),
                           default=0)
            if migrated:
                await resp.write(f": x-migrated {migrated}\n\n".encode())
            await resp.write(SSE_DONE)
            guard.ok()
            self.metrics.tokens_out[parsed.model] += n_out
            self._observe_queue_wait(parsed.model, ctxs)
        except (ConnectionResetError, asyncio.CancelledError):
            # client went away — stop the engine (ref: disconnect detection)
            for ctx in ctxs:
                ctx.kill()
            guard.status("disconnect")
        finally:
            for t in tasks:
                if not t.done():
                    t.cancel()
        await resp.write_eof()
        return resp

    def _observe_queue_wait(self, model: str, ctxs: list[Context]) -> None:
        for c in ctxs:
            qw = c.annotations.get("queue_wait_s")
            if qw is not None:
                self.metrics.queue_wait[model].observe(qw)

    async def _unary_response(
        self, ctxs: list[Context], streams: list[AsyncIterator[LLMEngineOutput]],
        rid: str, parsed, chat: bool, guard, xrid: str = "",
        affinity=None,
    ) -> web.Response:
        n = len(streams)
        texts: list[list[str]] = [[] for _ in range(n)]
        lp_entries: list[list[dict]] = [[] for _ in range(n)]
        finishes = [FinishReason.STOP] * n
        counts = [0] * n

        async def collect(i: int, s: AsyncIterator[LLMEngineOutput]) -> None:
            async for out in s:
                if out.token_ids:
                    guard.tokens(len(out.token_ids))
                counts[i] += len(out.token_ids)
                if out.text:
                    texts[i].append(out.text)
                if out.logprob_content:
                    lp_entries[i].extend(out.logprob_content)
                if out.finish_reason:
                    finishes[i] = out.finish_reason
                if out.finished:
                    break

        try:
            await asyncio.gather(*(collect(i, s) for i, s in enumerate(streams)))
        except asyncio.CancelledError:
            # client dropped the connection mid-generation — free the slots
            for ctx in ctxs:
                ctx.kill()
            guard.status("disconnect")
            raise
        n_out = sum(counts)
        usage = usage_dict(ctxs[0].annotations.get("prompt_tokens", 0), n_out)
        resp: Optional[dict] = None
        for i in range(n):
            text = "".join(texts[i])
            calls = None
            finish = finishes[i].as_openai()
            if chat and parsed.wants_tools:
                p = _tool_parser(parsed)
                visible = p.feed(text)
                leftover, calls = p.finish()
                text = visible + leftover
                if calls:
                    finish = "tool_calls"
            lp_block = None
            if lp_entries[i]:
                lp_block = (
                    chat_logprobs_block(lp_entries[i]) if chat
                    else completion_logprobs_block(lp_entries[i])
                )
            piece = (
                chat_response(rid, parsed.model, text, finish, usage,
                              index=i, logprobs=lp_block, tool_calls=calls)
                if chat else
                completion_response(rid, parsed.model, text,
                                    finishes[i].as_openai(), usage,
                                    index=i, logprobs=lp_block)
            )
            if resp is None:
                resp = piece
            else:
                resp["choices"].extend(piece["choices"])
        guard.ok()
        self.metrics.tokens_out[parsed.model] += n_out
        self._observe_queue_wait(parsed.model, ctxs)
        migrated = max((c.annotations.get("migrations", 0) for c in ctxs),
                       default=0)
        headers = {}
        if migrated:
            headers["x-migrated"] = str(migrated)
        if xrid:
            headers["x-request-id"] = xrid
        if affinity is not None and affinity.owner:
            headers["x-affinity-owner"] = affinity.owner
            headers["x-affinity-source"] = affinity.source
        return web.json_response(resp, headers=headers or None)
