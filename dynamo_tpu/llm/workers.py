"""Disaggregated prefill/decode worker roles.

Reference parity (examples/llm/components/worker.py, prefill_worker.py,
utils/prefill_queue.py; SURVEY.md §3.3 "the money path"):

  DecodeWorker.generate
    ├─ conditional disagg decision            (worker.py:180-207)
    ├─ local  → engine prefill+decode as one request
    └─ remote → allocate KV blocks up front, enqueue RemotePrefillRequest
                on the durable queue, stall until the prefill worker has
                written KV into those blocks and notified (worker.py:164-173,
                vllm patch scheduler stall)
  PrefillWorker.run
    └─ pull queue → prefill locally (remote_decode hold) → push blocks to
       the decode worker's transfer endpoint → notify → release
       (prefill_worker.py:119-177)

The KV hop rides dynamo_tpu/llm/kv/transfer.py (ICI/DCN) instead of NIXL.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import logging
from dataclasses import dataclass, field
from typing import AsyncIterator, Optional

import os

from dynamo_tpu.engine.async_engine import AsyncLLMEngine
from dynamo_tpu.engine.counters import kv_stream_counters
from dynamo_tpu.llm.disagg_router import DisaggregatedRouter
from dynamo_tpu.llm.kv.stream import KvStreamProducer, choose_handoff_path
from dynamo_tpu.llm.kv.transfer import KvTransferClient, KvTransferServer
from dynamo_tpu.obs.costs import transfer_costs
from dynamo_tpu.llm.protocols import (
    BackendInput,
    FinishReason,
    LLMEngineOutput,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.obs import tracing
from dynamo_tpu.runtime.engine import AsyncEngine, Context
from dynamo_tpu.tokens import TokenBlockSequence

log = logging.getLogger("dynamo_tpu.workers")

__all__ = [
    "RemotePrefillRequest",
    "PrefillQueue",
    "DecodeWorker",
    "PrefillWorker",
]


@dataclass
class RemotePrefillRequest:
    """Work item on the prefill queue (ref vllm patch remote_prefill.py:
    RemotePrefillRequest{engine_id, request_id, prompt_token_ids,
    sampling_params, block_ids, computed_block_ids})."""

    request_id: str
    token_ids: list[int]
    block_ids: list[int]       # decode-side blocks to fill
    skip_blocks: int           # leading blocks already resident on decode side
    transfer_url: str          # decode worker's KvTransferServer
    sampling: SamplingOptions = field(default_factory=SamplingOptions)
    # dtspan trace context [trace_id, span_id] — optional; carries the
    # decode side's trace across the durable queue so the prefill
    # worker's spans land in the same trace (None when tracing is off)
    trace: Optional[list] = None

    def to_wire(self) -> bytes:
        d = dataclasses.asdict(self)
        return json.dumps(d).encode()

    @classmethod
    def from_wire(cls, data: bytes) -> "RemotePrefillRequest":
        d = json.loads(data)
        d["sampling"] = SamplingOptions(**d.get("sampling", {}))
        return cls(**d)


class PrefillQueue:
    """Durable ack'd work queue for remote prefills — JetStream parity
    (examples/llm/utils/nats_queue.py) on the coordinator queue plane."""

    def __init__(self, coordinator, namespace: str = "default"):
        self.coord = coordinator
        self.name = f"{namespace}_prefill_queue"

    async def push(self, req: RemotePrefillRequest) -> int:
        return await self.coord.queue_push(self.name, req.to_wire())

    async def pull(
        self, timeout_s: float = 0.0
    ) -> Optional[tuple[int, RemotePrefillRequest]]:
        item = await self.coord.queue_pull(self.name, timeout_s)
        if item is None:
            return None
        msg_id, payload = item
        try:
            return msg_id, RemotePrefillRequest.from_wire(payload)
        except Exception:
            # poison message: ack (drop) it or it redelivers forever,
            # killing every worker that pulls it
            log.exception("dropping undecodable prefill queue message %s", msg_id)
            await self.ack(msg_id)
            return None

    async def ack(self, msg_id: int) -> None:
        await self.coord.queue_ack(self.name, msg_id)

    async def nack(self, msg_id: int) -> None:
        await self.coord.queue_nack(self.name, msg_id)

    async def size(self) -> int:
        return await self.coord.queue_len(self.name)


class DecodeWorker(AsyncEngine):
    """The decode-side engine wrapper: owns the conditional disagg decision
    and the KV ingest endpoint.  Drop-in AsyncEngine, so it slots behind
    endpoints / pipelines exactly like a plain engine."""

    def __init__(
        self,
        engine: AsyncLLMEngine,
        coordinator=None,
        namespace: str = "default",
        router: Optional[DisaggregatedRouter] = None,
        transfer_host: str = "127.0.0.1",
    ):
        self.engine = engine
        self.coord = coordinator
        self.namespace = namespace
        self.router = router or DisaggregatedRouter(namespace=namespace)
        self.queue = PrefillQueue(coordinator, namespace) if coordinator else None
        self._transfer: Optional[KvTransferServer] = None
        self._transfer_host = transfer_host
        self._cached_depth = 0
        self._cached_depth_at = -1.0

    # ------------------------------------------------------------- lifecycle
    async def start(self) -> "DecodeWorker":
        self._transfer = await KvTransferServer(
            write_sink=self._apply_write,
            notify_cb=self._on_notify,
            read_source=self._read_blocks,
            host=self._transfer_host,
        ).start()
        if self.coord is not None:
            await self.router.watch(self.coord)
        return self

    async def stop(self) -> None:
        if self._transfer:
            await self._transfer.stop()

    @property
    def transfer_url(self) -> str:
        return self._transfer.url

    # ------------------------------------------------- transfer plane bridge
    async def _apply_write(self, block_ids, arr, request_id=None) -> None:
        core = self.engine.core
        await self.engine.run_on_engine(
            lambda: core.scatter_external(block_ids, arr, request_id)
        )

    async def _read_blocks(self, block_ids):
        core = self.engine.core
        return await self.engine.run_on_engine(lambda: core.gather_blocks_np(block_ids))

    async def _on_notify(self, request_id, first_token, error) -> None:
        core = self.engine.core
        await self.engine.run_on_engine(
            lambda: core.complete_remote_prefill(request_id, first_token, error)
        )

    # ---------------------------------------------------------------- routing
    _QUEUE_DEPTH_TTL = 0.1  # seconds; routing heuristic tolerates staleness

    def _prefix_hit(self, token_ids: list[int]) -> tuple[int, list[int]]:
        # read-only dict probe against the block manager — GIL-safe from this
        # thread, at worst slightly stale, and avoids waiting out an engine
        # step boundary on the request's critical TTFT path
        core = self.engine.core
        seq = TokenBlockSequence(list(token_ids), core.config.block_size)
        hashes = seq.sequence_hashes()
        return core.prefix_hit_tokens(hashes, len(token_ids)), hashes

    async def _queue_depth(self) -> int:
        now = asyncio.get_running_loop().time()
        if now - self._cached_depth_at > self._QUEUE_DEPTH_TTL:
            self._cached_depth = await self.queue.size()
            self._cached_depth_at = now
        return self._cached_depth

    def _wire_edge(self) -> tuple[str, bool]:
        """(src, is_local) of the inbound KV edge: prefer a measured edge
        into our transfer endpoint (i.e. whichever prefill worker has
        actually been feeding us — obs/costs.py learns src/path from
        every push), defaulting to an unmeasured cross-host DCN edge so
        cold routing uses the conservative topology prior."""
        dst = self.transfer_url
        for (src, d, path) in transfer_costs.snapshot():
            if d == dst and path in ("ici", "dcn"):
                return src, path == "ici"
        return "prefill", False

    def _handoff_cost(
        self, token_ids: list[int], hit: int, hashes: list[int]
    ) -> tuple[str, float]:
        """NetKV-style transfer-cost term for the remote-prefill decision:
        predicted seconds to land this request's KV in OUR cache, over
        the cheapest of stream-over-the-wire vs persist-tier restore
        (when the persist index says the prefix is resident here).  Same
        thread-safety posture as ``_prefix_hit``: heuristic inputs."""
        core = self.engine.core
        bs = core.config.block_size
        total_blocks = -(-len(token_ids) // bs)
        remainder = max(0, total_blocks - hit // bs)
        if remainder == 0:
            return "ici", 0.0
        nbytes = core.kv_bytes_per_block() * remainder
        src, local = self._wire_edge()
        return choose_handoff_path(
            src, self.transfer_url, nbytes, local=local,
            persist_resident_blocks=core.persist_hit_blocks(hashes),
            total_blocks=remainder,
        )

    async def _should_remote(self, token_ids: list[int]) -> bool:
        if self.queue is None:
            return False
        hit, hashes = self._prefix_hit(token_ids)
        qsize = await self._queue_depth()
        path, cost = self._handoff_cost(token_ids, hit, hashes)
        if path == "persist":
            # the persist tier already holds (most of) this prefix
            # locally — restoring beats shipping fresh KV over the wire,
            # and the local prefill path is what triggers the restore
            remote = False
        else:
            remote = self.router.prefill_remote(
                len(token_ids), hit, qsize, transfer_cost_s=cost
            )
        # dtspan: the chosen handoff path + its predicted cost, on the
        # request's own trace (generate() runs under the request span)
        span = tracing.start_span(
            "disagg.route",
            attrs={"path": path, "cost_s": cost, "remote": remote,
                   "prefix_hit": hit, "queue": qsize},
        )
        span.end()
        return remote

    # --------------------------------------------------------------- generate
    def generate(self, request: Context[BackendInput]) -> AsyncIterator[LLMEngineOutput]:
        return self._generate(request)

    async def _generate(self, request: Context[BackendInput]):
        if not await self._should_remote(request.data.token_ids):
            async for out in self.engine.generate(request):
                yield out
            return
        async for out in self._generate_remote(request):
            yield out

    async def _generate_remote(self, request: Context[BackendInput]):
        loop = asyncio.get_running_loop()
        alloc_fut: asyncio.Future = loop.create_future()

        def on_allocated(req) -> None:  # engine thread
            ids, cached = list(req.block_ids), req.cached_tokens

            def _set() -> None:
                if not alloc_fut.done():
                    alloc_fut.set_result((ids, cached))

            loop.call_soon_threadsafe(_set)

        agen = self.engine.generate_ex(
            request, remote_prefill=True, on_allocated=on_allocated
        )
        first_task = asyncio.ensure_future(agen.__anext__())
        try:
            done, _ = await asyncio.wait(
                {first_task, alloc_fut}, return_when=asyncio.FIRST_COMPLETED
            )
            if alloc_fut in done:
                block_ids, cached = alloc_fut.result()
                bs = self.engine.core.config.block_size
                ctx_pair = tracing.current()
                await self.queue.push(
                    RemotePrefillRequest(
                        request_id=request.id,
                        token_ids=list(request.data.token_ids),
                        block_ids=block_ids,
                        skip_blocks=cached // bs,
                        transfer_url=self.transfer_url,
                        sampling=request.data.sampling,
                        trace=list(ctx_pair) if ctx_pair else None,
                    )
                )
            # stream everything the engine emits (first token arrives once a
            # prefill worker notifies)
            while True:
                out = await first_task
                yield out
                if out.finished:
                    return
                first_task = asyncio.ensure_future(agen.__anext__())
        except StopAsyncIteration:
            return
        finally:
            if not first_task.done():
                first_task.cancel()
                # let the cancellation reach the inner generator before
                # aclose() — aclose() on a still-running generator raises.
                # gather(return_exceptions=True) absorbs first_task's own
                # CancelledError/errors but still re-raises if THIS task is
                # cancelled from outside — swallowing that would wedge
                # shutdown (the caller's cancel would never land).
                await asyncio.gather(first_task, return_exceptions=True)
            if not alloc_fut.done():
                alloc_fut.cancel()
            await agen.aclose()


class PrefillWorker:
    """Pulls remote-prefill work, computes KV locally, pushes the blocks to
    the decode worker and notifies (ref prefill_worker.py:119-177).

    With ``stream=True`` (or ``DYN_KV_STREAM=1``) the push is the
    layer-wise streamed handoff (llm/kv/stream.py): a commit hook fires
    per prefill chunk and each committed span's layers go on the wire
    while later chunks still compute.  Any stream failure falls back to
    the blocking whole-cache push below — the fallback ladder in
    docs/kv_streaming.md."""

    def __init__(self, engine: AsyncLLMEngine, coordinator, namespace: str = "default",
                 stream: Optional[bool] = None):
        self.engine = engine
        self.queue = PrefillQueue(coordinator, namespace)
        self._stop = asyncio.Event()
        self.handled = 0
        if stream is None:
            stream = os.environ.get("DYN_KV_STREAM", "") == "1"
        self.stream = bool(stream)

    def request_stop(self) -> None:
        self._stop.set()

    async def run(self) -> None:
        """Main pull loop; returns after request_stop().  Transport errors
        back off and retry — the loop must outlive transient coordinator
        hiccups or every remote prefill stalls forever."""
        while not self._stop.is_set():
            try:
                item = await self.queue.pull(timeout_s=0.2)
            except Exception:
                log.exception("prefill queue pull failed; retrying")
                await asyncio.sleep(0.5)
                continue
            if item is None:
                continue
            msg_id, rpr = item
            try:
                await self.handle(rpr)
                await self.queue.ack(msg_id)
                self.handled += 1
            except Exception:
                log.exception("prefill of %s failed; nack for redelivery", rpr.request_id)
                try:
                    await self.queue.nack(msg_id)
                except Exception:
                    log.exception("nack of %s failed", msg_id)

    async def handle(self, rpr: RemotePrefillRequest) -> None:
        # dtspan: continue the decode side's trace across the queue hop —
        # the engine.generate span below and the kv.write_blocks/notify
        # spans all parent under this one
        token = tracing.attach(rpr.trace)
        span = (
            tracing.start_span(
                "disagg.prefill",
                attrs={"request_id": rpr.request_id,
                       "tokens": len(rpr.token_ids)})
            if rpr.trace else tracing.NOP_SPAN
        )
        try:
            await self._handle_inner(rpr)
        finally:
            span.end()
            tracing.detach(token)

    async def _handle_inner(self, rpr: RemotePrefillRequest) -> None:
        core = self.engine.core
        ctx: Context[BackendInput] = Context(
            BackendInput(
                token_ids=list(rpr.token_ids),
                sampling=rpr.sampling,
                stops=StopConditions(max_tokens=1),
            ),
            id=rpr.request_id,
        )
        client = None
        producer: Optional[KvStreamProducer] = None
        stream_task: Optional[asyncio.Task] = None
        if self.stream:
            # streamed handoff: dial the target and arm the commit hook
            # BEFORE prefill starts, so even the FIRST chunk's layers go
            # on the wire while later chunks compute.  The hook dict
            # write is GIL-atomic (same posture as the routing probes).
            client = await KvTransferClient.connect(rpr.transfer_url)
            producer = KvStreamProducer(
                self.engine, client, rpr.request_id,
                remote_block_ids=list(rpr.block_ids),
                skip_blocks=rpr.skip_blocks,
            )
            core.register_commit_hook(rpr.request_id, producer.on_commit)
            stream_task = asyncio.ensure_future(producer.run())
        try:
            outs = [o async for o in self.engine.generate_ex(ctx, remote_decode=True)]
            first_tokens = [t for o in outs for t in o.token_ids]
            failed = not first_tokens or any(
                o.finish_reason is FinishReason.ERROR for o in outs
            )
            streamed = False
            if stream_task is not None:
                if failed:
                    # a failed prefill never fires the done commit event —
                    # the drain would wait forever; cancel it instead
                    stream_task.cancel()
                    await asyncio.gather(stream_task, return_exceptions=True)
                else:
                    streamed = await stream_task
                stream_task = None
                if not streamed and (failed or producer.failure is not None):
                    # mid-stream sever / torn session / backpressure: the
                    # connection may be dead — redial for the fallback
                    # ladder (whole-cache push) and the notify
                    if producer.failure is not None:
                        kv_stream_counters.record_fallback()
                    await client.close()
                    client = None
            if client is None:
                client = await KvTransferClient.connect(rpr.transfer_url)
            if failed:
                await client.notify(rpr.request_id, -1, error="prefill failed")
                return
            local_ids = core.held_blocks(rpr.request_id)
            skip = rpr.skip_blocks
            if len(local_ids) != len(rpr.block_ids):
                await client.notify(rpr.request_id, -1, error="block count mismatch")
                return
            if not streamed and skip < len(local_ids):
                # blocking whole-cache push — the non-streamed default
                # AND the streamed path's fallback.
                # colocated target → device-side gather (blocks never leave
                # the device; scatter-side device_put reshards over ICI).
                # Remote target → host staging + TCP (the DCN path).
                gather = (
                    core.gather_blocks_device
                    if getattr(client, "is_local", False)
                    else core.gather_blocks_np
                )
                arr = await self.engine.run_on_engine(
                    lambda: gather(local_ids[skip:])
                )
                await client.write_blocks(
                    rpr.block_ids[skip:], arr, request_id=rpr.request_id
                )
            await client.notify(rpr.request_id, first_tokens[0])
        finally:
            core.unregister_commit_hook(rpr.request_id)
            if stream_task is not None and not stream_task.done():
                stream_task.cancel()
                await asyncio.gather(stream_task, return_exceptions=True)
            if client is not None:
                await client.close()
            await self.engine.run_on_engine(
                lambda: core.release_held(rpr.request_id)
            )
