"""API store: versioned registry of packaged serving graphs.

Reference parity: deploy/dynamo/api-store (FastAPI + Postgres store of
packaged graphs/"bentos" that the k8s operator pulls deployment specs
from).  Rebuilt lean: aiohttp + sqlite, storing DynamoTpuDeployment specs
(deploy/renderer.py shape) with versions, and serving rendered k8s
manifests straight from the store.

REST surface:
  POST   /api/v1/graphs                     {"name", "spec": <yaml|dict>, "labels"?}
  GET    /api/v1/graphs                     list graphs (latest version each)
  GET    /api/v1/graphs/{name}              all versions
  GET    /api/v1/graphs/{name}/{ver}        one version (spec + metadata)
  DELETE /api/v1/graphs/{name}/{ver}
  GET    /api/v1/graphs/{name}/{ver}/manifests   rendered k8s objects (JSON list)

Packaged graphs (the reference's "bento" archives — code + manifest,
built by ``dynamo-tpu package build``, deploy/packaging.py; weights ride
the model store instead):
  POST   /api/v1/packages                   raw tar.gz body -> {name, version}
  GET    /api/v1/packages                   list packages (latest each)
  GET    /api/v1/packages/{name}            all versions (manifest metadata)
  GET    /api/v1/packages/{name}/{ver}      manifest ("latest" ok)
  GET    /api/v1/packages/{name}/{ver}/archive   the tar.gz bytes
  DELETE /api/v1/packages/{name}/{ver}

Run via `dynamo-tpu api-store --db graphs.db --port 7180`.
"""

from __future__ import annotations

import asyncio
import json
import sqlite3
import time
from typing import Optional

import yaml
from aiohttp import web

from dynamo_tpu.deploy.renderer import DeploymentSpec, render_manifests

__all__ = ["ApiStore"]


class ApiStore:
    def __init__(self, db_path: str = ":memory:", host: str = "127.0.0.1", port: int = 7180):
        self.db = sqlite3.connect(db_path)
        self.db.execute(
            """CREATE TABLE IF NOT EXISTS graphs (
                 name TEXT NOT NULL,
                 version INTEGER NOT NULL,
                 spec TEXT NOT NULL,
                 labels TEXT NOT NULL DEFAULT '{}',
                 created_at REAL NOT NULL,
                 PRIMARY KEY (name, version)
               )"""
        )
        self.db.execute(
            """CREATE TABLE IF NOT EXISTS packages (
                 name TEXT NOT NULL,
                 version INTEGER NOT NULL,
                 manifest TEXT NOT NULL,
                 archive BLOB NOT NULL,
                 created_at REAL NOT NULL,
                 PRIMARY KEY (name, version)
               )"""
        )
        self.host = host
        self.port = port
        self._runner: Optional[web.AppRunner] = None

    # ------------------------------------------------------------------ CRUD
    def put_graph(self, name: str, spec: dict, labels: Optional[dict] = None) -> int:
        # the spec must render — reject broken uploads at the door
        render_manifests(self._to_spec(spec))
        return self._insert_graph(name, spec, labels)

    def _insert_graph(self, name: str, spec: dict,
                      labels: Optional[dict] = None) -> int:
        # sqlite connections are thread-bound: this must run on the
        # thread that created self.db (the event loop thread)
        cur = self.db.execute(
            "SELECT COALESCE(MAX(version), 0) FROM graphs WHERE name = ?", (name,)
        )
        version = cur.fetchone()[0] + 1
        self.db.execute(
            "INSERT INTO graphs (name, version, spec, labels, created_at) VALUES (?,?,?,?,?)",
            (name, version, json.dumps(spec), json.dumps(labels or {}), time.time()),
        )
        self.db.commit()
        return version

    def list_graphs(self) -> list[dict]:
        cur = self.db.execute(
            """SELECT name, MAX(version), created_at FROM graphs
               GROUP BY name ORDER BY name"""
        )
        return [
            {"name": n, "latest_version": v, "created_at": t}
            for n, v, t in cur.fetchall()
        ]

    def get_versions(self, name: str) -> list[dict]:
        cur = self.db.execute(
            "SELECT version, labels, created_at FROM graphs WHERE name = ? ORDER BY version",
            (name,),
        )
        return [
            {"version": v, "labels": json.loads(l), "created_at": t}
            for v, l, t in cur.fetchall()
        ]

    def get_graph(self, name: str, version: Optional[int] = None) -> Optional[dict]:
        if version is None:
            cur = self.db.execute(
                "SELECT version, spec, labels, created_at FROM graphs "
                "WHERE name = ? ORDER BY version DESC LIMIT 1", (name,),
            )
        else:
            cur = self.db.execute(
                "SELECT version, spec, labels, created_at FROM graphs "
                "WHERE name = ? AND version = ?", (name, version),
            )
        row = cur.fetchone()
        if row is None:
            return None
        v, spec, labels, t = row
        return {
            "name": name, "version": v, "spec": json.loads(spec),
            "labels": json.loads(labels), "created_at": t,
        }

    def delete_graph(self, name: str, version: int) -> bool:
        cur = self.db.execute(
            "DELETE FROM graphs WHERE name = ? AND version = ?", (name, version)
        )
        self.db.commit()
        return cur.rowcount > 0

    @staticmethod
    def _to_spec(spec: dict) -> DeploymentSpec:
        return DeploymentSpec.from_yaml(yaml.safe_dump(spec))

    def _validate_spec(self, spec: dict) -> None:
        """Blocking (template read_text): run via asyncio.to_thread from
        handlers."""
        render_manifests(self._to_spec(spec))

    # ------------------------------------------------------------- packages
    def put_package(self, archive: bytes) -> tuple[str, int]:
        """Store a package archive; name comes from its own (validated)
        manifest.  Returns (name, version)."""
        from dynamo_tpu.deploy.packaging import read_manifest

        manifest = read_manifest(archive)  # raises PackageError if bad
        name = manifest["name"]
        cur = self.db.execute(
            "SELECT COALESCE(MAX(version), 0) FROM packages WHERE name = ?",
            (name,),
        )
        version = cur.fetchone()[0] + 1
        self.db.execute(
            "INSERT INTO packages (name, version, manifest, archive, "
            "created_at) VALUES (?,?,?,?,?)",
            (name, version, json.dumps(manifest), archive, time.time()),
        )
        self.db.commit()
        return name, version

    def list_packages(self) -> list[dict]:
        cur = self.db.execute(
            """SELECT name, MAX(version), created_at FROM packages
               GROUP BY name ORDER BY name"""
        )
        return [
            {"name": n, "latest_version": v, "created_at": t}
            for n, v, t in cur.fetchall()
        ]

    def package_versions(self, name: str) -> list[dict]:
        cur = self.db.execute(
            "SELECT version, manifest, created_at FROM packages "
            "WHERE name = ? ORDER BY version", (name,),
        )
        return [
            {"version": v, "entry": json.loads(m).get("entry"),
             "created_at": t}
            for v, m, t in cur.fetchall()
        ]

    def get_package(self, name: str, version: Optional[int] = None,
                    with_archive: bool = False) -> Optional[dict]:
        # fetch the (potentially large) archive blob only when asked —
        # metadata requests must not materialize it
        cols = ("version, manifest, created_at, archive" if with_archive
                else "version, manifest, created_at")
        q = f"SELECT {cols} FROM packages WHERE name = ?"
        args: tuple = (name,)
        if version is None:
            q += " ORDER BY version DESC LIMIT 1"
        else:
            q += " AND version = ?"
            args = (name, version)
        row = self.db.execute(q, args).fetchone()
        if row is None:
            return None
        out = {"name": name, "version": row[0],
               "manifest": json.loads(row[1]), "created_at": row[2]}
        if with_archive:
            out["archive"] = row[3]
        return out

    def delete_package(self, name: str, version: int) -> bool:
        cur = self.db.execute(
            "DELETE FROM packages WHERE name = ? AND version = ?",
            (name, version),
        )
        self.db.commit()
        return cur.rowcount > 0

    # ------------------------------------------------------------------ HTTP
    async def _post_graph(self, request: web.Request) -> web.Response:
        body = await request.json()
        spec = body.get("spec")
        if isinstance(spec, str):
            spec = yaml.safe_load(spec)
        if not isinstance(spec, dict) or "name" not in body:
            raise web.HTTPBadRequest(text="need {name, spec}")
        try:
            # the render validation reads spec templates off disk
            # (DeploymentSpec.from_yaml) — keep it off the event loop;
            # the sqlite insert stays here (connections are thread-bound)
            await asyncio.to_thread(self._validate_spec, spec)
        except (KeyError, ValueError, TypeError) as e:
            raise web.HTTPUnprocessableEntity(text=f"spec does not render: {e}")
        version = self._insert_graph(body["name"], spec, body.get("labels"))
        return web.json_response({"name": body["name"], "version": version}, status=201)

    async def _list(self, request: web.Request) -> web.Response:
        return web.json_response(self.list_graphs())

    async def _versions(self, request: web.Request) -> web.Response:
        name = request.match_info["name"]
        versions = self.get_versions(name)
        if not versions:
            raise web.HTTPNotFound
        return web.json_response(versions)

    async def _get(self, request: web.Request) -> web.Response:
        g = self.get_graph(
            request.match_info["name"], int(request.match_info["ver"])
        )
        if g is None:
            raise web.HTTPNotFound
        return web.json_response(g)

    async def _delete(self, request: web.Request) -> web.Response:
        ok = self.delete_graph(
            request.match_info["name"], int(request.match_info["ver"])
        )
        if not ok:
            raise web.HTTPNotFound
        return web.json_response({"deleted": True})

    async def _manifests(self, request: web.Request) -> web.Response:
        g = self.get_graph(
            request.match_info["name"], int(request.match_info["ver"])
        )
        if g is None:
            raise web.HTTPNotFound
        spec = await asyncio.to_thread(self._to_spec, g["spec"])
        return web.json_response(render_manifests(spec))

    # ------------------------------------------------------- packages HTTP
    @staticmethod
    def _ver_arg(request: web.Request) -> Optional[int]:
        ver = request.match_info["ver"]
        if ver == "latest":
            return None
        try:
            return int(ver)
        except ValueError:
            raise web.HTTPBadRequest(
                text=f"version must be an integer or 'latest', got {ver!r}"
            ) from None

    async def _post_package(self, request: web.Request) -> web.Response:
        from dynamo_tpu.deploy.packaging import PackageError

        archive = await request.read()
        try:
            name, version = self.put_package(archive)
        except PackageError as e:
            raise web.HTTPUnprocessableEntity(text=str(e))
        return web.json_response({"name": name, "version": version},
                                 status=201)

    async def _list_packages(self, request: web.Request) -> web.Response:
        return web.json_response(self.list_packages())

    async def _package_versions(self, request: web.Request) -> web.Response:
        versions = self.package_versions(request.match_info["name"])
        if not versions:
            raise web.HTTPNotFound
        return web.json_response(versions)

    async def _get_package(self, request: web.Request) -> web.Response:
        g = self.get_package(request.match_info["name"],
                             self._ver_arg(request))
        if g is None:
            raise web.HTTPNotFound
        return web.json_response(g)

    async def _get_archive(self, request: web.Request) -> web.Response:
        g = self.get_package(request.match_info["name"],
                             self._ver_arg(request), with_archive=True)
        if g is None:
            raise web.HTTPNotFound
        return web.Response(
            body=g["archive"], content_type="application/gzip",
            headers={"X-Package-Version": str(g["version"])},
        )

    async def _delete_package(self, request: web.Request) -> web.Response:
        ver = self._ver_arg(request)
        if ver is None:
            raise web.HTTPBadRequest(text="delete needs an explicit version")
        if not self.delete_package(request.match_info["name"], ver):
            raise web.HTTPNotFound
        return web.json_response({"deleted": True})

    async def start(self) -> "ApiStore":
        app = web.Application(client_max_size=256 << 20)  # code archives
        app.router.add_post("/api/v1/graphs", self._post_graph)
        app.router.add_get("/api/v1/graphs", self._list)
        app.router.add_get("/api/v1/graphs/{name}", self._versions)
        app.router.add_get("/api/v1/graphs/{name}/{ver}", self._get)
        app.router.add_delete("/api/v1/graphs/{name}/{ver}", self._delete)
        app.router.add_get("/api/v1/graphs/{name}/{ver}/manifests", self._manifests)
        app.router.add_post("/api/v1/packages", self._post_package)
        app.router.add_get("/api/v1/packages", self._list_packages)
        app.router.add_get("/api/v1/packages/{name}", self._package_versions)
        app.router.add_get("/api/v1/packages/{name}/{ver}", self._get_package)
        app.router.add_get("/api/v1/packages/{name}/{ver}/archive",
                           self._get_archive)
        app.router.add_delete("/api/v1/packages/{name}/{ver}",
                              self._delete_package)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        self.port = self._runner.addresses[0][1]
        return self

    async def stop(self) -> None:
        if self._runner:
            await self._runner.cleanup()
            self._runner = None
        self.db.close()
