"""API store: versioned registry of packaged serving graphs.

Reference parity: deploy/dynamo/api-store (FastAPI + Postgres store of
packaged graphs/"bentos" that the k8s operator pulls deployment specs
from).  Rebuilt lean: aiohttp + sqlite, storing DynamoTpuDeployment specs
(deploy/renderer.py shape) with versions, and serving rendered k8s
manifests straight from the store.

REST surface:
  POST   /api/v1/graphs                     {"name", "spec": <yaml|dict>, "labels"?}
  GET    /api/v1/graphs                     list graphs (latest version each)
  GET    /api/v1/graphs/{name}              all versions
  GET    /api/v1/graphs/{name}/{ver}        one version (spec + metadata)
  DELETE /api/v1/graphs/{name}/{ver}
  GET    /api/v1/graphs/{name}/{ver}/manifests   rendered k8s objects (JSON list)

Run via `dynamo-tpu api-store --db graphs.db --port 7180`.
"""

from __future__ import annotations

import json
import sqlite3
import time
from typing import Optional

import yaml
from aiohttp import web

from dynamo_tpu.deploy.renderer import DeploymentSpec, render_manifests

__all__ = ["ApiStore"]


class ApiStore:
    def __init__(self, db_path: str = ":memory:", host: str = "127.0.0.1", port: int = 7180):
        self.db = sqlite3.connect(db_path)
        self.db.execute(
            """CREATE TABLE IF NOT EXISTS graphs (
                 name TEXT NOT NULL,
                 version INTEGER NOT NULL,
                 spec TEXT NOT NULL,
                 labels TEXT NOT NULL DEFAULT '{}',
                 created_at REAL NOT NULL,
                 PRIMARY KEY (name, version)
               )"""
        )
        self.host = host
        self.port = port
        self._runner: Optional[web.AppRunner] = None

    # ------------------------------------------------------------------ CRUD
    def put_graph(self, name: str, spec: dict, labels: Optional[dict] = None) -> int:
        # the spec must render — reject broken uploads at the door
        render_manifests(self._to_spec(spec))
        cur = self.db.execute(
            "SELECT COALESCE(MAX(version), 0) FROM graphs WHERE name = ?", (name,)
        )
        version = cur.fetchone()[0] + 1
        self.db.execute(
            "INSERT INTO graphs (name, version, spec, labels, created_at) VALUES (?,?,?,?,?)",
            (name, version, json.dumps(spec), json.dumps(labels or {}), time.time()),
        )
        self.db.commit()
        return version

    def list_graphs(self) -> list[dict]:
        cur = self.db.execute(
            """SELECT name, MAX(version), created_at FROM graphs
               GROUP BY name ORDER BY name"""
        )
        return [
            {"name": n, "latest_version": v, "created_at": t}
            for n, v, t in cur.fetchall()
        ]

    def get_versions(self, name: str) -> list[dict]:
        cur = self.db.execute(
            "SELECT version, labels, created_at FROM graphs WHERE name = ? ORDER BY version",
            (name,),
        )
        return [
            {"version": v, "labels": json.loads(l), "created_at": t}
            for v, l, t in cur.fetchall()
        ]

    def get_graph(self, name: str, version: Optional[int] = None) -> Optional[dict]:
        if version is None:
            cur = self.db.execute(
                "SELECT version, spec, labels, created_at FROM graphs "
                "WHERE name = ? ORDER BY version DESC LIMIT 1", (name,),
            )
        else:
            cur = self.db.execute(
                "SELECT version, spec, labels, created_at FROM graphs "
                "WHERE name = ? AND version = ?", (name, version),
            )
        row = cur.fetchone()
        if row is None:
            return None
        v, spec, labels, t = row
        return {
            "name": name, "version": v, "spec": json.loads(spec),
            "labels": json.loads(labels), "created_at": t,
        }

    def delete_graph(self, name: str, version: int) -> bool:
        cur = self.db.execute(
            "DELETE FROM graphs WHERE name = ? AND version = ?", (name, version)
        )
        self.db.commit()
        return cur.rowcount > 0

    @staticmethod
    def _to_spec(spec: dict) -> DeploymentSpec:
        return DeploymentSpec.from_yaml(yaml.safe_dump(spec))

    # ------------------------------------------------------------------ HTTP
    async def _post_graph(self, request: web.Request) -> web.Response:
        body = await request.json()
        spec = body.get("spec")
        if isinstance(spec, str):
            spec = yaml.safe_load(spec)
        if not isinstance(spec, dict) or "name" not in body:
            raise web.HTTPBadRequest(text="need {name, spec}")
        try:
            version = self.put_graph(body["name"], spec, body.get("labels"))
        except (KeyError, ValueError, TypeError) as e:
            raise web.HTTPUnprocessableEntity(text=f"spec does not render: {e}")
        return web.json_response({"name": body["name"], "version": version}, status=201)

    async def _list(self, request: web.Request) -> web.Response:
        return web.json_response(self.list_graphs())

    async def _versions(self, request: web.Request) -> web.Response:
        name = request.match_info["name"]
        versions = self.get_versions(name)
        if not versions:
            raise web.HTTPNotFound
        return web.json_response(versions)

    async def _get(self, request: web.Request) -> web.Response:
        g = self.get_graph(
            request.match_info["name"], int(request.match_info["ver"])
        )
        if g is None:
            raise web.HTTPNotFound
        return web.json_response(g)

    async def _delete(self, request: web.Request) -> web.Response:
        ok = self.delete_graph(
            request.match_info["name"], int(request.match_info["ver"])
        )
        if not ok:
            raise web.HTTPNotFound
        return web.json_response({"deleted": True})

    async def _manifests(self, request: web.Request) -> web.Response:
        g = self.get_graph(
            request.match_info["name"], int(request.match_info["ver"])
        )
        if g is None:
            raise web.HTTPNotFound
        return web.json_response(render_manifests(self._to_spec(g["spec"])))

    async def start(self) -> "ApiStore":
        app = web.Application()
        app.router.add_post("/api/v1/graphs", self._post_graph)
        app.router.add_get("/api/v1/graphs", self._list)
        app.router.add_get("/api/v1/graphs/{name}", self._versions)
        app.router.add_get("/api/v1/graphs/{name}/{ver}", self._get)
        app.router.add_delete("/api/v1/graphs/{name}/{ver}", self._delete)
        app.router.add_get("/api/v1/graphs/{name}/{ver}/manifests", self._manifests)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        self.port = self._runner.addresses[0][1]
        return self

    async def stop(self) -> None:
        if self._runner:
            await self._runner.cleanup()
            self._runner = None
        self.db.close()
