"""Standalone metrics aggregation component.

Reference parity: components/metrics/src/{lib,main}.rs — subscribes to
`kv_hit_rate` events and per-worker ForwardPassMetrics, aggregates, and
exposes Prometheus metrics (pull via /metrics; push mode posts the same
text body to a pushgateway URL, MetricsMode parity lib.rs:96).

Run via `dynamo-tpu metrics --coordinator tcp://...` or embed
MetricsService in-process (tests).
"""

from __future__ import annotations

import asyncio
import json
import logging
from dataclasses import dataclass, field
from typing import Optional

from aiohttp import ClientSession, web

from dynamo_tpu.llm.kv_router.publisher import metrics_subject
from dynamo_tpu.llm.kv_router.scheduler import WorkerMetrics
from dynamo_tpu.obs.metric_names import RouterMetric as RM

log = logging.getLogger("dynamo_tpu.metrics")

__all__ = ["PrometheusMetricsCollector", "MetricsService"]


@dataclass
class _HitStats:
    decisions: int = 0
    isl_blocks: int = 0
    overlap_blocks: int = 0


class PrometheusMetricsCollector:
    """Aggregates worker metrics + hit-rate events; renders Prometheus text."""

    def __init__(self) -> None:
        self.workers: dict[int, WorkerMetrics] = {}
        self.hits: dict[int, _HitStats] = {}

    # ------------------------------------------------------------- ingestion
    def on_worker_metrics(self, m: WorkerMetrics) -> None:
        self.workers[m.worker_id] = m

    def on_hit_rate_event(self, worker_id: int, isl_blocks: int, overlap_blocks: int) -> None:
        s = self.hits.setdefault(worker_id, _HitStats())
        s.decisions += 1
        s.isl_blocks += isl_blocks
        s.overlap_blocks += overlap_blocks

    def remove_worker(self, worker_id: int) -> None:
        self.workers.pop(worker_id, None)

    # -------------------------------------------------------------- exposure
    def render(self) -> str:
        lines: list[str] = []

        def gauge(name: str, help_: str) -> None:
            lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} gauge")

        gauge(RM.KV_BLOCKS_ACTIVE, "active KV blocks per worker")
        for wid, m in sorted(self.workers.items()):
            lines.append(f'{RM.KV_BLOCKS_ACTIVE}{{worker="{wid}"}} {m.kv_active_blocks}')
        gauge(RM.KV_BLOCKS_TOTAL, "total KV blocks per worker")
        for wid, m in sorted(self.workers.items()):
            lines.append(f'{RM.KV_BLOCKS_TOTAL}{{worker="{wid}"}} {m.kv_total_blocks}')
        gauge(RM.REQUEST_ACTIVE_SLOTS, "active request slots per worker")
        for wid, m in sorted(self.workers.items()):
            lines.append(f'{RM.REQUEST_ACTIVE_SLOTS}{{worker="{wid}"}} {m.request_active_slots}')
        gauge(RM.REQUESTS_WAITING, "queued requests per worker")
        for wid, m in sorted(self.workers.items()):
            lines.append(f'{RM.REQUESTS_WAITING}{{worker="{wid}"}} {m.num_requests_waiting}')
        gauge(RM.KV_CACHE_USAGE, "KV cache occupancy fraction per worker")
        for wid, m in sorted(self.workers.items()):
            lines.append(f'{RM.KV_CACHE_USAGE}{{worker="{wid}"}} {m.kv_usage:.6f}')

        lines.append(f"# HELP {RM.ROUTING_DECISIONS_TOTAL} KV-router decisions")
        lines.append(f"# TYPE {RM.ROUTING_DECISIONS_TOTAL} counter")
        for wid, s in sorted(self.hits.items()):
            lines.append(f'{RM.ROUTING_DECISIONS_TOTAL}{{worker="{wid}"}} {s.decisions}')
        lines.append(f"# HELP {RM.KV_HIT_RATE_PERCENT} cumulative prefix-hit rate")
        lines.append(f"# TYPE {RM.KV_HIT_RATE_PERCENT} gauge")
        for wid, s in sorted(self.hits.items()):
            rate = 100.0 * s.overlap_blocks / max(s.isl_blocks, 1)
            lines.append(f'{RM.KV_HIT_RATE_PERCENT}{{worker="{wid}"}} {rate:.3f}')
        return "\n".join(lines) + "\n"


class MetricsService:
    """Subscribes to the event plane and serves /metrics (pull) and/or pushes."""

    def __init__(
        self,
        coordinator,
        namespace: str = "default",
        host: str = "127.0.0.1",
        port: int = 9091,
        push_url: Optional[str] = None,
        push_interval_s: float = 5.0,
    ):
        self.coord = coordinator
        self.namespace = namespace
        self.host = host
        self.port = port
        self.push_url = push_url
        self.push_interval_s = push_interval_s
        self.collector = PrometheusMetricsCollector()
        self._subs: list[int] = []
        self._runner: Optional[web.AppRunner] = None
        self._push_task: Optional[asyncio.Task] = None

    # ---------------------------------------------------------- subscriptions
    def _on_metrics(self, subject: str, payload: bytes) -> None:
        try:
            self.collector.on_worker_metrics(WorkerMetrics(**json.loads(payload)))
        except Exception:
            log.exception("bad metrics payload on %s", subject)

    def _on_hit_rate(self, subject: str, payload: bytes) -> None:
        try:
            d = json.loads(payload)
            self.collector.on_hit_rate_event(
                d["worker_id"], d["isl_blocks"], d["overlap_blocks"]
            )
        except Exception:
            log.exception("bad hit-rate payload on %s", subject)

    # --------------------------------------------------------------- lifecycle
    async def start(self) -> "MetricsService":
        self._subs.append(
            await self.coord.subscribe(metrics_subject(self.namespace), self._on_metrics)
        )
        self._subs.append(
            await self.coord.subscribe(f"{self.namespace}.kv_hit_rate", self._on_hit_rate)
        )
        app = web.Application()
        app.router.add_get("/metrics", self._handle_metrics)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        self.port = self._runner.addresses[0][1]
        if self.push_url:
            self._push_task = asyncio.ensure_future(self._push_loop())
        return self

    async def stop(self) -> None:
        if self._push_task:
            self._push_task.cancel()
            try:
                await self._push_task
            except asyncio.CancelledError:
                pass
            self._push_task = None
        for sid in self._subs:
            try:
                await self.coord.unsubscribe(sid)
            except Exception:
                pass
        self._subs.clear()
        if self._runner:
            await self._runner.cleanup()
            self._runner = None

    async def _handle_metrics(self, request: web.Request) -> web.Response:
        return web.Response(text=self.collector.render(), content_type="text/plain")

    async def _push_loop(self) -> None:
        async with ClientSession() as session:
            while True:
                await asyncio.sleep(self.push_interval_s)
                try:
                    await session.post(self.push_url, data=self.collector.render())
                except asyncio.CancelledError:
                    raise
                except Exception:
                    log.warning("push to %s failed; retrying", self.push_url)
