"""Standalone deployable components (reference components/{http,router,metrics}).

The http frontend and router live behind the CLI (`dynamo-tpu http`,
`dynamo-tpu run in=dyn`); this package holds the metrics aggregation
service and the GPU-free mock worker used to exercise it.
"""
