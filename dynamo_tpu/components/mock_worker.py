"""Mock worker: fake ForwardPassMetrics + KV events, no TPU required.

Reference parity: components/metrics/src/bin/mock_worker.rs — lets the
whole metrics + router stack run on a laptop: the mock publishes plausible
load metrics and stored/removed block events, so a KvRouterSubscriber and
MetricsService behave exactly as with real engines.

Run via `dynamo-tpu mock-worker --coordinator tcp://...` or embed (tests).
"""

from __future__ import annotations

import asyncio
import random
from typing import Optional

from dynamo_tpu.llm.kv.events import KvRemovedEvent, KvStoredEvent
from dynamo_tpu.llm.kv_router.publisher import KvEventPublisher, KvMetricsPublisher
from dynamo_tpu.tokens import sequence_hashes

__all__ = ["MockWorker"]


class MockWorker:
    def __init__(
        self,
        coordinator,
        worker_id: int,
        namespace: str = "default",
        block_size: int = 16,
        total_blocks: int = 256,
        interval_s: float = 0.2,
        seed: Optional[int] = None,
    ):
        self.worker_id = worker_id
        self.block_size = block_size
        self.total_blocks = total_blocks
        self.interval_s = interval_s
        self._rng = random.Random(seed if seed is not None else worker_id)
        self._resident: list[int] = []  # block hashes currently "stored"
        self._active_slots = 0
        self.events = KvEventPublisher(
            coordinator, worker_id, namespace, flush_interval_s=interval_s / 2
        )
        self.metrics = KvMetricsPublisher(
            coordinator, worker_id, self._snapshot, namespace, interval_s=interval_s
        )
        self._task: Optional[asyncio.Task] = None

    def _snapshot(self) -> dict:
        return {
            "request_active_slots": self._active_slots,
            "request_total_slots": 8,
            "kv_active_blocks": len(self._resident),
            "kv_total_blocks": self.total_blocks,
            "num_requests_waiting": self._rng.randrange(0, 3),
            "cache_hit_rate": self._rng.random(),
        }

    def _tick(self) -> None:
        """One simulated engine step: maybe store a new sequence's blocks,
        maybe evict old ones — same event shapes a real engine emits."""
        self._active_slots = self._rng.randrange(0, 8)
        if self._rng.random() < 0.7:
            prompt = [self._rng.randrange(1000) for _ in range(self.block_size * self._rng.randrange(1, 5))]
            hashes = sequence_hashes(prompt, self.block_size)
            self._resident.extend(hashes)
            self.events.sink(KvStoredEvent(block_hashes=hashes))
        while len(self._resident) > self.total_blocks:
            evict = self._resident[: self.block_size]
            del self._resident[: self.block_size]
            self.events.sink(KvRemovedEvent(block_hashes=evict))

    async def _run(self) -> None:
        while True:
            self._tick()
            await asyncio.sleep(self.interval_s)

    async def start(self) -> "MockWorker":
        self.events.start()
        self.metrics.start()
        self._task = asyncio.ensure_future(self._run())
        return self

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        await self.events.stop()
        await self.metrics.stop()
