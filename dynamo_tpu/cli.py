"""dynamo-tpu CLI.

Reference parity:
  * ``run``    — launch/dynamo-run (lib.rs:84, opt.rs:23,91):
                 ``run in=<http|text|stdin|batch:FILE|dyn://ep>
                 out=<echo|tpu|dyn://ep>`` builds the local pipeline
                 frontend → preprocessor → engine → detokenizer
                 (input/common.rs:78-96) or serves/consumes endpoints.
  * ``serve``  — deploy/dynamo/sdk `dynamo serve` (graph + YAML config,
                 process supervisor).
  * ``http``   — components/http standalone OpenAI frontend with dynamic
                 model discovery from the coordinator (discovery.rs:58).
  * ``models`` — launch/llmctl (add/list/remove ModelEntry records).

Invoke as ``python -m dynamo_tpu <cmd> ...``.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import sys
import time
from pathlib import Path
from typing import Optional

log = logging.getLogger("dynamo_tpu.cli")

MODELS_PREFIX = "models/"  # under {namespace}/


# ------------------------------------------------------------ engine build ----


def _load_any_checkpoint(path: str, dtype):
    """(model, params, quantized) for any supported checkpoint format:
    native (dynamo-tpu quantize), GGUF, or HF safetensors dir (Llama
    family via the unified decoder; DeepSeek dirs via the MLA model).
    ``dtype`` None = native checkpoints keep their stored dtype, others
    bf16."""
    from dynamo_tpu.models.checkpoint import is_native_checkpoint, load_checkpoint
    from dynamo_tpu.models.llama import LlamaModel

    if is_native_checkpoint(path):
        # pre-converted native checkpoint: params load in their serving
        # dtype — no per-start bf16 load + quantize pass
        cfg, params, quantized = load_checkpoint(path, dtype=dtype)
        return LlamaModel(cfg), params, quantized
    if path.endswith(".gguf"):
        from dynamo_tpu.llm.gguf import load_gguf_model

        cfg, params = load_gguf_model(path, dtype=dtype or "bfloat16")
        return LlamaModel(cfg), params, False
    from dynamo_tpu.models.loader import (
        is_deepseek_dir,
        load_deepseek_dir,
        load_model_dir,
    )

    if is_deepseek_dir(path):
        from dynamo_tpu.models.deepseek import DeepseekModel

        dcfg, params = load_deepseek_dir(path, dtype=dtype or "bfloat16")
        return DeepseekModel(dcfg), params, False
    cfg, params = load_model_dir(path, dtype=dtype or "bfloat16")
    return LlamaModel(cfg), params, False


def _build_local_engine(args) -> tuple[object, object]:
    """out=tpu|echo → (engine, card): the native JAX engine or the echo stub."""
    from dynamo_tpu.llm.model_card import ModelDeploymentCard

    if args.model_path is None:
        raise SystemExit(f"out={args.out} needs --model-path (weights + tokenizer)")
    from dynamo_tpu.llm.model_store import is_model_ref, resolve_model_sync

    if is_model_ref(args.model_path):
        # dyn://models/<name>: pull from the coordinator blob store into
        # the local cache (artifact distribution — only the pushing host
        # needs the checkpoint on disk).  Covers run, serve graphs, and
        # the colocated worker's two engines, since they all build here.
        import os as _os

        ref = args.model_path
        args.model_path = resolve_model_sync(
            ref,
            getattr(args, "coordinator", None)
            or _os.environ.get("DYNTPU_COORDINATOR"),
        )
        log.info("resolved %s -> %s", ref, args.model_path)
    is_gguf = args.model_path.endswith(".gguf")
    card = (
        ModelDeploymentCard.from_gguf(args.model_path, name=args.model_name)
        if is_gguf
        else ModelDeploymentCard.from_hf_dir(args.model_path, name=args.model_name)
    )

    if args.out == "echo":
        from dynamo_tpu.llm.engines import EchoEngineCore

        return EchoEngineCore(), card

    from dynamo_tpu.engine import AsyncLLMEngine, EngineConfig, EngineCore
    from dynamo_tpu.utils.compilation_cache import enable_persistent_cache

    # persistent XLA compilation cache: a restarted worker re-jits from
    # disk instead of recompiling (VERDICT r5 next #1)
    enable_persistent_cache()

    # multi-host: join the jax.distributed mesh BEFORE any JAX array is
    # created — loading/quantizing weights initializes the backend, and
    # jax.distributed.initialize must run first for jax.devices() to be
    # global (runtime/multihost.py)
    from dynamo_tpu.runtime.multihost import MultiHostSpec, bootstrap
    from dynamo_tpu.utils.mesh import MESH_AXES, build_mesh

    nnodes = int(getattr(args, "nnodes", 1) or 1)
    if nnodes > 1:
        bootstrap(MultiHostSpec(
            num_processes=nnodes,
            process_id=int(getattr(args, "node_rank", 0) or 0),
            coordinator_url=getattr(args, "coordinator", None),
        ))

    # --dtype default is None so the native branch can tell "explicitly
    # requested" from "use the checkpoint's stored dtype"
    dtype = getattr(args, "dtype", None)
    model, params, quantized = _load_any_checkpoint(args.model_path, dtype)
    if getattr(args, "quantize", "none") == "int8" and not quantized:
        if not hasattr(model, "quantize_params"):
            raise SystemExit(
                "--quantize int8 is not wired for this model family yet"
            )
        # int8 weight-only serving (models/quant.py): ~2x HBM headroom
        params = model.quantize_params(params)

    mesh = None
    tp = int(getattr(args, "tp", 1) or 1)
    dp = int(getattr(args, "dp", 1) or 1)
    if tp * dp > 1:
        mesh = build_mesh((dp, tp), MESH_AXES)

    cfg = EngineConfig(
        max_batch_size=args.max_batch_size,
        max_model_len=args.max_model_len,
        block_size=args.block_size,
        num_blocks=args.num_blocks,
        num_host_blocks=int(getattr(args, "num_host_blocks", 0) or 0),
        # persistent prefix-cache tier (llm/kv/persist.py): default off
        kv_persist_dir=(getattr(args, "kv_persist_dir", None) or None),
        kv_persist_max_bytes=int(
            getattr(args, "kv_persist_max_bytes", 0) or 0),
        kv_persist_ttl_s=float(getattr(args, "kv_persist_ttl", 0) or 0),
        cache_dtype=(
            "int8" if getattr(args, "kv_cache_dtype", "model") == "int8" else None
        ),
        spec_tokens=int(getattr(args, "spec_tokens", 0) or 0),
        draft_num_blocks=int(getattr(args, "spec_draft_num_blocks", 0) or 0),
        # ring-attention context parallelism for long prompts (needs a
        # mesh whose "data" axis is > 1)
        sp_prefill_threshold=int(
            getattr(args, "sp_prefill_threshold", 0) or 0),
        prefill_chunk_tokens=int(
            getattr(args, "prefill_chunk_tokens", 0) or 0),
        # token-budget ragged prefill: pack several waiting prompts'
        # chunks into one dispatch (docs/engine_scheduling.md)
        prefill_token_budget=int(
            getattr(args, "prefill_token_budget", 0) or 0),
        # unified mixed prefill+decode dispatch: one token-budget ragged
        # step per turn when both phases have work
        unified_token_dispatch=bool(
            getattr(args, "unified_token_dispatch", False)),
        # double-buffered dispatch: fused bursts + speculative next-turn
        # prebuild overlapped with device compute (implies unified)
        lookahead_dispatch=bool(
            getattr(args, "lookahead_dispatch", False)),
        # dtspan profile hook: one jax.profiler capture over the first
        # profile_steps device steps
        profile_dir=(getattr(args, "profile_dir", None) or None),
        profile_steps=int(getattr(args, "profile_steps", 8) or 8),
    )
    draft = None
    dpath = getattr(args, "spec_draft_model", None)
    if dpath:
        if cfg.spec_tokens <= 0:
            raise SystemExit("--spec-draft-model requires --spec-tokens > 0")
        # draft-model speculation: a small same-tokenizer model proposes,
        # the target verifies (engine/draft.py).  Accepts the same
        # checkpoint formats as --model-path (native / GGUF / HF dir);
        # loads unsharded.
        dmodel, dparams, _ = _load_any_checkpoint(dpath, dtype)
        draft = (dmodel, dparams)
    core = EngineCore(
        model, params, cfg, mesh=mesh,
        eos_token_ids=card.eos_token_ids or None, draft=draft,
    )
    return AsyncLLMEngine(core).start(), card


async def _build_out_engine(args, runtime=None):
    """Resolve out= to a ParsedRequest-level engine (full local pipeline or
    a remote endpoint client).  Returns (pipeline, card, raw_engine) — the
    raw engine is what worker-side publishers hook into (the pipeline
    wrapper hides .core)."""
    from dynamo_tpu.llm.engines import build_serving_pipeline

    if args.out.startswith("dyn://"):
        from dynamo_tpu.runtime.protocols import parse_endpoint_url

        ns, comp, ep = parse_endpoint_url(args.out)
        client = await runtime.namespace(ns).component(comp).endpoint(ep).client()
        return client, None, None
    engine, card = _build_local_engine(args)
    return build_serving_pipeline(engine, card), card, engine


def _runtime_config(args):
    from dynamo_tpu.runtime.config import RuntimeConfig

    kw = {}
    if args.coordinator:
        kw["coordinator_url"] = args.coordinator
    if args.namespace:
        kw["namespace"] = args.namespace
    return RuntimeConfig(**kw)


# ------------------------------------------------------------------- run ------


async def _cmd_run(args) -> None:
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.runtime import serde

    serde.register_llm_types()
    needs_runtime = args.out.startswith("dyn://") or args.inp.startswith("dyn://")
    runtime = await DistributedRuntime.connect(_runtime_config(args)) if needs_runtime else None

    engine, card, raw_engine = await _build_out_engine(args, runtime)
    model_name = args.model_name or (card.name if card else "model")

    if args.inp.startswith("dyn://"):
        # serve the engine AT this endpoint (worker mode, Input::Endpoint)
        from dynamo_tpu.runtime.protocols import parse_endpoint_url

        ns, comp, ep = parse_endpoint_url(args.inp)
        await runtime.namespace(ns).component(comp).endpoint(ep).serve(engine)
        _attach_worker_publishers(runtime, raw_engine, ns)
        log.info("serving %s at %s — ctrl-c to stop", model_name, args.inp)
        await asyncio.Event().wait()

    elif args.inp == "http":
        from dynamo_tpu.llm.http.service import HttpService

        svc = HttpService(host=args.host, port=args.http_port)
        svc.manager.add_model(model_name, engine, card)
        await svc.start()
        log.info("OpenAI server on %s:%s — ctrl-c to stop", svc.host, svc.port)
        await asyncio.Event().wait()

    elif args.inp.startswith("text:"):
        await _one_prompt(engine, model_name, args.inp[5:], args)

    elif args.inp == "stdin":
        for line in sys.stdin:
            line = line.strip()
            if line:
                await _one_prompt(engine, model_name, line, args)

    elif args.inp.startswith("batch:"):
        await _batch(engine, model_name, Path(args.inp[6:]), args)

    else:
        raise SystemExit(f"unknown in={args.inp}")


async def _one_prompt(engine, model_name: str, prompt: str, args) -> None:
    from dynamo_tpu.llm.openai import parse_request
    from dynamo_tpu.runtime.engine import Context

    parsed = parse_request(
        {"model": model_name, "prompt": prompt, "max_tokens": args.max_tokens},
        chat=False,
    )
    async for out in engine.generate(Context(parsed)):
        if out.text:
            print(out.text, end="", flush=True)
    print()


async def _batch(engine, model_name: str, path: Path, args) -> None:
    """Input::Batch benchmark mode (ref input/batch.rs): JSONL in
    {"text": ...} → JSONL out with tokens + timing."""
    from dynamo_tpu.llm.openai import parse_request
    from dynamo_tpu.runtime.engine import Context

    async def one(text: str) -> dict:
        parsed = parse_request(
            {"model": model_name, "prompt": text, "max_tokens": args.max_tokens},
            chat=False,
        )
        t0 = time.perf_counter()
        ttft, n_tokens, chunks = None, 0, []
        async for out in engine.generate(Context(parsed)):
            if ttft is None:
                ttft = time.perf_counter() - t0
            n_tokens += len(out.token_ids)
            if out.text:
                chunks.append(out.text)
        dt = time.perf_counter() - t0
        return {
            "text": "".join(chunks),
            "output_tokens": n_tokens,
            "ttft_s": round(ttft or 0.0, 4),
            "total_s": round(dt, 4),
        }

    lines = [json.loads(l) for l in path.read_text().splitlines() if l.strip()]
    results = await asyncio.gather(*(one(l["text"]) for l in lines))
    out_path = path.with_suffix(".out.jsonl")
    with open(out_path, "w") as f:
        for r in results:
            f.write(json.dumps(r) + "\n")
    total_tok = sum(r["output_tokens"] for r in results)
    total_s = max(r["total_s"] for r in results) if results else 0.0
    print(
        json.dumps(
            {
                "requests": len(results),
                "output_tokens": total_tok,
                "tok_per_s": round(total_tok / total_s, 2) if total_s else 0.0,
                "results": str(out_path),
            }
        )
    )


def _attach_worker_publishers(runtime, engine, namespace: str) -> None:
    """Real-engine worker: publish KV events + ForwardPassMetrics so the
    smart router and metrics component see this worker (publisher.rs
    parity).  No-op for engines without a core (echo, remote clients).
    Unwraps pipeline (``._engine``) and DecodeWorker (``.engine``)
    wrappers until an EngineCore surfaces."""
    core = None
    seen = set()
    while engine is not None and id(engine) not in seen:
        seen.add(id(engine))
        core = getattr(engine, "core", None)
        if core is not None:
            break
        engine = getattr(engine, "_engine", None) or getattr(engine, "engine", None)
    if core is None or not hasattr(core, "block_manager"):
        return
    from dynamo_tpu.llm.kv_router.publisher import KvEventPublisher, KvMetricsPublisher

    wid = runtime.instance_id
    events = KvEventPublisher(runtime.coordinator, wid, namespace).start()
    core.block_manager.event_sink = events.sink
    metrics = KvMetricsPublisher(
        runtime.coordinator, wid, core.metrics, namespace
    ).start()
    # both publishers' flush loops must die with the runtime — nothing
    # else ever holds a reference that can reach their stop() (dtsan leak)
    runtime.on_shutdown(events.stop)
    runtime.on_shutdown(metrics.stop)
    # persistent tier replication: sync the content-addressed block store
    # with the coordinator index (boot-time pull = planner scale-up
    # pre-warm; periodic publish shares this worker's prefixes)
    store = getattr(core, "persist_store", None)
    if store is not None:
        from dynamo_tpu.llm.kv.persist import PersistReplicator

        replicator = PersistReplicator(runtime.coordinator, store, namespace)
        replicator.start_soon()
        runtime.on_shutdown(replicator.stop)


# ------------------------------------------------------------------ serve -----


async def _cmd_serve(args) -> None:
    from dynamo_tpu.sdk.config import ServiceConfig
    from dynamo_tpu.sdk.serving import ServeSupervisor

    graph = args.graph
    if getattr(args, "package", None):
        # packaged-graph deploy (the reference's bento flow): pull the
        # archive from the api-store, verify + unpack into the cache,
        # and serve its manifest entry with the package root importable
        # (sys.path for the supervisor's entry load, PYTHONPATH for the
        # worker processes it spawns)
        manifest, src_root = await _pull_package(
            args.package, args.api_store, args.package_cache)
        graph = graph if graph not in (None, "-") else manifest["entry"]
        sys.path.insert(0, str(src_root))
        prev = os.environ.get("PYTHONPATH")
        # no trailing separator when PYTHONPATH was unset: an empty
        # component means cwd, which packaged deploys must not import
        os.environ["PYTHONPATH"] = (
            f"{src_root}{os.pathsep}{prev}" if prev else str(src_root))
        log.info("serving package %s entry %s from %s",
                 args.package, graph, src_root)
    config = ServiceConfig.from_yaml(args.config) if args.config else ServiceConfig()
    sup = ServeSupervisor(graph, config, coordinator_url=args.coordinator)
    await sup.start()
    try:
        await sup.watch()
    finally:
        await sup.stop()


# ---------------------------------------------------------------- package -----


def _split_pkg_ref(ref: str) -> tuple[str, Optional[str]]:
    name, _, ver = ref.partition(":")
    return name, (ver or None)


async def _pull_package(ref: str, api_store: str, cache_root: str):
    """Resolve name[:version], reuse the local cache when it already
    holds that version, else download + unpack.  Returns (manifest,
    src_root)."""
    from aiohttp import ClientSession

    from dynamo_tpu.deploy.packaging import cache_lookup, cached_unpack

    name, ver = _split_pkg_ref(ref)
    async with ClientSession() as s:
        if ver is None:
            # cheap metadata GET resolves "latest" BEFORE any archive
            # transfer, so a cache hit skips the download entirely
            async with s.get(
                    f"{api_store}/api/v1/packages/{name}/latest") as resp:
                if resp.status == 404:
                    raise SystemExit(
                        f"package {ref!r} not found in {api_store}")
                resp.raise_for_status()
                ver = str((await resp.json())["version"])
        version = int(ver)
        hit = cache_lookup(cache_root, name, version)
        if hit is not None:
            return hit
        url = f"{api_store}/api/v1/packages/{name}/{version}/archive"
        async with s.get(url) as resp:
            if resp.status == 404:
                raise SystemExit(f"package {ref!r} not found in {api_store}")
            resp.raise_for_status()
            archive = await resp.read()
    return cached_unpack(archive, cache_root, name, version)


async def _cmd_package(args) -> None:
    from dynamo_tpu.deploy.packaging import build_package, read_manifest

    if args.pkg_cmd == "build":
        manifest = build_package(args.src, args.entry, args.name, args.out)
        print(json.dumps({"name": manifest["name"],
                          "entry": manifest["entry"],
                          "files": len(manifest["files"]),
                          "out": args.out}))
    elif args.pkg_cmd == "push":
        from aiohttp import ClientSession

        data = open(args.pkg, "rb").read()
        read_manifest(data)  # fail client-side with a good message
        async with ClientSession() as s:
            async with s.post(f"{args.api_store}/api/v1/packages",
                              data=data) as resp:
                body = await resp.text()
                if resp.status != 201:
                    raise SystemExit(f"push failed ({resp.status}): {body}")
                print(body)
    elif args.pkg_cmd == "pull":
        manifest, src_root = await _pull_package(
            args.ref, args.api_store, args.out)
        print(json.dumps({"name": manifest["name"],
                          "entry": manifest["entry"],
                          "src": str(src_root)}))
    elif args.pkg_cmd == "list":
        from aiohttp import ClientSession

        async with ClientSession() as s:
            async with s.get(f"{args.api_store}/api/v1/packages") as resp:
                resp.raise_for_status()
                print(json.dumps(await resp.json()))


# ------------------------------------------------------------------- http -----


async def _cmd_http(args) -> None:
    """Standalone OpenAI frontend: discovers ModelEntry records on the
    coordinator and builds a remote pipeline per model (ref
    components/http/src/main.rs + http/service/discovery.rs:58)."""
    from dynamo_tpu.llm.engines import build_serving_pipeline
    from dynamo_tpu.llm.http.service import HttpService
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.runtime import serde
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.runtime.protocols import parse_endpoint_url

    serde.register_llm_types()
    runtime = await DistributedRuntime.connect(_runtime_config(args))
    svc = HttpService(host=args.host, port=args.http_port)
    ns = args.namespace or "dynamo"
    clients: dict[str, object] = {}
    # discovery-event tasks, retained so a failed add_model (bad entry,
    # unreachable endpoint) is logged instead of vanishing with the task
    add_tasks: set[asyncio.Task] = set()

    def _add_done(task: asyncio.Task) -> None:
        add_tasks.discard(task)
        if not task.cancelled() and task.exception() is not None:
            log.error("add_model failed", exc_info=task.exception())

    async def add_model(name: str, entry: dict) -> None:
        e_ns, comp, ep = parse_endpoint_url(entry["endpoint"])
        client = await runtime.namespace(e_ns).component(comp).endpoint(ep).client()
        clients[name] = client
        card = (
            ModelDeploymentCard.from_hf_dir(entry["model_path"], name=name)
            if entry.get("model_path")
            else ModelDeploymentCard.from_dict(entry.get("card", {"name": name}))
        )
        svc.manager.add_model(name, build_serving_pipeline(client, card), card)
        log.info("model %s -> %s", name, entry["endpoint"])

    def on_event(event: str, key: str, value) -> None:
        name = key.rsplit("/", 1)[-1]
        if event == "put":
            task = asyncio.ensure_future(add_model(name, value))
            add_tasks.add(task)
            task.add_done_callback(_add_done)
        elif event == "delete":
            svc.manager.remove_model(name)
            clients.pop(name, None)

    _, snapshot = await runtime.coordinator.watch(f"{ns}/{MODELS_PREFIX}", on_event)
    for key, value in snapshot.items():
        try:
            await add_model(key.rsplit("/", 1)[-1], value)
        except Exception:
            # one bad registration must not take down the whole frontend
            log.exception("add_model %s failed at startup", key)

    await svc.start()
    log.info("OpenAI frontend on %s:%s (namespace %s)", svc.host, svc.port, ns)
    await asyncio.Event().wait()


# ------------------------------------------------------------- coordinator ----


async def _cmd_coordinator(args) -> None:
    """Run the control/event/queue-plane coordinator (etcd+NATS stand-in)."""
    from dynamo_tpu.runtime.transports.coordinator import CoordinatorServer

    server = await CoordinatorServer(
        host=args.host, port=args.port, data_dir=args.data_dir
    ).start()
    log.info("coordinator on %s (durable=%s)", server.url, bool(args.data_dir))
    await asyncio.Event().wait()


# ------------------------------------------------------------------ router ----


async def start_router_service(runtime, namespace: str = "default",
                               block_size: int = 16,
                               workers_endpoint: str | None = None):
    """Wire a live KvRouter behind `dyn://{ns}.router.generate` (shared by
    the CLI command and tests).  Returns the router.

    ``workers_endpoint`` ("component/endpoint", e.g. "backend/generate")
    watches that endpoint's discovery prefix so a dead worker's delete
    event evicts it from the router's candidate set immediately."""
    from dynamo_tpu.llm.kv_router.metrics_aggregator import KvRouterSubscriber
    from dynamo_tpu.llm.kv_router.router import KvRouter

    workers_prefix = None
    if workers_endpoint:
        comp, _, ep = workers_endpoint.partition("/")
        workers_prefix = f"{namespace}/components/{comp}/endpoints/{ep or 'generate'}/"
    router = KvRouter(block_size=block_size)
    sub = await KvRouterSubscriber(router, runtime.coordinator, namespace,
                                   workers_prefix=workers_prefix).start()
    # the subscriber's flush/watch tasks must die with the runtime, or
    # they outlive every caller that can reach sub.stop() (dtsan leak)
    runtime.on_shutdown(sub.stop)
    # KvRouter IS the endpoint engine: its generate() yields one
    # wire-serializable decision dict per request
    ep = runtime.namespace(namespace).component("router").endpoint("generate")
    await ep.serve(router)
    return router


async def _cmd_router(args) -> None:
    """Standalone KV-aware router service: serves routing decisions over
    `dyn://{ns}.router.generate` and keeps its prefix index + cost model
    live off the coordinator's KV-event/metrics subjects (ref
    components/router/src/main.rs)."""
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    runtime = await DistributedRuntime.connect(_runtime_config(args))
    ns = args.namespace or "default"
    await start_router_service(runtime, ns, args.block_size,
                               workers_endpoint=args.workers_endpoint)
    log.info("router service up: dyn://%s.router.generate", ns)
    await asyncio.Event().wait()


# ---------------------------------------------------------------- operator ----


async def _cmd_operator(args) -> None:
    """Run the reconcile loop over a watched directory of
    DynamoTpuDeployment specs and/or the DynamoTpuDeployment custom
    resources (--crd; ref deploy/dynamo/operator)."""
    from dynamo_tpu.deploy.operator import (
        KubectlCluster,
        KubectlCrSource,
        MemoryCluster,
        Operator,
    )

    if not args.specs_dir and not args.crd:
        raise SystemExit("operator needs a specs dir and/or --crd")
    cluster = MemoryCluster() if args.dry_run else KubectlCluster(
        context=args.context
    )
    coord = None
    if args.coordinator:
        from dynamo_tpu.runtime.transports.coordinator import CoordinatorClient

        coord = await CoordinatorClient(args.coordinator, reconnect=True).connect()
    cr_source = (
        KubectlCrSource(context=args.context, read_only=args.dry_run)
        if args.crd else None
    )
    op = Operator(cluster, interval_s=args.interval, watch_dir=args.specs_dir,
                  coordinator=coord, cr_source=cr_source)
    if args.specs_dir:
        op.load_dir(args.specs_dir)
    log.info("operator watching %s (crd=%s, %d specs, dry_run=%s, "
             "coordinator=%s)", args.specs_dir, args.crd, len(op.specs),
             args.dry_run, args.coordinator)
    await op.run()


# ------------------------------------------------------------------ deploy ----


async def _cmd_deploy(args) -> None:
    """Render k8s manifests from a DynamoTpuDeployment spec (operator-lite,
    ref deploy/dynamo/operator CRD controller)."""
    from dynamo_tpu.deploy import DeploymentSpec
    from dynamo_tpu.deploy.renderer import render_manifests, render_to_dir

    spec = DeploymentSpec.from_yaml(Path(args.spec))
    if args.out:
        paths = render_to_dir(spec, args.out)
        for p in paths:
            print(p)
    else:
        import yaml as _yaml

        print(_yaml.safe_dump_all(render_manifests(spec), sort_keys=False))


# -------------------------------------------------------------- api store -----


async def _cmd_api_store(args) -> None:
    """Versioned graph registry with manifest rendering (api-store parity)."""
    from dynamo_tpu.components.api_store import ApiStore

    store = await ApiStore(db_path=args.db, host=args.host, port=args.port).start()
    log.info("api-store on http://%s:%s (db %s)", store.host, store.port, args.db)
    await asyncio.Event().wait()


# ---------------------------------------------------------------- metrics -----


async def _cmd_metrics(args) -> None:
    """Standalone metrics aggregation service (components/metrics parity):
    Prometheus /metrics fed by worker ForwardPassMetrics + kv_hit_rate."""
    from dynamo_tpu.components.metrics import MetricsService
    from dynamo_tpu.runtime.transports.coordinator import CoordinatorClient

    coord = await CoordinatorClient(
        args.coordinator or "tcp://127.0.0.1:6180"
    ).connect()
    svc = await MetricsService(
        coord,
        namespace=args.namespace or "dynamo",
        host=args.host,
        port=args.port,
        push_url=args.push_url,
    ).start()
    log.info("metrics on http://%s:%s/metrics", svc.host, svc.port)
    await asyncio.Event().wait()


async def _cmd_planner(args) -> None:
    """SLA planner loop over the live metrics plane (reference Planner
    parity, docs/architecture.md:47): logs a per-tick plan — replica
    targets + role-flip decisions — from pool saturation and prefill
    queue depth.  Dry-run by default (LogActuator); in-cluster scaling
    actuates through the operator, local scaling through the sdk
    supervisor (docs/planner.md)."""
    from dynamo_tpu.llm.kv.persist import PrewarmActuator
    from dynamo_tpu.planner import LogActuator, PlannerConfig, PlannerLoop
    from dynamo_tpu.runtime.transports.coordinator import CoordinatorClient

    coord = await CoordinatorClient(
        args.coordinator or "tcp://127.0.0.1:6180"
    ).connect()
    ns = args.namespace or "dynamo"
    loop = await PlannerLoop(
        coord,
        namespace=ns,
        config=PlannerConfig(
            queue_target_per_replica=args.target_per_replica,
            decode_target_usage=args.target_usage,
        ),
        prefill_component=args.prefill_component,
        decode_component=args.decode_component,
        interval_s=args.interval,
        # scale-ups also publish a persist pre-warm hint: fresh workers'
        # PersistReplicators pull the shared KV store at boot instead of
        # cold-starting (docs/kv_persistence.md)
        actuators=(LogActuator(), PrewarmActuator(coord, ns)),
    ).start()
    log.info("planner loop on namespace %r — ctrl-c to stop", loop.namespace)
    await asyncio.Event().wait()


async def _cmd_mock_worker(args) -> None:
    """GPU/TPU-free fake worker for exercising the router + metrics stack
    (components/metrics/src/bin/mock_worker.rs parity)."""
    from dynamo_tpu.components.mock_worker import MockWorker
    from dynamo_tpu.runtime.transports.coordinator import CoordinatorClient

    coord = await CoordinatorClient(
        args.coordinator or "tcp://127.0.0.1:6180"
    ).connect()
    workers = [
        await MockWorker(
            coord, worker_id=args.worker_id + i, namespace=args.namespace or "dynamo"
        ).start()
        for i in range(args.count)
    ]
    log.info("%d mock worker(s) publishing — ctrl-c to stop", len(workers))
    await asyncio.Event().wait()


# ----------------------------------------------------------------- models -----


def _cmd_quantize(args) -> None:
    """Offline conversion: HF/GGUF -> native orbax checkpoint (+ tokenizer
    and config copied alongside so --model-path works unchanged)."""
    import shutil

    from dynamo_tpu.models.checkpoint import save_checkpoint
    from dynamo_tpu.models.llama import LlamaModel
    from dynamo_tpu.models.loader import load_model_dir

    t0 = time.monotonic()
    if args.src.endswith(".gguf"):
        from dynamo_tpu.llm.gguf import load_gguf_model

        cfg, params = load_gguf_model(args.src, dtype=args.dtype)
    else:
        cfg, params = load_model_dir(args.src, dtype=args.dtype)
    quantized = args.scheme == "int8"
    if quantized:
        params = LlamaModel(cfg).quantize_params(params)
    save_checkpoint(args.out, cfg, params, quantized=quantized)
    # tokenizer + config ride along so ModelDeploymentCard.from_hf_dir and
    # the preprocessor work off the converted dir directly
    src = Path(args.src)
    if src.is_dir():
        for name in ("tokenizer.json", "tokenizer_config.json", "config.json",
                     "generation_config.json", "special_tokens_map.json"):
            if (src / name).is_file():
                shutil.copy2(src / name, Path(args.out) / name)
    else:
        from dynamo_tpu.llm.model_card import ModelDeploymentCard

        card = ModelDeploymentCard.from_gguf(args.src)
        if card.tokenizer_path and Path(card.tokenizer_path).is_file():
            shutil.copy2(card.tokenizer_path, Path(args.out) / "tokenizer.json")
        else:
            log.warning(
                "gguf carried no materialisable tokenizer; place a "
                "tokenizer.json next to %s before serving", args.out,
            )
        if card.chat_template:
            # from_hf_dir picks this up, so chat rendering survives the
            # conversion instead of falling back to the default template
            (Path(args.out) / "chat_template.jinja").write_text(
                card.chat_template
            )
        # minimal config.json so from_hf_dir finds eos/context on the
        # converted dir (the gguf metadata carried them)
        (Path(args.out) / "config.json").write_text(json.dumps({
            "eos_token_id": card.eos_token_ids,
            "bos_token_id": card.bos_token_id,
            "max_position_embeddings": card.context_length,
        }))
    log.info("wrote %s (%s, scheme=%s) in %.1fs", args.out, cfg.dtype,
             args.scheme, time.monotonic() - t0)


async def _cmd_trace(args) -> None:
    """Fetch one request's Chrome trace-event JSON from a frontend's
    ``/debug/traces/{request_id}`` endpoint.  The output loads in
    chrome://tracing and https://ui.perfetto.dev; the serving processes
    must run with tracing on (``--trace`` or ``DYNAMO_TRACE=1``)."""
    from aiohttp import ClientSession

    url = f"{args.url.rstrip('/')}/debug/traces/{args.request_id}"
    async with ClientSession() as s:
        async with s.get(url) as resp:
            body = await resp.text()
            if resp.status != 200:
                raise SystemExit(f"trace fetch failed ({resp.status}): {body}")
    if args.out:
        Path(args.out).write_text(body)
        print(args.out)
    else:
        print(body)


async def _cmd_models(args) -> None:
    """llmctl parity: manage ModelEntry records on the coordinator — plus
    ``push``/``pull``: model-artifact distribution through the blob store
    (ref model.rs:150-199 NATS object store), so remote workers boot from
    a ``dyn://models/<name>`` ref with the checkpoint on one host only."""
    from dynamo_tpu.runtime.transports.coordinator import CoordinatorClient

    ns = args.namespace or "dynamo"
    coord = await CoordinatorClient(
        args.coordinator or "tcp://127.0.0.1:6180"
    ).connect()
    try:
        if args.action == "push":
            from dynamo_tpu.llm.model_store import push_model

            if not args.name or not args.endpoint:
                raise SystemExit("usage: models push <name> <model-dir>")
            manifest = await push_model(coord, args.name, args.endpoint)
            total = sum(f["size"] for f in manifest["files"].values())
            print(f"pushed {args.name}: {len(manifest['files'])} files, "
                  f"{total} bytes, digest {manifest['digest'][:12]}")
        elif args.action == "pull":
            from dynamo_tpu.llm.model_store import pull_model

            if not args.name:
                raise SystemExit("usage: models pull <name> [--out DIR]")
            path = await pull_model(coord, args.name,
                                    cache_dir=getattr(args, "out", None))
            print(path)
        elif args.action == "add":
            entry = {"endpoint": args.endpoint, "model_path": args.model_path}
            await coord.kv_put(f"{ns}/{MODELS_PREFIX}{args.name}", entry)
            print(f"added {args.name} -> {args.endpoint}")
        elif args.action == "remove":
            ok = await coord.kv_delete(f"{ns}/{MODELS_PREFIX}{args.name}")
            print(f"removed {args.name}" if ok else f"no such model {args.name}")
        else:  # list
            items = await coord.kv_get_prefix(f"{ns}/{MODELS_PREFIX}")
            for key, value in sorted(items.items()):
                print(f"{key.rsplit('/', 1)[-1]}\t{value.get('endpoint')}")
    finally:
        await coord.close()


# ------------------------------------------------------------------ parser ----


def _parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="dynamo-tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    def common(sp):
        sp.add_argument("--coordinator", default=None, help="tcp://host:port")
        sp.add_argument("--namespace", default=None)

    run = sub.add_parser("run", help="run a model or pipeline (dynamo-run parity)")
    run.add_argument("inout", nargs="+", help="in=<...> out=<...>")
    run.add_argument("--model-path", default=None)
    run.add_argument("--model-name", default=None)
    run.add_argument("--dtype", default=None,
                     help="activation dtype (default: bfloat16, or the "
                     "native checkpoint's stored dtype)")
    run.add_argument("--max-batch-size", type=int, default=8)
    run.add_argument("--spec-tokens", type=int, default=0,
                     help="speculative decoding: verify up to N proposed "
                     "tokens per dispatch (rejection-sampled — exact at "
                     "any temperature); proposals come from prompt-lookup "
                     "n-grams, or a draft model with --spec-draft-model")
    run.add_argument("--spec-draft-model", default=None,
                     help="small same-tokenizer model dir: draft-model "
                     "speculation instead of n-gram lookup")
    run.add_argument("--spec-draft-num-blocks", type=int, default=0,
                     help="draft cache block count (0 = same as "
                     "--num-blocks; shrink on HBM-tight deployments)")
    run.add_argument("--kv-cache-dtype", choices=["model", "int8"],
                     default="model",
                     help="model = cache in the model dtype; int8 = "
                     "quantized KV cache (ops/kv_quant.py): half the KV "
                     "HBM footprint and decode KV traffic")
    run.add_argument("--quantize", choices=["none", "int8"], default="none",
                     help="int8 weight-only quantization (halves weight HBM)")
    run.add_argument("--tp", type=int, default=1, help="tensor-parallel size")
    run.add_argument("--dp", type=int, default=1, help="data-parallel size")
    run.add_argument("--sp-prefill-threshold", type=int, default=0,
                     help="prompts at least this long prefill with the "
                     "sequence sharded over the mesh data axis (ring "
                     "attention context parallelism); 0 = off, needs dp>1")
    run.add_argument("--prefill-chunk-tokens", type=int, default=0,
                     help="chunked prefill: max prompt tokens per prefill "
                     "dispatch (0 = whole remainder); keeps decode ITL "
                     "flat under long prompts")
    run.add_argument("--prefill-token-budget", type=int, default=0,
                     help="token-budget ragged prefill: pack up to this "
                     "many tokens of several waiting prompts' chunks "
                     "into ONE dispatch (0 = one request per dispatch); "
                     "see docs/engine_scheduling.md")
    run.add_argument("--unified-token-dispatch", action="store_true",
                     help="unified mixed prefill+decode dispatch: when "
                     "both phases have work, run ONE token-budget "
                     "ragged step per turn (decode rows lead the flat "
                     "axis, prefill chunks pack the remaining "
                     "--prefill-token-budget, which defaults to 1024 "
                     "when unset); see docs/engine_scheduling.md")
    run.add_argument("--lookahead-dispatch", action="store_true",
                     default=bool(int(os.environ.get(
                         "DYNAMO_LOOKAHEAD", "0") or "0")),
                     help="double-buffered dispatch: fuse mixed "
                     "prefill+decode turns into multi-step bursts with "
                     "ONE device readback, and prebuild the next turn's "
                     "dispatch on the host while the device computes "
                     "(implies --unified-token-dispatch; also "
                     "DYNAMO_LOOKAHEAD=1); see docs/engine_scheduling.md")
    run.add_argument("--nnodes", type=int, default=1,
                     help="worker processes forming ONE mesh (multi-host)")
    run.add_argument("--node-rank", type=int, default=0)
    run.add_argument("--max-model-len", type=int, default=4096)
    run.add_argument("--block-size", type=int, default=16)
    run.add_argument("--num-blocks", type=int, default=512)
    run.add_argument("--num-host-blocks", type=int, default=0,
                     help="host-RAM KV offload tier (0 = disabled): "
                     "evicted device blocks park in host memory and "
                     "restore on prefix re-arrival")
    run.add_argument("--kv-persist-dir", default=None,
                     help="persistent prefix-cache tier (default off): "
                     "directory for the content-addressed KV block store "
                     "(llm/kv/persist.py).  Host-published blocks spill "
                     "here; restarts and coordinator-replicated peers "
                     "restore warm prefixes as cached_tokens.  Requires "
                     "--num-host-blocks > 0")
    run.add_argument("--kv-persist-max-bytes", type=int, default=0,
                     help="size cap for --kv-persist-dir (LRU by "
                     "last-touch; 0 = unbounded)")
    run.add_argument("--kv-persist-ttl", type=float, default=0,
                     help="TTL in seconds for persisted block groups "
                     "since last touch (0 = no expiry)")
    run.add_argument("--max-tokens", type=int, default=128)
    run.add_argument("--host", default="127.0.0.1")
    run.add_argument("--http-port", type=int, default=8080)
    run.add_argument("--trace", action="store_true",
                     help="enable the dtspan tracing plane (same as "
                     "DYNAMO_TRACE=1): per-request spans, exported as "
                     "Chrome trace JSON at /debug/traces/{request_id}")
    run.add_argument("--profile-dir", default=None,
                     help="wrap the first --profile-steps engine device "
                     "steps in ONE jax.profiler capture written under "
                     "this directory (keyed by first step id)")
    run.add_argument("--profile-steps", type=int, default=8)
    common(run)

    serve = sub.add_parser("serve", help="serve a graph of @service components")
    serve.add_argument("graph", nargs="?", default="-",
                       help="module.path:EntryService (optional with "
                            "--package: defaults to the manifest entry)")
    serve.add_argument("-f", "--config", default=None, help="YAML ServiceConfig")
    serve.add_argument("--package", default=None, metavar="NAME[:VER]",
                       help="serve a packaged graph pulled from the api-store")
    serve.add_argument("--api-store", default="http://127.0.0.1:7180",
                       dest="api_store")
    serve.add_argument("--package-cache",
                       default=os.path.expanduser("~/.cache/dynamo_tpu/packages"),
                       dest="package_cache")
    common(serve)

    pkg = sub.add_parser("package",
                         help="build/push/pull packaged serving graphs")
    pkg_sub = pkg.add_subparsers(dest="pkg_cmd", required=True)
    pb = pkg_sub.add_parser("build", help="archive a graph source tree")
    pb.add_argument("src", help="directory of graph sources")
    pb.add_argument("--entry", required=True,
                    help="module:Service relative to the package root")
    pb.add_argument("--name", required=True)
    pb.add_argument("-o", "--out", required=True, help="output .tar.gz")
    pp = pkg_sub.add_parser("push", help="upload a package to the api-store")
    pp.add_argument("pkg", help="package .tar.gz")
    pp.add_argument("--api-store", default="http://127.0.0.1:7180",
                    dest="api_store")
    pl = pkg_sub.add_parser("pull", help="download + unpack a package")
    pl.add_argument("ref", help="name[:version]")
    pl.add_argument("--api-store", default="http://127.0.0.1:7180",
                    dest="api_store")
    pl.add_argument("-o", "--out",
                    default=os.path.expanduser("~/.cache/dynamo_tpu/packages"))
    pls = pkg_sub.add_parser("list", help="list packages in the api-store")
    pls.add_argument("--api-store", default="http://127.0.0.1:7180",
                     dest="api_store")

    http = sub.add_parser("http", help="standalone OpenAI frontend w/ discovery")
    http.add_argument("--host", default="127.0.0.1")
    http.add_argument("--http-port", type=int, default=8080)
    common(http)

    coord = sub.add_parser("coordinator", help="run the coordinator service")
    coord.add_argument("--host", default="0.0.0.0")
    coord.add_argument("--port", type=int, default=6180)
    coord.add_argument("--data-dir", default=None,
                       help="WAL directory: KV + queues survive restarts")

    deploy = sub.add_parser("deploy", help="render k8s manifests from a deployment spec")
    deploy.add_argument("spec", help="DynamoTpuDeployment YAML")
    deploy.add_argument("-o", "--out", default=None, help="write one file per object")

    router = sub.add_parser(
        "router", help="standalone KV-aware router service"
    )
    router.add_argument("--block-size", type=int, default=16)
    router.add_argument("--workers-endpoint", default="backend/generate",
                        help="component/endpoint whose discovery deletes "
                             "evict workers from the router")
    common(router)

    operator = sub.add_parser(
        "operator", help="watch a specs dir and reconcile deployments"
    )
    operator.add_argument("specs_dir", nargs="?", default=None,
                          help="directory of DynamoTpuDeployment YAMLs")
    operator.add_argument("--crd", action="store_true",
                          help="watch DynamoTpuDeployment custom resources "
                               "(apply deploy/crd/ first) and write .status "
                               "back via the status subresource")
    operator.add_argument("--interval", type=float, default=5.0)
    operator.add_argument("--context", default=None, help="kubectl context")
    operator.add_argument("--dry-run", action="store_true",
                          help="reconcile against an in-memory cluster")
    operator.add_argument("--coordinator", default=None,
                          help="coordinator URL: enables truthful phases "
                               "from live registrations + queue-depth "
                               "autoscaling")

    store = sub.add_parser("api-store", help="versioned graph registry service")
    store.add_argument("--db", default="graphs.db")
    store.add_argument("--host", default="127.0.0.1")
    store.add_argument("--port", type=int, default=7180)

    metrics = sub.add_parser("metrics", help="metrics aggregation service (Prometheus)")
    metrics.add_argument("--host", default="127.0.0.1")
    metrics.add_argument("--port", type=int, default=9091)
    metrics.add_argument("--push-url", default=None, help="pushgateway URL (push mode)")
    common(metrics)

    planner = sub.add_parser(
        "planner", help="SLA planner loop (replica targets + role flips)")
    planner.add_argument("--interval", type=float, default=2.0)
    planner.add_argument("--prefill-component", default="prefill")
    planner.add_argument("--decode-component", default="decode")
    planner.add_argument("--target-per-replica", type=int, default=4,
                         help="prefill queue depth one replica absorbs")
    planner.add_argument("--target-usage", type=float, default=0.7,
                         help="decode saturation HPA target")
    common(planner)

    mock = sub.add_parser("mock-worker", help="fake worker publishing metrics/KV events")
    mock.add_argument("--worker-id", type=int, default=1)
    mock.add_argument("--count", type=int, default=1)
    common(mock)

    models = sub.add_parser(
        "models",
        help="manage model registrations (llmctl) + artifact push/pull",
    )
    models.add_argument(
        "action", choices=["add", "list", "remove", "push", "pull"]
    )
    models.add_argument("name", nargs="?")
    models.add_argument(
        "endpoint", nargs="?",
        help="dyn://ns.component.endpoint (add) | model dir (push)",
    )
    models.add_argument("--model-path", default=None)
    models.add_argument("--out", default=None,
                        help="pull: cache directory override")
    common(models)

    trace = sub.add_parser(
        "trace",
        help="fetch one request's Chrome trace-event JSON from a "
        "frontend's /debug/traces endpoint (server must run with "
        "--trace / DYNAMO_TRACE=1)",
    )
    trace.add_argument("request_id",
                       help="response id or the caller's x-request-id")
    trace.add_argument("--url", default="http://127.0.0.1:8080",
                       help="frontend base URL")
    trace.add_argument("-o", "--out", default=None,
                       help="write the JSON here instead of stdout")

    from dynamo_tpu.analysis.cli import configure_parser as _lint_parser

    _lint_parser(sub.add_parser(
        "lint",
        help="async-safety + JAX/TPU static analysis "
        "(docs/static_analysis.md); exit 1 on non-baselined findings",
    ))

    quant = sub.add_parser(
        "quantize",
        help="convert an HF/GGUF checkpoint to a native serving checkpoint "
        "(int8 weight-only by default) — engines then start without the "
        "per-boot load+quantize pass",
    )
    quant.add_argument("src", help="HF model dir or .gguf file")
    quant.add_argument("out", help="output checkpoint dir")
    quant.add_argument("--scheme", choices=["int8", "none"], default="int8",
                       help="none = just convert/stack weights, no quant")
    quant.add_argument("--dtype", default="bfloat16")
    return p


def main(argv: Optional[list[str]] = None) -> None:
    logging.basicConfig(level=logging.INFO, stream=sys.stderr)
    args = _parser().parse_args(argv)

    if args.cmd == "run":
        kv = dict(item.split("=", 1) for item in args.inout if "=" in item)
        if "in" not in kv or "out" not in kv:
            raise SystemExit("run needs in=<...> and out=<...>")
        args.inp, args.out = kv["in"], kv["out"]
        if getattr(args, "trace", False):
            from dynamo_tpu.obs import tracing

            tracing.enable(True)
        asyncio.run(_cmd_run(args))
    elif args.cmd == "serve":
        if args.graph == "-" and not args.package:
            raise SystemExit("serve needs a graph or --package")
        asyncio.run(_cmd_serve(args))
    elif args.cmd == "package":
        asyncio.run(_cmd_package(args))
    elif args.cmd == "http":
        asyncio.run(_cmd_http(args))
    elif args.cmd == "coordinator":
        asyncio.run(_cmd_coordinator(args))
    elif args.cmd == "deploy":
        asyncio.run(_cmd_deploy(args))
    elif args.cmd == "router":
        asyncio.run(_cmd_router(args))
    elif args.cmd == "operator":
        asyncio.run(_cmd_operator(args))
    elif args.cmd == "api-store":
        asyncio.run(_cmd_api_store(args))
    elif args.cmd == "metrics":
        asyncio.run(_cmd_metrics(args))
    elif args.cmd == "planner":
        asyncio.run(_cmd_planner(args))
    elif args.cmd == "mock-worker":
        asyncio.run(_cmd_mock_worker(args))
    elif args.cmd == "models":
        asyncio.run(_cmd_models(args))
    elif args.cmd == "trace":
        asyncio.run(_cmd_trace(args))
    elif args.cmd == "lint":
        from dynamo_tpu.analysis.cli import run_lint

        raise SystemExit(run_lint(args))
    elif args.cmd == "quantize":
        _cmd_quantize(args)


if __name__ == "__main__":
    main()
