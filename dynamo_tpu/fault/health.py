"""Active health probing over the TCP request plane.

Discovery (coordinator leases) tells us a worker died only after its TTL
lapses — typically seconds of requests routed into a black hole.  The
HealthMonitor pings each live instance over the SAME socket requests ride
(transports/tcp.py ``ping``/``pong`` control frames), so a worker whose
process is gone — or whose event loop is wedged — turns *suspect* within
a probe interval, and routing deprioritizes it immediately:

  * Client.pick_random / pick_round_robin skip suspect ids while any
    healthy instance remains (runtime/distributed.py _candidate_ids)
  * the KV-router scheduler drops suspects from its candidate set
    (llm/kv_router/scheduler.py mark_suspect) via on_suspect/on_recover

Suspect is a soft state: a successful probe clears it, and discovery
delete (lease expiry / drain) removes the instance outright.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Callable, Optional

from dynamo_tpu.fault.counters import counters
from dynamo_tpu.runtime.transports.tcp import TransportError

log = logging.getLogger("dynamo_tpu.fault")

__all__ = ["HealthMonitor"]


class HealthMonitor:
    """Probe a Client's instances; track suspects.

    ``fail_threshold`` consecutive probe failures mark an instance
    suspect; one success clears it.  ``on_suspect``/``on_recover`` hooks
    fan the state out (e.g. into a KvScheduler's worker set).
    """

    def __init__(
        self,
        client,
        interval_s: float = 1.0,
        timeout_s: float = 1.0,
        fail_threshold: int = 2,
        on_suspect: Optional[Callable[[int], None]] = None,
        on_recover: Optional[Callable[[int], None]] = None,
    ):
        self.client = client
        self.interval_s = interval_s
        self.timeout_s = timeout_s
        self.fail_threshold = max(1, fail_threshold)
        self.on_suspect = on_suspect
        self.on_recover = on_recover
        self._failures: dict[int, int] = {}
        self._suspects: set[int] = set()
        self._task: Optional[asyncio.Task] = None
        self.probes = 0  # total probe rounds (test observability)

    # ---------------------------------------------------------------- state
    def is_suspect(self, instance_id: int) -> bool:
        return instance_id in self._suspects

    def suspect_ids(self) -> set[int]:
        return set(self._suspects)

    def _mark(self, iid: int) -> None:
        if iid not in self._suspects:
            self._suspects.add(iid)
            log.warning("instance %x suspect after %d failed probes",
                        iid, self._failures.get(iid, 0))
            if self.on_suspect:
                self.on_suspect(iid)

    def _clear(self, iid: int) -> None:
        self._failures.pop(iid, None)
        if iid in self._suspects:
            self._suspects.discard(iid)
            log.info("instance %x recovered", iid)
            if self.on_recover:
                self.on_recover(iid)

    # --------------------------------------------------------------- probing
    async def probe_once(self) -> None:
        """One probe round over the client's current instance list."""
        live = set(self.client.instance_ids())
        # instances that left discovery are neither suspect nor failing
        for iid in list(self._suspects - live):
            self._suspects.discard(iid)
        for iid in list(self._failures.keys() - live):
            self._failures.pop(iid, None)
        for iid in live:
            try:
                conn = self.client._conn(iid)
                await conn.ping(self.timeout_s)
            except (TransportError, ConnectionError, OSError, KeyError):
                n = self._failures.get(iid, 0) + 1
                self._failures[iid] = n
                if n >= self.fail_threshold:
                    self._mark(iid)
            else:
                self._clear(iid)
        self.probes += 1

    async def _run(self) -> None:
        while True:
            try:
                await self.probe_once()
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("health probe round failed; continuing")
            await asyncio.sleep(self.interval_s)

    # -------------------------------------------------------------- lifecycle
    async def start(self) -> "HealthMonitor":
        if self._task is None:
            counters.register_suspect_source(self.suspect_ids)
            self._task = asyncio.ensure_future(self._run())
        return self

    async def stop(self) -> None:
        counters.unregister_suspect_source(self.suspect_ids)
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
