"""Deterministic fault injection for tests and the soak harness.

Every fault the plane defends against can be produced on demand, at an
exact point in the protocol, with no sleeps-and-hope timing:

  * ``kill_tcp_server``  — worker death: RST every connection mid-stream
    and stop listening (discovery key survives until lease expiry, like a
    real crash);
  * ``drop_frames`` / ``sever_after`` — transport faults at the N-th
    outbound frame, via the server's ``fault_hook`` seam;
  * ``stall_coordinator`` — control-plane brownout: the coordinator stops
    dispatching until released (lease keepalives and watches stall).

Injectors restore every seam they install (``clear`` / the returned
release callables), so one test's chaos can't leak into the next.
"""

from __future__ import annotations

import asyncio
import logging
import random
from typing import Callable, Optional

log = logging.getLogger("dynamo_tpu.fault")

__all__ = ["FaultInjector", "CRASH_OPS"]

# The shared crash-op vocabulary: every fault this injector can produce,
# named.  The protocol plane (analysis/protocheck.py) drives the same ops
# against its in-memory deterministic transport, and the fault soak picks
# from them with a seeded RNG — one fault surface, two harnesses.
#
#   kill   — process death: RST every connection, stop listening
#            (kill_tcp_server / MemNet server teardown)
#   sever  — cut one peer's transport at an exact outbound frame
#            (sever_after / MemNet conn sever triggers)
#   drop   — swallow N outbound frames of one type (drop_frames)
#   stall  — control-plane brownout: dispatch frozen until release
#            (stall_coordinator)
#   crash  — durability-boundary death: SimulatedCrash raised at a WAL
#            append/fsync/compact or frame-send label (the coordinator's
#            crash_hook seam; protocol plane only — a real process can't
#            un-crash, the model checker can)
CRASH_OPS = ("kill", "sever", "drop", "stall", "crash")


def _tcp_server(target):
    """Accept a DistributedRuntime or a bare EndpointTcpServer."""
    return getattr(target, "_tcp_server", None) or target


class FaultInjector:
    """``seed=`` makes every choice the injector itself takes (which op,
    which frame ordinal) deterministic: two injectors built with the same
    seed produce the same fault sequence, so a soak failure replays."""

    def __init__(self, seed: Optional[int] = None) -> None:
        self._hooked = []  # (server, prior_hook)
        self._stalls = []  # release callables
        self.seed = seed
        self.rng = random.Random(seed)

    def choose_op(self, ops: Optional[tuple[str, ...]] = None) -> str:
        """Seeded pick from the crash-op vocabulary (soak-loop driver)."""
        pool = [op for op in (ops or CRASH_OPS) if op in CRASH_OPS]
        if not pool:
            raise ValueError(f"no valid crash ops in {ops!r}")
        return self.rng.choice(pool)

    # ---------------------------------------------------------- worker death
    async def kill_tcp_server(self, target) -> None:
        """Abort the worker's request plane mid-stream — the 'process
        died' fault.  Peers see a reset, not a clean end-of-stream."""
        server = _tcp_server(target)
        log.info("FAULT: killing tcp server on port %s", server.port)
        await server.abort()

    # ------------------------------------------------------- frame-level faults
    def _install(self, target, hook) -> None:
        server = _tcp_server(target)
        self._hooked.append((server, server.fault_hook))
        server.fault_hook = hook

    def drop_frames(self, target, ftype: str = "item", nth: int = 1,
                    count: int = 1) -> Callable[[], int]:
        """Silently drop the ``nth``..``nth+count-1``-th outbound frames of
        ``ftype``.  Returns a callable reporting how many were dropped."""
        seen = 0
        dropped = 0

        def hook(header: dict) -> Optional[str]:
            nonlocal seen, dropped
            if header.get("type") != ftype:
                return None
            seen += 1
            if nth <= seen < nth + count:
                dropped += 1
                return "drop"
            return None

        self._install(target, hook)
        return lambda: dropped

    def sever_after(self, target, n_items: int, ftype: str = "item") -> None:
        """Cut the peer's transport the moment the ``n_items``-th frame of
        ``ftype`` would go out — a worker dying exactly mid-token."""
        seen = 0

        def hook(header: dict) -> Optional[str]:
            nonlocal seen
            if header.get("type") != ftype:
                return None
            seen += 1
            if seen >= n_items:
                return "sever"
            return None

        self._install(target, hook)

    def clear(self, target=None) -> None:
        """Remove installed frame hooks (all, or just ``target``'s)."""
        keep = []
        for server, prior in self._hooked:
            if target is None or server is _tcp_server(target):
                server.fault_hook = prior
            else:
                keep.append((server, prior))
        self._hooked = keep

    # ---------------------------------------------------- coordinator brownout
    def stall_coordinator(self, coord_server) -> Callable[[], None]:
        """Freeze the coordinator's dispatch loop (every client call hangs)
        until the returned release() — an event-loop stall / GC-pause /
        network-partition stand-in for the control plane."""
        gate = asyncio.Event()
        orig = coord_server._dispatch

        async def stalled(*args, **kwargs):
            await gate.wait()
            return await orig(*args, **kwargs)

        coord_server._dispatch = stalled
        released = False

        def release() -> None:
            nonlocal released
            if not released:
                released = True
                coord_server._dispatch = orig
                gate.set()
                try:
                    self._stalls.remove(release)
                except ValueError:
                    pass

        self._stalls.append(release)
        log.info("FAULT: coordinator stalled")
        return release

    # ------------------------------------------------------------- teardown
    def release_all(self) -> None:
        """Undo everything still installed — call from test teardown."""
        self.clear()
        for release in list(self._stalls):
            release()
