"""Process-global fault-plane counters.

Kept dependency-free so both the runtime layer (Endpoint.drain) and the
llm layer (http/metrics.py render) can import them without cycles.  The
HTTP metrics endpoint exposes these as:

    dynamo_tpu_fault_migrations_total      counter
    dynamo_tpu_fault_drains_in_progress    gauge
    dynamo_tpu_fault_suspect_instances     gauge
"""

from __future__ import annotations

from typing import Callable, Iterable

__all__ = ["FaultCounters", "counters"]


class FaultCounters:
    def __init__(self) -> None:
        self.migrations_total = 0
        self.drains_in_progress = 0
        # live suspect-set providers (HealthMonitor registers itself);
        # callables so the gauge reads current state, not a stale count
        self._suspect_sources: list[Callable[[], Iterable[int]]] = []

    def register_suspect_source(self, source: Callable[[], Iterable[int]]) -> None:
        self._suspect_sources.append(source)

    def unregister_suspect_source(self, source: Callable[[], Iterable[int]]) -> None:
        try:
            self._suspect_sources.remove(source)
        except ValueError:
            pass

    def suspect_instances(self) -> int:
        seen: set[int] = set()
        for source in self._suspect_sources:
            try:
                seen.update(source())
            except Exception:
                continue
        return len(seen)

    def reset(self) -> None:
        """Test isolation hook — the counters are process-global."""
        self.migrations_total = 0
        self.drains_in_progress = 0
        self._suspect_sources.clear()


counters = FaultCounters()
