"""Mid-stream request migration (reference: lib/llm/src/migration.rs).

A stream interrupted by worker death is not an error the user should
see: the tokens generated so far are re-seeded into the prompt and the
request re-dispatched onto a surviving instance, which continues decoding
from exactly where the dead worker stopped.  Semantics carried over from
the reference:

  * ``migration_limit`` — bounded re-dispatches per request (default 3);
  * per-request opt-out — ``ctx.annotations["migration_limit"] = 0``
    (the HTTP frontend maps an ``x-migration-limit`` request header here);
  * the response is marked — ``ctx.annotations["migrations"]`` counts
    hops, surfaced as ``x-migrated`` by the HTTP layer;
  * connect-time failures (nothing emitted yet) retry with jittered
    backoff without burning migration budget.

The wrapper is a plain AsyncEngine over BackendInput → LLMEngineOutput,
so it slots into build_serving_pipeline wherever a bare distributed
Client would.
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import random as _random
from typing import Any, AsyncIterator, Callable, Optional

from dynamo_tpu.fault.counters import counters
from dynamo_tpu.runtime.engine import AsyncEngine, Context

log = logging.getLogger("dynamo_tpu.fault")

__all__ = ["MigratingClient", "MigrationExhausted"]

# stream failures worth moving the request for: transport loss
# (EndpointDisconnected et al.) and remote `error` frames (RuntimeError) —
# a worker mid-crash often reports one before the socket dies
_MIGRATABLE = (ConnectionError, RuntimeError, KeyError)


class MigrationExhausted(ConnectionError):
    """Every re-dispatch attempt failed within the migration budget."""


class MigratingClient(AsyncEngine):
    """Fault-tolerant wrapper over a ``runtime.distributed.Client``.

    ``pick`` defaults to the client's suspect-aware random pick; pass a
    callable ``(exclude_ids) -> instance_id`` to integrate an external
    router decision (e.g. the KV-aware scheduler re-querying on failure).
    """

    def __init__(
        self,
        client,
        migration_limit: int = 3,
        connect_retries: int = 3,
        backoff_s: float = 0.05,
        backoff_max_s: float = 1.0,
        rng: Optional[_random.Random] = None,
        pick: Optional[Callable[[set], int]] = None,
    ):
        self.client = client
        self.migration_limit = migration_limit
        self.connect_retries = connect_retries
        self.backoff_s = backoff_s
        self.backoff_max_s = backoff_max_s
        self._rng = rng or _random.Random()
        self._pick = pick

    # ------------------------------------------------------------- internals
    def _limit_for(self, ctx: Context) -> int:
        limit = ctx.annotations.get("migration_limit")
        if limit is None:
            ann = getattr(ctx.data, "annotations", None)
            if isinstance(ann, dict):
                limit = ann.get("migration_limit")
        return self.migration_limit if limit is None else max(0, int(limit))

    @staticmethod
    def _reseedable(payload: Any) -> bool:
        return dataclasses.is_dataclass(payload) and hasattr(payload, "token_ids") \
            and hasattr(payload, "stops")

    @staticmethod
    def _reseed(payload: Any, emitted: list[int]) -> Any:
        """Original prompt + tokens already generated = the new prompt;
        max_tokens shrinks by what the user already received."""
        stops = payload.stops
        if stops.max_tokens is not None:
            stops = dataclasses.replace(
                stops, max_tokens=max(1, stops.max_tokens - len(emitted)))
        return dataclasses.replace(
            payload,
            token_ids=list(payload.token_ids) + list(emitted),
            stops=stops,
        )

    async def _backoff(self, attempt: int) -> None:
        base = min(self.backoff_max_s, self.backoff_s * (2 ** attempt))
        # full jitter: desynchronize a thundering herd of retrying requests
        await asyncio.sleep(base * (0.5 + self._rng.random() / 2))

    def _choose(self, exclude: set) -> int:
        if self._pick is not None:
            return self._pick(exclude)
        return self.client.pick_random(exclude=exclude)

    # --------------------------------------------------------------- generate
    def generate(self, request: Context) -> AsyncIterator[Any]:
        return self._run(request)

    async def _run(self, ctx: Context) -> AsyncIterator[Any]:
        limit = self._limit_for(ctx)
        payload = ctx.data
        emitted: list[int] = []
        failed: set[int] = set()
        migrations = 0
        connects = 0
        while True:
            if not self.client.instance_ids():
                # transient empty window (boot, watch replay) — same grace
                # the plain routed stream gives discovery
                await self.client._wait_until(
                    lambda: self.client._instances, 3.0)
            try:
                iid = self._choose(failed)
            except (RuntimeError, LookupError) as e:
                if connects + migrations >= self.connect_retries + limit:
                    raise MigrationExhausted(
                        f"no live instance of {self.client.endpoint.url} "
                        f"after {connects + migrations} attempts") from e
                connects += 1
                await self._backoff(connects)
                continue
            sub = ctx.map(self._reseed(payload, emitted)
                          if emitted else payload)
            got_this_hop = False
            try:
                async for item in self.client.direct(sub, iid):
                    toks = getattr(item, "token_ids", None)
                    if toks:
                        emitted.extend(toks)
                        got_this_hop = True
                    yield item
                return
            except _MIGRATABLE as e:
                if ctx.is_stopped or ctx.is_killed:
                    return  # the caller cancelled; nothing to save
                failed.add(iid)
                if not emitted:
                    # nothing delivered yet: plain connect retry, own budget
                    if connects >= self.connect_retries:
                        raise MigrationExhausted(
                            f"{self.client.endpoint.url}: connect failed "
                            f"{connects + 1}x") from e
                    connects += 1
                    log.info("connect retry %d/%d for %s after %r",
                             connects, self.connect_retries, ctx.id[:8], e)
                    await self._backoff(connects)
                    continue
                if not self._reseedable(payload):
                    raise  # can't rebuild the prompt — surface the loss
                if migrations >= limit:
                    raise MigrationExhausted(
                        f"request {ctx.id[:8]} exceeded migration_limit="
                        f"{limit} ({len(emitted)} tokens salvaged)") from e
                migrations += 1
                ctx.annotations["migrations"] = migrations
                counters.migrations_total += 1
                log.warning(
                    "migrating %s off instance %x (%d tokens re-seeded, "
                    "hop %d/%d): %r", ctx.id[:8], iid, len(emitted),
                    migrations, limit, e)
                if not got_this_hop:
                    # two dead hops in a row without progress: back off so
                    # a flapping pool doesn't spin the request red-hot
                    await self._backoff(migrations)
