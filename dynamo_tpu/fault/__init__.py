"""Fault-tolerance plane: request migration, health probes, graceful drain.

The reference Dynamo treats worker death as routine — lib/llm/src/
migration.rs re-seeds an interrupted stream onto a surviving worker with
the tokens generated so far, and workers deregister-then-drain on
shutdown.  This package is that plane for the TPU runtime:

  * :class:`MigratingClient`  — mid-stream request migration + connect
    retry over a runtime.distributed Client (migration.py)
  * :class:`HealthMonitor`    — active ping probes over the TCP request
    plane; marks instances *suspect* seconds before their coordinator
    lease would expire (health.py)
  * ``Endpoint.drain()`` / ``DistributedRuntime.drain_all()`` — the
    graceful-drain lifecycle lives on runtime.distributed; this package
    carries its counters
  * :class:`FaultInjector`    — deterministic fault injection for tests
    and the soak harness (injector.py)
"""

from dynamo_tpu.fault.counters import FaultCounters, counters
from dynamo_tpu.fault.health import HealthMonitor
from dynamo_tpu.fault.injector import FaultInjector
from dynamo_tpu.fault.migration import MigrationExhausted, MigratingClient

__all__ = [
    "MigratingClient",
    "MigrationExhausted",
    "HealthMonitor",
    "FaultInjector",
    "FaultCounters",
    "counters",
]
