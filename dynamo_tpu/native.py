"""ctypes bindings to the native C++ runtime library (native/).

The native library supplies the hot-path runtime components that the
reference implements in Rust/C (see native/include/dynamo_native.h for the
parity map): the KV prefix index, batched block gather/scatter for the DCN
KV-transfer plane, and the C event-queue API native engines publish KV
events through.

Loading order: prebuilt ``dynamo_tpu/_lib/libdynamo_native.so`` → auto-build
via ``make -C native`` if a toolchain is present → ``None`` (callers fall
back to the pure-Python implementations, which are semantically identical
and covered by the same tests).
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
from typing import Optional, Sequence

import numpy as np

log = logging.getLogger("dynamo_tpu.native")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_LIB_PATH = os.path.join(_REPO, "dynamo_tpu", "_lib", "libdynamo_native.so")
_NATIVE_DIR = os.path.join(_REPO, "native")

_lib: Optional[ctypes.CDLL] = None
_load_attempted = False

_u64p = ctypes.POINTER(ctypes.c_uint64)
_u32p = ctypes.POINTER(ctypes.c_uint32)
_i64p = ctypes.POINTER(ctypes.c_int64)
_i32p = ctypes.POINTER(ctypes.c_int32)
_u8p = ctypes.POINTER(ctypes.c_uint8)

EVENT_STORED = 0
EVENT_REMOVED = 1


def _declare(lib: ctypes.CDLL) -> None:
    lib.dyn_index_new.restype = ctypes.c_void_p
    lib.dyn_index_free.argtypes = [ctypes.c_void_p]
    lib.dyn_index_store.argtypes = [ctypes.c_void_p, ctypes.c_uint64, _u64p, ctypes.c_size_t]
    lib.dyn_index_remove.argtypes = [ctypes.c_void_p, ctypes.c_uint64, _u64p, ctypes.c_size_t]
    lib.dyn_index_remove_worker.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.dyn_index_clear.argtypes = [ctypes.c_void_p]
    lib.dyn_index_num_blocks.argtypes = [ctypes.c_void_p]
    lib.dyn_index_num_blocks.restype = ctypes.c_uint64
    lib.dyn_index_num_workers.argtypes = [ctypes.c_void_p]
    lib.dyn_index_num_workers.restype = ctypes.c_uint64
    lib.dyn_index_find_matches.argtypes = [
        ctypes.c_void_p, _u64p, ctypes.c_size_t, _u64p, _u32p, ctypes.c_size_t,
    ]
    lib.dyn_index_find_matches.restype = ctypes.c_size_t

    lib.dyn_blocks_gather.argtypes = [
        _u8p, ctypes.c_uint64, _i64p, ctypes.c_size_t, _u8p, ctypes.c_int,
    ]
    lib.dyn_blocks_scatter.argtypes = [
        _u8p, ctypes.c_uint64, _i64p, ctypes.c_size_t, _u8p, ctypes.c_int,
    ]

    lib.dyn_events_new.argtypes = [ctypes.c_size_t]
    lib.dyn_events_new.restype = ctypes.c_void_p
    lib.dyn_events_free.argtypes = [ctypes.c_void_p]
    lib.dyn_events_publish.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_uint64, _u64p, ctypes.c_size_t,
    ]
    lib.dyn_events_publish.restype = ctypes.c_int
    lib.dyn_events_drain.argtypes = [
        ctypes.c_void_p, _i32p, _u64p, _u64p, ctypes.c_size_t, _u64p, ctypes.c_size_t,
    ]
    lib.dyn_events_drain.restype = ctypes.c_size_t
    lib.dyn_events_dropped.argtypes = [ctypes.c_void_p]
    lib.dyn_events_dropped.restype = ctypes.c_uint64
    lib.dyn_native_version.restype = ctypes.c_char_p


def _try_build() -> bool:
    if not os.path.isdir(_NATIVE_DIR):
        return False
    try:
        subprocess.run(
            ["make", "-C", _NATIVE_DIR],
            check=True, capture_output=True, timeout=120,
        )
        return os.path.exists(_LIB_PATH)
    except (OSError, subprocess.SubprocessError) as e:
        log.debug("native build failed: %s", e)
        return False


def load() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _load_attempted
    if _lib is not None or _load_attempted:
        return _lib
    _load_attempted = True
    if os.environ.get("DYN_DISABLE_NATIVE"):
        return None
    if not os.path.exists(_LIB_PATH) and not _try_build():
        log.info("native library unavailable; using pure-Python fallbacks")
        return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
        _declare(lib)
        _lib = lib
        log.debug("loaded native library %s (v%s)", _LIB_PATH, lib.dyn_native_version().decode())
    except OSError as e:
        log.warning("failed to load native library: %s", e)
    return _lib


def available() -> bool:
    return load() is not None


def _as_u64(arr: Sequence[int] | np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(arr, dtype=np.uint64)


class NativeKvIndex:
    """Handle to a native dyn_index (see KvIndexer for the Python-facing API)."""

    def __init__(self) -> None:
        lib = load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._h = lib.dyn_index_new()

    def __del__(self) -> None:
        if getattr(self, "_h", None):
            self._lib.dyn_index_free(self._h)
            self._h = None

    def store(self, worker: int, hashes: Sequence[int]) -> None:
        a = _as_u64(hashes)
        self._lib.dyn_index_store(self._h, worker, a.ctypes.data_as(_u64p), len(a))

    def remove(self, worker: int, hashes: Sequence[int]) -> None:
        a = _as_u64(hashes)
        self._lib.dyn_index_remove(self._h, worker, a.ctypes.data_as(_u64p), len(a))

    def remove_worker(self, worker: int) -> None:
        self._lib.dyn_index_remove_worker(self._h, worker)

    def clear(self) -> None:
        self._lib.dyn_index_clear(self._h)

    @property
    def num_blocks(self) -> int:
        return self._lib.dyn_index_num_blocks(self._h)

    @property
    def num_workers(self) -> int:
        return self._lib.dyn_index_num_workers(self._h)

    def find_matches(self, hashes: Sequence[int]) -> dict[int, int]:
        a = _as_u64(hashes)
        cap = max(16, self.num_workers)
        while True:
            workers = np.empty(cap, dtype=np.uint64)
            scores = np.empty(cap, dtype=np.uint32)
            n = self._lib.dyn_index_find_matches(
                self._h, a.ctypes.data_as(_u64p), len(a),
                workers.ctypes.data_as(_u64p), scores.ctypes.data_as(_u32p), cap,
            )
            if n <= cap:
                return {int(workers[i]): int(scores[i]) for i in range(n)}
            cap = n


def _check_ids(idx: np.ndarray, n_blocks: int) -> None:
    # The native path is a raw memcpy — bounds must be enforced here, where
    # the numpy fallback would have raised an IndexError.
    if len(idx) and (idx.min() < 0 or idx.max() >= n_blocks):
        raise IndexError(f"block id out of range [0, {n_blocks}): {idx.min()}..{idx.max()}")


def blocks_gather(src: np.ndarray, ids: Sequence[int], threads: int = 0) -> np.ndarray:
    """Gather src[ids] (axis 0) into a fresh contiguous array via native memcpy.

    Same semantics regardless of backend: ids are bounds-checked (no
    negative-index wrapping) and a non-contiguous pool falls back to numpy
    fancy indexing rather than copying the whole pool to linearise it.
    """
    lib = load()
    idx = np.ascontiguousarray(ids, dtype=np.int64)
    _check_ids(idx, src.shape[0])
    if lib is None or not src.flags.c_contiguous:
        return np.ascontiguousarray(src[idx])
    out = np.empty((len(idx),) + src.shape[1:], dtype=src.dtype)
    block_bytes = src.dtype.itemsize * int(np.prod(src.shape[1:], dtype=np.int64))
    lib.dyn_blocks_gather(
        src.ctypes.data_as(_u8p), block_bytes,
        idx.ctypes.data_as(_i64p), len(idx), out.ctypes.data_as(_u8p), threads,
    )
    return out


def blocks_scatter(dst: np.ndarray, ids: Sequence[int], src: np.ndarray, threads: int = 0) -> None:
    """Scatter src rows into dst[ids] (axis 0) in place via native memcpy.

    Validation is identical on both backends (shape match, bounds-checked
    ids).  Duplicate ids resolve last-write-wins like numpy — the native
    threaded path would race on duplicates, so they are deduplicated first.
    """
    lib = load()
    idx = np.ascontiguousarray(ids, dtype=np.int64)
    src = np.asarray(src)
    if src.shape != (len(idx),) + dst.shape[1:]:
        raise ValueError(f"scatter shape mismatch: src {src.shape} vs {(len(idx),) + dst.shape[1:]}")
    _check_ids(idx, dst.shape[0])
    if lib is None or not dst.flags.c_contiguous:
        dst[idx] = src
        return
    if len(np.unique(idx)) != len(idx):
        # keep the LAST occurrence of each id (numpy scatter semantics)
        last = {int(b): i for i, b in enumerate(idx)}
        keep = np.fromiter(last.values(), dtype=np.int64)
        idx = idx[keep]
        src = src[keep]
    src = np.ascontiguousarray(src, dtype=dst.dtype)
    block_bytes = dst.dtype.itemsize * int(np.prod(dst.shape[1:], dtype=np.int64))
    lib.dyn_blocks_scatter(
        dst.ctypes.data_as(_u8p), block_bytes,
        idx.ctypes.data_as(_i64p), len(idx), src.ctypes.data_as(_u8p), threads,
    )


class NativeEventQueue:
    """Bounded queue native engines publish KV events into (C bindings parity)."""

    def __init__(self, capacity: int = 1 << 16) -> None:
        lib = load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._h = lib.dyn_events_new(capacity)

    def __del__(self) -> None:
        if getattr(self, "_h", None):
            self._lib.dyn_events_free(self._h)
            self._h = None

    def publish(self, kind: int, parent_hash: int, hashes: Sequence[int]) -> bool:
        a = _as_u64(hashes)
        rc = self._lib.dyn_events_publish(
            self._h, kind, parent_hash, a.ctypes.data_as(_u64p), len(a)
        )
        return rc == 0

    def drain(self, max_events: int = 1024, hashes_cap: int = 1 << 16) -> list[tuple[int, int, list[int]]]:
        kinds = np.empty(max_events, dtype=np.int32)
        parents = np.empty(max_events, dtype=np.uint64)
        hashes = np.empty(hashes_cap, dtype=np.uint64)
        offsets = np.empty(max_events + 1, dtype=np.uint64)
        n = self._lib.dyn_events_drain(
            self._h, kinds.ctypes.data_as(_i32p), parents.ctypes.data_as(_u64p),
            hashes.ctypes.data_as(_u64p), hashes_cap,
            offsets.ctypes.data_as(_u64p), max_events,
        )
        out = []
        for i in range(n):
            lo, hi = int(offsets[i]), int(offsets[i + 1])
            out.append((int(kinds[i]), int(parents[i]), [int(h) for h in hashes[lo:hi]]))
        return out

    @property
    def dropped(self) -> int:
        return self._lib.dyn_events_dropped(self._h)
