"""Interprocedural analysis — Plane A of the two-plane concurrency tool.

The per-file rules (rules_async.py, rules_jax.py) see one module at a
time; Dynamo's hardest bugs live *between* files — a task spawned in one
class and drained (or not) by another method, a lock held across an
await that bottoms out in a coordinator round-trip three calls away, a
KV-block stream left open on an exception path.  This pass builds a
project index in a first sweep (module symbol table + call graph +
task-spawn / lock / queue / stream-writer registries over every file)
and runs cross-module rules on top of the same registry / baseline /
noqa machinery:

  DT005  lock held across an await that transitively reaches a
         network/coordinator call (unbounded: not under wait_for)
  DT006  asyncio.Queue() created unbounded but fed from a network
         callback path (or a spawned pump task)
  DT007  stream/writer not closed on every exit path (close /
         wait_closed outside finally; transport teardown never awaited)
  DT008  task spawn site with no reachable cancel/drain on any
         shutdown-path method (close/stop/shutdown/drain/...)
  DT009  blocking file I/O reachable from an async function through a
         sync call chain (no asyncio.to_thread / run_in_executor) —
         the interprocedural complement of per-file DT003

Exposed as ``dynamo-tpu lint --project`` with the same JSON / baseline /
exit-code contract as the per-file pass.  Parsing is shared with the
per-file pass through core.parse_module, so running both costs one
ast.parse per file.

Like the per-file rules these are deliberately heuristic — tuned to this
codebase's idioms (retained-task sets drained in stop(), close_writer(),
write-locks that serialize exactly one write+drain) so the blessed
patterns pass untouched.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable, Iterator, Optional, Sequence

from dynamo_tpu.analysis.core import (
    Finding,
    ModuleContext,
    Rule,
    dotted_name,
    iter_python_files,
    parse_module,
)

__all__ = [
    "ProjectIndex",
    "ProjectRule",
    "project_rules",
    "lint_project",
]

_SPAWN_NAMES = {"asyncio.ensure_future", "asyncio.create_task"}
_SPAWN_ATTRS = {"ensure_future", "create_task"}

# calls that ARE the network: dials, listeners, HTTP clients
NET_PRIMITIVE_CALLS = {
    "asyncio.open_connection",
    "asyncio.start_server",
    "socket.create_connection",
    "aiohttp.ClientSession",
}
# awaiting one of these attr calls means waiting on a peer's bytes
NET_READER_ATTRS = {"readexactly", "readuntil"}
# codebase-tuned seeds: RPCs that await a response future the call graph
# cannot see through (the read loop resolves it on a different task)
KNOWN_ROUNDTRIP_SUFFIXES = ("CoordinatorClient._call",)

# ultra-generic method names excluded from by-name call-graph resolution
# (dict.get / Queue.put / StreamWriter.drain would otherwise alias every
# same-named project function and poison reachability)
GENERIC_ATTRS = frozenset({
    "get", "put", "put_nowait", "get_nowait", "pop", "add", "append",
    "appendleft", "popleft", "discard", "remove", "update", "close",
    "wait_closed", "drain", "write", "read", "readline", "send", "recv",
    "start", "stop", "run", "join", "cancel", "set", "clear", "acquire",
    "release", "flush", "sleep", "gather", "result", "done", "values",
    "items", "keys", "open", "wait", "setdefault", "extend", "copy",
    "encode", "decode", "format", "split", "strip", "sort",
    # step: engine loop / decode-stream / policy all expose one;
    # __init__: obj.__init__() would alias every constructor in the tree
    "step", "__init__",
})

# blocking file-I/O primitives (DT009 sinks): dotted calls that open or
# flush a file, plus the pathlib whole-file convenience methods (attr
# calls).  `.open()` the attr is deliberately absent — too many non-file
# objects expose an open() method (stores, pools, devices).
FILE_IO_CALLS = frozenset({"open", "io.open", "os.fsync"})
FILE_IO_ATTRS = frozenset({
    "read_bytes", "write_bytes", "read_text", "write_text",
})

SHUTDOWN_METHOD_NAMES = frozenset({
    "close", "stop", "shutdown", "aclose", "drain", "drain_all",
    "stop_all", "abort", "disconnect", "cleanup", "terminate",
    "unregister", "__aexit__", "__exit__", "close_when_idle",
})


# ------------------------------------------------------------- index model ----


@dataclass
class CallSite:
    kind: str        # "dotted" (import-resolved) | "self" | "attr"
    name: str        # canonical dotted name, method name, or attr name
    node: ast.Call = field(repr=False, default=None)


@dataclass
class FunctionInfo:
    qualname: str                    # "pkg.mod.Class.method" (or nested)
    module: str
    cls: Optional[str]               # owning class qualname, or None
    name: str
    node: ast.AST = field(repr=False, default=None)
    is_async: bool = False
    calls: list[CallSite] = field(default_factory=list)
    # names N such that the function contains N.put(...) / N.put_nowait(...)
    put_targets: set[str] = field(default_factory=set)
    lock_locals: set[str] = field(default_factory=set)


@dataclass
class ClassInfo:
    qualname: str
    module: str
    node: ast.AST = field(repr=False, default=None)
    methods: dict[str, FunctionInfo] = field(default_factory=dict)
    lock_attrs: set[str] = field(default_factory=set)


@dataclass
class QueueSite:
    fn: FunctionInfo
    node: ast.Call
    target: Optional[str]            # binding name ("q", "merged") if any
    has_maxsize: bool


@dataclass
class WriterBinding:
    fn: FunctionInfo
    node: ast.AST                    # the open_connection assignment
    kind: str                        # "local" | "attr"
    writer: str                      # local name or self-attribute name


@dataclass
class HandlerReg:
    fn: FunctionInfo                 # function containing start_server(...)
    node: ast.Call
    handler: str                     # method name (self.X) or module function


@dataclass
class SpawnSite:
    fn: FunctionInfo
    node: ast.Call
    attr: Optional[str]              # self-attribute the handle lands in


# ---------------------------------------------------------------- the index ----


class ProjectIndex:
    """Whole-project facts: symbol table, call graph, and the spawn /
    lock / queue / writer registries the cross-module rules key off."""

    def __init__(self) -> None:
        self.modules: dict[str, ModuleContext] = {}       # modname -> ctx
        self.ctx_by_path: dict[str, ModuleContext] = {}   # rel path -> ctx
        self.functions: dict[str, FunctionInfo] = {}
        self.classes: dict[str, ClassInfo] = {}
        self.by_name: dict[str, list[FunctionInfo]] = {}
        self.queue_sites: list[QueueSite] = []
        self.writer_bindings: list[WriterBinding] = []
        self.handler_regs: list[HandlerReg] = []
        self.spawn_sites: list[SpawnSite] = []
        self._net: Optional[set[str]] = None

    # ------------------------------------------------------------- building
    @classmethod
    def build(cls, files: Sequence[Path], root: Optional[Path] = None) -> "ProjectIndex":
        index = cls()
        for path in files:
            path = Path(path)
            rel = path
            if root is not None:
                try:
                    rel = path.resolve().relative_to(Path(root).resolve())
                except ValueError:
                    rel = path
            try:
                source, tree = parse_module(path)
            except (SyntaxError, OSError):
                continue  # the per-file pass reports DT000 for these
            relpos = rel.as_posix()
            modname = relpos[:-3].replace("/", ".")
            if modname.endswith(".__init__"):
                modname = modname[: -len(".__init__")]
            ctx = ModuleContext(relpos, source, tree)
            # reuse the per-file pre-scan's import table logic
            from dynamo_tpu.analysis.core import _prescan

            _prescan(ctx)
            index.modules[modname] = ctx
            index.ctx_by_path[relpos] = ctx
            _IndexWalker(index, ctx, modname).walk()
        return index

    # ------------------------------------------------------------ call graph
    def resolve(self, site: CallSite, fn: FunctionInfo) -> list[FunctionInfo]:
        """Candidate FunctionInfos a call site may target."""
        if site.kind == "dotted":
            hit = self.functions.get(site.name)
            if hit is None and "." not in site.name:
                # module-local call: `foo()` in mod -> "mod.foo"
                hit = self.functions.get(f"{fn.module}.{site.name}")
            return [hit] if hit else []
        if site.kind == "self" and fn.cls:
            ci = self.classes.get(fn.cls)
            if ci and site.name in ci.methods:
                return [ci.methods[site.name]]
            return []
        if site.kind == "attr" and site.name not in GENERIC_ATTRS:
            return self.by_name.get(site.name, [])
        return []

    def _is_net_sink(self, fn: FunctionInfo) -> bool:
        if fn.qualname.endswith(KNOWN_ROUNDTRIP_SUFFIXES):
            return True
        for site in fn.calls:
            if site.kind == "dotted" and site.name in NET_PRIMITIVE_CALLS:
                return True
            if site.kind == "attr" and site.name in NET_READER_ATTRS:
                return True
        return False

    @property
    def net(self) -> set[str]:
        """Qualnames of functions that transitively reach the network
        (dial, listen, await peer bytes, coordinator RPC)."""
        if self._net is not None:
            return self._net
        net = {q for q, f in self.functions.items() if self._is_net_sink(f)}
        # reverse-propagate to callers until fixpoint
        changed = True
        while changed:
            changed = False
            for q, f in self.functions.items():
                if q in net:
                    continue
                for site in f.calls:
                    if any(t.qualname in net for t in self.resolve(site, f)):
                        net.add(q)
                        changed = True
                        break
        self._net = net
        return net

    def network_callee(self, call: ast.Call, fn: FunctionInfo) -> Optional[str]:
        """If ``call`` (transitively) reaches the network, a short
        human-readable description of the sink edge; else None."""
        raw = dotted_name(call.func)
        ctx = self.modules.get(fn.module)
        canon = ctx.canonical(raw) if ctx and raw else raw
        if canon in NET_PRIMITIVE_CALLS:
            return canon
        site = _classify_call(call, ctx)
        if site is None:
            return None
        if site.kind == "attr" and site.name in NET_READER_ATTRS:
            return f".{site.name}() (awaiting peer bytes)"
        for target in self.resolve(site, fn):
            if target.qualname in self.net:
                return f"{site.name}() -> {_short(target.qualname)}"
        return None

    def is_lock_expr(self, expr: ast.AST, fn: FunctionInfo) -> bool:
        raw = dotted_name(expr)
        if not raw:
            return False
        leaf = raw.rsplit(".", 1)[-1]
        if raw.startswith("self.") and fn.cls:
            ci = self.classes.get(fn.cls)
            if ci and raw.split(".", 1)[1] in ci.lock_attrs:
                return True
        if leaf in fn.lock_locals:
            return True
        return "lock" in leaf.lower()


def _short(qualname: str) -> str:
    parts = qualname.split(".")
    return ".".join(parts[-2:]) if len(parts) > 1 else qualname


def _classify_call(node: ast.Call, ctx: Optional[ModuleContext]) -> Optional[CallSite]:
    raw = dotted_name(node.func)
    if not raw:
        return None
    if raw.startswith("self."):
        rest = raw.split(".", 1)[1]
        if "." not in rest:
            return CallSite("self", rest, node)
        return CallSite("attr", rest.rsplit(".", 1)[-1], node)
    head = raw.split(".", 1)[0]
    if ctx is not None and (head in ctx.imports or "." not in raw):
        canon = ctx.canonical(raw)
        # only resolvable (imported or module-level) names are "dotted";
        # a bare unknown name stays unresolved
        if head in ctx.imports or canon != raw or "." in canon:
            return CallSite("dotted", canon, node)
        return CallSite("dotted", canon, node)
    if isinstance(node.func, ast.Attribute):
        return CallSite("attr", node.func.attr, node)
    return CallSite("dotted", raw, node)


# ------------------------------------------------------------- index walker ----


class _IndexWalker:
    """One recursive pass per module: records functions, classes, call
    sites, and the rule registries, and links parents
    (``node._dt_pparent``) for ancestry queries."""

    def __init__(self, index: ProjectIndex, ctx: ModuleContext, modname: str):
        self.index = index
        self.ctx = ctx
        self.modname = modname
        self.class_stack: list[ClassInfo] = []
        self.func_stack: list[FunctionInfo] = []

    def walk(self) -> None:
        self._visit(self.ctx.tree, None)

    # ------------------------------------------------------------- helpers
    def _qual(self, name: str) -> str:
        parts = [self.modname]
        parts += [c.qualname.rsplit(".", 1)[-1] for c in self.class_stack]
        parts += [f.name for f in self.func_stack]
        parts.append(name)
        return ".".join(parts)

    @property
    def fn(self) -> Optional[FunctionInfo]:
        return self.func_stack[-1] if self.func_stack else None

    def _visit(self, node: ast.AST, parent: Optional[ast.AST]) -> None:
        node._dt_pparent = parent  # type: ignore[attr-defined]

        if isinstance(node, ast.ClassDef):
            ci = ClassInfo(self._qual(node.name), self.modname, node)
            self.index.classes[ci.qualname] = ci
            self.class_stack.append(ci)
            for child in ast.iter_child_nodes(node):
                self._visit(child, node)
            self.class_stack.pop()
            return

        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fi = FunctionInfo(
                qualname=self._qual(node.name),
                module=self.modname,
                cls=self.class_stack[-1].qualname if self.class_stack else None,
                name=node.name,
                node=node,
                is_async=isinstance(node, ast.AsyncFunctionDef),
            )
            self.index.functions[fi.qualname] = fi
            self.index.by_name.setdefault(node.name, []).append(fi)
            if self.class_stack and not self.func_stack:
                self.class_stack[-1].methods[node.name] = fi
            self.func_stack.append(fi)
            for child in ast.iter_child_nodes(node):
                self._visit(child, node)
            self.func_stack.pop()
            return

        if isinstance(node, ast.Assign):
            self._record_assign(node)
        elif isinstance(node, ast.Call):
            self._record_call(node)

        for child in ast.iter_child_nodes(node):
            self._visit(child, node)

    def _record_assign(self, node: ast.Assign) -> None:
        value = node.value
        call = value.value if isinstance(value, ast.Await) else value
        if not isinstance(call, ast.Call):
            return
        canon = self.ctx.canonical(dotted_name(call.func))
        targets = node.targets
        if canon == "asyncio.Lock":
            for tgt in targets:
                raw = dotted_name(tgt)
                if raw.startswith("self.") and self.class_stack:
                    self.class_stack[-1].lock_attrs.add(raw.split(".", 1)[1])
                elif isinstance(tgt, ast.Name) and self.fn:
                    self.fn.lock_locals.add(tgt.id)
        elif canon == "asyncio.open_connection" and self.fn:
            for tgt in targets:
                if isinstance(tgt, ast.Tuple) and len(tgt.elts) == 2:
                    w = tgt.elts[1]
                    raw = dotted_name(w)
                    if raw.startswith("self.") and "." not in raw[5:]:
                        self.index.writer_bindings.append(
                            WriterBinding(self.fn, node, "attr", raw[5:])
                        )
                    elif isinstance(w, ast.Name):
                        self.index.writer_bindings.append(
                            WriterBinding(self.fn, node, "local", w.id)
                        )

    def _record_call(self, node: ast.Call) -> None:
        fn = self.fn
        ctx = self.ctx
        raw = dotted_name(node.func)
        canon = ctx.canonical(raw) if raw else ""
        if fn is not None:
            site = _classify_call(node, ctx)
            if site is not None:
                fn.calls.append(site)
            # put-target registry (DT006 feeders)
            if isinstance(node.func, ast.Attribute) and node.func.attr in (
                "put", "put_nowait",
            ):
                base = dotted_name(node.func.value)
                if base:
                    fn.put_targets.add(base.rsplit(".", 1)[-1])
            # queue creations
            if canon == "asyncio.Queue":
                has_max = bool(node.args) or any(
                    kw.arg == "maxsize" for kw in node.keywords
                )
                self.index.queue_sites.append(
                    QueueSite(fn, node, _binding_name(node), has_max)
                )
            # spawn sites (handle destination resolved lazily by DT008)
            is_spawn = canon in _SPAWN_NAMES or (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _SPAWN_ATTRS
            )
            if is_spawn:
                self.index.spawn_sites.append(
                    SpawnSite(fn, node, attr=None)
                )
        # start_server handler registrations (also at module level)
        if canon == "asyncio.start_server" and node.args and fn is not None:
            h = dotted_name(node.args[0])
            if h.startswith("self."):
                h = h.split(".", 1)[1]
            if h and "." not in h:
                self.index.handler_regs.append(HandlerReg(fn, node, h))


def _binding_name(call: ast.Call) -> Optional[str]:
    """The name an expression is bound to, via parent links:
    ``q = asyncio.Queue()`` / ``q: asyncio.Queue = asyncio.Queue()``."""
    parent = getattr(call, "_dt_pparent", None)
    if isinstance(parent, ast.Assign) and len(parent.targets) == 1:
        tgt = parent.targets[0]
    elif isinstance(parent, ast.AnnAssign):
        tgt = parent.target
    else:
        return None
    raw = dotted_name(tgt)
    return raw.rsplit(".", 1)[-1] if raw else None


# -------------------------------------------------------- ancestry helpers ----


def _parents(node: ast.AST) -> Iterator[ast.AST]:
    node = getattr(node, "_dt_pparent", None)
    while node is not None:
        yield node
        node = getattr(node, "_dt_pparent", None)


def _enclosing_function(node: ast.AST) -> Optional[ast.AST]:
    for p in _parents(node):
        if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return p
    return None


def _walk_within(func_node: ast.AST, types) -> Iterator[ast.AST]:
    """ast.walk restricted to nodes whose nearest enclosing function is
    ``func_node`` (nested defs are their own FunctionInfo)."""
    for sub in ast.walk(func_node):
        if isinstance(sub, types) and _enclosing_function(sub) is func_node:
            yield sub


def _in_finally(node: ast.AST) -> bool:
    child = node
    for p in _parents(node):
        if isinstance(p, ast.Try):
            for stmt in p.finalbody:
                if child is stmt or any(child is d for d in ast.walk(stmt)):
                    return True
        child = p
    return False


def _is_bounded_await(awaitnode: ast.Await, ctx: ModuleContext) -> bool:
    """await asyncio.wait_for(...) — the round-trip is bounded."""
    v = awaitnode.value
    if isinstance(v, ast.Call):
        return ctx.canonical(dotted_name(v.func)) == "asyncio.wait_for"
    return False


def _awaited_calls(awaitnode: ast.Await, ctx: ModuleContext) -> list[ast.Call]:
    """The call(s) an await resolves to: the awaited call itself, or the
    arguments of a gather/wait/shield wrapper."""
    v = awaitnode.value
    if not isinstance(v, ast.Call):
        return []
    canon = ctx.canonical(dotted_name(v.func))
    if canon in ("asyncio.gather", "asyncio.wait", "asyncio.shield"):
        out = []
        for a in v.args:
            a = a.value if isinstance(a, ast.Starred) else a
            if isinstance(a, ast.Call):
                out.append(a)
        return out
    return [v]


# ------------------------------------------------------------ project rules ----


class ProjectRule(Rule):
    """A rule that checks the whole index rather than one module."""

    def check(self, index: ProjectIndex) -> Iterable[Finding]:
        return ()

    def finding(self, ctx: ModuleContext, node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(
            path=ctx.path,
            line=line,
            col=getattr(node, "col_offset", 0),
            rule=self.code,
            message=message,
            snippet=ctx.line_text(line),
        )


_PROJECT_REGISTRY: dict[str, type[ProjectRule]] = {}


def register_project(cls: type[ProjectRule]) -> type[ProjectRule]:
    _PROJECT_REGISTRY[cls.code] = cls
    return cls


def project_rules(select: Optional[Sequence[str]] = None) -> list[ProjectRule]:
    codes = sorted(_PROJECT_REGISTRY)
    if select:
        wanted = {c.strip().upper() for c in select}
        codes = [c for c in codes if c in wanted]
    return [_PROJECT_REGISTRY[c]() for c in codes]


@register_project
class LockHeldAcrossNetwork(ProjectRule):
    """DT005 — a lock held across an await that transitively reaches a
    network/coordinator call, unbounded.  If the peer wedges, every
    other acquirer queues behind the dead round-trip: a drain can't
    finish, shutdown hangs, keepalives stall.  Release the lock before
    awaiting, or bound the await with ``asyncio.wait_for``.  Locks that
    serialize exactly one local write+drain are fine (drain is local
    backpressure, not a round-trip)."""

    code = "DT005"
    name = "lock-held-across-network"
    summary = (
        "lock held across an unbounded await that transitively reaches "
        "a network/coordinator call"
    )

    def check(self, index: ProjectIndex) -> Iterable[Finding]:
        for fn in index.functions.values():
            if not fn.is_async:
                continue
            ctx = index.modules[fn.module]
            for aw in _walk_within(fn.node, ast.AsyncWith):
                if not any(
                    index.is_lock_expr(item.context_expr, fn)
                    for item in aw.items
                ):
                    continue
                for awaitnode in ast.walk(aw):
                    if not isinstance(awaitnode, ast.Await):
                        continue
                    if _enclosing_function(awaitnode) is not fn.node:
                        continue
                    if _is_bounded_await(awaitnode, ctx):
                        continue
                    for call in _awaited_calls(awaitnode, ctx):
                        desc = index.network_callee(call, fn)
                        if desc:
                            yield self.finding(
                                ctx, aw,
                                "lock held across an unbounded await that "
                                f"reaches the network ({desc}) — release "
                                "the lock before awaiting, or bound the "
                                "round-trip with asyncio.wait_for",
                            )
                            break
                    else:
                        continue
                    break  # one finding per async-with


@register_project
class UnboundedNetworkFedQueue(ProjectRule):
    """DT006 — ``asyncio.Queue()`` created unbounded but fed from a
    network callback path (a read loop, or a pump task spawned to drain
    a stream).  A slow consumer turns the queue into an unbounded
    buffer of peer-controlled data — an OOM with extra steps.  Give it
    a ``maxsize`` (the feeder's ``await put()`` then provides real
    backpressure) or justify the unboundedness."""

    code = "DT006"
    name = "unbounded-network-fed-queue"
    summary = (
        "unbounded asyncio.Queue fed from a network callback / pump task"
    )

    def check(self, index: ProjectIndex) -> Iterable[Finding]:
        for qs in index.queue_sites:
            if qs.has_maxsize or not qs.target:
                continue
            ctx = index.modules[qs.fn.module]
            feeders = [
                f for f in index.functions.values()
                if f.module == qs.fn.module and qs.target in f.put_targets
            ]
            why = None
            for f in feeders:
                if f.qualname in index.net:
                    why = f"fed by network-path {_short(f.qualname)}()"
                    break
                if self._is_spawned_pump(f, qs.fn, index):
                    why = f"fed by spawned pump task {f.name}()"
                    break
            if why:
                yield self.finding(
                    ctx, qs.node,
                    f"unbounded asyncio.Queue {qs.target!r} {why} — give "
                    "it a maxsize so a slow consumer applies backpressure "
                    "instead of buffering without bound",
                )

    @staticmethod
    def _is_spawned_pump(f: FunctionInfo, creator: FunctionInfo,
                         index: ProjectIndex) -> bool:
        """``f`` is a function nested in ``creator`` whose invocation is
        handed to ensure_future/create_task (the pump-task idiom)."""
        if not f.qualname.startswith(creator.qualname + "."):
            return False
        for sp in index.spawn_sites:
            if sp.fn.qualname != creator.qualname or not sp.node.args:
                continue
            arg = sp.node.args[0]
            if isinstance(arg, ast.Call) and dotted_name(arg.func) == f.name:
                return True
        return False


@register_project
class StreamNotClosedOnExit(ProjectRule):
    """DT007 — a stream/writer without a guaranteed close on every exit
    path.  Three shapes: a local writer from ``open_connection`` whose
    ``close()`` is not in a ``finally``; a class-owned writer
    (``self._writer``) the class never closes — or closes without ever
    awaiting ``wait_closed()`` (the transport teardown is never awaited,
    so tests and shutdown leak live TCP transports); and a
    ``start_server`` handler that doesn't close its writer in a
    ``finally``.  ``framing.close_writer()`` is the blessed helper
    (close + bounded wait_closed)."""

    code = "DT007"
    name = "stream-not-closed-on-exit"
    summary = (
        "stream/writer not closed on every exit path (close/wait_closed "
        "missing or outside finally)"
    )

    def check(self, index: ProjectIndex) -> Iterable[Finding]:
        seen_attr: set[tuple[str, str]] = set()
        for wb in index.writer_bindings:
            ctx = index.modules[wb.fn.module]
            if wb.kind == "local":
                yield from self._check_local(index, ctx, wb)
            else:
                key = (wb.fn.cls or wb.fn.module, wb.writer)
                if key in seen_attr:
                    continue
                seen_attr.add(key)
                yield from self._check_attr(index, ctx, wb)
        for reg in index.handler_regs:
            yield from self._check_handler(index, reg)

    # -- a local writer must be closed in a finally (or escape ownership)
    def _check_local(self, index, ctx, wb: WriterBinding):
        fn = wb.fn
        w = wb.writer
        closes, escapes = [], False
        for sub in _walk_within(fn.node, ast.AST):
            if isinstance(sub, ast.Call):
                f = sub.func
                if (
                    isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Name)
                    and f.value.id == w
                    and f.attr in ("close", "wait_closed", "abort")
                ):
                    closes.append(sub)
                    continue
                if dotted_name(f).endswith("close_writer") and any(
                    isinstance(a, ast.Name) and a.id == w for a in sub.args
                ):
                    closes.append(sub)
                    continue
                # writer handed to another call: ownership escapes
                for a in sub.args:
                    if isinstance(a, ast.Name) and a.id == w:
                        escapes = True
            elif isinstance(sub, (ast.Return, ast.Yield)):
                for n in ast.walk(sub):
                    if isinstance(n, ast.Name) and n.id == w:
                        escapes = True
            elif isinstance(sub, ast.Assign):
                raw = dotted_name(sub.targets[0]) if sub.targets else ""
                if raw.startswith("self.") and any(
                    isinstance(n, ast.Name) and n.id == w
                    for n in ast.walk(sub.value)
                ):
                    escapes = True
        if escapes:
            return
        if not closes:
            yield self.finding(
                ctx, wb.node,
                f"writer {w!r} from open_connection is never closed in "
                "this function and never escapes — close it (use "
                "framing.close_writer) in a finally",
            )
        elif not any(_in_finally(c) for c in closes):
            yield self.finding(
                ctx, wb.node,
                f"writer {w!r} from open_connection is closed only on "
                "the happy path — move close()/wait_closed() (or "
                "framing.close_writer) into a finally so exception "
                "paths don't leak the transport",
            )

    # -- a class-owned writer: some method must close it, and teardown
    #    must be awaited at least once (wait_closed or close_writer)
    def _check_attr(self, index, ctx, wb: WriterBinding):
        if wb.fn.cls is None:
            return
        attr = wb.writer
        closed = awaited = False
        for f in index.functions.values():
            if f.cls != wb.fn.cls:
                continue
            for sub in ast.walk(f.node):
                if not isinstance(sub, ast.Call):
                    continue
                fun = sub.func
                raw = dotted_name(fun)
                if raw == f"self.{attr}.close" or raw == f"self.{attr}.abort":
                    closed = True
                elif raw == f"self.{attr}.wait_closed":
                    awaited = True
                elif raw.endswith("close_writer") and any(
                    dotted_name(a) == f"self.{attr}" for a in sub.args
                ):
                    closed = awaited = True
        cls_name = _short(wb.fn.cls)
        if not closed:
            yield self.finding(
                ctx, wb.node,
                f"transport self.{attr} opened here is never closed by "
                f"any method of {cls_name} — close it on the shutdown "
                "path (framing.close_writer)",
            )
        elif not awaited:
            yield self.finding(
                ctx, wb.node,
                f"{cls_name} closes self.{attr} but never awaits "
                "wait_closed(): the transport teardown is never awaited "
                "and shutdown leaks live TCP transports — use "
                "framing.close_writer",
            )

    # -- a server handler owns its writer: close in a finally
    def _check_handler(self, index, reg: HandlerReg):
        candidates = []
        if reg.fn.cls:
            ci = index.classes.get(reg.fn.cls)
            if ci and reg.handler in ci.methods:
                candidates = [ci.methods[reg.handler]]
        if not candidates:
            candidates = [
                f for f in index.by_name.get(reg.handler, [])
                if f.module == reg.fn.module
            ]
        for h in candidates:
            args = h.node.args.args
            params = [a.arg for a in args if a.arg != "self"]
            if len(params) < 2:
                continue
            w = params[1]
            ctx = index.modules[h.module]
            closes = [
                sub for sub in _walk_within(h.node, ast.Call)
                if (
                    isinstance(sub.func, ast.Attribute)
                    and isinstance(sub.func.value, ast.Name)
                    and sub.func.value.id == w
                    and sub.func.attr in ("close", "abort")
                )
                or (
                    dotted_name(sub.func).endswith("close_writer")
                    and any(
                        isinstance(a, ast.Name) and a.id == w
                        for a in sub.args
                    )
                )
            ]
            if not closes or not any(_in_finally(c) for c in closes):
                yield self.finding(
                    ctx, h.node,
                    f"server handler {h.name}() must close its writer "
                    f"{w!r} in a finally — a raising request path leaks "
                    "the connection",
                )


@register_project
class SpawnWithoutShutdownDrain(ProjectRule):
    """DT008 — a task spawned into instance state with no reachable
    cancel/drain on any shutdown-path method.  The task outlives its
    owner: at loop teardown it is destroyed pending (exception lost), in
    tests it leaks into the next test, in production a drained worker
    keeps a zombie loop alive.  The blessed idiom: retain the handle,
    cancel (and await) it from close()/stop()/shutdown()."""

    code = "DT008"
    name = "spawn-without-shutdown-drain"
    summary = (
        "task spawned into self.<attr> with no cancel/drain reachable "
        "from any shutdown-path method"
    )

    def check(self, index: ProjectIndex) -> Iterable[Finding]:
        for sp in index.spawn_sites:
            fn = sp.fn
            if fn.cls is None:
                continue
            attr = self._handle_attr(sp)
            if attr is None:
                continue
            ci = index.classes.get(fn.cls)
            if ci is None:
                continue
            if not self._drained(index, ci, attr):
                ctx = index.modules[fn.module]
                yield self.finding(
                    ctx, sp.node,
                    f"task spawned into self.{attr} has no reachable "
                    "cancel/drain on any shutdown-path method "
                    f"({'/'.join(sorted(SHUTDOWN_METHOD_NAMES)[:4])}/...) "
                    f"of {_short(fn.cls)} — cancel and await it on close",
                )

    # ---- where does the handle land?
    @staticmethod
    def _handle_attr(sp: SpawnSite) -> Optional[str]:
        node = sp.node
        parent = getattr(node, "_dt_pparent", None)
        # self._tasks.add(spawn(...)) / self._tasks.append(spawn(...))
        if isinstance(parent, ast.Call) and isinstance(parent.func, ast.Attribute):
            if parent.func.attr in ("add", "append", "appendleft"):
                base = dotted_name(parent.func.value)
                if base.startswith("self."):
                    return base.split(".", 1)[1].split(".")[0]
        stmt = parent
        while stmt is not None and not isinstance(stmt, ast.stmt):
            stmt = getattr(stmt, "_dt_pparent", None)
        if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
            return None
        tgt = stmt.targets[0]
        raw = dotted_name(tgt)
        if raw.startswith("self."):
            return raw.split(".", 1)[1].split(".")[0]
        if isinstance(tgt, ast.Subscript):
            base = dotted_name(tgt.value)
            if base.startswith("self."):
                return base.split(".", 1)[1].split(".")[0]
        if isinstance(tgt, ast.Name):
            # local handle: follow one hop of add/append/subscript/pack
            return SpawnWithoutShutdownDrain._local_to_attr(sp, tgt.id)
        return None

    @staticmethod
    def _local_to_attr(sp: SpawnSite, local: str) -> Optional[str]:
        names = {local}
        fn_node = sp.fn.node
        # one aliasing hop: entry = (conn, task); x = task
        for sub in _walk_within(fn_node, ast.Assign):
            if any(
                isinstance(n, ast.Name) and n.id in names
                for n in ast.walk(sub.value)
            ):
                for tgt in sub.targets:
                    if isinstance(tgt, ast.Name):
                        names.add(tgt.id)
        for sub in _walk_within(fn_node, ast.AST):
            if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Attribute):
                if sub.func.attr in ("add", "append", "appendleft") and any(
                    isinstance(a, ast.Name) and a.id in names
                    for a in sub.args
                ):
                    base = dotted_name(sub.func.value)
                    if base.startswith("self."):
                        return base.split(".", 1)[1].split(".")[0]
            elif isinstance(sub, ast.Assign) and sub.targets:
                tgt = sub.targets[0]
                if isinstance(tgt, ast.Subscript) and any(
                    isinstance(n, ast.Name) and n.id in names
                    for n in ast.walk(sub.value)
                ):
                    base = dotted_name(tgt.value)
                    if base.startswith("self."):
                        return base.split(".", 1)[1].split(".")[0]
        return None

    # ---- is the attr cancelled/drained from a shutdown-path method?
    @staticmethod
    def _shutdown_methods(index: ProjectIndex, ci: ClassInfo) -> list[FunctionInfo]:
        roots = [
            m for n, m in ci.methods.items() if n in SHUTDOWN_METHOD_NAMES
        ]
        out, queue = {m.qualname: m for m in roots}, list(roots)
        while queue:
            m = queue.pop()
            for site in m.calls:
                if site.kind == "self" and site.name in ci.methods:
                    callee = ci.methods[site.name]
                    if callee.qualname not in out:
                        out[callee.qualname] = callee
                        queue.append(callee)
        return list(out.values())

    @classmethod
    def _drained(cls, index: ProjectIndex, ci: ClassInfo, attr: str) -> bool:
        dotted = f"self.{attr}"
        for m in cls._shutdown_methods(index, ci):
            loop_vars: set[str] = set()
            for sub in ast.walk(m.node):
                if isinstance(sub, ast.Call):
                    raw = dotted_name(sub.func)
                    # self.A.cancel()  (incl. guarded `if self.A:`)
                    if raw.startswith(dotted + ".") and raw.rsplit(".", 1)[-1] in (
                        "cancel", "join",
                    ):
                        return True
                    # gather(*self.A) / wait(self.A) / wait_for(self.A)
                    if raw in ("asyncio.gather", "asyncio.wait",
                               "asyncio.wait_for"):
                        for a in sub.args:
                            inner = a.value if isinstance(a, ast.Starred) else a
                            if dotted in _dotted_names(inner):
                                return True
                elif isinstance(sub, ast.Await):
                    # await self.A  — awaiting the handle drains it
                    if dotted_name(sub.value) == dotted:
                        return True
                elif isinstance(sub, (ast.For, ast.AsyncFor)):
                    # for t in (list(self.A) | self.A.values() | self.A):
                    if dotted in _dotted_names(sub.iter):
                        for n in ast.walk(sub.target):
                            if isinstance(n, ast.Name):
                                loop_vars.add(n.id)
            if loop_vars:
                for sub in ast.walk(m.node):
                    if (
                        isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr in ("cancel", "join")
                        and isinstance(sub.func.value, ast.Name)
                        and sub.func.value.id in loop_vars
                    ):
                        return True
        return False


@register_project
class BlockingFileIoFromAsync(ProjectRule):
    """DT009 — blocking file I/O reachable from an async function
    through a sync call chain.  The per-file pass (DT003) catches
    ``open()`` written directly inside an ``async def``; it cannot see
    an ``open()`` hiding one sync call away — the event loop stalls just
    the same (a slow disk or an fsync under a busy page cache holds
    every connection sharing the loop).  The fix is the coordinator's
    blob-I/O idiom: push the sync helper through ``asyncio.to_thread``
    or ``run_in_executor``.  Handing the helper to an executor passes it
    as an *argument*, not a call, so the blessed pattern creates no
    call edge and discharges naturally.  Async callees are not carriers:
    awaiting one suspends rather than blocks, and direct I/O in an
    async body is DT003's finding, not ours."""

    code = "DT009"
    name = "blocking-file-io-from-async"
    summary = (
        "async function calls a sync helper that performs blocking file "
        "I/O (open/read/write/fsync) without to_thread/run_in_executor"
    )

    @staticmethod
    def _direct_io(fn: FunctionInfo) -> Optional[str]:
        for site in fn.calls:
            if site.kind == "dotted" and site.name in FILE_IO_CALLS:
                return f"{site.name}()"
            if site.kind in ("attr", "self") and site.name in FILE_IO_ATTRS:
                return f".{site.name}()"
        return None

    def _io_reachers(self, index: ProjectIndex) -> dict[str, str]:
        """qualname -> leaf-sink description, for sync functions that do
        (or transitively reach, through sync calls only) blocking file
        I/O — the same reverse fixpoint as ProjectIndex.net."""
        io: dict[str, str] = {}
        for q, f in index.functions.items():
            if f.is_async:
                continue
            desc = self._direct_io(f)
            if desc:
                io[q] = desc
        changed = True
        while changed:
            changed = False
            for q, f in index.functions.items():
                if f.is_async or q in io:
                    continue
                for site in f.calls:
                    hit = next(
                        (t for t in index.resolve(site, f)
                         if not t.is_async and t.qualname in io),
                        None,
                    )
                    if hit is not None:
                        io[q] = io[hit.qualname]
                        changed = True
                        break
        return io

    def check(self, index: ProjectIndex) -> Iterable[Finding]:
        io = self._io_reachers(index)
        for fn in index.functions.values():
            if not fn.is_async:
                continue
            ctx = index.modules[fn.module]
            reported: set[str] = set()
            for site in fn.calls:
                for target in index.resolve(site, fn):
                    if target.is_async or target.qualname not in io:
                        continue
                    if target.qualname in reported:
                        continue
                    reported.add(target.qualname)
                    yield self.finding(
                        ctx, site.node,
                        f"async {fn.name}() calls "
                        f"{_short(target.qualname)}() which does blocking "
                        f"file I/O ({io[target.qualname]}) on the event "
                        "loop — wrap the call in asyncio.to_thread or "
                        "run_in_executor",
                    )


def _dotted_names(node: ast.AST) -> set[str]:
    return {dotted_name(n) for n in ast.walk(node)
            if isinstance(n, (ast.Attribute, ast.Name))} - {""}


# ----------------------------------------------------------------- driver ----


def lint_project(
    paths: Sequence[Path],
    rules: Optional[Sequence[ProjectRule]] = None,
    root: Optional[Path] = None,
    index: Optional[ProjectIndex] = None,
) -> list[Finding]:
    """Build the project index over ``paths`` and run the
    interprocedural rules; same Finding/noqa/sort contract as
    core.lint_paths."""
    rules = list(rules) if rules is not None else project_rules()
    if index is None:
        files = list(iter_python_files([Path(p) for p in paths]))
        index = ProjectIndex.build(files, root=root)
    findings: list[Finding] = []
    for rule in rules:
        findings.extend(rule.check(index))
    out = []
    for f in findings:
        ctx = index.ctx_by_path.get(f.path)
        if ctx is not None and ctx.is_suppressed(f):
            continue
        out.append(f)
    return sorted(set(out))
