"""dtsan — Plane B of the two-plane concurrency tool: a runtime sanitizer.

The static pass (project.py) proves properties the AST can see; this
module witnesses the ones only the clock can: a task still pending when
the test that spawned it has returned, a callback that held the event
loop for 200ms, a TCP transport nobody tore down, a frame written after
the peer severed the stream.  Four independent instruments, each
installable on its own:

  TaskTracker              every task created on any loop is recorded
                           with its creation traceback; pending tasks at
                           a test boundary are leaks
  BlockingCallbackMonitor  wall-clocks every event-loop callback; over
                           threshold -> report, with the blocking stack
                           sampled live by a watchdog thread
  TransportTracker         every selector-loop socket transport is
                           recorded with its creation traceback; alive
                           and not closing at a test boundary -> leak
  FrameStateMachine        per-writer protocol checker for
                           runtime/transports/framing.py: no
                           data-after-sever, no double-close

The pytest side (pytest_sanitizer.py + tests/conftest.py) turns these
into per-test failures: leak-checking runs by DEFAULT in tier-1 (with a
grandfather allowlist mirroring the lint baseline idiom);
``DYNAMO_SANITIZE=1`` upgrades to the full set; ``DYNAMO_SANITIZE=0``
switches everything off.

Everything installs by patching narrow, stable seams (the event-loop
policy's ``new_event_loop``, ``Handle._run``, the selector loop's
``_make_socket_transport``, and the framing module's functions across
every module that imported them) and every patch is reversible —
``uninstall()`` restores the originals.
"""

from __future__ import annotations

import asyncio
import os
import sys
import threading
import time
import traceback
import weakref
from asyncio import events as _aio_events
from asyncio import selector_events as _aio_selector
from dataclasses import dataclass, field
from typing import Any, Iterable, Optional

__all__ = [
    "TaskTracker",
    "BlockingCallbackMonitor",
    "TransportTracker",
    "FrameStateMachine",
    "FrameProtocolError",
    "FramingGuard",
    "Sanitizer",
    "MODE_OFF",
    "MODE_LEAKS",
    "MODE_FULL",
    "mode_from_env",
]

MODE_OFF = "off"
MODE_LEAKS = "leaks"   # task-leak checking only (the tier-1 default)
MODE_FULL = "full"     # + blocking callbacks, transports, framing guard


def mode_from_env(default: str = MODE_LEAKS) -> str:
    """DYNAMO_SANITIZE: unset -> ``default``; 0/off -> off; 1/full -> full."""
    raw = os.environ.get("DYNAMO_SANITIZE", "").strip().lower()
    if raw in ("0", "off", "no", "false"):
        return MODE_OFF
    if raw in ("1", "full", "on", "yes", "true"):
        return MODE_FULL
    if raw in ("leaks", "leak"):
        return MODE_LEAKS
    return default


# Frames from these files are noise in a creation traceback: the
# machinery between user code and the recorded event.
_INTERNAL_FILES = (os.sep + "asyncio" + os.sep, os.path.abspath(__file__))


def _creation_stack(limit: int = 16) -> list[traceback.FrameSummary]:
    stack = traceback.extract_stack()
    user = [f for f in stack
            if not any(m in (f.filename or "") for m in _INTERNAL_FILES)]
    return (user or stack)[-limit:]


def _format_stack(stack: Iterable[traceback.FrameSummary]) -> str:
    return "".join(traceback.format_list(list(stack))).rstrip()


# ------------------------------------------------------------ task tracker ----


class _TrackedTask(asyncio.tasks.Task):
    """Task subclass that remembers whether anyone ever asked it to
    cancel.  A pending-at-teardown task whose owner DID call cancel()
    (but returned before the loop could deliver it) is drained
    best-effort, not leaked — only never-cancelled pending tasks fail
    the default leak check."""

    def cancel(self, msg=None):
        self.dt_cancel_requested = True
        return super().cancel(msg) if msg is not None else super().cancel()


@dataclass
class TaskRecord:
    name: str
    coro: str
    epoch: int
    stack: list = field(repr=False, default_factory=list)

    def render(self) -> str:
        return (
            f"task {self.name!r} ({self.coro}) created at:\n"
            + _format_stack(self.stack)
        )


class TaskTracker:
    """Records the creation traceback of every task on every loop.

    Install patches the event-loop policy's ``new_event_loop`` so every
    subsequently created loop (asyncio.new_event_loop, asyncio.run, the
    threads the multihost tests spawn) gets a recording task factory.
    An *epoch* is a test window: ``begin_epoch()`` at test start, then
    ``pending_in_epoch()`` at teardown — any task created during the
    window and still not done is a leak (the tests here drive loops with
    bare ``run_until_complete``, so a pending task at that point is
    frozen forever, and at interpreter exit it is destroyed pending with
    its exception lost).
    """

    def __init__(self) -> None:
        self._records: dict[int, tuple[weakref.ref, TaskRecord]] = {}
        self._epoch = 0
        self._lock = threading.Lock()
        self._orig_new_event_loop = None
        self.installed = False

    # -------------------------------------------------------------- install
    def install(self) -> None:
        if self.installed:
            return
        tracker = self

        self._orig_new_event_loop = (
            _aio_events.BaseDefaultEventLoopPolicy.new_event_loop
        )
        orig = self._orig_new_event_loop

        def new_event_loop(policy):
            loop = orig(policy)
            tracker.instrument_loop(loop)
            return loop

        _aio_events.BaseDefaultEventLoopPolicy.new_event_loop = (
            new_event_loop
        )
        self.installed = True

    def uninstall(self) -> None:
        if not self.installed:
            return
        _aio_events.BaseDefaultEventLoopPolicy.new_event_loop = (
            self._orig_new_event_loop
        )
        self.installed = False

    def instrument_loop(self, loop: asyncio.AbstractEventLoop) -> None:
        """Attach the recording task factory (chains any existing one)."""
        tracker = self
        prev = loop.get_task_factory()

        def factory(lp, coro, **kw):
            if prev is not None:
                task = prev(lp, coro, **kw)
            else:
                task = _TrackedTask(coro, loop=lp, **kw)
            tracker.record(task, coro)
            return task

        loop.set_task_factory(factory)

    # ------------------------------------------------------------ recording
    def record(self, task: "asyncio.Task", coro: Any = None) -> None:
        rec = TaskRecord(
            name=task.get_name(),
            coro=getattr(coro, "__qualname__", None) or repr(coro),
            epoch=self._epoch,
            stack=_creation_stack(),
        )
        with self._lock:
            self._records[id(task)] = (weakref.ref(task), rec)

    # --------------------------------------------------------------- epochs
    def begin_epoch(self) -> int:
        """Open a new test window; prune records of collected tasks."""
        with self._lock:
            self._epoch += 1
            dead = [k for k, (ref, _) in self._records.items()
                    if ref() is None]
            for k in dead:
                del self._records[k]
            return self._epoch

    def pending_in_epoch(
        self,
        epoch: Optional[int] = None,
        include_cancel_requested: bool = False,
    ) -> list[tuple["asyncio.Task", TaskRecord]]:
        """Live, not-done tasks created in ``epoch`` (default: current).
        Tasks whose owner already requested cancellation are excluded
        unless ``include_cancel_requested`` — see _TrackedTask."""
        epoch = self._epoch if epoch is None else epoch
        out = []
        with self._lock:
            items = list(self._records.values())
        for ref, rec in items:
            task = ref()
            if task is None or rec.epoch != epoch:
                continue
            if not include_cancel_requested and getattr(
                task, "dt_cancel_requested", False
            ):
                continue
            try:
                if not task.done():
                    out.append((task, rec))
            except Exception:  # loop half-torn-down: treat as leaked
                out.append((task, rec))
        return out


# ------------------------------------------- blocking-callback monitor ----


@dataclass
class BlockingCallback:
    where: str
    duration_s: float
    epoch: int
    blocked_stack: str = ""     # sampled live by the watchdog, if caught

    def render(self) -> str:
        msg = (
            f"event-loop callback blocked for {self.duration_s * 1e3:.0f}ms: "
            f"{self.where}"
        )
        if self.blocked_stack:
            msg += f"\nstack sampled while blocking:\n{self.blocked_stack}"
        return msg


class BlockingCallbackMonitor:
    """Wall-clocks every event-loop callback via ``Handle._run``.

    A callback that exceeds ``threshold_s`` produces a report.  A single
    daemon watchdog thread samples ``sys._current_frames()`` for any
    thread whose current callback has already overrun the threshold, so
    the report carries the stack *while it was blocking* — the half of
    DT003 that static analysis cannot see (a C extension, a slow jit
    dispatch, a sync socket hidden behind three calls).
    """

    MAX_REPORTS = 100

    def __init__(self, threshold_s: float = 0.1):
        self.threshold_s = threshold_s
        self.reports: list[BlockingCallback] = []
        self._active: dict[int, list] = {}   # thread id -> [t0, stack|None]
        self._epoch = 0
        self._orig_run = None
        self._stop = threading.Event()
        self._watchdog: Optional[threading.Thread] = None
        self.installed = False

    def install(self) -> None:
        if self.installed:
            return
        mon = self
        self._orig_run = _aio_events.Handle._run
        orig = self._orig_run

        def _run(handle):
            tid = threading.get_ident()
            slot = [time.perf_counter(), None]
            mon._active[tid] = slot
            try:
                return orig(handle)
            finally:
                dt = time.perf_counter() - slot[0]
                mon._active.pop(tid, None)
                if dt >= mon.threshold_s and len(mon.reports) < mon.MAX_REPORTS:
                    mon.reports.append(BlockingCallback(
                        where=mon._describe(handle),
                        duration_s=dt,
                        epoch=mon._epoch,
                        blocked_stack=slot[1] or "",
                    ))

        _aio_events.Handle._run = _run
        self._stop.clear()
        self._watchdog = threading.Thread(
            target=self._watch, name="dtsan-watchdog", daemon=True
        )
        self._watchdog.start()
        self.installed = True

    def uninstall(self) -> None:
        if not self.installed:
            return
        _aio_events.Handle._run = self._orig_run
        self._stop.set()
        if self._watchdog is not None:
            self._watchdog.join(timeout=2)
        self.installed = False

    @staticmethod
    def _describe(handle) -> str:
        cb = getattr(handle, "_callback", None)
        name = getattr(cb, "__qualname__", None) or repr(cb)
        src = getattr(handle, "_source_traceback", None)
        if src:
            last = src[-1]
            return f"{name} (scheduled at {last.filename}:{last.lineno})"
        return name

    def _watch(self) -> None:
        interval = max(self.threshold_s / 4.0, 0.005)
        while not self._stop.wait(interval):
            now = time.perf_counter()
            for tid, slot in list(self._active.items()):
                if slot[1] is None and now - slot[0] >= self.threshold_s:
                    frame = sys._current_frames().get(tid)
                    if frame is not None:
                        slot[1] = "".join(
                            traceback.format_stack(frame, limit=12)
                        ).rstrip()

    # --------------------------------------------------------------- epochs
    def begin_epoch(self) -> None:
        self._epoch += 1

    def reports_in_epoch(self) -> list[BlockingCallback]:
        return [r for r in self.reports if r.epoch == self._epoch]


# --------------------------------------------------------- transport leaks ----


@dataclass
class TransportRecord:
    epoch: int
    stack: list = field(repr=False, default_factory=list)

    def render(self, transport) -> str:
        return (
            f"unclosed TCP transport {transport!r} created at:\n"
            + _format_stack(self.stack)
        )


class TransportTracker:
    """Records every selector-loop socket transport (both directions:
    ``open_connection`` dials and ``start_server`` accepts go through
    ``_make_socket_transport``).  A transport still alive and not
    ``is_closing()`` at a test boundary means some path skipped
    ``close_writer`` — the dynamic twin of DT007."""

    def __init__(self) -> None:
        self._records: dict[int, tuple[weakref.ref, TransportRecord]] = {}
        self._epoch = 0
        self._orig_make = None
        self.installed = False

    def install(self) -> None:
        if self.installed:
            return
        tracker = self
        self._orig_make = _aio_selector.BaseSelectorEventLoop._make_socket_transport
        orig = self._orig_make

        def _make_socket_transport(loop, *a, **kw):
            transport = orig(loop, *a, **kw)
            tracker._records[id(transport)] = (
                weakref.ref(transport),
                TransportRecord(epoch=tracker._epoch,
                                stack=_creation_stack()),
            )
            return transport

        _aio_selector.BaseSelectorEventLoop._make_socket_transport = (
            _make_socket_transport
        )
        self.installed = True

    def uninstall(self) -> None:
        if not self.installed:
            return
        _aio_selector.BaseSelectorEventLoop._make_socket_transport = (
            self._orig_make
        )
        self.installed = False

    def begin_epoch(self) -> None:
        self._epoch += 1
        dead = [k for k, (ref, _) in self._records.items() if ref() is None]
        for k in dead:
            del self._records[k]

    def unclosed_in_epoch(self) -> list[tuple[Any, TransportRecord]]:
        out = []
        for ref, rec in list(self._records.values()):
            t = ref()
            if t is None or rec.epoch != self._epoch:
                continue
            try:
                if not t.is_closing():
                    out.append((t, rec))
            except Exception:
                pass
        return out


# ------------------------------------------------------ frame state machine ----


class FrameProtocolError(RuntimeError):
    """An illegal transition on a framed stream (strict mode)."""


class FrameStateMachine:
    """Protocol checker for one framed stream (framing.py wire contract).

    States::

        OPEN ──sever──▶ SEVERED ──close──▶ CLOSED
          │                                  ▲
          └────────────close─────────────────┘

    Legal writes happen only in OPEN.  ``sever`` is the peer going away
    (EOF on read, reset) or a local ``close()`` scheduling teardown —
    after it, writing is the "data-after-sever" bug (bytes to a dead
    peer, or interleaved into a teardown).  ``close`` is terminal;
    closing twice is the "double-close" bug (two owners both think they
    hold the writer).  In strict mode violations raise
    FrameProtocolError; otherwise they accumulate in ``violations``.
    """

    OPEN, SEVERED, CLOSED = "open", "severed", "closed"

    def __init__(self, name: str = "stream", strict: bool = True):
        self.name = name
        self.strict = strict
        self.state = self.OPEN
        self.violations: list[str] = []

    def _violate(self, msg: str) -> None:
        full = f"frame protocol violation on {self.name}: {msg}"
        self.violations.append(full)
        if self.strict:
            raise FrameProtocolError(full)

    def on_write(self) -> None:
        if self.state == self.SEVERED:
            self._violate("data-after-sever (write on a severed stream)")
        elif self.state == self.CLOSED:
            self._violate("data-after-close (write on a closed stream)")

    def on_sever(self) -> None:
        if self.state == self.OPEN:
            self.state = self.SEVERED

    def on_close(self) -> None:
        if self.state == self.CLOSED:
            self._violate("double-close")
        self.state = self.CLOSED


class FramingGuard:
    """Wraps runtime/transports/framing.py in per-writer state machines.

    ``write_frame``/``close_writer`` are imported *by name* into every
    transport module, so patching the framing module alone would miss
    the live call sites — install rewrites the function objects in every
    already-imported module that holds a reference to the originals, and
    uninstall puts them back.  Machines are non-strict here: violations
    accumulate per epoch and the pytest plugin turns them into failures
    (a strict raise inside a transport's close path would mask the
    test's own result).
    """

    def __init__(self) -> None:
        self._machines: "weakref.WeakKeyDictionary[Any, FrameStateMachine]" = (
            weakref.WeakKeyDictionary()
        )
        self.violations: list[tuple[int, str]] = []   # (epoch, message)
        self._epoch = 0
        self._patched: list[tuple[Any, str, Any]] = []  # (module, attr, orig)
        self.installed = False

    def machine_for(self, writer) -> FrameStateMachine:
        m = self._machines.get(writer)
        if m is None:
            m = FrameStateMachine(name=repr(writer), strict=False)
            self._machines[writer] = m
        return m

    # ------------------------------------------------------------- install
    def install(self) -> None:
        if self.installed:
            return
        from dynamo_tpu.runtime.transports import framing

        guard = self
        orig_write = framing.write_frame
        orig_close = framing.close_writer

        def write_frame(writer, header, payload=b""):
            m = guard.machine_for(writer)
            if writer.is_closing():
                m.on_sever()
            m.on_write()
            guard._collect(m)
            return orig_write(writer, header, payload)

        async def close_writer(writer, timeout: float = 2.0):
            if writer is not None:
                m = guard.machine_for(writer)
                m.on_close()
                guard._collect(m)
            return await orig_close(writer, timeout)

        self._patch_everywhere(orig_write, write_frame)
        self._patch_everywhere(orig_close, close_writer)
        self.installed = True

    def uninstall(self) -> None:
        if not self.installed:
            return
        for module, attr, orig in self._patched:
            setattr(module, attr, orig)
        self._patched.clear()
        self.installed = False

    def _patch_everywhere(self, orig, wrapper) -> None:
        for module in list(sys.modules.values()):
            if module is None or not getattr(module, "__name__", "").startswith(
                "dynamo_tpu"
            ):
                continue
            for attr, value in list(vars(module).items()):
                if value is orig:
                    setattr(module, attr, wrapper)
                    self._patched.append((module, attr, orig))

    def _collect(self, m: FrameStateMachine) -> None:
        while m.violations:
            self.violations.append((self._epoch, m.violations.pop(0)))

    # --------------------------------------------------------------- epochs
    def begin_epoch(self) -> None:
        self._epoch += 1

    def violations_in_epoch(self) -> list[str]:
        return [msg for ep, msg in self.violations if ep == self._epoch]


# ----------------------------------------------------------------- facade ----


class Sanitizer:
    """The full instrument set behind one install/uninstall pair."""

    def __init__(self, mode: str = MODE_LEAKS,
                 blocking_threshold_s: float = 0.1):
        self.mode = mode
        self.tasks = TaskTracker()
        self.blocking = BlockingCallbackMonitor(blocking_threshold_s)
        self.transports = TransportTracker()
        self.framing = FramingGuard()

    def install(self) -> "Sanitizer":
        if self.mode == MODE_OFF:
            return self
        self.tasks.install()
        if self.mode == MODE_FULL:
            self.blocking.install()
            self.transports.install()
            self.framing.install()
        return self

    def uninstall(self) -> None:
        self.tasks.uninstall()
        self.blocking.uninstall()
        self.transports.uninstall()
        self.framing.uninstall()

    def begin_epoch(self) -> None:
        self.tasks.begin_epoch()
        self.blocking.begin_epoch()
        self.transports.begin_epoch()
        self.framing.begin_epoch()

    def epoch_report(self) -> list[str]:
        """Human-readable findings for the current epoch ([] = clean)."""
        if self.mode == MODE_OFF:
            return []
        out = [
            "leaked " + rec.render()
            for _, rec in self.tasks.pending_in_epoch()
        ]
        if self.mode == MODE_FULL:
            out += [r.render() for r in self.blocking.reports_in_epoch()]
            out += [
                rec.render(t) for t, rec in self.transports.unclosed_in_epoch()
            ]
            out += self.framing.violations_in_epoch()
        return out
