"""pytest integration for the dtsan runtime sanitizer (sanitizer.py).

tests/conftest.py delegates into this module (so its existing
``pytest_runtest_makereport`` time-budget hook and the sanitizer check
compose in one place); the module also exposes the same behavior as
standalone pytest hooks, so ``pytest -p dynamo_tpu.analysis.pytest_sanitizer``
works outside this repo's conftest.

Policy (satellite of ISSUE 5): task-LEAK checking is on by DEFAULT in
tier-1 — a passing test that leaves a live task behind fails with the
task's creation traceback.  ``DYNAMO_SANITIZE=1`` upgrades to the full
instrument set (blocking callbacks, unclosed transports, frame-protocol
violations); ``DYNAMO_SANITIZE=0`` disables everything.

Grandfathered files mirror the lint-baseline idiom (and conftest's
time-budget list): module-level entries whose tests intentionally keep
background services alive across tests.  Burn the list down; do NOT
grow it without a justification comment.
"""

from __future__ import annotations

import os
from typing import Optional

import pytest

from dynamo_tpu.analysis.sanitizer import (
    MODE_OFF,
    Sanitizer,
    mode_from_env,
)

__all__ = [
    "configure",
    "begin_test",
    "check_report",
    "get_sanitizer",
    "LEAK_GRANDFATHERED_FILES",
]

# Files exempt from per-test sanitizer failures.  Each entry carries the
# reason it is grandfathered; remove the entry when the file is fixed.
LEAK_GRANDFATHERED_FILES = {
    # multihost suites run worker event loops on background threads that
    # legitimately outlive individual tests (module-scoped mesh fixtures)
    "test_multihost.py",
    "test_multihost_disagg.py",
}

# threshold for the blocking-callback monitor (full mode); generous by
# default — tier-1 shares one CPU with jit compilation
_BLOCKING_THRESHOLD_S = float(
    os.environ.get("DYNAMO_SANITIZE_BLOCK_S", "0.25")
)

_sanitizer: Optional[Sanitizer] = None


def get_sanitizer() -> Optional[Sanitizer]:
    return _sanitizer


def configure(config=None) -> Optional[Sanitizer]:
    """Install the sanitizer per DYNAMO_SANITIZE (idempotent)."""
    global _sanitizer
    if _sanitizer is not None:
        return _sanitizer
    mode = mode_from_env()
    if mode == MODE_OFF:
        return None
    _sanitizer = Sanitizer(
        mode, blocking_threshold_s=_BLOCKING_THRESHOLD_S
    ).install()
    return _sanitizer


def unconfigure() -> None:
    global _sanitizer
    if _sanitizer is not None:
        _sanitizer.uninstall()
        _sanitizer = None


def begin_test(item=None) -> None:
    """Open a fresh epoch: findings are attributed to the test between
    this call and its check_report."""
    if _sanitizer is not None:
        _sanitizer.begin_epoch()


def check_report(item, call, rep) -> None:
    """Flip a PASSING call-phase report to failed on sanitizer findings.

    Mirrors the conftest time-budget guard: failing tests are left alone
    (the real failure is the signal there), and grandfathered files are
    exempt.  Mutates ``rep`` in place; call from a hookwrapper
    ``pytest_runtest_makereport``.
    """
    if _sanitizer is None or rep.when != "call" or not rep.passed:
        return
    fname = os.path.basename(str(item.fspath))
    if fname in LEAK_GRANDFATHERED_FILES:
        return
    if item.get_closest_marker("no_sanitize") is not None:
        return
    findings = _sanitizer.epoch_report()
    if not findings:
        return
    rep.outcome = "failed"
    rep.longrepr = (
        f"{item.nodeid}: dtsan found {len(findings)} issue"
        f"{'s' if len(findings) != 1 else ''} at teardown "
        "(docs/static_analysis.md#runtime-sanitizer):\n\n"
        + "\n\n".join(findings)
        + "\n\nFix the leak (cancel AND reap the task / close_writer the "
        "stream), mark the test @pytest.mark.no_sanitize with a reason, "
        "or — for pre-existing debt only — grandfather the file in "
        "pytest_sanitizer.LEAK_GRANDFATHERED_FILES."
    )


# ------------------------------------------------- standalone plugin hooks ----
# Only used when loaded with `-p dynamo_tpu.analysis.pytest_sanitizer`;
# this repo's tests/conftest.py calls the helpers above directly instead
# (its own makereport hook composes the time budget + sanitizer checks).


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "no_sanitize: exempt this test from dtsan runtime-sanitizer "
        "failures (leaked tasks / blocking callbacks / unclosed "
        "transports)",
    )
    configure(config)


def pytest_runtest_setup(item):
    begin_test(item)


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_makereport(item, call):
    outcome = yield
    check_report(item, call, outcome.get_result())
