"""Metrics contract plane (dtmet): static audit of the /metrics surface.

With the TPU tunnel down, `/metrics` scrapes and the dtperf/dtload
manifests ARE the perf currency — yet the surface is stitched together
from f-string literals on the render side and string-prefix matches on
the scrape side.  This plane closes the loop statically:

* **producers** — counter/gauge/histogram record sites (the process-
  global counter singletons in engine/counters.py, fault/counters.py,
  obs/costs.py, obs/timeline.py, obs/perfmodel.py) reached as the
  value expressions backing rendered samples;
* **renderers** — every ``# TYPE`` declaration and sample line built
  in a render context (``lines.append(...)`` / ``lines.extend(...)`` /
  ``yield``), with f-string name composition resolved through the
  project-wide const table (dtwire idiom) so registry constants like
  ``HttpMetric.REQUESTS_TOTAL`` bottom out at their literals;
* **consumers** — scrape-string literals and registry references in
  benchmarks/tests, plus constant-key reads of the
  ``EngineCore.metrics()`` dict.

The three meet on a name × labels × type census committed to
``analysis/metrics_manifest.json`` under the shared justification /
``--update-baseline`` contract (tracecheck.Manifest).

Rules:

* **MT001** recorded-but-never-rendered — a counter attr assigned in a
  producer's ``reset()`` (or a stats-dict key) that nothing in the
  serving tree ever reads: dead telemetry, or a renderer that forgot a
  family member.
* **MT002** scraped-but-never-produced — the WR002 twin: a scrape
  literal / registry reference / engine-dict key with no renderer
  behind it.  This is the rule that catches a renamed counter silently
  zeroing a banked bench column; the finding detail names the exact
  stale scrape site.
* **MT003** unbounded-label-cardinality — a label value data-flows
  from per-request identity (request/session/tenant/hash/trace ids)
  instead of a closed enum: the millions-of-users tripwire.
* **MT004** type-misuse — counter not ``_total``; histogram units not
  ``_seconds``/``_bytes``; a counter that is decremented or plainly
  re-assigned outside ``reset``/``__init__``; conflicting TYPE lines.
* **MT005** census-drift — the extracted census disagrees with the
  committed manifest, the metric_names registry SCHEMA, or the
  generated docs/observability.md reference table.
"""

from __future__ import annotations

import ast
import json
import re
from pathlib import Path
from typing import Optional

from dynamo_tpu.analysis.core import dotted_name, iter_python_files
from dynamo_tpu.analysis.project import ProjectIndex
from dynamo_tpu.analysis.tracecheck import Manifest, TraceFinding
from dynamo_tpu.analysis.wirecheck import _const_table, _lit_values, _param_names

__all__ = [
    "MET_RULES",
    "METRIC_PREFIX",
    "DEFAULT_METRICS_MANIFEST_PATH",
    "collect_metric_facts",
    "check_metric_facts",
    "census_snapshot",
    "render_docs_table",
    "run_metrics",
]

MET_RULES = {
    "MT001": ("recorded-never-rendered",
              "a producer records state no renderer or reader consumes"),
    "MT002": ("scraped-never-produced",
              "a scrape site names a metric no renderer emits"),
    "MT003": ("unbounded-label-cardinality",
              "a label value flows from per-request identity data"),
    "MT004": ("type-misuse",
              "metric name/TYPE disagrees with how the backing is used"),
    "MT005": ("census-drift",
              "extracted census disagrees with manifest/registry/docs"),
}

DEFAULT_METRICS_MANIFEST_PATH = Path(__file__).parent / "metrics_manifest.json"

METRIC_PREFIX = "dynamo_tpu_"

# histogram child-series suffixes fold back onto the base name
_HIST_SUFFIXES = ("_bucket", "_sum", "_count")

# identifier fragments that mark per-request identity flowing into a label
_CARDINALITY_TOKENS = (
    "request_id", "req_id", "session", "tenant", "user", "uuid",
    "trace", "span", "hash", "digest", "token_id",
)

_TYPE_RE = re.compile(r"^# TYPE ([A-Za-z_][A-Za-z0-9_]*) ([a-z]+)\s*$")
_SAMPLE_RE = re.compile(
    r"^(?P<name>[A-Za-z_][A-Za-z0-9_]*)"
    r"(?:\{(?P<labels>[^}]*)\})?(?P<rest> .*)?$",
    re.S,
)
_LABEL_RE = re.compile(r'([A-Za-z_][A-Za-z0-9_]*)="([^"]*)"')
_HOLE_RE = re.compile(r"^\x00(\d+)\x01$")
_NAME_RUN_RE = re.compile(r"[A-Za-z_][A-Za-z0-9_]*")


def _repo_root() -> Path:
    return Path(__file__).resolve().parents[2]


def _scan_files(root: Path) -> list[Path]:
    """Default scan scope: the package, the benchmarks, bench.py, and
    the tests — minus the analysis plane itself and its fixtures (the
    lint fixtures deliberately contain every violation)."""
    roots = [root / "dynamo_tpu", root / "benchmarks", root / "tests"]
    bench = root / "bench.py"
    files: list[Path] = []
    for p in iter_python_files([r for r in roots if r.exists()]):
        rel = p.as_posix()
        if "lint_fixtures" in rel or "metrics_golden" in rel:
            continue
        if "dynamo_tpu/analysis/" in rel:
            continue
        if p.name == "test_metcheck.py":
            continue
        files.append(p)
    if bench.is_file():
        files.append(bench)
    return files


def _flatten(parts: list) -> tuple[str, list]:
    """Parts -> (text-with-hole-sentinels, holes).  A hole renders as
    \\x00<idx>\\x01 so regexes can treat it as an opaque token."""
    text: list[str] = []
    holes: list = []
    for kind, val in parts:
        if kind == "lit":
            text.append(val)
        else:
            text.append(f"\x00{len(holes)}\x01")
            holes.append(val)
    return "".join(text), holes


def _merge_lits(parts: list) -> list:
    out: list = []
    for kind, val in parts:
        if kind == "lit" and out and out[-1][0] == "lit":
            out[-1] = ("lit", out[-1][1] + val)
        else:
            out.append((kind, val))
    return out


# ------------------------------------------------------------- extraction ----


class _Sink:
    """Cross-module fact accumulator for one collect run."""

    def __init__(self) -> None:
        # (name, type, site, modname) from render-context TYPE lines
        self.type_decls: list[tuple[str, str, str, str]] = []
        # sample dicts: name/labels/backing/site/modname
        self.samples: list[dict] = []
        # (name, wildcard, site) scrape-string occurrences
        self.raw_consumers: list[tuple[str, bool, str]] = []
        # (modname, literal, site) registry references outside renderers
        self.dotted_refs: list[tuple[str, str, str]] = []
        # constant dict keys read anywhere (subscript Load / .get)
        self.consumed_keys: set[str] = set()
        # (class_key, method) registered dict surfaces
        self.dict_surfaces: set[tuple[str, str]] = set()
        # engine-dict constant-key reads: key -> [sites]
        self.engine_reads: dict[str, list[str]] = {}


class _ModuleWalk:
    """Statement-level walk of one module: binds template/alias env,
    recognizes render contexts, and records facts into the sink."""

    def __init__(self, sink: _Sink, ctx, modname: str,
                 consts: dict[str, str],
                 singletons: dict[str, str],
                 classmap: dict[str, tuple[str, ast.ClassDef]]):
        self.sink = sink
        self.ctx = ctx
        self.modname = modname
        self.consts = consts
        self.singletons = singletons
        self.classmap = classmap
        self.path = ctx.path.as_posix() if hasattr(ctx.path, "as_posix") \
            else str(ctx.path)
        self._used: set[int] = set()

    # ------------------------------------------------------------- entry ----
    def run(self) -> None:
        self._stmts(self.ctx.tree.body, {}, {}, 0)
        for node in self.ctx.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._stmts(node.body, {}, {}, 0)
            elif isinstance(node, ast.ClassDef):
                for m in node.body:
                    if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        self._stmts(m.body, {}, {}, 0)

    def _site(self, node) -> str:
        return f"{self.path}:{getattr(node, 'lineno', 0)}"

    # -------------------------------------------------------- resolution ----
    def _resolve(self, expr, env) -> list:
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            return [("lit", expr.value)]
        if isinstance(expr, ast.JoinedStr):
            parts: list = []
            for v in expr.values:
                if isinstance(v, ast.Constant):
                    parts.append(("lit", str(v.value)))
                elif isinstance(v, ast.FormattedValue):
                    parts.extend(self._resolve_hole(v.value, env))
            return _merge_lits(parts)
        return self._resolve_hole(expr, env)

    def _resolve_hole(self, expr, env) -> list:
        if isinstance(expr, ast.Name):
            b = env.get(expr.id)
            if b and b[0] == "tpl":
                return list(b[1])
        vals = _lit_values(expr, self.ctx, self.modname, self.consts)
        if len(vals) == 1 and vals[0] != "?":
            return [("lit", vals[0])]
        self._consume_in(expr, env)
        return [("hole", expr)]

    def _consume_in(self, expr, env) -> None:
        """Constant dict-key reads inside an unresolved template hole
        still count as consumption (``{round(tl['ewma_wall_ms'], 6)}``
        consumes the snapshot key)."""
        for n in ast.walk(expr):
            if isinstance(n, ast.Subscript) and isinstance(n.ctx, ast.Load):
                key = self._const_key(n.slice, env)
                if key is not None:
                    self.sink.consumed_keys.add(key)
            elif (isinstance(n, ast.Call)
                  and isinstance(n.func, ast.Attribute)
                  and n.func.attr == "get" and n.args):
                key = self._const_key(n.args[0], env)
                if key is not None:
                    self.sink.consumed_keys.add(key)

    def _const_key(self, expr, env) -> Optional[str]:
        """Literal value of a subscript/.get key expression, through
        env-bound loop variables."""
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            return expr.value
        if isinstance(expr, ast.Name):
            b = env.get(expr.id)
            if b and b[0] == "tpl" and len(b[1]) == 1 and b[1][0][0] == "lit":
                return b[1][0][1]
        return None

    def _lit_of(self, expr, env) -> Optional[str]:
        parts = self._resolve(expr, env)
        if len(parts) == 1 and parts[0][0] == "lit":
            return parts[0][1]
        return None

    def _backing(self, expr, env) -> Optional[tuple[str, str]]:
        """(class_key, attr) behind a sample value expression, resolved
        through numeric wrappers, env object aliases, and the
        module-level singleton table."""
        e = expr
        while (isinstance(e, ast.Call) and isinstance(e.func, ast.Name)
               and e.func.id in ("round", "int", "float", "abs", "len")
               and e.args):
            e = e.args[0]
        d = dotted_name(e)
        if not d:
            return None
        head, _, rest = d.partition(".")
        b = env.get(head)
        cands = []
        if b and b[0] == "obj":
            cands.append(b[1] + ("." + rest if rest else ""))
        else:
            cands.append(self.ctx.canonical(d))
            cands.append(f"{self.modname}.{d}")
        for cand in cands:
            for s_dotted, cls_key in self.singletons.items():
                if cand.startswith(s_dotted + "."):
                    attr = cand[len(s_dotted) + 1:]
                    if attr and "." not in attr:
                        return (cls_key, attr)
        return None

    def _singleton_of(self, expr, env) -> Optional[str]:
        """Singleton dotted key an expression resolves to, or None."""
        d = dotted_name(expr)
        if not d:
            return None
        head, _, rest = d.partition(".")
        b = env.get(head)
        cands = []
        if b and b[0] == "obj":
            cands.append(b[1] + ("." + rest if rest else ""))
        else:
            cands.append(self.ctx.canonical(d))
            cands.append(f"{self.modname}.{d}")
        for cand in cands:
            if cand in self.singletons:
                return cand
        return None

    # ------------------------------------------------------------- walk ----
    def _stmts(self, body, env, lf, depth) -> None:
        for stmt in body:
            self._stmt(stmt, env, lf, depth)

    def _stmt(self, stmt, env, lf, depth) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            lf[stmt.name] = stmt
            return
        if isinstance(stmt, ast.ClassDef):
            return
        if isinstance(stmt, ast.If):
            self._scan(stmt.test, env, lf, depth)
            self._stmts(stmt.body, env, lf, depth)
            self._stmts(stmt.orelse, env, lf, depth)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            if self._literal_for(stmt, env, lf, depth):
                return
            self._scan(stmt.iter, env, lf, depth)
            self._stmts(stmt.body, env, lf, depth)
            self._stmts(stmt.orelse, env, lf, depth)
            return
        if isinstance(stmt, ast.While):
            self._scan(stmt.test, env, lf, depth)
            self._stmts(stmt.body, env, lf, depth)
            self._stmts(stmt.orelse, env, lf, depth)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._scan(item.context_expr, env, lf, depth)
            self._stmts(stmt.body, env, lf, depth)
            return
        if isinstance(stmt, ast.Try):
            self._stmts(stmt.body, env, lf, depth)
            for h in stmt.handlers:
                self._stmts(h.body, env, lf, depth)
            self._stmts(stmt.orelse, env, lf, depth)
            self._stmts(stmt.finalbody, env, lf, depth)
            return
        # simple statements -------------------------------------------------
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
            return  # docstring / bare literal — not a scrape site
        self._render_contexts(stmt, env, lf, depth)
        if isinstance(stmt, ast.Assign):
            self._assign(stmt, env)
        self._scan(stmt, env, lf, depth)

    def _literal_for(self, stmt, env, lf, depth) -> bool:
        """``for a, b in ((lit, lit), ...)`` and ``for a in ("x", "y")``
        unroll with the loop variables bound to their literal values, so
        templates built from them resolve fully."""
        tgt, it = stmt.target, stmt.iter
        if not isinstance(it, ast.Tuple):
            return False
        rows: list[list[Optional[str]]] = []
        if isinstance(tgt, ast.Tuple) and all(
                isinstance(n, ast.Name) for n in tgt.elts):
            names = [n.id for n in tgt.elts]
            for elt in it.elts:
                if not (isinstance(elt, ast.Tuple)
                        and len(elt.elts) == len(names)):
                    return False
                row = [self._lit_of(e, env) for e in elt.elts]
                if any(v is None for v in row):
                    return False
                rows.append(row)
        elif isinstance(tgt, ast.Name):
            names = [tgt.id]
            for elt in it.elts:
                v = self._lit_of(elt, env)
                if v is None:
                    return False
                rows.append([v])
        else:
            return False
        self._mark_used(it)
        for row in rows:
            env2 = dict(env)
            for name, val in zip(names, row):
                env2[name] = ("tpl", [("lit", val)])
            self._stmts(stmt.body, env2, lf, depth)
        return True

    def _assign(self, stmt: ast.Assign, env) -> None:
        if len(stmt.targets) != 1 or not isinstance(stmt.targets[0], ast.Name):
            return
        name = stmt.targets[0].id
        val = stmt.value
        # template binding: labels = f'model="{m}"'
        if isinstance(val, (ast.Constant, ast.JoinedStr)):
            parts = self._resolve(val, env)
            if any(k == "lit" for k, _ in parts):
                env[name] = ("tpl", parts)
            return
        # engine metrics dict: stats = engine.metrics()
        if isinstance(val, ast.Call) and not val.args and not val.keywords:
            fd = dotted_name(val.func)
            if fd and fd.endswith(".metrics"):
                env[name] = ("eng",)
                return
            # dict surface: tl = step_timeline.snapshot()
            if fd and isinstance(val.func, ast.Attribute):
                s = self._singleton_of(val.func.value, env)
                if s is not None:
                    cls_key = self.singletons[s]
                    method = val.func.attr
                    if method in _surface_methods(self.classmap, cls_key):
                        self.sink.dict_surfaces.add((cls_key, method))
                        env[name] = ("dict", cls_key, method)
                        return
        # object alias: sc = kv_shard_counters
        if isinstance(val, (ast.Name, ast.Attribute)):
            s = self._singleton_of(val, env)
            if s is not None:
                env[name] = ("obj", s)

    # ---------------------------------------------------- render contexts ----
    def _render_contexts(self, stmt, env, lf, depth) -> None:
        expr = stmt.value if isinstance(stmt, ast.Expr) else None
        if isinstance(expr, ast.Yield) and expr.value is not None:
            self._emit_render(expr.value, env)
            return
        if (isinstance(expr, ast.Call) and isinstance(expr.func, ast.Attribute)
                and expr.func.attr in ("append", "extend")):
            for a in expr.args:
                if isinstance(a, (ast.Constant, ast.JoinedStr)):
                    self._emit_render(a, env)
                elif isinstance(a, ast.Call):
                    self._maybe_hist_render(a, env)

    def _maybe_hist_render(self, call: ast.Call, env) -> bool:
        """``lines.extend(h.render(NAME, labels))`` — the Histogram
        helper expands to _bucket/_sum/_count series for NAME."""
        if not (isinstance(call.func, ast.Attribute)
                and call.func.attr == "render" and len(call.args) == 2):
            return False
        name = self._lit_of(call.args[0], env)
        if not name or not name.startswith(METRIC_PREFIX):
            return False
        parts = self._resolve(call.args[1], env)
        text, holes = _flatten(parts)
        labels = []
        for ln, lv in _LABEL_RE.findall(text):
            hm = _HOLE_RE.match(lv)
            src = ast.unparse(holes[int(hm.group(1))]) if hm else None
            labels.append((ln, src))
        self.sink.samples.append({
            "name": name, "labels": labels, "backing": None,
            "site": self._site(call), "modname": self.modname,
        })
        self._mark_used(call)
        return True

    def _emit_render(self, expr, env) -> None:
        if not isinstance(expr, (ast.Constant, ast.JoinedStr)):
            return
        parts = self._resolve(expr, env)
        text, holes = _flatten(parts)
        self._mark_used(expr)
        if text.startswith("# HELP"):
            return
        m = _TYPE_RE.match(text)
        if m:
            if m.group(1).startswith(METRIC_PREFIX):
                self.sink.type_decls.append(
                    (m.group(1), m.group(2), self._site(expr), self.modname))
            return
        m = _SAMPLE_RE.match(text)
        if not m or not m.group("name").startswith(METRIC_PREFIX):
            return
        rest = m.group("rest")
        if not rest or not rest.strip():
            return
        labels = []
        for ln, lv in _LABEL_RE.findall(m.group("labels") or ""):
            hm = _HOLE_RE.match(lv)
            src = ast.unparse(holes[int(hm.group(1))]) if hm else None
            labels.append((ln, src))
        vh = _HOLE_RE.match(rest.strip())
        backing = None
        if vh is not None:
            backing = self._backing(holes[int(vh.group(1))], env)
        self.sink.samples.append({
            "name": m.group("name"), "labels": labels, "backing": backing,
            "site": self._site(expr), "modname": self.modname,
        })

    def _mark_used(self, node) -> None:
        for n in ast.walk(node):
            self._used.add(id(n))

    # ------------------------------------------------------- generic scan ----
    def _scan(self, node, env, lf, depth) -> None:
        if node is None or id(node) in self._used:
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return
        if isinstance(node, (ast.Constant, ast.JoinedStr)):
            self._consumer_string(node, env)
            if isinstance(node, ast.JoinedStr):
                for v in node.values:
                    if isinstance(v, ast.FormattedValue):
                        self._scan(v.value, env, lf, depth)
            return
        if isinstance(node, ast.Attribute):
            self._dotted_ref(node, env)
            self._scan(node.value, env, lf, depth)
            return
        if isinstance(node, ast.Subscript):
            key = self._const_key(node.slice, env)
            if key is not None and isinstance(node.ctx, ast.Load):
                self.sink.consumed_keys.add(key)
                self._engine_read(node.value, key, env, node)
            for child in ast.iter_child_nodes(node):
                self._scan(child, env, lf, depth)
            return
        if isinstance(node, ast.Call):
            self._call(node, env, lf, depth)
            for child in ast.iter_child_nodes(node):
                self._scan(child, env, lf, depth)
            return
        for child in ast.iter_child_nodes(node):
            self._scan(child, env, lf, depth)

    def _call(self, node: ast.Call, env, lf, depth) -> None:
        # .get("key") consumption (incl. engine dict reads)
        if (isinstance(node.func, ast.Attribute) and node.func.attr == "get"
                and node.args):
            key = self._const_key(node.args[0], env)
            if key is not None:
                self.sink.consumed_keys.add(key)
                self._engine_read(node.func.value, key, env, node)
        # local helper call: recurse with literal args bound (the
        # components/metrics.py ``gauge(name, help)`` idiom)
        if (isinstance(node.func, ast.Name) and node.func.id in lf
                and depth < 2):
            fn = lf[node.func.id]
            env2: dict = {}
            for pname, arg in zip(_param_names(fn), node.args):
                parts = self._resolve(arg, env)
                if all(k == "lit" for k, _ in parts):
                    env2[pname] = ("tpl", parts)
            self._stmts(fn.body, env2, dict(lf), depth + 1)

    def _engine_read(self, base, key: str, env, node) -> None:
        """Record constant-key reads rooted in an ``.metrics()`` call or
        a variable bound to one."""
        eng = False
        if isinstance(base, ast.Name):
            b = env.get(base.id)
            eng = bool(b and b[0] == "eng")
        elif isinstance(base, ast.Call) and not base.args:
            fd = dotted_name(base.func)
            eng = bool(fd and fd.endswith(".metrics"))
        if eng:
            self.sink.engine_reads.setdefault(key, []).append(self._site(node))

    def _consumer_string(self, node, env) -> None:
        parts = self._resolve(node, env)
        text, _holes = _flatten(parts)
        if text.startswith("# TYPE "):
            m = _TYPE_RE.match(text)
            if m and m.group(1).startswith(METRIC_PREFIX):
                self.sink.raw_consumers.append(
                    (m.group(1), False, self._site(node)))
            return
        for m in _NAME_RUN_RE.finditer(text):
            name = m.group(0)
            if not name.startswith(METRIC_PREFIX):
                continue
            # a hole right after the run, or a trailing underscore,
            # marks a family-prefix match rather than one full name
            wildcard = ((m.end() < len(text) and text[m.end()] == "\x00")
                        or name.endswith("_"))
            self.sink.raw_consumers.append((name, wildcard, self._site(node)))

    def _dotted_ref(self, node: ast.Attribute, env) -> None:
        d = dotted_name(node)
        if not d:
            return
        for cand in (self.ctx.canonical(d), f"{self.modname}.{d}"):
            lit = self.consts.get(cand)
            if lit and lit.startswith(METRIC_PREFIX):
                self.sink.dotted_refs.append(
                    (self.modname, lit, self._site(node)))
                return


# ---------------------------------------------------------- class analysis ----


def _surface_methods(classmap, cls_key: str) -> set[str]:
    """Methods of ``cls_key`` that return a dict literal (stats/snapshot
    surfaces)."""
    entry = classmap.get(cls_key)
    if entry is None:
        return set()
    _, node = entry
    out = set()
    for m in node.body:
        if not isinstance(m, ast.FunctionDef):
            continue
        for n in ast.walk(m):
            if isinstance(n, ast.Return) and isinstance(n.value, ast.Dict):
                out.add(m.name)
                break
    return out


def _surface_keys(classmap, cls_key: str, method: str) -> dict[str, str]:
    """Constant dict keys a registered surface exposes: dict literals in
    the method itself, plus dict literals the class stores into ``self``
    containers (the TransferCostTable.record idiom).  -> key: site"""
    entry = classmap.get(cls_key)
    if entry is None:
        return {}
    modpath, node = entry
    keys: dict[str, str] = {}

    def add_dicts(scope) -> None:
        for n in ast.walk(scope):
            if isinstance(n, ast.Dict):
                for k in n.keys:
                    if isinstance(k, ast.Constant) and isinstance(k.value, str):
                        keys.setdefault(
                            k.value, f"{modpath}:{getattr(k, 'lineno', 0)}")

    for m in node.body:
        if isinstance(m, ast.FunctionDef) and m.name == method:
            add_dicts(m)
    for m in node.body:
        if not isinstance(m, ast.FunctionDef):
            continue
        for n in ast.walk(m):
            if (isinstance(n, ast.Assign) and len(n.targets) == 1
                    and isinstance(n.targets[0],
                                   (ast.Subscript, ast.Attribute))
                    and isinstance(n.value, ast.Dict)):
                t = n.targets[0]
                base = t.value if isinstance(t, ast.Subscript) else t
                d = dotted_name(base)
                if d and d.split(".")[0] == "self":
                    add_dicts(n.value)
    return keys


def _reset_attrs(node: ast.ClassDef) -> dict[str, int]:
    """Public ``self.X = ...`` assignments in reset() -> attr: lineno."""
    out: dict[str, int] = {}
    for m in node.body:
        if not (isinstance(m, ast.FunctionDef) and m.name == "reset"):
            continue
        for n in ast.walk(m):
            targets = []
            if isinstance(n, ast.Assign):
                targets = n.targets
            elif isinstance(n, ast.AnnAssign):
                targets = [n.target]
            for t in targets:
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                        and not t.attr.startswith("_")):
                    out.setdefault(t.attr, n.lineno)
    return out


def _mutation_profile(node: ast.ClassDef) -> tuple[set[str], set[str]]:
    """(decremented attrs, plainly-assigned-outside-init/reset attrs)."""
    dec: set[str] = set()
    assigned: set[str] = set()
    for m in node.body:
        if not isinstance(m, ast.FunctionDef):
            continue
        for n in ast.walk(m):
            if (isinstance(n, ast.AugAssign) and isinstance(n.op, ast.Sub)
                    and isinstance(n.target, ast.Attribute)):
                dec.add(n.target.attr)
            if (isinstance(n, ast.Assign)
                    and m.name not in ("reset", "__init__")):
                for t in n.targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        assigned.add(t.attr)
    return dec, assigned


def _producer_scope(path: str) -> bool:
    """Modules whose attribute reads count as in-tree consumption for
    MT001 (tests/benchmarks must not mask dead telemetry)."""
    p = path
    return not (p.startswith("tests/") or p.startswith("benchmarks/")
                or p == "bench.py" or "/tests/" in p)


# ------------------------------------------------------------- engine dict ----


def _engine_facts(index: ProjectIndex, classmap,
                  sink: _Sink) -> dict:
    """EngineCore.metrics() key surface + its constant-key consumers."""
    entry = None
    for key in classmap:
        if key.endswith(".EngineCore"):
            entry = classmap[key]
            break
    keys: set[str] = set()
    if entry is not None:
        modpath, node = entry
        attrtype: dict[str, str] = {}
        for n in ast.walk(node):
            if (isinstance(n, ast.Assign) and len(n.targets) == 1
                    and isinstance(n.targets[0], ast.Attribute)
                    and isinstance(n.targets[0].value, ast.Name)
                    and n.targets[0].value.id == "self"
                    and isinstance(n.value, ast.Call)):
                cd = dotted_name(n.value.func)
                if cd:
                    attrtype[n.targets[0].attr] = cd
        metrics_fn = None
        for m in node.body:
            if isinstance(m, ast.FunctionDef) and m.name == "metrics":
                metrics_fn = m
                break
        if metrics_fn is not None:
            for n in ast.walk(metrics_fn):
                if isinstance(n, ast.Dict):
                    for k in n.keys:
                        if isinstance(k, ast.Constant) and isinstance(
                                k.value, str):
                            keys.add(k.value)
                if (isinstance(n, ast.Assign) and len(n.targets) == 1
                        and isinstance(n.targets[0], ast.Subscript)
                        and isinstance(n.targets[0].slice, ast.Constant)
                        and isinstance(n.targets[0].slice.value, str)):
                    keys.add(n.targets[0].slice.value)
                # out.update(self.X.stats()) — fold in that class's keys
                if (isinstance(n, ast.Call)
                        and isinstance(n.func, ast.Attribute)
                        and n.func.attr == "update" and n.args
                        and isinstance(n.args[0], ast.Call)
                        and isinstance(n.args[0].func, ast.Attribute)):
                    inner = n.args[0].func
                    d = dotted_name(inner.value)
                    if d and d.startswith("self."):
                        cd = attrtype.get(d[5:])
                        cls_entry = _resolve_class(index, classmap, cd)
                        if cls_entry:
                            keys.update(_surface_keys(
                                classmap, cls_entry, inner.attr))
    return {
        "keys": sorted(keys),
        "consumers": {k: sorted(set(v))
                      for k, v in sorted(sink.engine_reads.items())},
    }


def _resolve_class(index: ProjectIndex, classmap,
                   dotted: Optional[str]) -> Optional[str]:
    """Constructor dotted name -> classmap key (searched by class
    basename when the canonical path isn't a direct hit)."""
    if not dotted:
        return None
    if dotted in classmap:
        return dotted
    base = dotted.split(".")[-1]
    hits = [k for k in classmap if k.endswith("." + base)]
    return hits[0] if len(hits) == 1 else None


# ---------------------------------------------------------------- assembly ----


def collect_metric_facts(paths=None, root=None) -> tuple[dict, list]:
    """Extract the metrics census + intrinsic findings (MT001/3/4).

    Returns ``(facts, intrinsic)``: facts carries the renderer census,
    the consumer sites, and the engine-dict surface; intrinsic carries
    the findings that are properties of the tree itself (drift rules
    MT002/MT005 need the manifest and live in check_metric_facts)."""
    root = Path(root) if root is not None else _repo_root()
    files = [Path(p) for p in paths] if paths is not None \
        else _scan_files(root)
    index = ProjectIndex.build(files, root=root)
    consts = _const_table(index)

    classmap: dict[str, tuple[str, ast.ClassDef]] = {}
    singletons: dict[str, str] = {}
    for modname, ctx in index.modules.items():
        p = ctx.path.as_posix() if hasattr(ctx.path, "as_posix") \
            else str(ctx.path)
        for node in ctx.tree.body:
            if isinstance(node, ast.ClassDef):
                classmap[f"{modname}.{node.name}"] = (p, node)
    for modname, ctx in index.modules.items():
        for node in ctx.tree.body:
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Call)):
                cd = dotted_name(node.value.func)
                if not cd:
                    continue
                for cand in (ctx.canonical(cd), f"{modname}.{cd}"):
                    if cand in classmap:
                        singletons[
                            f"{modname}.{node.targets[0].id}"] = cand
                        break

    sink = _Sink()
    for modname, ctx in index.modules.items():
        if modname.endswith("metric_names"):
            continue  # the registry defines names; it neither renders
        _ModuleWalk(sink, ctx, modname, consts, singletons, classmap).run()

    # census: renderer TYPE decls + samples folded onto base names -------
    census: dict[str, dict] = {}
    type_conflicts: dict[str, set[str]] = {}
    for name, typ, site, _mod in sink.type_decls:
        if name in census:
            if census[name]["type"] != typ:
                type_conflicts.setdefault(
                    name, {census[name]["type"]}).add(typ)
        else:
            census[name] = {"type": typ, "labels": set(), "renderer": site,
                            "backings": []}
    render_modules = {mod for _n, _t, _s, mod in sink.type_decls}
    untyped: dict[str, str] = {}
    for s in sink.samples:
        base = s["name"]
        if base not in census:
            for suf in _HIST_SUFFIXES:
                if base.endswith(suf) and base[:-len(suf)] in census:
                    base = base[:-len(suf)]
                    break
        if base not in census:
            untyped.setdefault(s["name"], s["site"])
            continue
        for ln, _src in s["labels"]:
            if ln != "le":
                census[base]["labels"].add(ln)
        if s["backing"]:
            census[base]["backings"].append(s["backing"])

    # consumers: scrape strings + registry refs outside renderers --------
    def _normalize(name: str) -> str:
        if name in census:
            return name
        for suf in _HIST_SUFFIXES:
            if name.endswith(suf) and name[:-len(suf)] in census:
                return name[:-len(suf)]
        return name

    consumers: dict[str, set] = {}
    consumers_prefix: dict[str, set] = {}
    for name, wildcard, site in sink.raw_consumers:
        if wildcard:
            consumers_prefix.setdefault(name, set()).add(site)
        else:
            consumers.setdefault(_normalize(name), set()).add(site)
    for modname, lit, site in sink.dotted_refs:
        if modname in render_modules:
            continue
        consumers.setdefault(_normalize(lit), set()).add(site)

    facts = {
        "metrics": {
            name: {
                "type": info["type"],
                "labels": sorted(info["labels"]),
                "renderer": info["renderer"],
            }
            for name, info in sorted(census.items())
        },
        "consumers": {n: sorted(s) for n, s in sorted(consumers.items())},
        "consumers_prefix": {n: sorted(s) for n, s
                             in sorted(consumers_prefix.items())},
        "engine": _engine_facts(index, classmap, sink),
    }

    intrinsic = _intrinsic_findings(
        index, classmap, sink, census, type_conflicts, untyped)
    return facts, intrinsic


def _intrinsic_findings(index, classmap, sink: _Sink, census,
                        type_conflicts, untyped) -> list:
    findings: list[TraceFinding] = []

    # ---- MT004: name/TYPE conventions ---------------------------------
    for name, types in sorted(type_conflicts.items()):
        findings.append(TraceFinding(
            name, "MT004", "type-conflict",
            f"conflicting TYPE declarations: {sorted(types)}"))
    for name, site in sorted(untyped.items()):
        findings.append(TraceFinding(
            name, "MT004", "missing-type",
            f"sample rendered at {site} with no # TYPE declaration"))
    for name, info in sorted(census.items()):
        if info["type"] == "counter" and not name.endswith("_total"):
            findings.append(TraceFinding(
                name, "MT004", "counter-name",
                "counter does not end in _total — scrapers derive rates "
                "from the suffix convention"))
        if (info["type"] == "histogram"
                and not name.endswith(("_seconds", "_bytes"))):
            findings.append(TraceFinding(
                name, "MT004", "histogram-units",
                "histogram name lacks a base-unit suffix "
                "(_seconds/_bytes per Prometheus conventions)"))

    # ---- MT004 c3/c5 + MT001 attr census via backing classes ----------
    producer_classes: set[str] = set()
    for info in census.values():
        for cls_key, _attr in info["backings"]:
            producer_classes.add(cls_key)
    for cls_key, _method in sink.dict_surfaces:
        producer_classes.add(cls_key)

    backing_by_class: dict[str, dict[str, list[str]]] = {}
    for name, info in census.items():
        for cls_key, attr in info["backings"]:
            backing_by_class.setdefault(cls_key, {}).setdefault(
                attr, []).append(name)

    attr_reads: set[str] = set()
    for modname, ctx in index.modules.items():
        p = ctx.path.as_posix() if hasattr(ctx.path, "as_posix") \
            else str(ctx.path)
        if not _producer_scope(p):
            continue
        for n in ast.walk(ctx.tree):
            if isinstance(n, ast.Attribute) and isinstance(n.ctx, ast.Load):
                attr_reads.add(n.attr)

    for cls_key in sorted(producer_classes):
        entry = classmap.get(cls_key)
        if entry is None:
            continue
        modpath, node = entry
        dec, assigned = _mutation_profile(node)
        short = cls_key.split(".")[-1]
        for attr, names in sorted(backing_by_class.get(cls_key, {}).items()):
            for name in sorted(set(names)):
                if census[name]["type"] != "counter":
                    continue
                if attr in dec:
                    findings.append(TraceFinding(
                        name, "MT004", "decremented-counter",
                        f"backed by {short}.{attr} which is decremented — "
                        "counters must be monotone (use a gauge)"))
                if attr in assigned:
                    findings.append(TraceFinding(
                        name, "MT004", "assigned-counter",
                        f"backed by {short}.{attr} which is plainly "
                        "re-assigned outside reset/__init__ — counters "
                        "must be monotone (use a gauge)"))
        # MT001 attr level: reset()-declared state nothing reads
        for attr, lineno in sorted(_reset_attrs(node).items()):
            if attr not in attr_reads:
                findings.append(TraceFinding(
                    short, "MT001", attr,
                    f"recorded at {modpath}:{lineno} but never read by "
                    "any renderer or in-tree consumer"))

    # ---- MT001 dict-surface level -------------------------------------
    for cls_key, method in sorted(sink.dict_surfaces):
        short = cls_key.split(".")[-1]
        for key, site in sorted(
                _surface_keys(classmap, cls_key, method).items()):
            if key not in sink.consumed_keys:
                findings.append(TraceFinding(
                    f"{short}.{method}", "MT001", key,
                    f"surfaced at {site} but no constant-key read "
                    "consumes it"))

    # ---- MT003: per-request identity in label values ------------------
    seen_mt003: set[tuple[str, str]] = set()
    for s in sink.samples:
        base = s["name"]
        if base not in census:
            for suf in _HIST_SUFFIXES:
                if base.endswith(suf) and base[:-len(suf)] in census:
                    base = base[:-len(suf)]
                    break
        for ln, src in s["labels"]:
            if src is None:
                continue
            idents = set(_NAME_RUN_RE.findall(src))
            bad = [t for t in _CARDINALITY_TOKENS
                   if any(t in i for i in idents)]
            if bad and (base, ln) not in seen_mt003:
                seen_mt003.add((base, ln))
                findings.append(TraceFinding(
                    base, "MT003", ln,
                    f"label value `{src}` at {s['site']} flows from "
                    f"per-request identity ({', '.join(bad)}) — "
                    "unbounded cardinality"))
    return sorted(findings)


def census_snapshot(facts: dict) -> dict:
    """The committed shape: name -> {type, labels} (no line numbers, so
    the manifest doesn't churn on unrelated edits)."""
    return {
        name: {"type": info["type"], "labels": list(info["labels"])}
        for name, info in facts["metrics"].items()
    }


# ------------------------------------------------------------------ check ----


def check_metric_facts(facts: dict, manifest: Manifest, intrinsic: list, *,
                       registry: Optional[dict] = None,
                       docs_text: Optional[str] = None,
                       drift: bool = True) -> list:
    """Combine intrinsic findings with the cross-checks that need the
    committed manifest: MT002 (consumer vs census) and MT005 (census vs
    manifest / registry SCHEMA / generated docs table)."""
    findings = list(intrinsic)
    metrics = facts["metrics"]

    for name, sites in facts["consumers"].items():
        if name in metrics:
            continue
        for site in sites:
            findings.append(TraceFinding(
                name, "MT002", site,
                f"scraped at {site} but no renderer emits this metric — "
                "a renamed or dropped series silently zeroes this "
                "consumer"))
    for prefix, sites in facts["consumers_prefix"].items():
        if any(m.startswith(prefix) for m in metrics):
            continue
        for site in sites:
            findings.append(TraceFinding(
                prefix + "*", "MT002", site,
                f"prefix-scraped at {site} but no rendered metric "
                "starts with this prefix"))
    engine = facts.get("engine") or {}
    ekeys = set(engine.get("keys") or [])
    if ekeys:
        for key, sites in (engine.get("consumers") or {}).items():
            if key in ekeys:
                continue
            for site in sites:
                findings.append(TraceFinding(
                    f"EngineCore.metrics:{key}", "MT002", site,
                    f"read at {site} but EngineCore.metrics() never "
                    "sets this key"))

    if drift:
        committed = manifest.entrypoints or {}
        if committed:
            for name in sorted(set(metrics) - set(committed)):
                findings.append(TraceFinding(
                    name, "MT005", "added",
                    "rendered but absent from the committed census — "
                    "run --metrics --update-baseline"))
            for name in sorted(set(committed) - set(metrics)):
                findings.append(TraceFinding(
                    name, "MT005", "removed",
                    "in the committed census but no longer rendered — "
                    "run --metrics --update-baseline"))
            for name in sorted(set(metrics) & set(committed)):
                cur, old = metrics[name], committed[name]
                if cur["type"] != old.get("type"):
                    findings.append(TraceFinding(
                        name, "MT005", "type",
                        f"TYPE drifted: {old.get('type')} -> "
                        f"{cur['type']}"))
                if sorted(cur["labels"]) != sorted(old.get("labels") or []):
                    findings.append(TraceFinding(
                        name, "MT005", "labels",
                        f"label set drifted: {sorted(old.get('labels') or [])}"
                        f" -> {sorted(cur['labels'])}"))

    if registry is not None:
        for name in sorted(set(metrics) - set(registry)):
            findings.append(TraceFinding(
                name, "MT005", "registry-missing",
                "rendered but absent from obs/metric_names.SCHEMA"))
        for name in sorted(set(registry) - set(metrics)):
            findings.append(TraceFinding(
                name, "MT005", "registry-unrendered",
                "declared in obs/metric_names.SCHEMA but never rendered"))
        for name in sorted(set(metrics) & set(registry)):
            rtyp, rlabels = registry[name]
            if metrics[name]["type"] != rtyp:
                findings.append(TraceFinding(
                    name, "MT005", "registry-type",
                    f"SCHEMA says {rtyp}, renderer declares "
                    f"{metrics[name]['type']}"))
            if sorted(metrics[name]["labels"]) != sorted(rlabels):
                findings.append(TraceFinding(
                    name, "MT005", "registry-labels",
                    f"SCHEMA labels {sorted(rlabels)} != rendered "
                    f"{sorted(metrics[name]['labels'])}"))

    if docs_text is not None:
        expected = render_docs_table(metrics)
        actual = _docs_table_section(docs_text)
        if actual is None:
            findings.append(TraceFinding(
                "docs/observability.md", "MT005", "docs-markers",
                f"missing {DOCS_BEGIN} / {DOCS_END} markers around the "
                "metric reference table"))
        elif actual.strip() != expected.strip():
            findings.append(TraceFinding(
                "docs/observability.md", "MT005", "docs-table",
                "metric reference table drifted from the census — "
                "regenerate with "
                "`dynamo-tpu lint --metrics --update-baseline`"))
    return sorted(findings)


# ------------------------------------------------------------------- docs ----

DOCS_BEGIN = "<!-- metcheck:begin -->"
DOCS_END = "<!-- metcheck:end -->"


def render_docs_table(metrics: dict) -> str:
    """The generated metric reference table (between the metcheck
    markers in docs/observability.md)."""
    lines = ["| metric | type | labels |", "| --- | --- | --- |"]
    for name in sorted(metrics):
        info = metrics[name]
        labels = ", ".join(info["labels"]) if info["labels"] else "-"
        lines.append(f"| `{name}` | {info['type']} | {labels} |")
    return "\n".join(lines) + "\n"


def _docs_table_section(text: str) -> Optional[str]:
    if DOCS_BEGIN not in text or DOCS_END not in text:
        return None
    return text.split(DOCS_BEGIN, 1)[1].split(DOCS_END, 1)[0]


def _write_docs_table(root: Path, metrics: dict) -> bool:
    path = root / "docs" / "observability.md"
    if not path.is_file():
        return False
    text = path.read_text()
    if DOCS_BEGIN not in text or DOCS_END not in text:
        return False
    head, rest = text.split(DOCS_BEGIN, 1)
    _old, tail = rest.split(DOCS_END, 1)
    path.write_text(
        head + DOCS_BEGIN + "\n" + render_docs_table(metrics)
        + DOCS_END + tail)
    return True


# -------------------------------------------------------------------- CLI ----

# paths whose changes can affect metrics-plane facts (for `--changed`)
_TOUCHES = (
    "dynamo_tpu/obs/",
    "dynamo_tpu/engine/counters.py",
    "dynamo_tpu/engine/core.py",
    "dynamo_tpu/fault/counters.py",
    "dynamo_tpu/llm/http/metrics.py",
    "dynamo_tpu/components/metrics.py",
    "benchmarks/",
    "bench.py",
    "dynamo_tpu/analysis/metcheck.py",
    "dynamo_tpu/analysis/metrics_manifest.json",
    "docs/observability.md",
    "tests/",
)


def _metrics_affected(root: Path) -> bool:
    from dynamo_tpu.analysis.cli import _git_changed_paths

    dirty = [str(p) for p in _git_changed_paths(root)]
    return any(frag in d for d in dirty for frag in _TOUCHES)


def _met_header() -> dict:
    return {
        "note": (
            "Static producer->renderer->scraper census of the /metrics "
            "surface (dtmet plane). Entrypoints are metric names with "
            "their declared TYPE and label schema; accepted entries are "
            "justified deviations from the MT conventions."
        ),
    }


def run_metrics(args, out) -> int:
    """``dynamo-tpu lint --metrics``: extract the metrics census, diff
    against the committed metrics manifest / registry SCHEMA / docs
    table, exit 1 on any non-accepted finding.  ``--update-baseline``
    re-snapshots the census (and regenerates the docs table)."""
    manifest_path = Path(
        getattr(args, "manifest", None) or DEFAULT_METRICS_MANIFEST_PATH)
    manifest = Manifest.load(manifest_path)
    root = Path(getattr(args, "root", None)
                or Path(__file__).resolve().parents[2])
    if getattr(args, "changed", False) and not _metrics_affected(root):
        print("metrics plane unaffected by changed files", file=out)
        return 0

    facts, intrinsic = collect_metric_facts(root=root)
    from dynamo_tpu.obs.metric_names import SCHEMA
    registry = {name: (typ, list(labels))
                for name, (typ, labels) in SCHEMA.items()}
    docs_path = root / "docs" / "observability.md"
    docs_text = docs_path.read_text() if docs_path.is_file() else None

    if getattr(args, "update_baseline", False):
        _write_docs_table(root, facts["metrics"])
        docs_text = docs_path.read_text() if docs_path.is_file() else None
        findings = check_metric_facts(
            facts, manifest, intrinsic, registry=registry,
            docs_text=docs_text, drift=False)
        accepted = [f for f in findings if f.rule != "MT005"]
        m = Manifest.from_facts(census_snapshot(facts), accepted, manifest)
        m.header = manifest.header or _met_header()
        m.save(manifest_path)
        print(
            f"metrics manifest updated: {len(facts['metrics'])} metrics, "
            f"{len(accepted)} accepted finding"
            f"{'' if len(accepted) == 1 else 's'} -> {manifest_path}",
            file=out,
        )
        return 0

    findings = check_metric_facts(
        facts, manifest, intrinsic, registry=registry,
        docs_text=docs_text, drift=True)
    fresh = manifest.filter(findings)
    n_accepted = len(findings) - len(fresh)
    if getattr(args, "fmt", "text") == "json":
        doc = {
            "findings": [f.to_json() for f in fresh],
            "accepted": n_accepted,
            "total": len(findings),
            "metrics": len(facts["metrics"]),
            "consumers": sum(
                len(s) for s in facts["consumers"].values()),
        }
        print(json.dumps(doc, indent=2, sort_keys=True), file=out)
    else:
        for f in fresh:
            print(f.render(), file=out)
        print(
            f"{len(fresh)} metrics finding"
            f"{'s' if len(fresh) != 1 else ''} ({n_accepted} accepted) "
            f"over {len(facts['metrics'])} metrics",
            file=out,
        )
    return 1 if fresh else 0
