"""JAX/TPU rules (DT101–DT105) for the engine hot path.

These encode the discipline engine/core.py's step functions follow: jit
once at init, donate the cache and never touch the stale buffer, pull
results host-side in ONE batched device_get per step, never leak
tracers onto ``self`` from inside a jitted function, and route Pallas
kernel geometry through the kernel registry so the kernel-plane audit
(``dynamo-tpu lint --kern``) prices the shapes that actually ship.
"""

from __future__ import annotations

import ast
from typing import Iterable

from dynamo_tpu.analysis.core import (
    PARTIAL_NAMES,
    Finding,
    ModuleContext,
    Rule,
    dotted_name,
    is_jit_call,
    register,
)


def _assigned_names(stmt: ast.AST) -> set[str]:
    """Dotted names a statement (re)binds."""
    names: set[str] = set()
    targets: list[ast.AST] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        targets = [stmt.target]
    for t in targets:
        for el in ast.walk(t):
            name = dotted_name(el)
            if name:
                names.add(name)
    return names


@register
class JitPerCall(Rule):
    """DT101 — ``jax.jit`` constructed per call.  An immediately-invoked
    ``jax.jit(f)(x)`` (or a jit built inside a loop / rebuilt in a plain
    local each call) makes a fresh jitted callable every time: its
    Python-scalar arguments re-trigger tracing, and on TPU that's a
    recompilation storm — seconds of XLA compile on the per-token path.
    Build the jit once at init scope and declare per-call Python scalars
    in ``static_argnums`` (or pass them as arrays)."""

    code = "DT101"
    name = "jit-per-call"
    summary = (
        "jax.jit constructed per call (recompilation storm); hoist it "
        "and use static_argnums for varying Python scalars"
    )
    interests = (ast.Call,)

    def visit(self, node: ast.Call, ctx: ModuleContext) -> Iterable[Finding]:
        if not is_jit_call(node, ctx):
            return
        parent = getattr(node, "_dt_parent", None)
        immediately_invoked = (
            isinstance(parent, ast.Call) and parent.func is node
        )
        if immediately_invoked:
            yield ctx.finding(
                self, node,
                "jax.jit(...) immediately invoked: a fresh jitted "
                "callable (and a fresh trace) per call — hoist the jit "
                "to init scope and mark varying Python scalars "
                "static_argnums",
            )
            return
        if ctx.loop_depth > 0:
            yield ctx.finding(
                self, node,
                "jax.jit(...) constructed inside a loop: re-jits every "
                "iteration — hoist it out of the loop",
            )
            return
        func = ctx.current_func
        if func is None or func.name == "__init__":
            return  # module/class/init scope: built once, fine
        # inside a regular function: fine only if cached somewhere that
        # outlives the call (an attribute target, e.g. ``self._fn = ...``
        # or ``fn = self._fn = jax.jit(...)``)
        stmt = parent
        while stmt is not None and not isinstance(stmt, ast.stmt):
            stmt = getattr(stmt, "_dt_parent", None)
        if isinstance(stmt, ast.Assign) and any(
            "." in n for n in _assigned_names(stmt)
        ):
            return
        if (
            node.args
            and isinstance(node.args[0], ast.Call)
            and ctx.canonical(dotted_name(node.args[0].func))
            in PARTIAL_NAMES
        ):
            # the partial-inside-method shape: even if the wrapped fn is
            # stable, each call builds a DISTINCT partial object, so the
            # jit cache keys never hit — the compile-plane census
            # (`dynamo-tpu lint --trace`, TR003 unstable-trace-key) sees
            # the same defect as an unstable signature
            yield ctx.finding(
                self, node,
                "jax.jit(functools.partial(...)) built per call: every "
                "call makes a fresh partial (and a fresh jitted "
                "callable), so the trace cache never hits — one compile "
                "PER STEP.  The compile-plane census flags this as "
                "TR003 unstable-trace-key (`dynamo-tpu lint --trace`); "
                "hoist the jit+partial to __init__/module scope or bind "
                "the varying value via static_argnums",
            )
            return
        yield ctx.finding(
            self, node,
            "jax.jit(...) built inside a function without caching the "
            "result on an attribute: re-jits on every call — hoist to "
            "__init__/module scope or cache it",
        )


# Debug/callback APIs that smuggle a host round trip into compiled
# code: each firing stalls the dispatch queue exactly like an explicit
# device_get, but survives jit so it ships to production silently.
_HOST_SYNC_FNS = frozenset({
    "jax.debug.print",
    "jax.debug.callback",
    "jax.experimental.io_callback",
    "jax.pure_callback",
})
_HOST_CALLBACK_PREFIX = "jax.experimental.host_callback."


@register
class DeviceGetInLoop(Rule):
    """DT102 — host round trips on the hot path.  Two shapes:

    ``jax.device_get``/``block_until_ready`` inside a Python loop —
    each call is a device→host round trip that serialises the
    pipelined dispatch queue; on a remote-attached TPU the per-call
    latency dominates.  Batch the pulls: stack outputs device-side and
    issue ONE device_get per step, the way engine/core.py's decode path
    does (its blessed batched-pull sites are loop-free).

    ``jax.debug.print`` / ``jax.debug.callback`` / ``io_callback`` /
    ``pure_callback`` / ``host_callback.*`` inside a loop OR inside a
    jit-compiled function — these survive compilation, so a debug print
    left in a jitted step fn costs a host callback on EVERY step in
    production.  Gate them behind a config flag at trace time (so the
    compiled program omits them) or delete before committing."""

    code = "DT102"
    name = "device-get-in-loop"
    summary = (
        "per-iteration device_get/block_until_ready (or a debug/host "
        "callback reachable from compiled code): serialise into one "
        "batched pull per step"
    )
    interests = (ast.Call,)

    def visit(self, node: ast.Call, ctx: ModuleContext) -> Iterable[Finding]:
        fn = ctx.call_name(node)
        if fn in _HOST_SYNC_FNS or fn.startswith(_HOST_CALLBACK_PREFIX):
            func = ctx.current_func
            in_jitted = (
                func is not None
                and getattr(func, "name", None) in ctx.jit.jitted_fns
            )
            if ctx.loop_depth > 0 or in_jitted:
                where = (
                    "inside a jit-compiled function"
                    if in_jitted else "inside a loop"
                )
                yield ctx.finding(
                    self, node,
                    f"{fn.rsplit('.', 1)[-1]} {where}: the callback "
                    "survives compilation and fires a host round trip "
                    "every execution — gate it behind a debug flag at "
                    "trace time or remove it",
                )
            return
        if ctx.loop_depth <= 0:
            return
        is_pull = fn in ("jax.device_get", "jax.block_until_ready") or (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "block_until_ready"
        )
        if not is_pull:
            return
        yield ctx.finding(
            self, node,
            "device_get/block_until_ready inside a loop: one "
            "device->host sync per iteration — batch outputs and pull "
            "once per step (engine/core.py pattern)",
        )


@register
class UseAfterDonate(Rule):
    """DT103 — reading a donated buffer after the jitted call.  With
    ``donate_argnums`` XLA reuses the input's HBM for the output; the
    Python reference now points at freed/aliased memory and JAX raises
    (or worse, silently reads garbage under some transfer paths).  The
    engine's convention: the donated cache is rebound by the same
    statement (``out, self.cache = self._step_fn(self.params,
    self.cache, ...)``)."""

    code = "DT103"
    name = "use-after-donate"
    summary = "donated buffer read after the jitted call"
    interests = (ast.Call,)

    def visit(self, node: ast.Call, ctx: ModuleContext) -> Iterable[Finding]:
        callee = ctx.canonical(dotted_name(node.func))
        # donated registry keys are un-canonicalised dotted names
        # ("self._step_fn", "_scatter_donated")
        raw = dotted_name(node.func)
        positions = ctx.jit.donated.get(raw) or ctx.jit.donated.get(callee)
        if not positions:
            return
        func = ctx.current_func
        if func is None:
            return
        stmt = getattr(node, "_dt_parent", None)
        while stmt is not None and not isinstance(stmt, ast.stmt):
            stmt = getattr(stmt, "_dt_parent", None)
        if stmt is None:
            return
        rebound = _assigned_names(stmt)
        call_line = stmt.lineno
        for pos in positions:
            if pos >= len(node.args):
                continue
            donated = dotted_name(node.args[pos])
            if not donated or donated in rebound:
                continue  # dynamic arg, or rebound by the same statement
            # collect later stores (kills) and loads of the donated name
            kills: list[int] = []
            uses: list[tuple[int, ast.AST]] = []
            for sub in ast.walk(func):
                name = dotted_name(sub)
                if name != donated:
                    continue
                lineno = getattr(sub, "lineno", 0)
                if lineno <= call_line:
                    continue
                ctx_attr = getattr(sub, "ctx", None)
                if isinstance(ctx_attr, ast.Store):
                    kills.append(lineno)
                elif isinstance(ctx_attr, ast.Load):
                    uses.append((lineno, sub))
            for lineno, use in sorted(uses):
                if any(k <= lineno for k in kills):
                    break  # rebound before (or at) this use
                yield ctx.finding(
                    self, use,
                    f"'{donated}' was donated to {raw or callee}() at "
                    f"line {call_line} (donate_argnums) and read "
                    "afterwards: the buffer is freed/aliased — rebind it "
                    "from the call's outputs",
                )
                break  # one finding per donated arg is enough


@register
class TracerOnSelf(Rule):
    """DT104 — storing values on ``self`` from inside a jitted function.
    Under trace the value is a Tracer; stashing it on the instance leaks
    it past the trace, and the next (non-traced or re-traced) read
    raises ``UnexpectedTracerError`` — or silently freezes a stale
    constant into the compiled graph.  Return the value instead and let
    the non-jitted caller store it."""

    code = "DT104"
    name = "tracer-on-self"
    summary = "attribute store on self inside a jitted function"
    interests = (ast.Assign, ast.AugAssign, ast.AnnAssign)

    def visit(self, node: ast.AST, ctx: ModuleContext) -> Iterable[Finding]:
        func = ctx.current_func
        if func is None or func.name not in ctx.jit.jitted_fns:
            return
        targets = (
            list(node.targets)
            if isinstance(node, ast.Assign)
            else [node.target]
        )
        for t in targets:
            for el in ast.walk(t):
                if (
                    isinstance(el, ast.Attribute)
                    and isinstance(el.value, ast.Name)
                    and el.value.id in ("self", "cls")
                ):
                    yield ctx.finding(
                        self, node,
                        f"store to {el.value.id}.{el.attr} inside jitted "
                        f"function {func.name}(): leaks a tracer out of "
                        "the trace — return the value and store it in "
                        "the caller",
                    )
                    return


_PALLAS_CALL = "jax.experimental.pallas.pallas_call"
_BLOCKSPEC = "jax.experimental.pallas.BlockSpec"


@register
class PallasCallHygiene(Rule):
    """DT105 — Pallas call sites bypassing the kernel registry.  The
    kernel-plane audit (``dynamo-tpu lint --kern``, analysis/kerncheck)
    prices every registered kernel's VMEM residency, index maps and
    padding behaviour from ``ops/pallas/registry.py``'s tile table; a
    call site that hardcodes its geometry (or pins ``interpret=True``)
    drifts out from under that audit silently.  Three shapes, in any
    module that calls ``pl.pallas_call``:

    * ``interpret=True`` as a literal kwarg — interpret mode is a
      debugging/audit device; a hardcoded literal ships the ~1000x
      slower emulation path to serving.  Thread a parameter instead.
    * integer literals > 1 in ``grid=`` or a ``BlockSpec`` block shape —
      tile geometry must come from registry constants (or values derived
      from them) so the kerncheck VMEM/index-map proofs cover the shapes
      that actually run.  0 and 1 are structural (singleton/blocked-out
      axes), not tile sizes, and stay allowed.
    * an integer-literal default on a ``*_per_*`` parameter
      (``blocks_per_chunk=4``) — same drift through the back door: the
      default IS the served geometry, so it must be a registry name.
    """

    code = "DT105"
    name = "pallas-geometry-bypass"
    summary = (
        "pallas_call geometry hardcoded at the call site (literal "
        "interpret=True, literal grid/BlockSpec tile sizes, or int "
        "defaults on *_per_* params) — route it through "
        "ops/pallas/registry.py so the kernel-plane audit covers it"
    )
    interests = (ast.Module,)

    def visit(self, node: ast.Module, ctx: ModuleContext) -> Iterable[Finding]:
        calls = [n for n in ast.walk(node) if isinstance(n, ast.Call)]
        psites = [c for c in calls if ctx.call_name(c) == _PALLAS_CALL]
        if not psites:
            return  # module doesn't build kernels — nothing to audit
        for call in psites:
            for kw in call.keywords:
                if (
                    kw.arg == "interpret"
                    and isinstance(kw.value, ast.Constant)
                    and kw.value.value is True
                ):
                    yield ctx.finding(
                        self, kw.value,
                        "pallas_call(interpret=True) hardcoded: the "
                        "interpret emulator is audit-only and ~1000x "
                        "slower — thread an `interpret: bool = False` "
                        "parameter so serving code takes the compiled "
                        "path",
                    )
                if kw.arg == "grid":
                    yield from self._literal_dims(kw.value, "grid=", ctx)
        for call in calls:
            if ctx.call_name(call) == _BLOCKSPEC and call.args:
                yield from self._literal_dims(
                    call.args[0], "BlockSpec block shape", ctx
                )
        for fn in ast.walk(node):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._per_defaults(fn, ctx)

    def _literal_dims(
        self, value: ast.AST, where: str, ctx: ModuleContext
    ) -> Iterable[Finding]:
        dims = (
            list(value.elts) if isinstance(value, ast.Tuple) else [value]
        )
        for d in dims:
            if (
                isinstance(d, ast.Constant)
                and isinstance(d.value, int)
                and not isinstance(d.value, bool)
                and d.value > 1
            ):
                yield ctx.finding(
                    self, d,
                    f"integer literal {d.value} in {where}: tile "
                    "geometry hardcoded at the call site escapes the "
                    "kernel-plane audit — derive it from a registry "
                    "constant (ops/pallas/registry.py)",
                )

    def _per_defaults(
        self, fn: ast.AST, ctx: ModuleContext
    ) -> Iterable[Finding]:
        args = list(fn.args.posonlyargs) + list(fn.args.args)
        defaults = list(fn.args.defaults)
        paired = list(zip(args[len(args) - len(defaults):], defaults))
        paired += [
            (a, d)
            for a, d in zip(fn.args.kwonlyargs, fn.args.kw_defaults)
            if d is not None
        ]
        for arg, default in paired:
            if "_per_" not in arg.arg:
                continue
            if (
                isinstance(default, ast.Constant)
                and isinstance(default.value, int)
                and not isinstance(default.value, bool)
                and default.value > 1
            ):
                yield ctx.finding(
                    self, default,
                    f"{fn.name}({arg.arg}={default.value}): the default "
                    "IS the served tile geometry — bind it to a "
                    "registry constant so kerncheck's VMEM/index-map "
                    "proofs cover what actually runs",
                )
