"""Kernel-plane static analysis (dtkern): the Pallas audit.

Eight planes audit source, traces, wire contracts, priced jaxprs,
placement, protocol state machines and the scaled control plane — and
none of them sees the kernels.  `dynamo_tpu/ops/pallas/` (flash decode,
flash prefill + ragged variant, dequant-in-kernel int8 matmul) is where
ROADMAP item 2's unified-kernel rewrite will land, and until this plane
existed it was audited by nothing: VMEM footprints were a docstring
claim, index maps were reviewed by eye, padded-lane masking was
spot-tested at two geometries, and dtperf priced the ops via their XLA
fallback jaxprs with a written caveat.

The plane audits every `pallas_call` site registered in
``ops/pallas/registry.py`` across that registry's geometry matrix
(decode bf16/int8, multi-query decode, prefill, ragged prefill
bf16/int8 with adversarial rows — empty, 1-token with non-block-aligned
starts, non-block-divisible lengths, max-block — int8 matmul, plus
serving-scale spec-only shapes), entirely on CPU.  A `pallas_call` spy
captures grid, BlockSpecs, scratch and operand avals at call time; the
small geometries then execute in interpret mode against the pure-XLA
oracles, the serving-scale ones are shape-traced only
(``jax.eval_shape``).  Four audit families:

- **VMEM budget (KN001)**: per-grid-step resident bytes = blocked
  operand/output block shapes x dtypes x the pipeline double-buffering
  multiplier + VMEM scratch, against the per-core v5e budget
  (``registry.VMEM_BUDGET_BYTES``).  Snapshotted per (kernel,
  geometry), so "128 rows/chunk fits VMEM at S=2048" is a checked fact,
  not a comment.
- **index-map audit (KN002/KN003)**: every BlockSpec index map is
  evaluated concretely over the full grid.  A block index outside the
  operand's block range is KN002 (out-of-bounds touch).  Two grid steps
  mapping to the same OUTPUT block are only sound when the revisits are
  consecutive in sequential grid order (the TPU revisit-accumulate
  pattern, e.g. the matmul K axis); non-consecutive revisits are a
  write race under arbitrary grid order — KN003.
- **padding oracles (KN004)**: interpret-mode differential runs on the
  adversarial geometries vs the pure-XLA oracle, with NaN-poisoned
  padding lanes and NaN-poisoned out-of-``seq_len`` cache blocks (f32
  scale lanes for the int8 cache — int8 data can't hold a NaN).  A
  canary reaching a live output lane, or a live-lane mismatch beyond
  the case tolerance, is a padding leak.  This is the correctness
  harness the item-2 unified kernel will be built against.
- **kernel pricing (KN005)**: the registry's analytic cost model
  (HBM-DMA bytes, FLOPs, transcendentals, arithmetic intensity) per
  (kernel, geometry), exported to dtperf — perfcheck attaches these to
  the entrypoints that dispatch Pallas kernels on TPU, replacing the
  XLA-fallback pricing caveat for those ops.  Drift vs the committed
  manifest (pricing, VMEM, grid) is KN005.

Cross-plane tripwires (KN006): the registry's kernel census records
that decode and ragged-prefill attention are SEPARATE kernels while the
unified kernel (ROADMAP item 2, *Ragged Paged Attention*, arxiv
2604.15464) is a placeholder — a permanent finding whose accepted
manifest entry cites item 2, so landing the unified kernel re-trips
this gate and forces the acceptance (and the shard plane's fallback
entries) to be retired deliberately.  The same census pins the shard
manifest's accepted SH002 fallback-gather counts and requires every
registered kernel to carry a bench probe.

Facts commit to ``analysis/kern_manifest.json`` under the shared
justification / ``--update-baseline`` contract (tracecheck's
``Manifest``).  A nightly ``kern-fuzz`` mode
(``DTKERN_BUDGET``/``DTKERN_SEED_BASE``) sweeps seeded random ragged
geometries through the KN004 oracle; failures print ``dtk1.`` replay
tokens that re-run one geometry exactly.

Interpret-mode caveats (recorded in the manifest header): interpret
mode checks semantics, not Mosaic lowering — a kernel can pass here and
still fail to compile on hardware (probe_kernels.py owns that half);
the manual DMA double-buffering runs serially in interpret mode, so
overlap bugs (wait-before-start) surface as wrong values, not hangs.
"""

from __future__ import annotations

import base64
import itertools
import json
import math
import os
import zlib
from pathlib import Path

from dynamo_tpu.analysis.tracecheck import Manifest, TraceFinding

__all__ = [
    "DEFAULT_MANIFEST_PATH",
    "KERN_RULES",
    "check_kern_facts",
    "collect_kern_facts",
    "decode_token",
    "encode_token",
    "run_kern",
]

DEFAULT_MANIFEST_PATH = Path(__file__).parent / "kern_manifest.json"

_TOKEN_PREFIX = "dtk1."

KERN_RULES = {
    "KN001": ("vmem-over-budget",
              "per-grid-step resident bytes (blocked operands x "
              "double-buffering + VMEM scratch) exceed the per-core "
              "VMEM budget"),
    "KN002": ("index-map-out-of-bounds",
              "a BlockSpec index map touches a block outside the "
              "operand's block range at some grid step"),
    "KN003": ("output-aliasing-race",
              "two non-consecutive grid steps map to the same output "
              "block — a write race under arbitrary grid order"),
    "KN004": ("padding-leak",
              "a NaN canary planted in padding lanes / dead cache "
              "slots reached a live output lane, or live lanes diverge "
              "from the pure-XLA oracle beyond tolerance"),
    "KN005": ("kernel-drift",
              "kernel pricing / VMEM / grid facts drifted vs the "
              "committed kern manifest (re-snapshot deliberately with "
              "--update-baseline)"),
    "KN006": ("census-drift",
              "kernel census out of sync: the two-kernel decode/ragged "
              "split (ROADMAP item 2 tripwire), the shard-plane "
              "fallback acceptances, or a registered kernel without a "
              "bench probe"),
}

_MANIFEST_NOTE = (
    "CPU-derived Pallas kernel facts over the registry geometry matrix "
    "(ops/pallas/registry.py).  VMEM/index-map/pricing facts come from "
    "a pallas_call capture (spec math, no execution); KN004 canaries "
    "execute the small geometries in INTERPRET mode against the "
    "pure-XLA oracles with NaN-poisoned padding, so they check "
    "semantics, not Mosaic lowering (probe_kernels.py owns on-TPU "
    "compilation).  Serving-scale geometries are shape-traced only.  "
    "The accepted two-kernel-split entry pins ROADMAP item 2: landing "
    "the unified ragged kernel (arxiv 2604.15464) re-trips KN006 and "
    "forces this acceptance and the shard-plane fallback entries to be "
    "retired together."
)

# KN005 pricing drift tolerance: the model is deterministic integer
# math, so any change is a real change — exact match required.


def _kern_header() -> dict:
    from dynamo_tpu.ops.pallas.registry import (
        V5E_VMEM_BYTES,
        VMEM_BUDGET_BYTES,
    )

    return {
        "note": _MANIFEST_NOTE,
        "vmem_budget": {
            "chip": "v5e",
            "vmem_bytes": int(V5E_VMEM_BYTES),
            "budget_bytes": int(VMEM_BUDGET_BYTES),
        },
    }


# ------------------------------------------------------------ replay token


def encode_token(payload: dict) -> str:
    raw = json.dumps(payload, sort_keys=True,
                     separators=(",", ":")).encode()
    return _TOKEN_PREFIX + base64.urlsafe_b64encode(
        zlib.compress(raw, 9)).decode().rstrip("=")


def decode_token(token: str) -> dict:
    if not token.startswith(_TOKEN_PREFIX):
        raise ValueError(f"not a dtkern replay token: {token[:16]!r}")
    body = token[len(_TOKEN_PREFIX):]
    body += "=" * (-len(body) % 4)
    return json.loads(zlib.decompress(base64.urlsafe_b64decode(body)))


def _budget_env() -> tuple[int, int, bool]:
    """(budget, seed_base, pinned).  The pinned default run (budget 1,
    seed base 0) audits exactly the committed geometry matrix; the
    nightly fuzz job raises DTKERN_BUDGET and derives DTKERN_SEED_BASE
    from the date, adding seeded random ragged geometries that are
    canary-checked but never enter the manifest."""
    budget = max(1, int(os.environ.get("DTKERN_BUDGET", "1") or 1))
    seed_base = int(os.environ.get("DTKERN_SEED_BASE", "0") or 0)
    return budget, seed_base, budget == 1 and seed_base == 0


# ----------------------------------------------------------- VMEM facts ----


def _space_name(spec_or_ref) -> str:
    ms = getattr(spec_or_ref, "memory_space", None)
    return str(getattr(ms, "name", ms) or "").lower()


def _itemsize(dtype) -> int:
    import jax.numpy as jnp

    return jnp.dtype(dtype).itemsize


def _blocked_entries(rec: dict) -> list[dict]:
    """One entry per pallas operand/output: label, backing array shape,
    block shape (None for un-blocked ANY-space residents) and the
    per-step VMEM block bytes."""
    nsp = rec["num_scalar_prefetch"]
    entries = []
    pairs = (
        [(f"in{i}", spec, aval) for i, (spec, aval) in
         enumerate(zip(rec["in_specs"], rec["operands"][nsp:]))]
        + [(f"out{i}", spec, aval) for i, (spec, aval) in
           enumerate(zip(rec["out_specs"], rec["out_shapes"]))]
    )
    for label, spec, (shape, dtype) in pairs:
        block = getattr(spec, "block_shape", None)
        space = _space_name(spec)
        if block is None or "any" in space:
            entries.append({
                "operand": label, "shape": list(shape), "dtype": dtype,
                "block": None, "block_bytes": 0, "space": space or "any",
                "index_map": None,
            })
            continue
        block = [int(x) for x in block]
        nbytes = _itemsize(dtype)
        for x in block:
            nbytes *= x
        entries.append({
            "operand": label, "shape": list(shape), "dtype": dtype,
            "block": block, "block_bytes": int(nbytes),
            "space": space or "vmem",
            "index_map": getattr(spec, "index_map", None),
        })
    return entries


def _scratch_bytes(rec: dict) -> int:
    total = 0
    for ref in rec["scratch"]:
        if "sem" in _space_name(ref):
            continue  # semaphores don't occupy VMEM data space
        nbytes = _itemsize(ref.dtype)
        for x in ref.shape:
            nbytes *= int(x)
        total += nbytes
    return total


def _vmem_facts(rec: dict) -> dict:
    from dynamo_tpu.ops.pallas.registry import (
        DOUBLE_BUFFER,
        VMEM_BUDGET_BYTES,
    )

    entries = _blocked_entries(rec)
    blocked = sum(e["block_bytes"] for e in entries)
    scratch = _scratch_bytes(rec)
    return {
        "blocked_bytes": int(blocked),
        "scratch_bytes": int(scratch),
        "resident_bytes": int(blocked * DOUBLE_BUFFER + scratch),
        "budget_bytes": int(VMEM_BUDGET_BYTES),
        "blocks": [
            {k: e[k] for k in
             ("operand", "shape", "dtype", "block", "block_bytes",
              "space")}
            for e in entries
        ],
    }


# ------------------------------------------------------ index-map facts ----

_MAX_OOB_PER_OPERAND = 4  # cap the recorded offenders per operand


def _index_map_facts(rec: dict) -> dict:
    """Evaluate every blocked index map over the full grid.  Grid steps
    enumerate in sequential TPU order (row-major, last axis fastest) —
    the order the race check's "consecutive revisits" notion refers
    to."""
    grid = rec["grid"]
    steps = list(itertools.product(*[range(int(n)) for n in grid]))
    oob: list[dict] = []
    races: list[dict] = []
    max_revisit = 1
    for e in _blocked_entries(rec):
        im, block = e["index_map"], e["block"]
        if im is None or block is None:
            continue
        nblocks = [
            max(1, -(-int(dim) // int(bd)))
            for dim, bd in zip(e["shape"], block)
        ]
        seen: dict[tuple, list[int]] = {}
        n_oob = 0
        for pos, step in enumerate(steps):
            idx = tuple(int(x) for x in im(*step))
            if len(idx) != len(nblocks) or any(
                    not 0 <= i < n for i, n in zip(idx, nblocks)):
                if n_oob < _MAX_OOB_PER_OPERAND:
                    oob.append({
                        "operand": e["operand"],
                        "step": list(step), "block_index": list(idx),
                        "block_range": nblocks,
                    })
                n_oob += 1
                continue
            if e["operand"].startswith("out"):
                seen.setdefault(idx, []).append(pos)
        for idx, positions in sorted(seen.items()):
            if len(positions) <= 1:
                continue
            max_revisit = max(max_revisit, len(positions))
            consecutive = positions[-1] - positions[0] == \
                len(positions) - 1
            if not consecutive:
                races.append({
                    "operand": e["operand"], "block_index": list(idx),
                    "steps": [list(steps[p]) for p in positions[:4]],
                    "revisits": len(positions),
                })
    return {"oob": oob, "races": races, "max_revisit": int(max_revisit)}


# --------------------------------------------------------- canary facts ----


def _canary_facts(case: dict, inp: dict, clean_out) -> dict:
    """The KN004 differential: clean interpret output vs the pure-XLA
    oracle on live lanes (+ exact-zero claims), then a NaN-poisoned run
    whose live lanes must stay finite AND on-oracle."""
    import numpy as np

    ref, live, zero = case["oracle"](inp)
    out = np.asarray(clean_out, np.float32)
    err = float(np.abs(out - ref)[live].max()) if live.any() else 0.0
    zero_ok = bool((out[zero] == 0).all()) if zero.any() else True
    pout = np.asarray(case["run"](inp, poisoned=True), np.float32)
    nonfinite = int((~np.isfinite(pout[live])).sum())
    perr = (float(np.abs(pout - ref)[live].max())
            if live.any() and nonfinite == 0 else float("inf")
            if nonfinite else 0.0)
    return {
        "ran": True,
        "atol": float(case["atol"]),
        "max_abs_err": round(err, 9),
        "poisoned_max_abs_err":
            round(perr, 9) if math.isfinite(perr) else "inf",
        "nonfinite_live": nonfinite,
        "zero_rows_ok": zero_ok,
        "live_lanes": int(live.sum()),
    }


def _canary_failed(canary: dict) -> bool:
    if not canary.get("ran"):
        return False
    perr = canary["poisoned_max_abs_err"]
    perr = float("inf") if perr == "inf" else float(perr)
    return (
        canary["nonfinite_live"] > 0
        or canary["max_abs_err"] > canary["atol"]
        or perr > canary["atol"]
        or not canary["zero_rows_ok"]
    )


# -------------------------------------------------------------- collect ----


def _case_facts(case: dict) -> dict:
    from dynamo_tpu.ops.pallas.registry import capture_pallas_calls

    inp = case["build"]()
    records: list[dict] = []
    with capture_pallas_calls(records):
        out = case["run"](inp, poisoned=False)
    assert len(records) == 1, (case["name"], len(records))
    rec = records[0]
    canary = (_canary_facts(case, inp, out)
              if case["mode"] == "interpret" else {"ran": False})
    return {
        "kernel": case["kernel"],
        "geometry": case["name"],
        "mode": case["mode"],
        "grid": [int(x) for x in rec["grid"]],
        "vmem": _vmem_facts(rec),
        "index_map": _index_map_facts(rec),
        "canary": canary,
        "pricing": case["pricing"](),
    }


def _shard_accepted_sh002(path: Path | None = None) -> dict:
    """The SH002 entries the shard manifest currently accepts, as
    {entrypoint: {collective: count}} — read at collect time so the
    KN006 sync check is against the file as committed."""
    from dynamo_tpu.analysis import shardcheck

    path = path or shardcheck.DEFAULT_MANIFEST_PATH
    try:
        doc = json.loads(Path(path).read_text())
    except (OSError, ValueError):
        return {}
    out: dict[str, dict] = {}
    for a in doc.get("accepted", []):
        if a.get("rule") != "SH002":
            continue
        op, _, count = a.get("key", "").rpartition("x")
        try:
            out.setdefault(a["entrypoint"], {})[op] = int(count)
        except ValueError:
            out.setdefault(a["entrypoint"], {})[a["key"]] = -1
    return out


def _census_facts() -> dict:
    from dynamo_tpu.ops.pallas.registry import (
        KERNELS,
        audit_cases,
        fallback_census,
        probe_coverage,
    )

    geoms: dict[str, list] = {}
    for case in audit_cases():
        geoms.setdefault(case["kernel"], []).append(case["name"])
    probed = probe_coverage()
    return {
        "kernels": {
            name: {
                "module": meta["module"],
                "placeholder": bool(meta["placeholder"]),
                "probed": bool(probed.get(name, False)),
                "geometries": sorted(geoms.get(name, [])),
            }
            for name, meta in sorted(KERNELS.items())
        },
        "split": {
            "decode": "paged_decode_attention_mq",
            "ragged_prefill": "ragged_paged_prefill_attention",
            "unified": None,
        },
        "sh_fallback": fallback_census(),
        "shard_accepted": _shard_accepted_sh002(),
    }


def collect_kern_facts(budget: int = 1, seed_base: int = 0) -> dict:
    """The full kernel-plane fact snapshot: one entry per (kernel,
    geometry) of the registry matrix, plus the cross-plane census.
    budget > 1 or a nonzero seed base appends seeded fuzz geometries
    (canary-only; they never enter the manifest)."""
    from dynamo_tpu.ops.pallas.registry import audit_cases, fuzz_case

    cases = list(audit_cases())
    if budget > 1 or seed_base:
        cases += [fuzz_case(seed_base + i) for i in range(budget)]
    facts: dict[str, dict] = {}
    for case in cases:
        facts[f"pallas.{case['kernel']}[{case['name']}]"] = \
            _case_facts(case)
    facts["(kern-census)"] = _census_facts()
    return facts


# ---------------------------------------------------------------- check ----


def _is_fuzz(name: str) -> bool:
    return "[fuzz[" in name


def _check_census(census: dict) -> list[TraceFinding]:
    findings = []
    split = census.get("split", {})
    kernels = census.get("kernels", {})
    unified = split.get("unified")
    unified_real = bool(
        unified and not kernels.get(unified, {}).get("placeholder", True))
    if split.get("decode") and split.get("ragged_prefill") \
            and not unified_real:
        findings.append(TraceFinding(
            "(kern-census)", "KN006", "two-kernel-split",
            f"decode ({split['decode']}) and ragged prefill "
            f"({split['ragged_prefill']}) are separate kernels and the "
            "unified ragged kernel is a placeholder — ROADMAP item 2 "
            "(Ragged Paged Attention, arxiv 2604.15464) replaces both "
            "with ONE kernel; this acceptance is the machine-readable "
            "pin, and landing item 2 re-trips it",
        ))
    want = census.get("sh_fallback", {})
    have = census.get("shard_accepted", {})
    for ep in sorted(set(want) | set(have)):
        if want.get(ep) != have.get(ep):
            findings.append(TraceFinding(
                "(kern-census)", "KN006", f"sh-fallback:{ep}",
                f"registry fallback census {want.get(ep)} != shard "
                f"manifest accepted SH002 {have.get(ep)} for {ep} — "
                "the XLA-fallback gather acceptances and the kernel "
                "census must move together (retiring a kernel or "
                "landing the unified kernel updates BOTH planes)",
            ))
    for kname, meta in sorted(kernels.items()):
        if not meta.get("placeholder") and not meta.get("probed"):
            findings.append(TraceFinding(
                "(kern-census)", "KN006", f"probe:{kname}",
                f"registered kernel {kname} has no bench probe — "
                "probe coverage must equal registry coverage "
                "(benchmarks/probe_kernels.py builds from the "
                "registry's probe builders)",
            ))
    return findings


def check_kern_facts(facts: dict, manifest: Manifest,
                     drift: bool = True) -> list[TraceFinding]:
    """Findings = drift vs the committed manifest (KN005, resolved by
    fixing the kernel or re-snapshotting) + intrinsic defects
    (KN001-KN004, KN006, acceptable with a justification).  Fuzz
    entries are canary-only: never drift, never 'added'."""
    findings: list[TraceFinding] = []
    known = manifest.entrypoints
    if drift:
        for name in sorted(set(facts) - set(known)):
            if _is_fuzz(name):
                continue
            findings.append(TraceFinding(
                name, "KN005", "added",
                "fact entry not in the committed kern manifest — audit "
                "it and re-snapshot (`dynamo-tpu lint --kern "
                "--update-baseline`)",
            ))
        for name in sorted(set(known) - set(facts)):
            findings.append(TraceFinding(
                name, "KN005", "removed",
                "manifest entry no longer produced — re-snapshot if "
                "the kernel/geometry removal is intended",
            ))
    for name, f in sorted(facts.items()):
        if name == "(kern-census)":
            findings.extend(_check_census(f))
            continue
        vm = f["vmem"]
        if vm["resident_bytes"] > vm["budget_bytes"]:
            findings.append(TraceFinding(
                name, "KN001", "vmem-budget",
                f"per-grid-step resident {vm['resident_bytes']:,} B "
                f"(blocked {vm['blocked_bytes']:,} x double-buffer + "
                f"scratch {vm['scratch_bytes']:,}) exceeds the "
                f"per-core VMEM budget {vm['budget_bytes']:,} B — "
                "shrink the block/chunk geometry",
            ))
        for o in f["index_map"]["oob"]:
            findings.append(TraceFinding(
                name, "KN002",
                f"{o['operand']}@{','.join(map(str, o['step']))}",
                f"index map of {o['operand']} touches block "
                f"{o['block_index']} at grid step {o['step']} — "
                f"outside the valid block range {o['block_range']}",
            ))
        for r in f["index_map"]["races"]:
            findings.append(TraceFinding(
                name, "KN003", r["operand"],
                f"grid steps {r['steps']} all map {r['operand']} to "
                f"block {r['block_index']} NON-consecutively — a "
                "revisit-accumulate pattern is only sound on adjacent "
                "sequential steps; this is a write race under "
                "arbitrary grid order",
            ))
        if _canary_failed(f["canary"]):
            c = f["canary"]
            findings.append(TraceFinding(
                name, "KN004", "padding-leak",
                f"NaN canary reached live lanes ({c['nonfinite_live']}"
                f" nonfinite) or live lanes diverge from the oracle "
                f"(clean err {c['max_abs_err']}, poisoned err "
                f"{c['poisoned_max_abs_err']}, atol {c['atol']}, "
                f"zero-rows {'ok' if c['zero_rows_ok'] else 'VIOLATED'}"
                ") — padding/dead-slot data is influencing real "
                "outputs",
            ))
        committed = known.get(name)
        if not drift or committed is None or _is_fuzz(name):
            continue
        if f["pricing"] != committed.get("pricing"):
            findings.append(TraceFinding(
                name, "KN005", "pricing",
                f"kernel pricing drifted: {committed.get('pricing')} "
                f"-> {f['pricing']} — dtperf consumers see different "
                "costs; verify the kernel change, then re-snapshot",
            ))
        cvm = committed.get("vmem", {})
        if vm["resident_bytes"] != cvm.get("resident_bytes"):
            findings.append(TraceFinding(
                name, "KN005", "vmem",
                "per-grid-step VMEM drifted: "
                f"{cvm.get('resident_bytes')} -> "
                f"{vm['resident_bytes']} B — verify, then re-snapshot",
            ))
        if f["grid"] != committed.get("grid"):
            findings.append(TraceFinding(
                name, "KN005", "grid",
                f"grid drifted: {committed.get('grid')} -> {f['grid']}"
                " — verify the dispatch geometry, then re-snapshot",
            ))
    return sorted(findings)


# ------------------------------------------------------------------ CLI ----

# paths whose changes can affect kernel-plane facts (for `--changed`)
_TOUCHES = (
    "dynamo_tpu/ops/pallas",
    "dynamo_tpu/ops/kv_quant.py",
    "dynamo_tpu/ops/paged_attention.py",
    "dynamo_tpu/analysis/kerncheck.py",
    "dynamo_tpu/analysis/kern_manifest.json",
    "dynamo_tpu/analysis/shard_manifest.json",
)


def _kern_affected(root: Path) -> bool:
    from dynamo_tpu.analysis.cli import _git_changed_paths

    dirty = [str(p) for p in _git_changed_paths(root)]
    return any(frag in d for d in dirty for frag in _TOUCHES)


def _replay(token: str, fmt: str, out) -> int:
    """Re-run one fuzz geometry from its replay token (KN004 only —
    fuzz entries carry no committed baseline)."""
    import numpy as np

    from dynamo_tpu.ops.pallas.registry import fuzz_case

    seed = int(decode_token(token)["seed"])
    case = fuzz_case(seed)
    inp = case["build"]()
    clean = case["run"](inp, poisoned=False)
    canary = _canary_facts(case, inp, np.asarray(clean, np.float32))
    failed = _canary_failed(canary)
    if fmt == "json":
        doc = {"geometry": case["name"], "seed": seed,
               "canary": canary, "failed": failed}
        print(json.dumps(doc, indent=2, sort_keys=True), file=out)
    else:
        print(
            f"{case['name']}: clean err {canary['max_abs_err']} / "
            f"poisoned err {canary['poisoned_max_abs_err']} "
            f"(atol {canary['atol']}), {canary['nonfinite_live']} "
            f"nonfinite live lanes -> "
            f"{'PADDING LEAK' if failed else 'clean'}",
            file=out,
        )
    return 1 if failed else 0


def run_kern(args, out) -> int:
    """``dynamo-tpu lint --kern``: audit the registry geometry matrix,
    diff against the committed kern manifest, exit 1 on any
    non-accepted finding.  ``--update-baseline`` re-snapshots (pinned
    runs only); ``--replay dtk1.TOKEN`` re-runs one fuzz geometry."""
    token = getattr(args, "replay", None)
    if token:
        if not token.startswith(_TOKEN_PREFIX):
            print(f"not a dtkern replay token: {token[:16]!r} "
                  f"(expected {_TOKEN_PREFIX}...)", file=out)
            return 2
        return _replay(token, getattr(args, "fmt", "text"), out)

    manifest_path = Path(
        getattr(args, "manifest", None) or DEFAULT_MANIFEST_PATH)
    manifest = Manifest.load(manifest_path)
    budget, seed_base, pinned = _budget_env()
    root = Path(getattr(args, "root", None)
                or Path(__file__).resolve().parents[2])
    if getattr(args, "changed", False) and not _kern_affected(root):
        print("kernel plane unaffected by changed files", file=out)
        return 0
    facts = collect_kern_facts(budget=budget, seed_base=seed_base)
    # drift rules only judge the pinned default matrix: fuzz runs add
    # transient entries and must not demand a re-snapshot
    findings = check_kern_facts(facts, manifest, drift=pinned)

    if getattr(args, "update_baseline", False):
        if not pinned:
            print("refusing to update the kern manifest from a "
                  "non-default-budget/seed fuzz run", file=out)
            return 2
        intrinsic = [f for f in findings if f.rule != "KN005"]
        m = Manifest.from_facts(facts, intrinsic, manifest)
        m.header = _kern_header()
        m.save(manifest_path)
        print(
            f"kern manifest updated: {len(facts)} entries, "
            f"{len(intrinsic)} accepted finding"
            f"{'' if len(intrinsic) == 1 else 's'} -> {manifest_path}",
            file=out,
        )
        return 0

    fresh = manifest.filter(findings)
    n_accepted = len(findings) - len(fresh)
    n_fuzz = sum(1 for name in facts if _is_fuzz(name))
    if getattr(args, "fmt", "text") == "json":
        doc = {
            "findings": [f.to_json() for f in fresh],
            "accepted": n_accepted,
            "total": len(findings),
            "entries": sorted(facts),
            "fuzz": {
                "budget": budget, "seed_base": seed_base,
                "replay_tokens": _fuzz_tokens(fresh, facts),
            },
        }
        print(json.dumps(doc, indent=2, sort_keys=True), file=out)
    else:
        for f in fresh:
            print(f.render(), file=out)
        for name, tok in sorted(_fuzz_tokens(fresh, facts).items()):
            print(f"  replay: dynamo-tpu lint --kern --replay {tok}",
                  file=out)
        print(
            f"{len(fresh)} kern finding{'s' if len(fresh) != 1 else ''}"
            f" ({n_accepted} accepted) over {len(facts)} entries"
            + (f" incl. {n_fuzz} fuzz geometries" if n_fuzz else ""),
            file=out,
        )
    return 1 if fresh else 0


def _fuzz_tokens(fresh: list[TraceFinding], facts: dict) -> dict:
    """entrypoint -> replay token for every fresh finding on a fuzz
    geometry (the artifact the nightly job uploads)."""
    tokens = {}
    for f in fresh:
        if not _is_fuzz(f.entrypoint):
            continue
        geometry = facts[f.entrypoint]["geometry"]
        seed = int(geometry.split("ragged-")[1].rstrip("]"))
        tokens[f.entrypoint] = encode_token({"seed": seed})
    return tokens
