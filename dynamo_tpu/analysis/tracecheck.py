"""Compile-plane static analysis (dttrace): jaxpr/HLO trace audit.

The AST rules (rules_jax.py) and the project pass see *source*; the
runtime sanitizer sees *tasks*.  Neither sees what XLA actually
compiles — yet the costliest TPU bugs only exist at trace/lowering
level: a silent retrace in the decode hot loop (an unbucketed shape or
an unhashed static sneaks into a dispatch), a ``donate_argnums`` buffer
that does not actually alias in the lowered HLO (the whole KV pool gets
copied every step), an f32 upcast on a bf16 hot path (double the HBM
traffic), or a config change that statically cannot fit a chip's HBM.
With hardware down (ROADMAP standing note), these CPU-side compile-level
checks are the only guard on TPU behavior.

This pass registers every jitted serving entrypoint — the five donated
``EngineCore`` impls (incl. the unified mixed prefill+decode dispatch),
the model forwards, the Pallas-backed ops (audited
through their XLA fallback lowerings on CPU) — and, per entrypoint and
per config of a small representative matrix, extracts four fact
families **without running any model math** (``jax.eval_shape`` /
``jax.make_jaxpr`` / ``.lower()`` over ``ShapeDtypeStruct`` args):

- **trace-signature census** — the declared matrix of shape/dtype/static
  signatures the scheduler can produce (prefill buckets × prefix-block
  buckets, burst lengths, spec table slices, ragged token/row buckets).
  The matrix is enumerated twice and hashed; an axis change, an
  unhashed static, or an undeclared signature shows up as drift.  The
  seeded runtime complement (tests/test_tracecheck.py) proves the hot
  loop compiles exactly once per declared bucket.
- **donation audit** — every ``donate_argnums`` leaf must carry a
  ``tf.aliasing_output`` attribute in the lowered module (the
  jaxpr-level complement of AST rule DT103) and must actually be *used*
  by the computation; donated-but-unaliased and dead donations are
  findings.
- **dtype-propagation** — widening ``convert_element_type`` sites
  (bf16/f16/int8 → f32) at or above a hidden-size worth of elements,
  walked recursively through scan/pjit sub-jaxprs.  By-design sites
  (f32 logits, f32 softmax/norm accumulation) carry justifications in
  the manifest; a new site is a finding.
- **static HBM footprint** — params + KV pool + peak temporaries (from
  the jaxpr, donated-shaped outputs excluded as in-place) against a
  per-chip budget, so an OOM-at-deploy config fails in tier-1 instead.

Facts snapshot into the committed ``trace_manifest.json`` with the same
baseline/justification/``--update`` contract as ``baseline.json``:
``dynamo-tpu lint --trace`` exits 1 on any non-accepted finding or any
fact drift, ``--update-baseline`` re-snapshots facts and carries
justifications over by (entrypoint, rule, key).
"""

from __future__ import annotations

import hashlib
import itertools
import json
import re
import warnings
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Optional

__all__ = [
    "DEFAULT_MANIFEST_PATH",
    "TRACE_RULES",
    "TraceFinding",
    "Manifest",
    "Entrypoint",
    "Signature",
    "build_registry",
    "collect_facts",
    "check_facts",
    "run_trace",
]

DEFAULT_MANIFEST_PATH = Path(__file__).parent / "trace_manifest.json"

# Per-chip HBM budget for the representative deployment config.  v5e has
# 16 GiB; the estimate must leave runtime slack (XLA scratch, framework
# overhead, collectives buffers) so the budget is 95% of the chip.
V5E_HBM_BYTES = 16 * (1 << 30)
HBM_BUDGET_FRACTION = 0.95

TRACE_RULES = {
    "TR001": ("entrypoint-drift",
              "registered entrypoint set changed vs the manifest"),
    "TR002": ("signature-drift",
              "declared trace-signature matrix changed vs the manifest"),
    "TR003": ("unstable-trace-key",
              "rebuilding the signature matrix yields different keys "
              "(unhashed static / id-keyed object in a dispatch)"),
    "TR004": ("donated-not-aliased",
              "donate_argnums leaf not aliased in the lowered HLO "
              "(jaxpr-level complement of AST rule DT103)"),
    "TR005": ("dead-donation",
              "donated leaf is never read by the computation"),
    "TR006": ("f32-upcast",
              "widening dtype conversion on a bf16/int8 hot path"),
    "TR007": ("hbm-over-budget",
              "params + KV pool + peak temporaries exceed the per-chip "
              "HBM budget"),
}

_MANIFEST_NOTE = (
    "CPU-derived facts (jax.eval_shape/make_jaxpr/.lower() over "
    "ShapeDtypeStructs; Pallas ops audited via their XLA fallback "
    "lowerings): HBM figures and kernel peaks are compile-plane "
    "estimates pending hardware return — the TPU tunnel has been down "
    "since BENCH_r04 (ROADMAP standing note), so any perf-claiming PR "
    "must re-land on-chip numbers via bench.py's bank-after-every-phase "
    "flow when hardware returns."
)


# ---------------------------------------------------------------- findings ----


@dataclass(frozen=True, order=True)
class TraceFinding:
    """One compile-plane finding.  ``key`` is the stable acceptance key:
    (entrypoint, rule, key) matches manifest ``accepted`` entries the
    way (path, rule, content) matches baseline.json entries."""

    entrypoint: str
    rule: str
    key: str
    message: str

    @property
    def accept_key(self) -> tuple[str, str, str]:
        return (self.entrypoint, self.rule, self.key)

    def render(self) -> str:
        return f"{self.entrypoint}: {self.rule}[{self.key}] {self.message}"

    def to_json(self) -> dict:
        return {
            "entrypoint": self.entrypoint,
            "rule": self.rule,
            "key": self.key,
            "message": self.message,
        }


# ---------------------------------------------------------------- manifest ----


class Manifest:
    """Committed compile-plane snapshot + accepted (justified) findings.

    Same contract as core.Baseline: ``accepted`` entries carry a
    one-line justification and are matched as a (entrypoint, rule, key)
    multiset; ``--update-baseline`` (with ``--trace``) re-snapshots the
    facts and carries justifications over where the key still matches.
    """

    def __init__(self, entrypoints: Optional[dict] = None,
                 accepted: Optional[list[dict]] = None,
                 header: Optional[dict] = None):
        self.entrypoints: dict = entrypoints or {}
        self.accepted: list[dict] = accepted or []
        self.header: dict = header or {}

    @classmethod
    def load(cls, path: Path) -> "Manifest":
        if not Path(path).is_file():
            return cls()
        data = json.loads(Path(path).read_text())
        return cls(dict(data.get("entrypoints", {})),
                   list(data.get("accepted", [])),
                   dict(data.get("header", {})))

    def save(self, path: Path) -> None:
        doc = {
            "version": 1,
            "header": self.header or {
                "note": _MANIFEST_NOTE,
                "hbm_budget": {
                    "chip": "v5e",
                    "bytes": int(V5E_HBM_BYTES * HBM_BUDGET_FRACTION),
                },
            },
            "entrypoints": self.entrypoints,
            "accepted": sorted(
                self.accepted,
                key=lambda e: (e["entrypoint"], e["rule"], e["key"]),
            ),
        }
        Path(path).write_text(
            json.dumps(doc, indent=2, sort_keys=True) + "\n"
        )

    def _counts(self) -> dict[tuple[str, str, str], int]:
        counts: dict[tuple[str, str, str], int] = {}
        for e in self.accepted:
            key = (e["entrypoint"], e["rule"], e["key"])
            counts[key] = counts.get(key, 0) + 1
        return counts

    def filter(self, findings: list[TraceFinding]) -> list[TraceFinding]:
        """Findings NOT covered by an accepted entry (stable-sorted)."""
        budget = self._counts()
        fresh: list[TraceFinding] = []
        for f in sorted(findings):
            if budget.get(f.accept_key, 0) > 0:
                budget[f.accept_key] -= 1
            else:
                fresh.append(f)
        return fresh

    @classmethod
    def from_facts(cls, facts: dict, findings: list[TraceFinding],
                   previous: "Manifest") -> "Manifest":
        """Re-snapshot: current facts become the committed entrypoints;
        intrinsic findings become accepted entries, carrying the previous
        justification where (entrypoint, rule, key) still matches."""
        just: dict[tuple[str, str, str], list[str]] = {}
        for e in previous.accepted:
            key = (e["entrypoint"], e["rule"], e["key"])
            just.setdefault(key, []).append(e.get("justification", ""))
        accepted = []
        for f in sorted(findings):
            carried = just.get(f.accept_key)
            accepted.append({
                "entrypoint": f.entrypoint,
                "rule": f.rule,
                "key": f.key,
                "message": f.message,
                "justification": (
                    carried.pop(0) if carried else "TODO: justify"
                ),
            })
        return cls(facts, accepted, previous.header or None)


# ------------------------------------------------------------- entrypoints ----


@dataclass
class Signature:
    """One declared dispatch signature: positional args (pytrees of
    ShapeDtypeStruct) plus static kwargs."""

    label: str
    args: tuple
    statics: dict = field(default_factory=dict)


@dataclass
class Entrypoint:
    """One registered jitted serving entrypoint.

    ``build(**axis_values)`` returns a Signature (or None for an
    invalid axis combination); ``axes`` declares the full matrix the
    scheduler can produce.  ``jit_fn`` (the live jitted callable) is
    lowered for the donation audit; ``raw_fn`` (the unjitted impl) is
    traced for jaxpr-level facts.
    """

    name: str
    axes: dict[str, list]
    build: Callable[..., Optional[Signature]]
    jit_fn: Optional[Callable] = None
    raw_fn: Optional[Callable] = None
    donate_argnums: tuple[int, ...] = ()
    # axis-value dicts to eval_shape / lower (first is the donation rep)
    representatives: list[dict] = field(default_factory=list)
    upcast_min_elems: int = 0  # 0 = skip the dtype audit
    hbm: Optional[Callable[[], dict]] = None


def _sig_key(sig: Signature) -> str:
    """Stable short hash of one dispatch signature: flattened input
    avals + tree structure + sorted statics.  Two dispatches with the
    same key hit the same compiled executable; an unhashable/id-keyed
    static makes the key unstable across rebuilds (TR003)."""
    import jax

    leaves, treedef = jax.tree.flatten(sig.args)
    payload = (
        tuple((tuple(l.shape), str(l.dtype)) for l in leaves),
        str(treedef),
        tuple(sorted((k, repr(v)) for k, v in sig.statics.items())),
    )
    return hashlib.sha256(repr(payload).encode()).hexdigest()[:16]


def enumerate_signatures(ep: Entrypoint) -> dict[str, str]:
    """{label: key} over the declared axis matrix (invalid combos
    skipped)."""
    out: dict[str, str] = {}
    names = sorted(ep.axes)
    for combo in itertools.product(*(ep.axes[n] for n in names)):
        values = dict(zip(names, combo))
        sig = ep.build(**values)
        if sig is None:
            continue
        out[sig.label] = _sig_key(sig)
    return out


def _matrix_hash(signatures: dict[str, str]) -> str:
    payload = tuple(sorted(signatures.items()))
    return hashlib.sha256(repr(payload).encode()).hexdigest()[:16]


# ---------------------------------------------------------------- registry ----


def _pow2s_upto(n: int) -> list[int]:
    out, b = [], 1
    while b <= n:
        out.append(b)
        b *= 2
    return out


def _sds(shape, dtype):
    import jax

    return jax.ShapeDtypeStruct(tuple(shape), dtype)


def _tiny_model_config():
    from dynamo_tpu.models.config import ModelConfig

    return ModelConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64, num_layers=2,
        num_heads=4, num_kv_heads=2, head_dim=8,
        max_position_embeddings=256, dtype="bfloat16",
    )


def _tiny_engine_config(**kw):
    from dynamo_tpu.engine.config import EngineConfig

    base = dict(
        max_batch_size=4, max_model_len=128, block_size=8, num_blocks=64,
        prefill_buckets=[16, 32, 64, 128],
    )
    base.update(kw)
    return EngineConfig(**base)


def _engine_entrypoints(tag: str, model_cfg, engine_cfg) -> list[Entrypoint]:
    """The donated EngineCore impls (step / multi-decode / spec-verify /
    ragged-prefill / unified-mixed) under one (model, engine)
    config.  The core is built with shape-only params (eval_shape), so
    registration never materializes weights."""
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.engine.core import EngineCore
    from dynamo_tpu.engine.sampling import K_MAX
    from dynamo_tpu.models.llama import LlamaModel

    model = LlamaModel(model_cfg)
    params = jax.eval_shape(model.init_params, jax.random.PRNGKey(0))
    core = EngineCore(model, params, engine_cfg)
    cfg = engine_cfg
    m = cfg.max_blocks_per_seq
    b = cfg.max_batch_size
    cache = jax.eval_shape(
        lambda: model.init_kv_cache(
            cfg.num_blocks, cfg.block_size,
            cfg.cache_dtype or model_cfg.dtype,
        )
    )
    i32, f32 = jnp.int32, jnp.float32
    rng = _sds((2,), jnp.uint32)
    pb_axis = [0] + _pow2s_upto(m)
    min_elems = model_cfg.hidden_size
    eps: list[Entrypoint] = []

    def build_step(s_bucket, prefix_blocks):
        args = (params, cache,
                _sds((1, s_bucket), i32), _sds((1, s_bucket), i32),
                _sds((1, m), i32), _sds((1,), i32),
                _sds((1, s_bucket), i32), _sds((1,), i32), rng,
                _sds((1,), f32), _sds((1,), i32), _sds((1,), f32))
        return Signature(
            f"s={s_bucket},pb={prefix_blocks}", args,
            dict(prefix_blocks=prefix_blocks, k_cand=K_MAX, exact=False),
        )

    eps.append(Entrypoint(
        name=f"engine.step[{tag}]",
        axes={"s_bucket": list(cfg.prefill_buckets),
              "prefix_blocks": pb_axis},
        build=build_step,
        jit_fn=core._step_fn, raw_fn=core._step_impl,
        donate_argnums=(1,),
        representatives=[
            dict(s_bucket=cfg.prefill_buckets[-1], prefix_blocks=0),
            dict(s_bucket=cfg.prefill_buckets[0], prefix_blocks=pb_axis[-1]),
        ],
        upcast_min_elems=min_elems,
    ))

    def build_multi(num_steps):
        args = (params, cache,
                _sds((b,), i32), _sds((b,), i32), _sds((b, m), i32),
                _sds((b,), i32), _sds((b,), i32), rng,
                _sds((b,), f32), _sds((b,), i32), _sds((b,), f32))
        return Signature(
            f"k={num_steps}", args,
            dict(num_steps=num_steps, k_cand=K_MAX, exact=False,
                 use_penalties=False),
        )

    bursts = sorted({cfg.interactive_decode_steps, max(1, cfg.decode_steps)})
    eps.append(Entrypoint(
        name=f"engine.decode_multi[{tag}]",
        axes={"num_steps": bursts},
        build=build_multi,
        jit_fn=core._multi_fn, raw_fn=core._multi_impl,
        donate_argnums=(1,),
        representatives=[dict(num_steps=bursts[-1])],
        upcast_min_elems=min_elems,
    ))

    if cfg.spec_tokens > 0:
        s = cfg.spec_tokens + 1

        def build_spec(m_used):
            args = (params, cache,
                    _sds((b, s), i32), _sds((b, s), i32),
                    _sds((b, m_used), i32), _sds((b,), i32),
                    _sds((b, s), i32), rng,
                    _sds((b,), f32), _sds((b,), i32), _sds((b,), f32),
                    _sds((b,), f32), _sds((b,), i32), _sds((b,), bool))
            return Signature(f"m_used={m_used}", args,
                             dict(k_cand=K_MAX, exact=False))

        eps.append(Entrypoint(
            name=f"engine.spec_verify[{tag}]",
            axes={"m_used": _pow2s_upto(m)},
            build=build_spec,
            jit_fn=core._spec_fn, raw_fn=core._spec_impl,
            donate_argnums=(1,),
            representatives=[dict(m_used=_pow2s_upto(m)[-1])],
            upcast_min_elems=min_elems,
        ))

    if cfg.prefill_token_budget > 0 and getattr(
            model, "supports_ragged_prefill", False):
        bs = cfg.block_size
        t_max = cfg.bucket_for(cfg.prefill_token_budget)
        t_axis = [t for t in cfg.prefill_buckets if t <= t_max]
        r_axis = _pow2s_upto(1 << max(0, (b - 1).bit_length()))

        def build_ragged(t_bucket, r_pad, prefix_blocks):
            # pow2ceil(r_real) == r_pad needs r_real > r_pad/2 rows, each
            # at least one block wide on the flat axis
            min_rows = r_pad // 2 + 1 if r_pad > 1 else 1
            if min_rows * bs > t_bucket:
                return None
            args = (params, cache,
                    _sds((1, t_bucket), i32), _sds((1, t_bucket), i32),
                    _sds((r_pad, m), i32), _sds((r_pad,), i32),
                    _sds((1, t_bucket), i32), _sds((1, t_bucket), i32),
                    _sds((r_pad,), i32), _sds((r_pad,), i32),
                    _sds((r_pad,), i32), rng,
                    _sds((r_pad,), f32), _sds((r_pad,), i32),
                    _sds((r_pad,), f32))
            return Signature(
                f"t={t_bucket},r={r_pad},pb={prefix_blocks}", args,
                dict(prefix_blocks=prefix_blocks, k_cand=K_MAX,
                     exact=False),
            )

        eps.append(Entrypoint(
            name=f"engine.prefill_ragged[{tag}]",
            axes={"t_bucket": t_axis, "r_pad": r_axis,
                  "prefix_blocks": pb_axis},
            build=build_ragged,
            jit_fn=core._ragged_fn, raw_fn=core._ragged_impl,
            donate_argnums=(1,),
            representatives=[
                dict(t_bucket=t_axis[-1], r_pad=r_axis[-1],
                     prefix_blocks=0),
            ],
            upcast_min_elems=min_elems,
        ))

    if cfg.unified_token_dispatch and cfg.prefill_token_budget > 0 and \
            getattr(model, "supports_unified_dispatch", False):
        bs = cfg.block_size
        # mirror engine _run_unified's flat-axis math exactly: a STATIC
        # decode region leads the axis, prefill spans pack the remainder
        d_region = -(-b // bs) * bs
        pf_budget = max(bs, cfg.prefill_token_budget - d_region)
        pf_budget = min(pf_budget, cfg.max_model_len - d_region)
        t_lo = cfg.bucket_for(d_region + bs)
        t_hi = cfg.bucket_for(d_region + pf_budget)
        tu_axis = [t for t in cfg.prefill_buckets if t_lo <= t <= t_hi]
        ru_axis = [r for r in _pow2s_upto(1 << max(0, (b - 1).bit_length()))
                   if r >= 2]  # a mixed dispatch has >= 2 rows

        def build_unified(t_bucket, r_pad, prefix_blocks):
            # pow2ceil(r_real) == r_pad needs more rows than the slots
            # can supply, or no block-wide span fits past the region
            min_rows = r_pad // 2 + 1 if r_pad > 1 else 1
            if min_rows > b or (t_bucket - d_region) // bs < 1:
                return None
            args = (params, cache,
                    _sds((1, t_bucket), i32), _sds((1, t_bucket), i32),
                    _sds((r_pad, m), i32), _sds((r_pad,), i32),
                    _sds((1, t_bucket), i32), _sds((1, t_bucket), i32),
                    _sds((r_pad,), i32), _sds((r_pad,), i32),
                    _sds((r_pad,), i32), rng,
                    _sds((r_pad,), f32), _sds((r_pad,), i32),
                    _sds((r_pad,), f32))
            return Signature(
                f"t={t_bucket},r={r_pad},pb={prefix_blocks}", args,
                dict(row_tokens=d_region, prefix_blocks=prefix_blocks,
                     k_cand=K_MAX, exact=False),
            )

        eps.append(Entrypoint(
            name=f"engine.unified[{tag}]",
            axes={"t_bucket": tu_axis, "r_pad": ru_axis,
                  "prefix_blocks": pb_axis},
            build=build_unified,
            jit_fn=core._unified_fn, raw_fn=core._unified_impl,
            donate_argnums=(1,),
            representatives=[
                dict(t_bucket=tu_axis[-1], r_pad=ru_axis[-1],
                     prefix_blocks=0),
            ],
            upcast_min_elems=min_elems,
        ))

        if cfg.lookahead_dispatch and cfg.interactive_decode_steps >= 2:
            # double-buffered dispatch: the fused burst shares the
            # unified step's axes plus the per-row limits operand and a
            # static burst depth (one value — the interactive burst
            # length, the only depth _run_unified ever dispatches)
            k_burst = cfg.interactive_decode_steps

            def build_burst(t_bucket, r_pad, prefix_blocks, num_steps):
                min_rows = r_pad // 2 + 1 if r_pad > 1 else 1
                if min_rows > b or (t_bucket - d_region) // bs < 1:
                    return None
                args = (params, cache,
                        _sds((1, t_bucket), i32), _sds((1, t_bucket), i32),
                        _sds((r_pad, m), i32), _sds((r_pad,), i32),
                        _sds((1, t_bucket), i32), _sds((1, t_bucket), i32),
                        _sds((r_pad,), i32), _sds((r_pad,), i32),
                        _sds((r_pad,), i32), _sds((r_pad,), i32), rng,
                        _sds((r_pad,), f32), _sds((r_pad,), i32),
                        _sds((r_pad,), f32))
                return Signature(
                    f"t={t_bucket},r={r_pad},pb={prefix_blocks},"
                    f"k={num_steps}", args,
                    dict(num_steps=num_steps, row_tokens=d_region,
                         prefix_blocks=prefix_blocks, k_cand=K_MAX,
                         exact=False, use_penalties=False),
                )

            eps.append(Entrypoint(
                name=f"engine.unified_burst[{tag}]",
                axes={"t_bucket": tu_axis, "r_pad": ru_axis,
                      "prefix_blocks": pb_axis, "num_steps": [k_burst]},
                build=build_burst,
                jit_fn=core._burst_fn, raw_fn=core._burst_impl,
                donate_argnums=(1,),
                representatives=[
                    dict(t_bucket=tu_axis[-1], r_pad=ru_axis[-1],
                         prefix_blocks=0, num_steps=k_burst),
                ],
                upcast_min_elems=min_elems,
            ))

    if cfg.spec_tokens > 0:
        # the sixth donated serving dispatch: the draft proposer's
        # ingest+draft step owns its own paged cache (engine/draft.py)
        from dynamo_tpu.engine.draft import DraftProposer

        proposer = DraftProposer(model, params, cfg)
        dcache = jax.eval_shape(
            lambda: model.init_kv_cache(
                cfg.num_blocks, cfg.block_size, cfg.cache_dtype)
        )

        def build_draft(u, m_used, k):
            args = (params, dcache,
                    _sds((b, u), i32), _sds((b, u), i32),
                    _sds((b, m_used), i32), _sds((b,), i32),
                    _sds((b, u), i32), _sds((b,), i32), _sds((b,), bool))
            return Signature(f"u={u},m={m_used},k={k}", args, dict(k=k))

        eps.append(Entrypoint(
            name=f"engine.draft_propose[{tag}]",
            axes={"u": _pow2s_upto(16), "m_used": _pow2s_upto(m),
                  "k": sorted({1, cfg.spec_tokens})},
            build=build_draft,
            jit_fn=proposer._fn, raw_fn=proposer._impl,
            donate_argnums=(1,),
            representatives=[dict(u=4, m_used=_pow2s_upto(m)[-1],
                                  k=cfg.spec_tokens)],
            upcast_min_elems=min_elems,
        ))
    return eps


def _llama_forward_entrypoint(tag: str, model_cfg, *, num_blocks: int,
                              block_size: int, batch: int,
                              max_model_len: int,
                              hbm_budget: Optional[int] = None,
                              cache_dtype=None) -> Entrypoint:
    """Model-level forward census (decode + prefill shapes) with an
    optional static HBM footprint check against a per-chip budget."""
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.models.llama import LlamaModel

    model = LlamaModel(model_cfg)
    params = jax.eval_shape(model.init_params, jax.random.PRNGKey(0))
    cache = jax.eval_shape(
        lambda: model.init_kv_cache(
            num_blocks, block_size, cache_dtype or model_cfg.dtype)
    )
    m = -(-max_model_len // block_size)
    i32 = jnp.int32

    def build(phase):
        b, s = (batch, 1) if phase == "decode" else (1, max_model_len)
        statics = {} if phase == "decode" else dict(prefix_blocks=0)
        args = (params, _sds((b, s), i32), _sds((b, s), i32), cache,
                _sds((b, m), i32), _sds((b,), i32), _sds((b, s), i32))
        return Signature(phase, args, statics)

    def fwd(params, tokens, positions, cache, bt, lens, slots,
            prefix_blocks=None):
        return model.forward(params, tokens, positions, cache, bt, lens,
                             slots, prefix_blocks=prefix_blocks)

    hbm = None
    if hbm_budget is not None:
        def hbm():
            return _hbm_facts(build, fwd, params, cache, hbm_budget)

    return Entrypoint(
        name=f"models.llama.forward[{tag}]",
        axes={"phase": ["decode", "prefill"]},
        build=build,
        raw_fn=fwd,
        representatives=[dict(phase="decode")],
        upcast_min_elems=model_cfg.hidden_size,
        hbm=hbm,
    )


def _deepseek_forward_entrypoint() -> Entrypoint:
    """Tiny absorbed-MLA decode forward: census + dtype audit for the
    second model family (the latent-cache path has its own upcast and
    layout hazards — ROADMAP item 5 inherits this entry)."""
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.models.deepseek import DeepseekConfig, DeepseekModel

    cfg = DeepseekConfig(
        vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
        qk_nope_head_dim=8, qk_rope_head_dim=4, v_head_dim=8,
        kv_lora_rank=16, intermediate_size=64, moe_intermediate_size=32,
        n_routed_experts=4, num_experts_per_tok=2,
        first_k_dense_replace=1, dtype="bfloat16",
    )
    model = DeepseekModel(cfg)
    params = jax.eval_shape(model.init_params, jax.random.PRNGKey(0))
    cache = jax.eval_shape(lambda: model.init_kv_cache(16, 8))
    m, b = 8, 2
    i32 = jnp.int32

    def build(phase):
        s = 1 if phase == "decode" else 16
        bb = b if phase == "decode" else 1
        args = (params, _sds((bb, s), i32), _sds((bb, s), i32), cache,
                _sds((bb, m), i32), _sds((bb,), i32), _sds((bb, s), i32))
        return Signature(phase, args, {})

    return Entrypoint(
        name="models.deepseek.forward[tiny-mla]",
        axes={"phase": ["decode", "prefill"]},
        build=build,
        raw_fn=model.forward,
        representatives=[dict(phase="decode")],
        upcast_min_elems=cfg.hidden_size,
    )


def _ops_entrypoints(model_cfg, engine_cfg) -> list[Entrypoint]:
    """The Pallas-backed serving ops, audited through the lowerings CPU
    produces (the XLA fallback paths — the manifest header records the
    caveat).  scatter_blocks_inplace is the fifth donated entrypoint."""
    import jax
    import jax.numpy as jnp

    import importlib

    from dynamo_tpu.models.llama import LlamaModel
    from dynamo_tpu.ops import block_copy

    # ops/__init__ re-exports `paged_attention` (the function) under the
    # submodule's name — fetch the module itself
    pa = importlib.import_module("dynamo_tpu.ops.paged_attention")

    model = LlamaModel(model_cfg)
    cfg = engine_cfg
    cache = jax.eval_shape(
        lambda: model.init_kv_cache(cfg.num_blocks, cfg.block_size)
    )
    m = cfg.max_blocks_per_seq
    b = cfg.max_batch_size
    h, d = model_cfg.num_heads, model_cfg.head_dim
    hk = model_cfg.num_kv_heads
    dt = model_cfg.jax_dtype
    i32 = jnp.int32
    eps: list[Entrypoint] = []

    def build_decode(s):
        args = (_sds((b, s, h, d), dt), cache, _sds((), i32),
                _sds((b, m), i32), _sds((b,), i32), _sds((b, s), i32))
        return Signature(f"s={s}", args, {})

    eps.append(Entrypoint(
        name="ops.paged_attention_layer[tiny-llama]",
        axes={"s": [1, 3]},  # flash-decode and multi-query verify shapes
        build=build_decode,
        raw_fn=pa.paged_attention_layer,
        representatives=[dict(s=1)],
        upcast_min_elems=hk * d,
    ))

    def build_ragged_op(t, r):
        args = (_sds((1, t, h, d), dt), _sds((1, t, hk, d), dt),
                _sds((1, t, hk, d), dt), cache, _sds((), i32),
                _sds((r, m), i32), _sds((r,), i32), _sds((r,), i32),
                _sds((r,), i32), _sds((1, t), i32))
        return Signature(f"t={t},r={r}", args, dict(prefix_blocks=2))

    def ragged_op(q, k, v, cache, layer, bt, lens, starts, roff, ids,
                  prefix_blocks=0):
        return pa.ragged_prefill_attention(
            q, k, v, cache, layer, bt, lens, starts, roff, ids,
            prefix_blocks)

    eps.append(Entrypoint(
        name="ops.ragged_prefill_attention[tiny-llama]",
        axes={"t": [32, 64], "r": [2]},
        build=build_ragged_op,
        raw_fn=ragged_op,
        representatives=[dict(t=64, r=2)],
        upcast_min_elems=hk * d,
    ))

    def build_scatter(n):
        l_ = model_cfg.num_layers
        blocks = _sds((l_, n, 2, cfg.block_size, hk * d), dt)
        args = (cache, _sds((n,), i32), blocks)
        return Signature(f"n={n}", args, {})

    eps.append(Entrypoint(
        name="ops.scatter_blocks_inplace[tiny-llama]",
        axes={"n": _pow2s_upto(8)},
        build=build_scatter,
        jit_fn=block_copy._scatter_donated,
        raw_fn=lambda cache, ids, blocks: jax.tree.map(
            lambda c, bl: c.at[:, ids].set(bl.astype(c.dtype)), cache,
            blocks),
        donate_argnums=(0,),
        representatives=[dict(n=4)],
    ))
    return eps


def build_registry() -> list[Entrypoint]:
    """The full compile-plane registry: every jitted serving entrypoint
    across a small representative config matrix.

    - ``tiny-llama``: bf16 tiny Llama under the test engine shape, all
      four EngineCore impls (spec + token-budget ragged prefill on).
    - ``tiny-llama-int8``: int8 quantized KV cache — the QuantKvCache
      pytree doubles the donated leaf count, so donation is audited per
      leaf.
    - ``tiny-mla``: absorbed-MLA DeepSeek decode forward.
    - ``llama3b-v5e``: representative single-chip deployment dims — the
      entry whose static HBM estimate gates config changes against the
      v5e budget.
    - ``ops.*``: the Pallas-backed ops via their XLA fallback lowerings.
    """
    from dynamo_tpu.models.config import ModelConfig

    tiny = _tiny_model_config()
    eps: list[Entrypoint] = []
    eps += _engine_entrypoints(
        "tiny-llama", tiny,
        # lookahead on: the fused unified burst (double-buffered
        # dispatch) joins the census alongside the single-turn unified
        # impl it falls back to
        _tiny_engine_config(decode_steps=16, spec_tokens=2,
                            prefill_token_budget=64,
                            lookahead_dispatch=True),
    )
    eps += _engine_entrypoints(
        "tiny-llama-int8", tiny,
        # budget + unified on: the QuantKvCache pytree doubles the
        # donated leaf count of the ragged AND unified impls, so their
        # donation audit covers both cache layouts
        _tiny_engine_config(cache_dtype="int8", prefill_token_budget=64,
                            unified_token_dispatch=True),
    )
    eps.append(_llama_forward_entrypoint(
        "tiny-llama", tiny, num_blocks=64, block_size=8, batch=4,
        max_model_len=128,
    ))
    eps.append(_deepseek_forward_entrypoint())
    # Llama-3.2-3B-class dims on one v5e chip: ~6.4 GB bf16 params +
    # a 4096-block KV pool; a num_blocks/model_len bump that would OOM
    # the chip trips TR007 here before it ships.
    llama3b = ModelConfig(
        vocab_size=128256, hidden_size=3072, intermediate_size=8192,
        num_layers=28, num_heads=24, num_kv_heads=8, head_dim=128,
        max_position_embeddings=8192, dtype="bfloat16",
    )
    eps.append(_llama_forward_entrypoint(
        "llama3b-v5e", llama3b, num_blocks=4096, block_size=16, batch=16,
        max_model_len=8192,
        hbm_budget=int(V5E_HBM_BYTES * HBM_BUDGET_FRACTION),
    ))
    eps += _ops_entrypoints(
        tiny, _tiny_engine_config())
    return eps


# -------------------------------------------------------------- extraction ----


def _bytes_of(aval) -> int:
    try:
        return int(aval.size) * aval.dtype.itemsize
    except (AttributeError, TypeError):
        return 0


def _iter_subjaxprs(eqn):
    for v in eqn.params.values():
        if hasattr(v, "jaxpr"):
            yield v.jaxpr
        elif isinstance(v, (list, tuple)):
            for x in v:
                if hasattr(x, "jaxpr"):
                    yield x.jaxpr


def _walk_upcasts(jaxpr, min_elems: int, acc: dict[str, int]) -> dict:
    """Count widening convert_element_type sites (bf16/f16/int8 -> f32)
    with at least ``min_elems`` output elements, recursing into
    scan/pjit/cond sub-jaxprs.  Site key = src->dst dtype pair + output
    rank — stable across bucket sizes, so the manifest entry doesn't
    churn when a shape axis is re-bucketed."""
    for eqn in jaxpr.eqns:
        for sub in _iter_subjaxprs(eqn):
            _walk_upcasts(sub, min_elems, acc)
        if eqn.primitive.name != "convert_element_type":
            continue
        src, dst = eqn.invars[0].aval, eqn.outvars[0].aval
        if str(src.dtype) not in ("bfloat16", "float16", "int8"):
            continue
        if str(dst.dtype) != "float32" or dst.size < min_elems:
            continue
        key = f"{src.dtype}->f32[r{len(dst.shape)}]"
        acc[key] = acc.get(key, 0) + 1
    return acc


def _peak_temp_bytes(jaxpr, skip_bytes: set) -> int:
    """Upper-bound single-eqn temporary footprint: max over eqns of the
    summed output bytes, recursing into sub-jaxprs.  Outputs whose byte
    size matches a donated input (``skip_bytes``) are excluded: those
    are the in-place cache update and its pure relayouts
    (reshape/transpose to per-head form), which XLA aliases rather than
    materializes under donation."""
    peak = 0
    for eqn in jaxpr.eqns:
        inner = [_peak_temp_bytes(s, skip_bytes) for s in
                 _iter_subjaxprs(eqn)]
        if inner:
            peak = max(peak, max(inner))
            continue
        size = sum(
            _bytes_of(v.aval) for v in eqn.outvars
            if _bytes_of(v.aval) not in skip_bytes
        )
        peak = max(peak, size)
    return peak


def _hbm_facts(build, fwd, params, cache, budget: int) -> dict:
    """Static per-chip footprint: params + KV pool + the larger of the
    decode/prefill peak temporaries (donated cache-shaped outputs are
    in-place and excluded)."""
    import jax

    params_bytes = sum(_bytes_of(l) for l in jax.tree.leaves(params))
    kv_bytes = sum(_bytes_of(l) for l in jax.tree.leaves(cache))
    skip = {_bytes_of(l) for l in jax.tree.leaves(cache)}
    peaks = {}
    for phase in ("decode", "prefill"):
        sig = build(phase)
        closed = jax.make_jaxpr(
            lambda *a: fwd(*a, **sig.statics))(*sig.args)
        peaks[phase] = _peak_temp_bytes(closed.jaxpr, skip)
    total = params_bytes + kv_bytes + peaks["decode"]
    return {
        "params_bytes": params_bytes,
        "kv_bytes": kv_bytes,
        "peak_temp_decode_bytes": peaks["decode"],
        # prefill peak is informational: the XLA fallback materializes
        # score matrices the Pallas kernels stream on-chip
        "peak_temp_prefill_bytes_xla": peaks["prefill"],
        "total_bytes": total,
        "budget_bytes": budget,
        "headroom_bytes": budget - total,
    }


def _closed_call(ep: Entrypoint, sig: Signature):
    fn = ep.raw_fn
    statics = dict(sig.statics)
    return lambda *a: fn(*a, **statics)


def _donation_facts(ep: Entrypoint) -> Optional[dict]:
    """Lower the representative signature and audit donation: every
    donated leaf must carry tf.aliasing_output in the module (TR004) and
    be read by the jaxpr (TR005)."""
    import jax

    if not ep.donate_argnums or ep.jit_fn is None:
        return None
    sig = ep.build(**ep.representatives[0])
    donated_leaves = sum(
        len(jax.tree.leaves(sig.args[i])) for i in ep.donate_argnums
    )
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        lowered = ep.jit_fn.lower(*sig.args, **sig.statics)
    text = lowered.as_text()
    aliased = len(re.findall(r"tf\.aliasing_output", text))
    notes = sorted({
        str(w.message).splitlines()[0][:160] for w in caught
        if "donat" in str(w.message).lower()
    })

    # dead-donation: donated invars with no reader in the jaxpr
    dead: list[str] = []
    if ep.raw_fn is not None:
        closed = jax.make_jaxpr(_closed_call(ep, sig))(*sig.args)
        offsets = []
        pos = 0
        for i, arg in enumerate(sig.args):
            n = len(jax.tree.leaves(arg))
            if i in ep.donate_argnums:
                offsets.extend(range(pos, pos + n))
            pos += n
        used: set = set()

        def mark(jaxpr):
            for eqn in jaxpr.eqns:
                used.update(id(v) for v in eqn.invars)
                for sub in _iter_subjaxprs(eqn):
                    mark(sub)
        mark(closed.jaxpr)
        used.update(id(v) for v in closed.jaxpr.outvars)
        for off in offsets:
            var = closed.jaxpr.invars[off]
            if id(var) not in used:
                dead.append(f"leaf{off}")
    return {
        "donated_leaves": donated_leaves,
        "aliased_leaves": aliased,
        "dead_leaves": dead,
        "lowering_notes": notes,
        "signature": sig.label,
    }


def collect_facts(registry: Optional[list[Entrypoint]] = None) -> dict:
    """Extract the full fact snapshot for every registered entrypoint.
    Pure shape-level work: eval_shape / make_jaxpr / lower over
    ShapeDtypeStructs — no weights, no compiles, no model math."""
    import jax

    registry = registry if registry is not None else build_registry()
    facts: dict[str, dict] = {}
    for ep in registry:
        signatures = enumerate_signatures(ep)
        # stability probe: a second enumeration must produce the same
        # keys (an id-keyed static would hash differently per build)
        stable = _matrix_hash(enumerate_signatures(ep)) == \
            _matrix_hash(signatures)
        traced: dict[str, str] = {}
        for rep in ep.representatives:
            sig = ep.build(**rep)
            if sig is None:
                continue
            target = (ep.jit_fn if ep.raw_fn is None else
                      _closed_call(ep, sig))
            out = jax.eval_shape(target, *sig.args)
            leaves = jax.tree.leaves(out)
            traced[sig.label] = (
                f"{len(leaves)} outputs, "
                f"{sum(_bytes_of(l) for l in leaves)} bytes"
            )
        upcasts: dict[str, int] = {}
        if ep.upcast_min_elems and ep.raw_fn is not None:
            sig = ep.build(**ep.representatives[0])
            closed = jax.make_jaxpr(_closed_call(ep, sig))(*sig.args)
            _walk_upcasts(closed.jaxpr, ep.upcast_min_elems, upcasts)
        facts[ep.name] = {
            "axes": {k: list(v) for k, v in sorted(ep.axes.items())},
            "n_signatures": len(signatures),
            "signature_hash": _matrix_hash(signatures),
            "stable": stable,
            "traced": traced,
            "donation": _donation_facts(ep),
            "upcasts": dict(sorted(upcasts.items())),
            "hbm": ep.hbm() if ep.hbm is not None else None,
        }
    return facts


# ------------------------------------------------------------------- check ----


def check_facts(facts: dict, manifest: Manifest) -> list[TraceFinding]:
    """Findings = drift (facts vs manifest snapshot) + intrinsic
    compile-plane defects.  Intrinsic findings (TR004-TR007) can be
    accepted with a justification; drift (TR001-TR003) is resolved by
    fixing the code or re-snapshotting with ``--update``."""
    findings: list[TraceFinding] = []
    known = manifest.entrypoints
    for name in sorted(set(facts) - set(known)):
        findings.append(TraceFinding(
            name, "TR001", "added",
            "entrypoint not in the committed manifest — audit it and "
            "re-snapshot (`dynamo-tpu lint --trace --update-baseline`)",
        ))
    for name in sorted(set(known) - set(facts)):
        findings.append(TraceFinding(
            name, "TR001", "removed",
            "manifest entrypoint no longer registered — re-snapshot if "
            "the removal is intended",
        ))
    for name, f in sorted(facts.items()):
        committed = known.get(name)
        if committed is not None:
            if f["signature_hash"] != committed.get("signature_hash"):
                old_axes, new_axes = committed.get("axes"), f["axes"]
                detail = (
                    f"axes {old_axes} -> {new_axes}"
                    if old_axes != new_axes else
                    f"{committed.get('n_signatures')} -> "
                    f"{f['n_signatures']} signatures (same axes: an arg "
                    "shape/dtype or static changed)"
                )
                findings.append(TraceFinding(
                    name, "TR002", "matrix",
                    "declared trace-signature matrix drifted from the "
                    f"manifest: {detail} — a retrace surface changed; "
                    "verify bucketing, then re-snapshot",
                ))
        # TR006 is intrinsic: every upcast site class fires with its
        # count embedded in the acceptance key, so a count CHANGE (a new
        # f32 site on a reduced-precision hot path) invalidates the
        # accepted entry and trips the gate until re-justified
        for ul, count in f["upcasts"].items():
            old = (committed or {}).get("upcasts", {}).get(ul)
            drift = f" (manifest had {old})" if old not in (None, count) \
                else ""
            findings.append(TraceFinding(
                name, "TR006", f"{ul}x{count}",
                f"{count} widening-conversion site(s) {ul} on a "
                f"reduced-precision hot path{drift} — accept with a "
                "justification only if the accumulation is by design",
            ))
        if not f["stable"]:
            findings.append(TraceFinding(
                name, "TR003", "unstable",
                "signature matrix hashes differently across two "
                "enumerations: a dispatch static is unhashed/id-keyed "
                "(e.g. a config object) — every call would retrace",
            ))
        don = f.get("donation")
        if don is not None:
            if don["aliased_leaves"] < don["donated_leaves"]:
                findings.append(TraceFinding(
                    name, "TR004",
                    f"unaliased={don['donated_leaves'] - don['aliased_leaves']}",
                    f"{don['donated_leaves'] - don['aliased_leaves']} of "
                    f"{don['donated_leaves']} donated leaves carry no "
                    "tf.aliasing_output in the lowered module "
                    f"(sig {don['signature']}): the donated buffer is "
                    "copied, not updated in place — the lowered-HLO "
                    "complement of AST rule DT103",
                ))
            for leaf in don["dead_leaves"]:
                findings.append(TraceFinding(
                    name, "TR005", leaf,
                    f"donated {leaf} is never read by the jaxpr — dead "
                    "donation: drop it from donate_argnums or wire the "
                    "buffer through",
                ))
        hbm = f.get("hbm")
        if hbm is not None and hbm["total_bytes"] > hbm["budget_bytes"]:
            findings.append(TraceFinding(
                name, "TR007", "total",
                f"static footprint {hbm['total_bytes']:,} B (params "
                f"{hbm['params_bytes']:,} + KV {hbm['kv_bytes']:,} + "
                f"decode peak {hbm['peak_temp_decode_bytes']:,}) exceeds "
                f"the per-chip budget {hbm['budget_bytes']:,} B",
            ))
    return sorted(findings)


# --------------------------------------------------------------------- CLI ----


def run_trace(args, out) -> int:
    """`dynamo-tpu lint --trace`: text or stable JSON, exit 1 on any
    non-accepted finding, `--update-baseline` re-snapshots the manifest
    (carrying justifications by key)."""
    manifest_path = Path(
        getattr(args, "manifest", None) or DEFAULT_MANIFEST_PATH
    )
    manifest = Manifest.load(manifest_path)
    facts = collect_facts()
    findings = check_facts(facts, manifest)

    if getattr(args, "update_baseline", False):
        # drift findings (TR001-TR003) are resolved by the snapshot
        # itself; intrinsic findings become accepted entries
        intrinsic = [f for f in findings
                     if f.rule in ("TR004", "TR005", "TR006", "TR007")]
        Manifest.from_facts(facts, intrinsic, manifest).save(manifest_path)
        print(
            f"trace manifest updated: {len(facts)} entrypoints, "
            f"{len(intrinsic)} accepted finding"
            f"{'' if len(intrinsic) == 1 else 's'} -> {manifest_path}",
            file=out,
        )
        return 0

    fresh = manifest.filter(findings)
    n_accepted = len(findings) - len(fresh)
    if getattr(args, "fmt", "text") == "json":
        doc = {
            "findings": [f.to_json() for f in fresh],
            "accepted": n_accepted,
            "total": len(findings),
            "entrypoints": sorted(facts),
        }
        print(json.dumps(doc, indent=2, sort_keys=True), file=out)
    else:
        for f in fresh:
            print(f.render(), file=out)
        print(
            f"{len(fresh)} trace finding{'s' if len(fresh) != 1 else ''} "
            f"({n_accepted} accepted) over {len(facts)} entrypoints",
            file=out,
        )
    return 1 if fresh else 0
