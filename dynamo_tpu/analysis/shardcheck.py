"""Sharding-plane static analysis (dtshard): SPMD placement audit.

The five existing planes see source (rules/project), traces
(tracecheck), wire contracts (wirecheck) and priced jaxprs (perfcheck)
— none of them sees *where arrays land*.  Yet every ROADMAP item that
scales past one chip lives or dies on placement: a param or KV pool
the specs silently replicate costs per-chip HBM on every device, a
GSPMD-inserted all-gather reshards a hot path the program never asked
to gather, and a donated buffer whose output sharding differs from its
input sharding is copied, not aliased.  With hardware down (ROADMAP
standing note) these CPU-side placement facts are the only guard on
multi-chip behavior.

The plane audits THREE fact families under one canonical audit mesh
(``utils/mesh.py``; (data=1, model=4) — the single-host v5e-4 TP
shape), sharing tracecheck's entrypoint registry:

- **placement census** (no devices needed — pure PartitionSpec math
  over an ``AbstractMesh``): for each model rig of the registry's
  config matrix, every param and KV-cache leaf gets its pruned spec,
  global bytes, and per-chip resident bytes
  (``global / prod(mesh axis sizes named in the spec)``) — the
  sharding-aware successor of tracecheck's global TR007 picture — plus
  a replication census of leaves the model axis never splits.
- **entrypoint coverage**: every registered (entrypoint, config) pair
  maps onto its placement rig, and its representative signature's arg
  leaves are classified against the rig's param/cache leaf sets to
  give per-chip argument bytes per dispatch.
- **compile probes** (need ≥ 4 CPU devices —
  ``XLA_FLAGS=--xla_force_host_platform_device_count``, forced by
  :func:`ensure_audit_devices` before the backend initializes): the
  two model decode forwards are jitted with their real shardings under
  the real mesh, compiled, and the optimized HLO's collectives are
  counted and cross-referenced against the *user program's* collective
  primitives (dtperf's PF002 vocabulary) — what remains is what GSPMD
  *inserted*.  Inserted all-gather / all-to-all on the decode path is
  an implicit reshard (SH002); the probes also read the compiled
  output sharding of every donated cache leaf and compare it with the
  requested input sharding (SH005 — donation only aliases when the
  shardings agree; a mismatch means a full copy per step, the
  per-shard extension of TR004).

Rules (committed ``shard_manifest.json``, same justification /
``--update-baseline`` contract as the trace/wire/perf manifests):

- SH001 large-array-replicated: a leaf above the size floor that the
  model axis never splits.  The absorbed-MLA latent cache fires this
  by construction (one shared latent row, nothing head-sharded) — its
  accepted entry pins ROADMAP item 5's premise (TPLA, arxiv
  2508.15881) until the latent-sharding refactor lands, at which point
  the stale entry re-trips the gate.
- SH002 implicit-reshard: GSPMD-inserted all-gather/all-to-all on a
  decode probe (count-keyed like PF002, so a new reshard invalidates
  the accepted entry).
- SH003 per-chip-hbm-over-budget: params + KV pool per-chip resident
  bytes against the per-chip budget (per-chip successor of TR007).
- SH004 placement-drift: spec-table hash drift vs the committed
  manifest, and added/removed fact entries (resolved by fixing the
  specs or re-snapshotting with ``--update-baseline``).
- SH005 donated-buffer-sharding-mismatch: a donated cache leaf whose
  compiled output sharding is not equivalent to its input sharding.

CPU caveat (recorded in the manifest header): the probes audit the
XLA *fallback* lowerings — the Pallas kernels keep the paged cache
resident on-chip on TPU, so fallback-only gathers are justified
accepted entries, not fixes.
"""

from __future__ import annotations

import hashlib
import json
import re
from pathlib import Path
from typing import Callable, Optional

from dynamo_tpu.analysis.tracecheck import (
    HBM_BUDGET_FRACTION,
    V5E_HBM_BYTES,
    Entrypoint,
    Manifest,
    TraceFinding,
    _bytes_of,
    _iter_subjaxprs,
    _tiny_model_config,
    build_registry,
)

__all__ = [
    "AUDIT_MESH_SHAPE",
    "DEFAULT_MANIFEST_PATH",
    "SHARD_RULES",
    "check_shard_facts",
    "collect_shard_facts",
    "ensure_audit_devices",
    "leaf_per_chip_bytes",
    "run_shard",
]

DEFAULT_MANIFEST_PATH = Path(__file__).parent / "shard_manifest.json"

# The audit mesh: (data, model) sizes.  dp=1, tp=4 is the single-host
# v5e-4 deployment shape — the smallest mesh where every TP split and
# every replication cost is visible.  Axis NAMES come from
# utils/mesh.py so the specs audited here are provably the specs the
# engine lowers under.
AUDIT_MESH_SHAPE = (1, 4)

SHARD_RULES = {
    "SH001": ("large-array-replicated",
              "param/KV leaf above the size floor is replicated across "
              "the model axis (full copy in every chip's HBM)"),
    "SH002": ("implicit-reshard",
              "GSPMD-inserted all-gather/all-to-all on a decode probe "
              "that the user program never asked for"),
    "SH003": ("per-chip-hbm-over-budget",
              "params + KV pool per-chip resident bytes exceed the "
              "per-chip HBM budget (sharding-aware TR007)"),
    "SH004": ("placement-drift",
              "placement spec table changed vs the committed shard "
              "manifest"),
    "SH005": ("donated-sharding-mismatch",
              "donated buffer's compiled output sharding differs from "
              "its input sharding — donation copies instead of "
              "aliasing (per-shard extension of TR004)"),
}

# SH001 size floors: absolute (real deployments) OR a fraction of the
# rig's per-chip total (so the tiny test rigs exhibit the same
# findings their full-size counterparts would).
SH001_MIN_BYTES = 1 << 20
SH001_MIN_FRACTION = 0.05

_MANIFEST_NOTE = (
    "CPU-derived placement facts under the canonical (data=1, model=4) "
    "audit mesh (utils/mesh.py axis names).  Census/per-chip figures "
    "are pure PartitionSpec math over an AbstractMesh; the SH002/SH005 "
    "probes compile the decode forwards on forced virtual CPU devices "
    "and therefore audit the XLA FALLBACK lowerings — the Pallas "
    "kernels keep the paged cache on-chip on TPU, so fallback-only "
    "gathers are accepted with that justification, not fixed."
)


def _shard_header() -> dict:
    from dynamo_tpu.utils.mesh import MESH_AXES

    return {
        "note": _MANIFEST_NOTE,
        "audit_mesh": dict(zip(MESH_AXES, AUDIT_MESH_SHAPE)),
        "hbm_budget": {
            "chip": "v5e",
            "bytes": int(V5E_HBM_BYTES * HBM_BUDGET_FRACTION),
        },
    }


def ensure_audit_devices(minimum: int = 4) -> None:
    """Force the virtual CPU device count BEFORE the jax backend
    initializes (utils/platform.py) and verify the probes have a mesh
    to compile under.  A backend already initialized with fewer
    devices cannot be re-forced — fail with the remedy."""
    from dynamo_tpu.utils.platform import force_cpu_devices

    force_cpu_devices(minimum)
    import jax

    if len(jax.devices()) < minimum:
        raise RuntimeError(
            f"shard plane needs >= {minimum} devices but the jax "
            f"backend initialized with {len(jax.devices())} — run the "
            "lint CLI in a fresh process, or export "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={minimum}"
            " before anything imports jax"
        )


# -------------------------------------------------------- per-chip math ----


def _spec_axis_names(spec) -> list[str]:
    names: list[str] = []
    for entry in tuple(spec):
        if entry is None:
            continue
        names.extend(entry if isinstance(entry, tuple) else (entry,))
    return names


def leaf_per_chip_bytes(spec, nbytes: int, mesh_shape: dict) -> int:
    """Per-chip resident bytes of one leaf: global bytes divided by the
    product of the mesh-axis sizes its (pruned) spec names.  Exact for
    pruned specs — prune_specs only keeps axes that divide the dim."""
    div = 1
    for nm in _spec_axis_names(spec):
        div *= int(mesh_shape.get(nm, 1))
    return -(-int(nbytes) // div)


def _audit_mesh():
    from dynamo_tpu.utils.mesh import MESH_AXES, abstract_mesh

    return abstract_mesh(AUDIT_MESH_SHAPE, MESH_AXES)


def _spec_str(spec) -> str:
    return "P(" + ", ".join(
        repr(e) if not isinstance(e, tuple) else repr(tuple(e))
        for e in tuple(spec)
    ) + ")"


# ------------------------------------------------------------ model rigs ----


def _tiny_deepseek_config():
    """Same dims as tracecheck's tiny-mla entrypoint — the absorbed-MLA
    rig whose latent cache is the plane's headline SH001 finding."""
    from dynamo_tpu.models.deepseek import DeepseekConfig

    return DeepseekConfig(
        vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
        qk_nope_head_dim=8, qk_rope_head_dim=4, v_head_dim=8,
        kv_lora_rank=16, intermediate_size=64, moe_intermediate_size=32,
        n_routed_experts=4, num_experts_per_tok=2,
        first_k_dense_replace=1, dtype="bfloat16",
    )


def _llama3b_config():
    from dynamo_tpu.models.config import ModelConfig

    return ModelConfig(
        vocab_size=128256, hidden_size=3072, intermediate_size=8192,
        num_layers=28, num_heads=24, num_kv_heads=8, head_dim=128,
        max_position_embeddings=8192, dtype="bfloat16",
    )


def _model_rigs() -> list[dict]:
    """One rig per registry config tag: model + shape-only params/cache
    + pruned specs under the audit mesh.  num_blocks/block_size match
    the tracecheck entrypoints of the same tag, so the coverage pass
    can classify their arg leaves exactly."""
    import jax

    from dynamo_tpu.models.deepseek import DeepseekModel
    from dynamo_tpu.models.llama import LlamaModel
    from dynamo_tpu.models.quant import prune_specs

    amesh = _audit_mesh()
    rigs: list[dict] = []

    def add(tag, model, cache, quant_cache, budget=None):
        params = jax.eval_shape(model.init_params, jax.random.PRNGKey(0))
        specs = prune_specs(params, model.partition_specs(), amesh)
        cspec = prune_specs(cache, model.cache_spec(quant_cache), amesh)
        rigs.append(dict(tag=tag, model=model, params=params,
                         cache=cache, specs=specs, cspec=cspec,
                         budget=budget))

    tiny = LlamaModel(_tiny_model_config())
    add("tiny-llama", tiny,
        jax.eval_shape(lambda: tiny.init_kv_cache(64, 8)), False)
    # int8 rig: same bf16 params (the engine entrypoints of this tag
    # quantize only the cache), QuantKvCache data+scale pools
    add("tiny-llama-int8", tiny,
        jax.eval_shape(lambda: tiny.init_kv_cache(64, 8, "int8")), True)
    mla = DeepseekModel(_tiny_deepseek_config())
    add("tiny-mla", mla,
        jax.eval_shape(lambda: mla.init_kv_cache(16, 8)), False)
    big = LlamaModel(_llama3b_config())
    add("llama3b-v5e", big,
        jax.eval_shape(lambda: big.init_kv_cache(4096, 16)), False,
        budget=int(V5E_HBM_BYTES * HBM_BUDGET_FRACTION))
    return rigs


def _leaf_table(tree, specs, mesh_shape: dict, prefix: str) -> dict:
    """{leaf name: placement fact} over one (pytree, spec-pytree)."""
    import jax
    import jax.tree_util as jtu

    from jax.sharding import PartitionSpec as P

    leaves = jtu.tree_flatten_with_path(tree)[0]
    spec_leaves = jtu.tree_flatten_with_path(
        specs, is_leaf=lambda x: isinstance(x, P))[0]
    if len(leaves) != len(spec_leaves):
        raise ValueError(
            f"{prefix}: {len(leaves)} leaves vs {len(spec_leaves)} specs"
        )
    model_axis_size = mesh_shape.get(_model_axis(), 1)
    out: dict[str, dict] = {}
    for (path, leaf), (_, spec) in zip(leaves, spec_leaves):
        name = prefix + jtu.keystr(path)
        nbytes = _bytes_of(leaf)
        per_chip = leaf_per_chip_bytes(spec, nbytes, mesh_shape)
        out[name] = {
            "shape": list(leaf.shape),
            "dtype": str(leaf.dtype),
            "spec": _spec_str(spec),
            "bytes_global": nbytes,
            "bytes_per_chip": per_chip,
            # replicated across the model axis: the TP mesh never
            # splits this leaf — every chip holds a full copy
            "replicated_model": (
                model_axis_size > 1
                and _model_axis() not in _spec_axis_names(spec)
            ),
        }
    return out


def _model_axis() -> str:
    from dynamo_tpu.utils.mesh import AXIS_MODEL

    return AXIS_MODEL


def _placement_facts(rig: dict) -> dict:
    from dynamo_tpu.utils.mesh import MESH_AXES

    mesh_shape = dict(zip(MESH_AXES, AUDIT_MESH_SHAPE))
    leaves = {}
    leaves.update(_leaf_table(rig["params"], rig["specs"], mesh_shape,
                              "params"))
    leaves.update(_leaf_table(rig["cache"], rig["cspec"], mesh_shape,
                              "cache"))
    params_pc = sum(v["bytes_per_chip"] for k, v in leaves.items()
                    if k.startswith("params"))
    cache_pc = sum(v["bytes_per_chip"] for k, v in leaves.items()
                   if k.startswith("cache"))
    total_pc = params_pc + cache_pc
    replicated_pc = sum(v["bytes_per_chip"] for v in leaves.values()
                        if v["replicated_model"])
    payload = tuple(sorted(
        (k, v["spec"], tuple(v["shape"]), v["dtype"])
        for k, v in leaves.items()
    )) + (tuple(sorted(mesh_shape.items())),)
    return {
        "mesh": mesh_shape,
        "leaves": leaves,
        "params_bytes_per_chip": params_pc,
        "cache_bytes_per_chip": cache_pc,
        "total_bytes_per_chip": total_pc,
        "replicated_bytes_per_chip": replicated_pc,
        "budget_bytes": rig["budget"],
        "spec_hash": hashlib.sha256(
            repr(payload).encode()).hexdigest()[:16],
    }


# --------------------------------------------------- entrypoint coverage ----


_TAG_RE = re.compile(r"\[([^\]]+)\]$")


def _coverage_facts(registry: list[Entrypoint],
                    placements: dict[str, dict]) -> dict:
    """Per registered (entrypoint, config) pair: its placement rig and
    the per-chip bytes of its representative signature's args, with
    each arg leaf classified against the rig's param/cache leaf sets by
    (shape, dtype).  Unmatched leaves (token buffers, tables) are small
    and replicated — they count at global size."""
    import jax

    lookup: dict[str, dict[tuple, tuple[str, int]]] = {}
    for pname, p in placements.items():
        tag = _TAG_RE.search(pname).group(1)
        table: dict[tuple, tuple[str, int]] = {}
        for lname, leaf in p["leaves"].items():
            key = (tuple(leaf["shape"]), leaf["dtype"])
            kind = "params" if lname.startswith("params") else "cache"
            table.setdefault(key, (kind, leaf["bytes_per_chip"]))
        lookup[tag] = table
    out: dict[str, dict] = {}
    for ep in registry:
        m = _TAG_RE.search(ep.name)
        tag = m.group(1) if m else None
        table = lookup.get(tag, {})
        sig = ep.build(**ep.representatives[0])
        matched = {"params": 0, "cache": 0, "other": 0}
        pc_bytes = 0
        for leaf in jax.tree.leaves(sig.args):
            key = (tuple(leaf.shape), str(leaf.dtype))
            hit = table.get(key)
            if hit is None:
                matched["other"] += 1
                pc_bytes += _bytes_of(leaf)
            else:
                matched[hit[0]] += 1
                pc_bytes += hit[1]
        out[ep.name] = {
            "placement": f"placement[{tag}]" if tag in lookup else None,
            "signature": sig.label,
            "arg_leaves": sum(matched.values()),
            "matched": matched,
            "arg_bytes_per_chip": pc_bytes,
        }
    return out


# --------------------------------------------------------- compile probes ----


# dtperf's PF002 vocabulary (perfcheck._COLLECTIVE_PRIMS): the user
# program's collectives, counted at jaxpr level so the probes can
# subtract them from what the compiled HLO contains.
def _user_collectives(fn: Callable, args) -> dict[str, int]:
    import jax

    from dynamo_tpu.analysis.perfcheck import _COLLECTIVE_PRIMS

    counts: dict[str, int] = {}

    def walk(jaxpr):
        for eqn in jaxpr.eqns:
            if eqn.primitive.name in _COLLECTIVE_PRIMS:
                counts[eqn.primitive.name] = (
                    counts.get(eqn.primitive.name, 0) + 1
                )
            for sub in _iter_subjaxprs(eqn):
                walk(sub)

    walk(jax.make_jaxpr(fn)(*args).jaxpr)
    return counts


_HLO_COLLECTIVE_RE = re.compile(
    r"\b(all-gather|all-reduce|all-to-all|collective-permute)"
    r"(?:-start)?\("
)

# HLO opcode -> the jaxpr primitives that legitimately lower to it
# (shared vocabulary with dtperf's collective census)
_HLO_TO_PRIMS = {
    "all-gather": ("all_gather",),
    "all-to-all": ("all_to_all",),
    "all-reduce": ("psum", "pmax", "pmin"),
    "collective-permute": ("ppermute", "pbroadcast"),
}


def _hlo_collectives(text: str) -> dict[str, int]:
    counts: dict[str, int] = {}
    for m in _HLO_COLLECTIVE_RE.finditer(text):
        counts[m.group(1)] = counts.get(m.group(1), 0) + 1
    return counts


def _named(mesh, spec_tree):
    import jax

    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    return jax.tree.map(lambda s: NamedSharding(mesh, s), spec_tree,
                        is_leaf=lambda x: isinstance(x, P))


def _probe_decode(rig: dict, mesh, m: int) -> dict:
    """Compile the rig's decode forward with its real shardings under
    the real mesh and extract the SH002/SH005 facts: optimized-HLO
    collective census minus the user program's collectives (what GSPMD
    *inserted*), and the compiled output sharding of every donated
    cache leaf vs its requested input sharding."""
    import jax
    import jax.numpy as jnp

    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    model, params, cache = rig["model"], rig["params"], rig["cache"]
    b, i32 = 1, jnp.int32

    def fwd(p, tokens, positions, c, bt, lens, slots):
        return model.forward(p, tokens, positions, c, bt, lens, slots)

    rep = NamedSharding(mesh, P())
    in_shardings = (
        _named(mesh, rig["specs"]), rep, rep,
        _named(mesh, rig["cspec"]), rep, rep, rep,
    )
    args = (params,
            jax.ShapeDtypeStruct((b, 1), i32),
            jax.ShapeDtypeStruct((b, 1), i32),
            cache,
            jax.ShapeDtypeStruct((b, m), i32),
            jax.ShapeDtypeStruct((b,), i32),
            jax.ShapeDtypeStruct((b, 1), i32))
    compiled = jax.jit(
        fwd, in_shardings=in_shardings, donate_argnums=(3,),
    ).lower(*args).compile()

    hlo = _hlo_collectives(compiled.as_text())
    user = _user_collectives(lambda *a: fwd(*a), args)
    inserted: dict[str, int] = {}
    for op, count in sorted(hlo.items()):
        expected = sum(user.get(p, 0) for p in _HLO_TO_PRIMS[op])
        if count > expected:
            inserted[op] = count - expected

    # donated cache leaves: compiled OUTPUT sharding must be equivalent
    # to the requested input sharding or donation degenerates to a copy
    import jax.tree_util as jtu

    out_leaves = jax.tree.leaves(compiled.output_shardings)
    out_avals = jax.tree.leaves(jax.eval_shape(
        lambda *a: fwd(*a), *args))
    cache_in = jtu.tree_flatten_with_path(
        _named(mesh, rig["cspec"]),
        is_leaf=lambda x: isinstance(x, NamedSharding))[0]
    donated = []
    for path, want in cache_in:
        name = "cache" + jtu.keystr(path)
        cache_leaf = jtu.tree_flatten_with_path(cache)[0]
        shape = dict(
            ("cache" + jtu.keystr(p), l) for p, l in cache_leaf
        )[name]
        match = None
        for got, aval in zip(out_leaves, out_avals):
            if tuple(aval.shape) == tuple(shape.shape) and \
                    str(aval.dtype) == str(shape.dtype):
                match = want.is_equivalent_to(got, len(shape.shape))
                break
        donated.append({
            "leaf": name,
            "in_spec": _spec_str(want.spec),
            "matches_output": bool(match),
        })
    return {
        "mesh": {k: int(v) for k, v in mesh.shape.items()},
        "hlo_collectives": hlo,
        "user_collectives": user,
        "inserted": inserted,
        "donated": donated,
    }


def _probe_facts() -> dict:
    from dynamo_tpu.models.deepseek import DeepseekModel
    from dynamo_tpu.models.llama import LlamaModel
    from dynamo_tpu.models.quant import prune_specs
    from dynamo_tpu.utils.mesh import MESH_AXES, build_mesh

    import jax

    mesh = build_mesh(AUDIT_MESH_SHAPE, MESH_AXES)
    amesh = _audit_mesh()
    out: dict[str, dict] = {}

    tiny = LlamaModel(_tiny_model_config())
    rig = dict(
        model=tiny,
        params=jax.eval_shape(tiny.init_params, jax.random.PRNGKey(0)),
        cache=jax.eval_shape(lambda: tiny.init_kv_cache(64, 8)),
    )
    rig["specs"] = prune_specs(rig["params"], tiny.partition_specs(),
                               amesh)
    rig["cspec"] = prune_specs(rig["cache"], tiny.cache_spec(False),
                               amesh)
    out["probe.llama.decode[tiny-llama]"] = _probe_decode(rig, mesh, 16)

    mla = DeepseekModel(_tiny_deepseek_config())
    rig = dict(
        model=mla,
        params=jax.eval_shape(mla.init_params, jax.random.PRNGKey(0)),
        cache=jax.eval_shape(lambda: mla.init_kv_cache(16, 8)),
    )
    rig["specs"] = prune_specs(rig["params"], mla.partition_specs(),
                               amesh)
    rig["cspec"] = prune_specs(rig["cache"], mla.cache_spec(False),
                               amesh)
    out["probe.deepseek.decode[tiny-mla]"] = _probe_decode(rig, mesh, 8)
    return out


# -------------------------------------------------------------- collect ----


def collect_shard_facts(
        registry: Optional[list[Entrypoint]] = None) -> dict:
    """The full sharding-plane fact snapshot: placement census per rig,
    coverage per registered entrypoint, and the two compile probes.
    Census/coverage are pure spec math (no devices); the probes need
    :func:`ensure_audit_devices` to have run first."""
    facts: dict[str, dict] = {}
    rigs = _model_rigs()
    placements = {
        f"placement[{rig['tag']}]": _placement_facts(rig) for rig in rigs
    }
    facts.update(placements)
    registry = registry if registry is not None else build_registry()
    facts.update(_coverage_facts(registry, placements))
    facts.update(_probe_facts())
    return facts


# ---------------------------------------------------------------- check ----


def check_shard_facts(facts: dict,
                      manifest: Manifest) -> list[TraceFinding]:
    """Findings = placement drift (SH004, resolved by fixing specs or
    re-snapshotting) + intrinsic placement defects (SH001/2/3/5,
    acceptable with a justification)."""
    findings: list[TraceFinding] = []
    known = manifest.entrypoints
    for name in sorted(set(facts) - set(known)):
        findings.append(TraceFinding(
            name, "SH004", "added",
            "fact entry not in the committed shard manifest — audit it "
            "and re-snapshot (`dynamo-tpu lint --shard "
            "--update-baseline`)",
        ))
    for name in sorted(set(known) - set(facts)):
        findings.append(TraceFinding(
            name, "SH004", "removed",
            "manifest entry no longer produced — re-snapshot if the "
            "removal is intended",
        ))
    for name, f in sorted(facts.items()):
        committed = known.get(name)
        if name.startswith("placement["):
            if committed is not None and \
                    f["spec_hash"] != committed.get("spec_hash"):
                findings.append(TraceFinding(
                    name, "SH004", "specs",
                    "placement spec table drifted from the manifest "
                    f"(hash {committed.get('spec_hash')} -> "
                    f"{f['spec_hash']}) — an array's sharding, shape "
                    "or dtype changed; verify the placement, then "
                    "re-snapshot",
                ))
            floor = max(
                int(SH001_MIN_FRACTION * f["total_bytes_per_chip"]), 1)
            for lname, leaf in sorted(f["leaves"].items()):
                if not leaf["replicated_model"]:
                    continue
                if leaf["bytes_global"] < SH001_MIN_BYTES and \
                        leaf["bytes_per_chip"] < floor:
                    continue
                findings.append(TraceFinding(
                    name, "SH001", lname,
                    f"{lname} {leaf['shape']} {leaf['dtype']} "
                    f"({leaf['bytes_global']:,} B) is replicated "
                    "across the model axis — every chip holds a full "
                    f"copy (spec {leaf['spec']}); shard it or accept "
                    "with a justification",
                ))
            budget = f.get("budget_bytes")
            if budget and f["total_bytes_per_chip"] > budget:
                findings.append(TraceFinding(
                    name, "SH003", "total",
                    f"per-chip resident {f['total_bytes_per_chip']:,} B"
                    f" (params {f['params_bytes_per_chip']:,} + KV "
                    f"{f['cache_bytes_per_chip']:,}) exceeds the "
                    f"per-chip budget {budget:,} B",
                ))
        elif name.startswith("probe."):
            for op, count in sorted(f.get("inserted", {}).items()):
                if op not in ("all-gather", "all-to-all"):
                    # inserted all-reduce is the expected TP pattern
                    # (row-parallel matmul partial sums); permutes are
                    # halo exchanges — recorded in facts, not findings
                    continue
                findings.append(TraceFinding(
                    name, "SH002", f"{op}x{count}",
                    f"{count} GSPMD-inserted {op}(s) on the decode "
                    "probe not present in the user program — an "
                    "implicit reshard on the hot path; fix the specs "
                    "or accept with a justification (count-keyed: a "
                    "new reshard re-trips the gate)",
                ))
            for d in f.get("donated", []):
                if not d["matches_output"]:
                    findings.append(TraceFinding(
                        name, "SH005", d["leaf"],
                        f"donated {d['leaf']} (in {d['in_spec']}) "
                        "compiles to a DIFFERENT output sharding — "
                        "the donation reshards/copies every step "
                        "instead of aliasing",
                    ))
    return sorted(findings)


# ------------------------------------------------------------------ CLI ----


def run_shard(args, out) -> int:
    """`dynamo-tpu lint --shard`: text or stable JSON, exit 1 on any
    non-accepted finding, `--update-baseline` re-snapshots the manifest
    (carrying justifications by key)."""
    ensure_audit_devices()
    manifest_path = Path(
        getattr(args, "manifest", None) or DEFAULT_MANIFEST_PATH
    )
    manifest = Manifest.load(manifest_path)
    facts = collect_shard_facts()
    findings = check_shard_facts(facts, manifest)

    if getattr(args, "update_baseline", False):
        intrinsic = [f for f in findings
                     if f.rule in ("SH001", "SH002", "SH003", "SH005")]
        m = Manifest.from_facts(facts, intrinsic, manifest)
        m.header = _shard_header()
        m.save(manifest_path)
        print(
            f"shard manifest updated: {len(facts)} entries, "
            f"{len(intrinsic)} accepted finding"
            f"{'' if len(intrinsic) == 1 else 's'} -> {manifest_path}",
            file=out,
        )
        return 0

    fresh = manifest.filter(findings)
    n_accepted = len(findings) - len(fresh)
    if getattr(args, "fmt", "text") == "json":
        doc = {
            "findings": [f.to_json() for f in fresh],
            "accepted": n_accepted,
            "total": len(findings),
            "entries": sorted(facts),
        }
        print(json.dumps(doc, indent=2, sort_keys=True), file=out)
    else:
        for f in fresh:
            print(f.render(), file=out)
        print(
            f"{len(fresh)} shard finding{'s' if len(fresh) != 1 else ''}"
            f" ({n_accepted} accepted) over {len(facts)} entries",
            file=out,
        )
    return 1 if fresh else 0
