"""Protocol plane (dtproto) — deterministic-schedule model checking and
systematic crash-point exploration of the control-plane protocols.

The six planes so far audit *artifacts* (source, jaxprs, placements).
This plane executes the REAL protocol code — ``CoordinatorServer`` /
``CoordinatorClient``, the endpoint TCP transport, the persist
replicator — under ``analysis/detloop.DetLoop``: a seeded scheduler owns
every interleaving, time is virtual, and the network is an in-memory
shim speaking the real ``framing.py`` bytes.  Two exploration axes:

* **schedules** — each scenario runs under a range of seeds; even seeds
  use uniform random scheduling, odd seeds a PCT-style priority
  scheduler with seeded inversion points;
* **crash points** — the coordinator's ``crash_hook`` seam fires at
  every WAL append/fsync/compact boundary and frame send; the explorer
  kills the process at each (label, occurrence) with ``proc`` (flushed
  file survives), ``power`` (truncate to the last fsync) and ``torn``
  (half the unsynced tail) disk semantics, then drives recovery.

Every run checks a registry of executable invariants (WAL replay
idempotence, acked-durable, no lost/duplicated queue message, drain
returns only at zero in-flight, router index == server truth at
quiescence, reconnect never double-applies).  A failing run prints a
compact replay token — ``dtp1.`` + base64(zlib(json)) of the scenario,
seed, crash plan and full choice list — that re-executes the exact
interleaving.

Facts (per-channel op state machines, crash-point census, invariant
registry) snapshot to the committed ``analysis/proto_manifest.json``
with the same accepted-entries contract as the other planes: every
accepted finding carries a one-line justification, and
``--update-baseline`` (with ``--proto``) re-snapshots carrying
justifications over by (scenario, rule, key).

Budget: ``DTPROTO_BUDGET`` multiplies seeds and crash occurrences
(nightly CI runs 100x), ``DTPROTO_SEED_BASE`` shifts the seed range for
fresh exploration.  Under non-default budget/seeds the drift rules
PR004/PR005 are skipped — new schedules legitimately discover new
edges; only invariant violations and non-quiescence are failures there.
"""

from __future__ import annotations

import base64
import json
import logging
import os
import shutil
import tempfile
import zlib
from dataclasses import asdict, dataclass, field
from pathlib import Path
from typing import Any, Callable, Optional

import asyncio

from dynamo_tpu.analysis.detloop import (
    DeadlockError,
    DetLoop,
    HorizonExceeded,
    MemNet,
    ReplayMismatch,
    SimulatedCrash,
    make_scheduler,
)
from dynamo_tpu.runtime.transports.coordinator import (
    CoordinatorClient,
    CoordinatorServer,
)
from dynamo_tpu.runtime.transports.protocol import CoordOp
from dynamo_tpu.runtime.transports.tcp import (
    EndpointTcpClient,
    EndpointTcpServer,
)

__all__ = [
    "DEFAULT_PROTO_MANIFEST_PATH",
    "PROTO_RULES",
    "SCENARIOS",
    "CrashPlan",
    "RunResult",
    "ScenarioReport",
    "ProtoFinding",
    "ProtoManifest",
    "encode_token",
    "decode_token",
    "run_one",
    "replay_token",
    "explore_scenario",
    "facts_from",
    "check_proto",
    "affected_scenarios",
    "run_proto",
]

DEFAULT_PROTO_MANIFEST_PATH = Path(__file__).parent / "proto_manifest.json"

_MANIFEST_NOTE = (
    "Committed protocol-plane snapshot (dynamo-tpu lint --proto): "
    "per-scenario channel state machines, crash-point census and "
    "invariant registry from the pinned-seed exploration.  Regenerate "
    "with --proto --update-baseline; every accepted entry needs a real "
    "justification."
)

PROTO_RULES = {
    "PR001": "protocol invariant violated in an explored schedule",
    "PR002": "same-seed schedule replay diverged (nondeterminism)",
    "PR003": "scenario failed to quiesce (deadlock/horizon/replay error)",
    "PR004": "protocol state machine drifted from the committed manifest",
    "PR005": "crash-point census drifted from the committed manifest",
}

# drift rules are resolved by re-snapshotting, not by justification
_DRIFT_RULES = ("PR004", "PR005")

_TOKEN_PREFIX = "dtp1."

_DEL = object()   # recorded kv op value meaning "delete"
_ABSENT = "<absent>"


# ---------------------------------------------------------------- findings


@dataclass(frozen=True, order=True)
class ProtoFinding:
    """One protocol-plane finding.  ``(scenario, rule, key)`` is the
    stable acceptance key — replay tokens live in ``detail`` only, so an
    accepted entry survives schedule-budget changes."""

    scenario: str
    rule: str
    key: str
    detail: str

    @property
    def accept_key(self) -> tuple[str, str, str]:
        return (self.scenario, self.rule, self.key)

    def render(self) -> str:
        return f"{self.scenario}: {self.rule}[{self.key}] {self.detail}"

    def to_json(self) -> dict:
        return {
            "scenario": self.scenario,
            "rule": self.rule,
            "key": self.key,
            "detail": self.detail,
        }


# ---------------------------------------------------------------- manifest


class ProtoManifest:
    """Committed protocol-plane snapshot + accepted (justified) findings.

    Same contract as the other planes: ``accepted`` entries carry a
    one-line justification and are matched as a (scenario, rule, key)
    multiset; ``--update-baseline`` (with ``--proto``) re-snapshots the
    scenario facts and carries justifications over where the key still
    matches."""

    def __init__(self, scenarios: Optional[dict] = None,
                 accepted: Optional[list[dict]] = None,
                 header: Optional[dict] = None):
        self.scenarios: dict = scenarios or {}
        self.accepted: list[dict] = accepted or []
        self.header: dict = header or {}

    @classmethod
    def load(cls, path: Path) -> "ProtoManifest":
        if not Path(path).is_file():
            return cls()
        data = json.loads(Path(path).read_text())
        return cls(dict(data.get("scenarios", {})),
                   list(data.get("accepted", [])),
                   dict(data.get("header", {})))

    def save(self, path: Path) -> None:
        doc = {
            "version": 1,
            "header": self.header or {"note": _MANIFEST_NOTE},
            "scenarios": self.scenarios,
            "accepted": sorted(
                self.accepted,
                key=lambda e: (e["scenario"], e["rule"], e["key"]),
            ),
        }
        Path(path).write_text(
            json.dumps(doc, indent=2, sort_keys=True) + "\n"
        )

    def _counts(self) -> dict[tuple[str, str, str], int]:
        counts: dict[tuple[str, str, str], int] = {}
        for e in self.accepted:
            key = (e["scenario"], e["rule"], e["key"])
            counts[key] = counts.get(key, 0) + 1
        return counts

    def filter(self, findings: list[ProtoFinding]) -> list[ProtoFinding]:
        """Findings NOT covered by an accepted entry (stable-sorted)."""
        budget = self._counts()
        fresh: list[ProtoFinding] = []
        for f in sorted(findings):
            if budget.get(f.accept_key, 0) > 0:
                budget[f.accept_key] -= 1
            else:
                fresh.append(f)
        return fresh

    @classmethod
    def from_facts(cls, facts: dict, findings: list[ProtoFinding],
                   previous: "ProtoManifest") -> "ProtoManifest":
        just: dict[tuple[str, str, str], list[str]] = {}
        for e in previous.accepted:
            key = (e["scenario"], e["rule"], e["key"])
            just.setdefault(key, []).append(e.get("justification", ""))
        accepted = []
        for f in sorted(findings):
            carried = just.get(f.accept_key)
            accepted.append({
                "scenario": f.scenario,
                "rule": f.rule,
                "key": f.key,
                "detail": f.detail,
                "justification": (
                    carried.pop(0) if carried else "TODO: justify"
                ),
            })
        return cls(facts, accepted, previous.header or None)


# ------------------------------------------------------------ replay token


def encode_token(payload: dict) -> str:
    raw = json.dumps(payload, sort_keys=True,
                     separators=(",", ":")).encode()
    return _TOKEN_PREFIX + base64.urlsafe_b64encode(
        zlib.compress(raw, 9)).decode().rstrip("=")


def decode_token(token: str) -> dict:
    if not token.startswith(_TOKEN_PREFIX):
        raise ValueError(f"not a dtproto replay token: {token[:16]!r}")
    body = token[len(_TOKEN_PREFIX):]
    body += "=" * (-len(body) % 4)
    return json.loads(zlib.decompress(base64.urlsafe_b64decode(body)))


# -------------------------------------------------------------- crash plan


@dataclass(frozen=True)
class CrashPlan:
    """One injected fault: ``crash`` kills the coordinator process at a
    (label, occurrence) with the given disk mode; ``sever`` cuts one
    connection at its k-th complete frame in one direction (the shared
    fault vocabulary's ops, driven deterministically)."""

    kind: str = "crash"       # "crash" | "sever"
    label: str = ""           # crash-hook label
    occurrence: int = 0
    mode: str = "proc"        # "proc" | "power" | "torn"
    conn: int = 0             # sever: connection ordinal
    after_frames: int = 0     # sever: trigger frame count
    direction: str = "s2c"

    @classmethod
    def from_json(cls, d: Optional[dict]) -> Optional["CrashPlan"]:
        return cls(**d) if d else None


# ----------------------------------------------------------------- harness


class Harness:
    """Per-run state shared between a scenario driver and the checker:
    the loop/net pair, crash-plan machinery, expectation bookkeeping and
    the violation list the invariants write into."""

    def __init__(self, loop: DetLoop, net: MemNet, root: Path, *,
                 bug: Optional[str] = None,
                 crash: Optional[CrashPlan] = None):
        self.loop = loop
        self.net = net
        self.root = root
        self.data_dir = root / "coord"
        self.bug = bug
        self.fault = crash          # any injected plan, crash or sever
        self.crash = crash if crash and crash.kind == "crash" else None
        if crash and crash.kind == "sever":
            net.sever_conn_after(crash.conn, crash.after_frames,
                                 crash.direction)
        self.crash_fired = False
        self.crash_census: dict[str, int] = {}
        self.violations: list[tuple[str, str]] = []
        self.servers: list[CoordinatorServer] = []
        self.clients: list[CoordinatorClient] = []
        self.coord_port = 0
        self._synced: dict[Path, int] = {}   # wal path -> fsynced offset
        # scenario scratch
        self.kv_ops: dict[str, list[tuple[str, Any]]] = {}
        self.queue_pushes: list[tuple[bytes, str]] = []
        self.queue_acks: list[tuple[bytes, str]] = []
        self.blob_expect: Optional[tuple[str, str]] = None
        self.leased_keys: set[str] = set()
        self.notes: dict[str, Any] = {}

    # ------------------------------------------------------------ invariants
    def check(self, invariant: str, cond: bool, msg: str = "") -> None:
        if not cond:
            self.violations.append((invariant, msg or invariant))

    # ---------------------------------------------------------- bug variants
    def pick(self, kind: str, default):
        impl = _BUG_IMPLS.get(self.bug or "", {}).get(kind)
        return impl if impl is not None else default

    # ------------------------------------------------------------ crash hook
    def hook_for(self, srv: CoordinatorServer) -> Callable[[str], None]:
        def hook(label: str) -> None:
            n = self.crash_census.get(label, 0)
            self.crash_census[label] = n + 1
            path = (srv._data_dir / "wal.jsonl"
                    if srv._data_dir is not None else None)
            if path is not None:
                # track the durable frontier for power/torn modeling
                if label.startswith("wal.fsync.") or \
                        label == "wal.compact.done":
                    try:
                        self._synced[path] = path.stat().st_size
                    except OSError:
                        pass
            plan = self.crash
            if (plan is not None and not self.crash_fired
                    and label == plan.label and n == plan.occurrence):
                self.crash_fired = True
                self._die(srv, label, n, plan.mode)
        return hook

    def _die(self, srv: CoordinatorServer, label: str, occ: int,
             mode: str) -> None:
        """Instant process death at a crash point.  Freezes the WAL
        first (a dead process writes nothing — post-crash finally blocks
        must not append revocation records), applies the disk mode's
        lost-tail semantics, then severs the network and unwinds the
        current stack with SimulatedCrash."""
        wal = getattr(srv, "_wal", None)
        if wal is not None:
            try:
                wal.flush()
                wal.close()
            except (OSError, ValueError):
                pass
            srv._wal = None
        path = (srv._data_dir / "wal.jsonl"
                if srv._data_dir is not None else None)
        if (mode in ("power", "torn") and path is not None
                and path.exists() and label.startswith("wal.append.")):
            # power loss: the OS page cache died with the machine — only
            # bytes up to the last fsync survive; "torn" keeps half the
            # unsynced tail, cutting the last record mid-line
            size = path.stat().st_size
            synced = min(self._synced.get(path, 0), size)
            keep = synced if mode == "power" else \
                synced + (size - synced + 1) // 2
            with path.open("rb+") as f:
                f.truncate(keep)
        server = getattr(srv, "_server", None)
        if server is not None and getattr(server, "port", None) is not None:
            self.net.kill_server(server.port)
            srv._server = None
        if srv._expiry_task is not None:
            srv._expiry_task.cancel()
        for t in list(srv._bg_tasks):
            t.cancel()
        for t in srv._conn_tasks.values():
            if t is not None:
                t.cancel()
        raise SimulatedCrash(f"{label}#{occ} [{mode}]")

    def kill_current(self, srv: CoordinatorServer) -> None:
        """Driver-scripted process kill (proc semantics: flushed bytes
        survive) — the scripted-restart half of every durability run."""
        wal = getattr(srv, "_wal", None)
        if wal is not None:
            try:
                wal.flush()
                wal.close()
            except (OSError, ValueError):
                pass
            srv._wal = None
        server = getattr(srv, "_server", None)
        if server is not None and getattr(server, "port", None) is not None:
            self.net.kill_server(server.port)
            srv._server = None
        if srv._expiry_task is not None:
            srv._expiry_task.cancel()
        for t in list(srv._bg_tasks):
            t.cancel()
        for t in srv._conn_tasks.values():
            if t is not None:
                t.cancel()

    # --------------------------------------------------------------- helpers
    async def start_coordinator(self, *, durable: bool = True,
                                port: int = 0):
        cls = self.pick("server", CoordinatorServer)
        srv = cls(port=port,
                  data_dir=str(self.data_dir) if durable else None,
                  net=self.net)
        srv.crash_hook = self.hook_for(srv)
        self.servers.append(srv)
        if durable:
            path = self.data_dir / "wal.jsonl"
            if path.exists():
                # whatever survived a previous incarnation is durable
                self._synced[path] = path.stat().st_size
        try:
            await srv.start()
        except SimulatedCrash:
            return srv, False
        self.coord_port = srv.port
        self.net.name_port(srv.port, "coord")
        return srv, True

    async def client(self, *, reconnect: bool = True) -> CoordinatorClient:
        cls = self.pick("client", CoordinatorClient)
        c = cls(f"tcp://mem:{self.coord_port}", reconnect=reconnect,
                net=self.net)
        await c.connect()
        self.clients.append(c)
        return c

    async def op(self, fn, *args, timeout: float = 60.0, **kw):
        """Run one client call with a virtual-time bound; a call the
        crash ate comes back ("lost", exc) — maybe-applied."""
        try:
            return "ok", await asyncio.wait_for(fn(*args, **kw), timeout)
        except (ConnectionError, OSError, RuntimeError,
                asyncio.TimeoutError) as e:
            return "lost", e

    async def teardown(self) -> None:
        for c in self.clients:
            try:
                await asyncio.wait_for(c.close(), 10.0)
            except (asyncio.TimeoutError, ConnectionError, OSError,
                    RuntimeError):
                pass
        for srv in self.servers:
            try:
                await asyncio.wait_for(srv.stop(), 10.0)
            except (asyncio.TimeoutError, ConnectionError, OSError,
                    RuntimeError):
                pass

    # ------------------------------------------------ expectation bookkeeping
    def record_kv(self, key: str, status: str, value: Any) -> None:
        self.kv_ops.setdefault(key, []).append((status, value))

    @staticmethod
    def _canon(value: Any) -> str:
        if value is _DEL:
            return _ABSENT
        return json.dumps(value, sort_keys=True, default=repr)

    def kv_allowed(self, key: str, *, weak: bool) -> set[str]:
        """Final values consistent with the op log: the server applies
        in order, a lost op may or may not have applied, so the final
        value is the last op of some superset of the acked set — any
        value at or after the last acked index.  ``weak`` (power/torn
        crashes: only fsynced records are promised) relaxes to "some
        op's value or absent" (prefix consistency, no corruption)."""
        ops = self.kv_ops.get(key, [])
        vals = [self._canon(v) for _s, v in ops]
        acked = [i for i, (s, _v) in enumerate(ops) if s == "ok"]
        if weak or not acked:
            return {_ABSENT, *vals}
        last = acked[-1]
        return set(vals[last:])


# ------------------------------------------------------------ bug variants
#
# Deliberately-broken protocol implementations, used for the violating
# golden fixtures and the gate's "the checker actually catches bugs"
# proof.  Each reintroduces a bug class the real code handles (two of
# them — stranded-pull and racy-drain — are the pre-fix versions of real
# bugs this plane found).


class _ReorderedTruncateServer(CoordinatorServer):
    """WAL compaction bug: truncates wal.jsonl IN PLACE before writing
    the replacement (instead of tmp+fsync+rename).  A crash inside the
    window loses every durable record."""

    def _recover(self) -> None:
        path = self._data_dir / "wal.jsonl"
        self._data_dir.mkdir(parents=True, exist_ok=True)
        data = path.read_bytes() if path.exists() else b""
        path.write_bytes(b"")          # the reordered truncate
        if self.crash_hook is not None:
            self.crash_hook("bug.compact.truncate")
        path.write_bytes(data)
        super()._recover()


class _StrandedPullServer(CoordinatorServer):
    """Pre-fix QUEUE_PULL: registers the delivery into _pending_acks
    without checking the puller's connection is still alive.  A consumer
    severed during a long pull strands the item forever — the conn-drop
    redelivery sweep already ran."""

    async def _dispatch(self, conn_id, writer, h, payload):
        if h.get("op") != CoordOp.QUEUE_PULL:
            return await super()._dispatch(conn_id, writer, h, payload)
        rid = h.get("id")

        async def _pull(queue=h["queue"],
                        timeout=h.get("timeout_ms", 0) / 1e3, rid=rid):
            item = await self._queue_take(queue, timeout)
            if item is None:
                await self._send(conn_id, writer,
                                 {"id": rid, "ok": False, "empty": True})
                return
            item.header["conn_id"] = conn_id
            self._pending_acks[(queue, item.msg_id)] = item
            await self._send(
                conn_id, writer,
                {"id": rid, "ok": True, "msg_id": item.msg_id}, item.payload)

        self._spawn(_pull())


class _BlindReputClient(CoordinatorClient):
    """Reconnect-heal bug: re-puts every leased key unconditionally
    (ignores the create-exclusive flag), clobbering a rival that
    legitimately claimed the key during the outage."""

    async def _reregister(self) -> None:
        self._leased_kv = {
            k: (v, lh, False) for k, (v, lh, _c) in self._leased_kv.items()
        }
        await super()._reregister()


class _NoSynthDeleteClient(CoordinatorClient):
    """Watch-heal bug: forgets the pre-outage known-key set, so keys
    that vanished while the client was down never get a synthesized
    delete — the router index keeps dead workers forever."""

    async def _reregister(self) -> None:
        for handle in self._watch_keys:
            self._watch_keys[handle] = set()
        await super()._reregister()


class _RacyDrainTcpServer(EndpointTcpServer):
    """Pre-fix wait_idle: trusts the idle event's wake without
    re-reading the live count — a request admitted between set() and the
    waiter's resumption makes drain report idle with a live stream."""

    async def wait_idle(self, subject: str, timeout: float = 30.0) -> bool:
        if self._inflight.get(subject, 0) <= 0:
            return True
        ev = self._idle_events.setdefault(subject, asyncio.Event())
        ev.clear()
        if self._inflight.get(subject, 0) <= 0:
            return True
        try:
            await asyncio.wait_for(ev.wait(), timeout)
            return True
        except asyncio.TimeoutError:
            return self._inflight.get(subject, 0) <= 0


def _make_eager_known_replicator():
    from dynamo_tpu.llm.kv.persist import PersistReplicator

    class _EagerKnownReplicator(PersistReplicator):
        """Publish bug: marks a stem _known before the blob/index
        round-trip lands.  A coordinator crash mid-publish makes the
        replicator skip the stem forever — replicas never converge."""

        async def publish_once(self) -> int:
            n = 0
            for stem, path, hashes, _size in self.store.export_files():
                if stem in self._known:
                    continue
                if await self.coord.kv_get(self._kv_key(stem)) is not None:
                    self._known.add(stem)
                    continue
                data = await asyncio.to_thread(path.read_bytes)
                self._known.add(stem)   # the bug: marked before the upload
                info = await self.coord.blob_put(self._blob_key(stem), data)
                await self.coord.kv_put(self._kv_key(stem), {
                    "stem": stem, "hashes": hashes, "size": len(data),
                    "sha256": info["sha256"],
                })
                n += 1
            return n

    return _EagerKnownReplicator


def _make_stale_gen_replica():
    from dynamo_tpu.llm.kv_router.shards.lifecycle import ShardReplica
    from dynamo_tpu.llm.kv_router.shards.scatter import probe_shard
    from dynamo_tpu.llm.kv_router.shards.wire import (
        decode_scatter_request,
        encode_scatter_reply,
    )

    class _StaleGenShardReplica(ShardReplica):
        """Fence bug: echoes the REQUEST's generation in scatter replies
        instead of the replica's own map generation.  A replica that
        missed a membership change (partition, slow watch) forges
        currency, and its pre-handoff holder data merges into gathers it
        no longer has any right to answer."""

        def _on_scatter(self, subject: str, payload: bytes) -> None:
            try:
                request_id, shard_id, seq_hashes, gen, reply_subject = (
                    decode_scatter_request(payload))
            except Exception:
                return
            reply = probe_shard(self.index.shard(shard_id), shard_id,
                                self.index.n_shards, seq_hashes, gen)
            self._spawn(self.coord.publish(
                reply_subject, encode_scatter_reply(request_id, reply)))

    return _StaleGenShardReplica


_BUG_IMPLS: dict[str, dict[str, Any]] = {
    "reorder-truncate": {"server": _ReorderedTruncateServer},
    "stranded-pull": {"server": _StrandedPullServer},
    "blind-reput": {"client": _BlindReputClient},
    "no-synth-deletes": {"client": _NoSynthDeleteClient},
    "racy-drain": {"tcp_server": _RacyDrainTcpServer},
    "eager-known": {"replicator": _make_eager_known_replicator},
    # kv.stream producer bug: notify the decode worker as soon as the
    # session opens, before a single layer frame lands — the exact
    # notify-races-KV hazard the stream_end ordering contract forbids
    "notify-early": {"stream_notify_early": True},
    # router.shard fence bug: a scatter reply that forges the gather's
    # generation — the resurrected stale-shard-after-handoff class
    "stale-generation": {"shard_replica": _make_stale_gen_replica},
}


# ---------------------------------------------------------------- scenarios


async def _wal_ops(h: Harness, c: CoordinatorClient) -> None:
    async def put(key, val):
        st, _ = await h.op(c.kv_put, key, val)
        h.record_kv(key, st, val)

    await put("cfg/a", 1)
    await put("cfg/b", {"x": 2})
    await put("cfg/a", 3)
    st, _ = await h.op(c.kv_delete, "cfg/b")
    h.record_kv("cfg/b", st, _DEL)
    for p in (b"job-1", b"job-2"):
        st, _ = await h.op(c.queue_push, "work", p)
        h.queue_pushes.append((p, st))
    st, r = await h.op(c.queue_pull, "work", timeout_s=1.0)
    if st == "ok" and r is not None:
        mid, payload = r
        st2, _ = await h.op(c.queue_ack, "work", mid)
        h.queue_acks.append((bytes(payload), st2))
    st, _ = await h.op(c.blob_put, "ckpt/w", b"0123456789" * 40)
    h.blob_expect = ("ckpt/w", st)
    stl, lease = await h.op(c.lease_create, 5.0, True)
    if stl == "ok":
        st, _ = await h.op(c.kv_put, "inst/w0", {"port": 1}, lease)
        h.leased_keys.add("inst/w0")


async def _run_coord_wal(h: Harness) -> None:
    srv, ok = await h.start_coordinator(durable=True)
    c = None
    if ok:
        c = await h.client()
        await _wal_ops(h, c)
    # scripted restart: every run exercises recovery, and under a crash
    # plan the recovery compaction itself is in the crash matrix
    h.kill_current(srv)
    ok2 = False
    for _ in range(2):
        srv2, ok2 = await h.start_coordinator(durable=True,
                                              port=h.coord_port)
        if ok2:
            break
    h.check("recovery_restarts", ok2,
            "coordinator failed to restart after crash")
    if ok2 and c is not None:
        # a call racing the client's discovery of the dropped conn can
        # legitimately fail (maybe-applied); liveness only demands that
        # a RETRIED call eventually lands on the recovered server
        pre_fired = h.crash_fired
        st = "lost"
        for _attempt in range(3):
            st, _ = await h.op(c.kv_put, "post/recovery", "alive")
            if st == "ok":
                break
            await asyncio.sleep(2.0)
        h.record_kv("post/recovery", st, "alive")
        # a crash plan that fires in THIS epoch killed the recovered
        # server out from under the probe — durability checks still
        # apply, liveness legitimately can't
        late_crash = h.crash_fired and not pre_fired
        h.check("post_recovery_liveness", st == "ok" or late_crash,
                f"put after recovery did not complete: {st}")
    await h.teardown()


def _offline_state(h: Harness) -> dict:
    """Replay the on-disk WAL in a fresh process model (no event loop —
    ``_recover`` is synchronous) and snapshot the recovered state."""
    srv = CoordinatorServer(data_dir=str(h.data_dir))
    srv._recover()
    state = {
        "kv": dict(srv._kv),
        "queues": {
            q: sorted((it.msg_id, it.payload.decode("latin1"))
                      for it in dq)
            for q, dq in srv._queues.items() if dq
        },
        "blobs": {name: rec.get("sha256")
                  for name, rec in srv._blobs.items()},
        "kv_lease": dict(srv._kv_lease),
    }
    if srv._wal is not None:
        srv._wal.close()
        srv._wal = None
    return state


def _post_coord_wal(h: Harness) -> None:
    path = h.data_dir / "wal.jsonl"
    if not path.exists():
        h.check("wal_version_head", False, "wal.jsonl missing after run")
        return
    s1 = _offline_state(h)
    try:
        first = path.read_text().splitlines()[0]
        head_ok = json.loads(first).get("t") == "ver"
    except (IndexError, json.JSONDecodeError):
        head_ok = False
    h.check("wal_version_head", head_ok,
            "compacted WAL does not start with a version record")
    s2 = _offline_state(h)
    h.check("wal_replay_idempotent", s1 == s2,
            "recovering twice from the same WAL produced different state")
    # acked-durable: proc crashes keep flushed bytes; power/torn only
    # promise the fsynced prefix, so kv/blob checks weaken to prefix
    # consistency there (queue records are fsynced — always strong)
    weak = h.crash is not None and h.crash.mode in ("power", "torn")
    for key in h.kv_ops:
        observed = (_ABSENT if key not in s1["kv"]
                    else Harness._canon(s1["kv"][key]))
        allowed = h.kv_allowed(key, weak=weak)
        h.check("kv_acked_durable", observed in allowed,
                f"{key} recovered as {observed}, allowed {sorted(allowed)}")
    counts: dict[str, int] = {}
    for items in s1["queues"].values():
        for _mid, p in items:
            counts[p] = counts.get(p, 0) + 1
    acked_ok = {p for p, st in h.queue_acks if st == "ok"}
    ack_tried = {p for p, _st in h.queue_acks}
    for p, st in h.queue_pushes:
        key = p.decode("latin1")
        n = counts.get(key, 0)
        if p in acked_ok:
            h.check("queue_acked_consumed", n == 0,
                    f"acked message {key} redelivered after recovery")
        elif st == "ok" and p not in ack_tried:
            h.check("queue_acked_durable", n == 1,
                    f"acked push {key} appears {n} times after recovery")
        else:
            h.check("queue_no_duplicates", n <= 1,
                    f"message {key} duplicated ({n}x) after recovery")
    if h.blob_expect is not None and not weak:
        name, st = h.blob_expect
        if st == "ok":
            h.check("blob_acked_durable", name in s1["blobs"],
                    f"acked blob {name} missing after recovery")
    for k in h.leased_keys:
        h.check("leased_keys_ephemeral",
                k not in s1["kv"] and k not in s1["kv_lease"],
                f"lease-bound key {k} survived a restart")


async def _run_coord_reconnect(h: Harness) -> None:
    srv, ok = await h.start_coordinator(durable=False)
    if not ok:
        await h.teardown()
        return
    a = await h.client()
    stl, la = await h.op(a.lease_create, 3.0, True)
    sta, _ = await h.op(a.kv_create, "slot/leader", "A", la)
    stw, _ = await h.op(a.watch, "slot/", lambda e, k, v: None)
    # restart; a rival claims the slot while A's reconnect races it
    h.kill_current(srv)
    srv2, ok2 = await h.start_coordinator(durable=False,
                                          port=h.coord_port)
    h.check("recovery_restarts", ok2, "restart failed")
    createdb = None
    b = None
    if ok2:
        b = await h.client()
        stlb, lb = await h.op(b.lease_create, 3.0, True)
        stb, createdb = await h.op(b.kv_create, "slot/leader", "B", lb)
        if stb != "ok":
            createdb = None
    await asyncio.sleep(8.0)   # heals land, loser's unused leases expire
    if ok2 and b is not None:
        stv, val = await h.op(b.kv_get, "slot/leader")
        if stv == "ok" and createdb is not None:
            # B won the create -> A must cede; B lost it -> A re-claimed
            want = "B" if createdb else "A"
            h.check("exactly_one_owner", val == want,
                    f"slot/leader={val!r} but rival create returned "
                    f"{createdb} (expected {want!r})")
        # reconnect must not double-register: A holds exactly one watch
        n_watches = len(srv2._watches)
        h.check("reregister_idempotent", n_watches <= 1,
                f"{n_watches} live watches after one client's heal")
        for k, lid in srv2._kv_lease.items():
            h.check("no_orphan_lease_keys", lid in srv2._leases,
                    f"key {k} bound to dead lease {lid}")
    await h.teardown()


async def _run_coord_queue(h: Harness) -> None:
    srv, ok = await h.start_coordinator(durable=False)
    if not ok:
        await h.teardown()
        return
    prod = await h.client()
    cons = await h.client()
    pushed = [f"task-{i}".encode() for i in range(4)]
    got: set[bytes] = set()
    unacked: set[bytes] = set()   # deliveries whose ack was lost

    async def take(r) -> None:
        mid, payload = r
        p = bytes(payload)
        got.add(p)
        try:
            await cons.queue_ack("jobs", mid)
            unacked.discard(p)
        except (ConnectionError, OSError, RuntimeError):
            unacked.add(p)   # at-least-once: redelivery is legal

    async def consume() -> None:
        # park a long-poll pull in the server BEFORE anything is pushed,
        # then touch the connection again (ping) so a frame-triggered
        # sever can kill the conn while the pull waits in the queue —
        # the stranded-delivery window the conn-drop sweep must cover
        first = asyncio.ensure_future(cons.queue_pull("jobs",
                                                      timeout_s=30.0))
        await asyncio.sleep(0.05)
        try:
            await cons.ping()
        except (ConnectionError, OSError, RuntimeError):
            pass
        try:
            r = await asyncio.wait_for(first, 35.0)
            if r is not None:
                await take(r)
        except (ConnectionError, OSError, RuntimeError,
                asyncio.TimeoutError):
            pass
        misses = 0
        while len(got) < len(pushed) and misses < 6:
            try:
                r = await cons.queue_pull("jobs", timeout_s=1.0)
            except (ConnectionError, OSError, RuntimeError):
                await asyncio.sleep(0.3)
                continue
            if r is None:
                misses += 1
                continue
            await take(r)
        # sweep redelivered copies of lost acks so a clean protocol
        # quiesces to an empty queue
        for _ in range(4):
            try:
                r = await cons.queue_pull("jobs", timeout_s=0.5)
            except (ConnectionError, OSError, RuntimeError):
                break
            if r is None:
                break
            await take(r)

    t = asyncio.ensure_future(consume())
    await asyncio.sleep(0.2)   # let the long poll park first
    for p in pushed:
        await h.op(prod.queue_push, "jobs", p)
    try:
        await asyncio.wait_for(t, 120.0)
        h.check("consumer_terminates", True)
    except asyncio.TimeoutError:
        t.cancel()
        h.check("consumer_terminates", False,
                "consumer loop did not finish within its budget")
    await asyncio.sleep(2.0)
    h.check("queue_no_lost", got == set(pushed),
            f"pushed {sorted(p.decode() for p in pushed)} but consumed "
            f"{sorted(p.decode() for p in got)}")
    stranded = [bytes(it.payload)
                for it in srv._pending_acks.values()]
    stranded += [bytes(it.payload)
                 for dq in srv._queues.values() for it in dq]
    # a delivery whose ack the fault ate may legally sit requeued at
    # quiescence; anything else stranded is a lost-delivery bug
    orphans = [p for p in stranded if p not in unacked]
    h.check("queue_drained", not orphans,
            f"{len(orphans)} item(s) stranded at quiescence: "
            f"{sorted(p.decode() for p in orphans)}")
    await h.teardown()


async def _run_router_index(h: Harness) -> None:
    srv, ok = await h.start_coordinator(durable=False)
    if not ok:
        await h.teardown()
        return
    router = await h.client()
    index: dict[str, Any] = {}

    def on_event(event: str, key: str, value: Any) -> None:
        if event == "put":
            index[key] = value
        else:
            index.pop(key, None)

    await h.op(router.watch, "inst/", on_event)
    workers = []
    for i in (1, 2):
        w = await h.client()
        stl, lw = await h.op(w.lease_create, 5.0, True)
        await h.op(w.kv_put, f"inst/{i}", {"port": 9000 + i}, lw)
        workers.append(w)
    await asyncio.sleep(1.0)
    # restart storm: the coordinator dies; worker 2 dies during the
    # outage and never comes back
    h.kill_current(srv)
    try:
        await asyncio.wait_for(workers[1].close(), 10.0)
    except (asyncio.TimeoutError, ConnectionError, OSError):
        pass
    srv2, ok2 = await h.start_coordinator(durable=False,
                                          port=h.coord_port)
    h.check("recovery_restarts", ok2, "restart failed")
    await asyncio.sleep(10.0)   # reconnect heals + lease expiry settle
    if ok2:
        truth = {k: v for k, v in srv2._kv.items()
                 if k.startswith("inst/")}
        h.check("router_index_matches", index == truth,
                f"router index {sorted(index)} != server truth "
                f"{sorted(truth)} at quiescence")
        h.check("router_converges", "inst/1" in index,
                "surviving worker missing from the healed index")
    await h.teardown()


class _SlowEngine:
    """Tiny AsyncEngine: yields its items across scheduling points
    (zero-length sleeps), so in-flight requests overlap the drain window
    and the interleaving is entirely the scheduler's choice."""

    def __init__(self, items: int = 2, delay: float = 0.0):
        self.items = items
        self.delay = delay

    async def generate(self, ctx):
        for i in range(self.items):
            await asyncio.sleep(self.delay)
            yield {"i": i}


async def _run_tcp_drain(h: Harness) -> None:
    from dynamo_tpu.runtime.engine import Context

    cls = h.pick("tcp_server", EndpointTcpServer)
    tsrv = cls(net=h.net)
    await tsrv.start()
    h.net.name_port(tsrv.port, "endpoint")
    tsrv.register("gen", _SlowEngine())
    clients = [EndpointTcpClient("mem", tsrv.port, "gen", net=h.net)
               for _ in range(2)]

    async def pump(cli, n: int) -> None:
        # back-to-back requests on one conn: the next request frame is
        # already in the server's read buffer when the previous stream
        # ends, so admissions race the idle-event wake
        for i in range(n):
            async for _item in cli.generate(Context({"i": i})):
                pass

    async def drainer() -> None:
        # everything runs at virtual t=0 (zero-length sleeps), so join
        # mid-traffic by spinning scheduling points, not by sleeping;
        # sample the drain repeatedly — every idle transition during the
        # burst is a chance for a racy wait_idle to vouch for a live one
        rounds = 0
        while not h.notes.get("traffic_done") and rounds < 12:
            rounds += 1
            for _ in range(200):
                if (tsrv._inflight.get("gen", 0) > 0
                        or h.notes.get("traffic_done")):
                    break
                await asyncio.sleep(0)
            if h.notes.get("traffic_done"):
                break
            okd = await tsrv.wait_idle("gen", timeout=120.0)
            # no await between wait_idle's return and this read: the
            # count IS the one the return value vouched for
            live = tsrv._inflight.get("gen", 0)
            h.check("drain_zero_inflight", not okd or live <= 0,
                    f"wait_idle returned True with {live} stream(s) "
                    "live")
        h.notes["drain_done"] = True

    async def traffic() -> None:
        await asyncio.gather(pump(clients[0], 4), pump(clients[1], 4))
        h.notes["traffic_done"] = True

    await asyncio.gather(traffic(), drainer())
    h.check("drain_terminates", h.notes.get("drain_done", False),
            "wait_idle never returned")
    for cli in clients:
        try:
            await asyncio.wait_for(cli.close(), 10.0)
        except (asyncio.TimeoutError, ConnectionError, OSError):
            pass
    await tsrv.stop()
    await h.teardown()


async def _run_kv_persist(h: Harness) -> None:
    try:
        import numpy as np
        from dynamo_tpu.llm.kv.persist import (
            PersistentKvStore,
            PersistReplicator,
        )
    except ImportError:   # pragma: no cover - numpy is baked into the image
        h.notes["skipped"] = "numpy/persist unavailable"
        return
    srv, ok = await h.start_coordinator(durable=True)
    if not ok:
        srv, ok = await h.start_coordinator(durable=True)
        if not ok:
            await h.teardown()
            return
    c_a = await h.client()
    c_b = await h.client()
    store_a = PersistentKvStore(h.root / "nodeA", "gen1")
    await asyncio.to_thread(
        store_a.spill, [101, 102],
        np.arange(8, dtype=np.float32).reshape(2, 4))
    await asyncio.to_thread(
        store_a.spill, [103, 104],
        np.arange(8, 16, dtype=np.float32).reshape(2, 4))
    repl_cls = h.pick("replicator", None)
    repl_cls = repl_cls() if callable(repl_cls) and repl_cls is not None \
        else PersistReplicator
    ra = repl_cls(c_a, store_a, namespace="ns")
    await h.op(ra.publish_once)
    if h.crash_fired:
        ok2 = False
        for _ in range(2):
            srv2, ok2 = await h.start_coordinator(durable=True,
                                                  port=h.coord_port)
            if ok2:
                break
        h.check("recovery_restarts", ok2, "restart after crash failed")
        await h.op(ra.publish_once)   # heal: republish what the crash ate
    store_b = PersistentKvStore(h.root / "nodeB", "gen1")
    rb = PersistReplicator(c_b, store_b, namespace="ns")
    await h.op(rb.pull_once)
    h.check("persist_converges",
            set(store_b._files) == set(store_a._files),
            f"replica B has {sorted(store_b._files)}, "
            f"A has {sorted(store_a._files)}")
    h.check("persist_no_duplicate_blocks",
            store_b.resident_blocks == len(set(store_b.resident_hashes())),
            "replica B indexed a block twice")
    h.check("persist_sha_verified", store_b.invalid_files == 0,
            f"{store_b.invalid_files} corrupt file(s) imported")
    store_a.close()
    store_b.close()
    await h.teardown()


async def _run_kv_stream(h: Harness) -> None:
    """Streamed layer-wise KV handoff (llm/kv/stream.py) under the sever
    matrix: a two-chunk/two-layer session on conn 1, with the prefill
    worker's fallback ladder (reconnect + whole-cache push + notify) on
    any stream failure, plus a deliberately torn completion every run.
    Invariant: the decode side either applies a sha-verified COMPLETE
    cache or nothing — and notify never precedes the applied KV."""
    try:
        import numpy as np
    except ImportError:   # pragma: no cover - numpy is baked into the image
        h.notes["skipped"] = "numpy unavailable"
        return
    from dynamo_tpu.llm.kv.stream import KvStreamSession
    from dynamo_tpu.llm.kv.transfer import KvTransferClient, KvTransferServer

    ops: list[tuple] = []

    async def sink(ids, arr, rid) -> None:
        ops.append(("apply", rid, [int(b) for b in ids],
                    np.asarray(arr).copy()))

    async def notify(rid, first_token, error) -> None:
        ops.append(("notify", rid, int(first_token), error))

    srv = KvTransferServer(write_sink=sink, notify_cb=notify,
                           host="mem", net=h.net)
    await srv.start()
    h.net.name_port(srv.port, "kvxfer")
    url = f"tcp://mem:{srv.port}"

    rng = np.random.default_rng(7)
    chunks = [rng.standard_normal((2, 2, 3)).astype(np.float32)
              for _ in range(2)]           # 2 chunks of [L=2, n=2, 3]
    full = np.concatenate(chunks, axis=1)  # [L=2, n=4, 3]
    spans = [[0, 1], [2, 3]]

    async def close_quiet(cli) -> None:
        try:
            await asyncio.wait_for(cli.close(), 10.0)
        except (asyncio.TimeoutError, ConnectionError, OSError):
            pass

    notify_early = h.pick("stream_notify_early", False)

    async def streamed() -> bool:
        cli = await KvTransferClient.connect(url, net=h.net,
                                             force_tcp=True)
        try:
            sess = KvStreamSession(cli, "r1", num_layers=2,
                                   session_id="s-r1")
            await sess.begin()
            if notify_early:
                await cli.notify("r1", 7)
            for ids, arr in zip(spans, chunks):
                await sess.write_chunk(ids, arr, compute_live=True)
            await sess.end()
            if not notify_early:
                await cli.notify("r1", 7)
            return True
        except (ConnectionError, RuntimeError, OSError,
                asyncio.TimeoutError):
            return False
        finally:
            await close_quiet(cli)

    if not await streamed():
        # fallback ladder, exactly as llm/workers.py runs it: fresh
        # connection (the severed one is dead), whole-cache push, notify
        cli = await KvTransferClient.connect(url, net=h.net,
                                             force_tcp=True)
        try:
            await cli.write_blocks([0, 1, 2, 3], full, request_id="r1")
            await cli.notify("r1", 7)
        finally:
            await close_quiet(cli)

    # deliberately torn completion, every run: right frames, wrong sha —
    # the END must be rejected and NOTHING applied for r2
    torn_rejected = False
    cli2 = await KvTransferClient.connect(url, net=h.net, force_tcp=True)
    try:
        sess2 = KvStreamSession(cli2, "r2", num_layers=2,
                                session_id="s-r2")
        await sess2.begin()
        await sess2.write_chunk(spans[0], chunks[0], compute_live=False)
        try:
            await cli2.stream_end(
                {"session": "s-r2", "frames": 2, "sha": "0" * 64})
        except RuntimeError:
            torn_rejected = True
    finally:
        await close_quiet(cli2)

    applies = [o for o in ops if o[0] == "apply" and o[1] == "r1"]
    h.check("stream_no_partial_admit",
            all(o[2] == [0, 1, 2, 3] and np.array_equal(o[3], full)
                for o in applies),
            "decode admitted partial or wrong KV")
    h.check("stream_delivered", len(applies) >= 1,
            "no complete cache ever applied (stream AND fallback lost)")
    first_apply = next((i for i, o in enumerate(ops)
                        if o[0] == "apply" and o[1] == "r1"), None)
    notifies = [i for i, o in enumerate(ops)
                if o[0] == "notify" and o[1] == "r1"]
    h.check("stream_notify_ordered",
            bool(notifies) and first_apply is not None
            and first_apply < notifies[0],
            "notify raced ahead of the applied KV")
    h.check("stream_torn_is_miss",
            torn_rejected and not any(
                o[0] == "apply" and o[1] == "r2" for o in ops),
            "torn completion frame was admitted")
    await srv.stop()
    await h.teardown()


async def _run_router_shard(h: Harness) -> None:
    """Sharded control plane (llm/kv_router/shards) under membership
    churn on the real coordinator pub/sub plane: two replicas host a
    4-shard partition fed from the live KV event stream; a third joins
    (index handoff with generation fence), then one replica is
    half-partitioned (serves scatters, sees neither events nor
    membership) and declared dead.  Safety: a gather never merges a
    reply whose generation it did not ask for, so scores never exceed
    the singleton truth index — the stale-generation bug variant breaks
    exactly this.  Liveness: a gather with missing shards still
    completes, degraded."""
    from dynamo_tpu.llm.kv.events import (
        KvRemovedEvent,
        KvStoredEvent,
        event_to_wire,
    )
    from dynamo_tpu.llm.kv_router.indexer import KvIndexer
    from dynamo_tpu.llm.kv_router.shards import (
        PubSubShardClient,
        ShardReplica,
        gather_overlaps,
        shard_of,
    )
    from dynamo_tpu.tokens import sequence_hashes

    replica_cls = h.pick("shard_replica", None)
    replica_cls = replica_cls() if callable(replica_cls) \
        and replica_cls is not None else ShardReplica

    srv, ok = await h.start_coordinator(durable=False)
    if not ok:
        await h.teardown()
        return
    n_shards = 4
    clean = h.fault is None
    ev_subject = "ns.kv_events.w"
    net_errs = (ConnectionError, OSError, RuntimeError,
                asyncio.TimeoutError)

    async def up(replica: "ShardReplica") -> bool:
        try:
            await replica.start()
            await replica.subscribe_events(ev_subject)
            return True
        except net_errs:
            return False

    ca, cb, cg = await h.client(), await h.client(), await h.client()
    ra = replica_cls(ca, "ra", n_shards, namespace="ns")
    rb = replica_cls(cb, "rb", n_shards, namespace="ns")
    ra_ok, rb_ok = await up(ra), await up(rb)
    await asyncio.sleep(1.0)
    if clean:
        h.check("shard_replicas_start", ra_ok and rb_ok,
                "replica registration failed on a fault-free run")
        h.check("shard_maps_converge",
                ra.map.generation == rb.map.generation
                and ra.map.owners == rb.map.owners,
                f"maps diverge: ra gen {ra.map.generation} owners "
                f"{ra.map.owners} vs rb gen {rb.map.generation} owners "
                f"{rb.map.owners}")

    # worker KV events on the live plane: w1 and w2 share a 3-block
    # prefix, w1 continues for 3 more blocks; truth is a singleton
    # KvIndexer fed the same logical events directly
    truth = KvIndexer(use_native=False)
    seq1 = sequence_hashes(list(range(1, 97)), 16)               # 6 blocks
    seq2 = sequence_hashes(
        list(range(1, 49)) + list(range(1000, 1048)), 16)        # 3+3 blocks
    eid = 0
    for wid, hashes in ((1, seq1), (2, seq2)):
        ev = KvStoredEvent(block_hashes=list(hashes))
        truth.apply_event(wid, ev)
        eid += 1
        st, _ = await h.op(cg.publish, ev_subject,
                           event_to_wire(eid, wid, ev))
    await asyncio.sleep(1.0)

    query = list(seq1)
    probes = []
    for s in range(n_shards):
        cli = PubSubShardClient(cg, "ns", s, "g")
        try:
            await cli.start()
        except net_errs:
            pass          # probes through a dead inbox just time out
        probes.append(cli)

    async def scatter(generation: int) -> dict:
        async def one(cli):
            try:
                return await asyncio.wait_for(
                    cli.probe(query, generation), 5.0)
            except net_errs:
                return None
        results = await asyncio.gather(*(one(c) for c in probes))
        return dict(enumerate(results))

    def overcount(scores, ref) -> str:
        bad = [(w, s, ref.scores.get(w, 0))
               for w, s in scores.scores.items()
               if s > ref.scores.get(w, 0)]
        bad += [(w, s, ref.persist_scores.get(w, 0))
                for w, s in scores.persist_scores.items()
                if s > ref.persist_scores.get(w, 0)]
        return ", ".join(f"w{w}: {s} > truth {t}" for w, s, t in bad)

    tr = truth.find_matches(query)
    gen1 = ra.map.generation
    scores1, partial1 = gather_overlaps(query, n_shards,
                                        await scatter(gen1), gen1)
    if clean:
        h.check("shard_gather_matches_truth",
                not partial1 and scores1.scores == tr.scores
                and scores1.persist_scores == tr.persist_scores,
                f"clean gather {scores1.scores} (partial={partial1}) != "
                f"singleton truth {tr.scores}")

    # third replica joins: the ranges it inherits predate its event
    # subscription, so every byte it serves for them arrived via the
    # handoff frames its join triggered
    cc = await h.client()
    rc = replica_cls(cc, "rc", n_shards, namespace="ns")
    rc_ok = await up(rc)
    await asyncio.sleep(2.0)
    gen2 = ra.map.generation
    if clean:
        h.check("shard_maps_converge",
                rc_ok and ra.map.owners == rb.map.owners == rc.map.owners
                and ra.map.generation == rb.map.generation
                == rc.map.generation,
                "maps did not reconverge after a join")
        scores2, partial2 = gather_overlaps(query, n_shards,
                                            await scatter(gen2), gen2)
        h.check("shard_handoff_delivers",
                not partial2 and scores2.scores == tr.scores,
                f"post-join gather {scores2.scores} (partial={partial2}) "
                f"!= truth {tr.scores} — moved ranges lost in handoff")

    # half-partition the replica owning the query's 4th position: its
    # scatter subscriptions stay live (it still answers probes) but it
    # sees neither further events nor the membership change that
    # declares it dead — the stale-shard-after-handoff surface
    by_id = {"ra": ra, "rb": rb, "rc": rc}
    victim = by_id.get(
        ra.map.owner(shard_of(query[3], n_shards))) or rb
    if victim._ev_sub is not None:
        await h.op(victim.coord.unsubscribe, victim._ev_sub)
        victim._ev_sub = None
    if victim._watch_id is not None:
        await h.op(victim.coord.unwatch, victim._watch_id)
        victim._watch_id = None
    if victim._lease is not None:
        await h.op(victim.coord.lease_revoke, victim._lease)
        victim._lease = None
    await asyncio.sleep(2.0)
    survivors = [r for r in (ra, rb, rc) if r is not victim]
    gen3 = survivors[0].map.generation
    if clean:
        h.check("shard_rebind_after_death", gen3 != gen2,
                "membership delete did not rebind the survivors")

    # the dead replica's blocks age out of the workers: w1 evicts its
    # tail — the removal reaches the survivors but NOT the partitioned
    # victim, whose frozen index now overstates w1
    rm = KvRemovedEvent(block_hashes=list(seq1[3:]))
    truth.apply_event(1, rm)
    eid += 1
    await h.op(cg.publish, ev_subject, event_to_wire(eid, 1, rm))
    await asyncio.sleep(1.0)
    tr3 = truth.find_matches(query)
    scores3, _partial3 = gather_overlaps(query, n_shards,
                                         await scatter(gen3), gen3)
    if clean:
        # the victim still answers its old shards with its old
        # generation; the fence must keep that data out of the merge
        h.check("shard_no_stale_overcount",
                not overcount(scores3, tr3),
                f"stale shard data merged past the fence: "
                f"{overcount(scores3, tr3)}")

    # total outage: with every replica stopped, the scatter times out
    # shard by shard and the gather still completes, fully degraded
    for r in (ra, rb, rc):
        try:
            await asyncio.wait_for(r.stop(), 10.0)
        except net_errs:
            pass
    scores4, partial4 = gather_overlaps(query, n_shards,
                                        await scatter(gen3), gen3)
    h.check("shard_gather_completes_degraded",
            partial4 and not overcount(scores4, tr3),
            f"all-shards-down gather: partial={partial4}, "
            f"scores={scores4.scores}")
    for cli in probes:
        try:
            await asyncio.wait_for(cli.stop(), 10.0)
        except net_errs:
            pass
    await h.teardown()


# ----------------------------------------------------------- crash matrices


def _occurrences(label: str, count: int, budget: int) -> list[int]:
    if label.startswith(("wal.compact.", "bug.")):
        # first AND last firing: the last compaction runs against the
        # populated recovery WAL — the interesting window
        return sorted({0, count - 1})
    return list(range(min(count, budget)))


def _wal_plans(base: "RunResult", budget: int) -> list[CrashPlan]:
    plans: list[CrashPlan] = []
    for label in sorted(base.census):
        count = base.census[label]
        if label.startswith(("wal.", "bug.")):
            modes = (("proc", "power", "torn")
                     if label.startswith("wal.append.") else ("proc",))
            for occ in _occurrences(label, count, budget):
                for mode in modes:
                    plans.append(CrashPlan("crash", label, occ, mode))
        elif label == "frame.send.reply":
            for occ in range(min(count, budget)):
                plans.append(CrashPlan("crash", label, occ, "proc"))
    return plans


def _queue_plans(base: "RunResult", budget: int) -> list[CrashPlan]:
    # sever the CONSUMER's transport at each of its first k complete
    # frames, both directions (conn 2: clients dial in order, producer
    # first) — the s2c cut at the ping reply kills the conn while the
    # long-poll pull is parked in the server
    plans = []
    for direction in ("s2c", "c2s"):
        frames = base.frame_counts.get(f"coord/2/{direction}", 0)
        cap = min(frames, 3 * budget)
        plans.extend(
            CrashPlan(kind="sever", conn=2, after_frames=k + 1,
                      direction=direction)
            for k in range(cap))
    return plans


def _stream_plans(base: "RunResult", budget: int) -> list[CrashPlan]:
    # sever the streaming connection (conn 1: the producer dials first)
    # at every complete frame, both directions — each c2s cut lands at a
    # different layer-frame boundary of the session, each s2c cut drops
    # a different ack/reply, so the matrix covers "torn at layer k" for
    # every k plus "END applied but ack lost"
    plans: list[CrashPlan] = []
    for direction in ("s2c", "c2s"):
        frames = base.frame_counts.get(f"kvxfer/1/{direction}", 0)
        cap = min(frames, 6 * budget)
        plans.extend(
            CrashPlan(kind="sever", conn=1, after_frames=k + 1,
                      direction=direction)
            for k in range(cap))
    return plans


def _shard_plans(base: "RunResult", budget: int) -> list[CrashPlan]:
    # sever a replica's conn (2: clients dial ra, rb, gatherer, rc) and
    # the gatherer's (3) at spread frame offsets, both directions —
    # replica death lands mid-scatter, mid-handoff and mid-membership
    # depending on the offset; the gatherer cut exercises partial
    # gathers and probe-publish failures
    plans: list[CrashPlan] = []
    for conn in (2, 3):
        for direction in ("s2c", "c2s"):
            frames = base.frame_counts.get(f"coord/{conn}/{direction}", 0)
            if not frames:
                continue
            cap = min(frames, 3 * budget)
            cuts = sorted({max(1, (k + 1) * frames // (cap + 1))
                           for k in range(cap)})
            plans.extend(
                CrashPlan(kind="sever", conn=conn, after_frames=n,
                          direction=direction)
                for n in cuts)
    return plans


def _persist_plans(base: "RunResult", budget: int) -> list[CrashPlan]:
    plans: list[CrashPlan] = []
    for label in sorted(base.census):
        if label in ("wal.append.blob", "wal.append.kv",
                     "frame.send.reply"):
            for occ in range(min(base.census[label], budget)):
                plans.append(CrashPlan("crash", label, occ, "proc"))
    return plans


@dataclass(frozen=True)
class Scenario:
    name: str
    run: Callable
    invariants: tuple[str, ...]
    touches: tuple[str, ...]
    post_check: Optional[Callable] = None
    plans: Optional[Callable] = None
    seeds: int = 3


SCENARIOS: dict[str, Scenario] = {
    s.name: s for s in [
        Scenario(
            name="coord.wal",
            run=_run_coord_wal,
            post_check=_post_coord_wal,
            plans=_wal_plans,
            seeds=3,
            invariants=(
                "recovery_restarts", "post_recovery_liveness",
                "wal_replay_idempotent", "wal_version_head",
                "kv_acked_durable", "queue_acked_durable",
                "queue_acked_consumed", "queue_no_duplicates",
                "blob_acked_durable", "leased_keys_ephemeral",
            ),
            touches=("runtime/transports/coordinator",
                     "runtime/transports/framing",
                     "runtime/transports/protocol",
                     "runtime/transports/net"),
        ),
        Scenario(
            name="coord.reconnect",
            run=_run_coord_reconnect,
            seeds=4,
            invariants=("recovery_restarts", "exactly_one_owner",
                        "reregister_idempotent", "no_orphan_lease_keys"),
            touches=("runtime/transports/coordinator",
                     "runtime/transports/protocol"),
        ),
        Scenario(
            name="coord.queue",
            run=_run_coord_queue,
            plans=_queue_plans,
            seeds=3,
            invariants=("queue_no_lost", "queue_drained",
                        "consumer_terminates"),
            touches=("runtime/transports/coordinator",
                     "runtime/transports/protocol", "fault/"),
        ),
        Scenario(
            name="router.index",
            run=_run_router_index,
            seeds=2,
            invariants=("recovery_restarts", "router_index_matches",
                        "router_converges"),
            touches=("runtime/transports/coordinator",
                     "runtime/distributed"),
        ),
        Scenario(
            name="tcp.drain",
            run=_run_tcp_drain,
            seeds=6,
            invariants=("drain_zero_inflight", "drain_terminates"),
            touches=("runtime/transports/tcp", "runtime/distributed",
                     "fault/"),
        ),
        Scenario(
            name="kv.persist",
            run=_run_kv_persist,
            plans=_persist_plans,
            seeds=2,
            invariants=("recovery_restarts", "persist_converges",
                        "persist_no_duplicate_blocks",
                        "persist_sha_verified"),
            touches=("llm/kv/persist", "runtime/transports/coordinator"),
        ),
        Scenario(
            name="kv.stream",
            run=_run_kv_stream,
            plans=_stream_plans,
            seeds=3,
            invariants=("stream_no_partial_admit", "stream_delivered",
                        "stream_torn_is_miss", "stream_notify_ordered"),
            touches=("llm/kv/stream", "llm/kv/transfer",
                     "runtime/transports/framing",
                     "runtime/transports/protocol"),
        ),
        Scenario(
            name="router.shard",
            run=_run_router_shard,
            plans=_shard_plans,
            seeds=3,
            invariants=("shard_replicas_start", "shard_maps_converge",
                        "shard_gather_matches_truth",
                        "shard_handoff_delivers",
                        "shard_rebind_after_death",
                        "shard_no_stale_overcount",
                        "shard_gather_completes_degraded"),
            touches=("llm/kv_router/shards", "llm/kv_router/indexer",
                     "utils/chash",
                     "runtime/transports/coordinator"),
        ),
    ]
}


# ------------------------------------------------------------------ runner


@dataclass
class RunResult:
    scenario: str
    seed: int
    crash: Optional[CrashPlan]
    bug: Optional[str]
    outcome: str = "ok"
    error: str = ""
    violations: list = field(default_factory=list)
    trace: list = field(default_factory=list)
    choices: list = field(default_factory=list)
    census: dict = field(default_factory=dict)
    channels: dict = field(default_factory=dict)
    frame_counts: dict = field(default_factory=dict)
    token: str = ""


def _op_of(header: dict) -> str:
    return header.get("op") or header.get("type") or "reply"


def run_one(scenario: Scenario, seed: int, *,
            crash: Optional[CrashPlan] = None, bug: Optional[str] = None,
            forced: Optional[list[int]] = None) -> RunResult:
    """One deterministic execution of a scenario: seeded schedule,
    optional crash/sever plan, optional bug variant, optional forced
    choice list (replay)."""
    tmp = Path(tempfile.mkdtemp(prefix="dtproto-"))
    loop = DetLoop(make_scheduler(seed), forced_choices=forced)
    net = MemNet(loop)
    h = Harness(loop, net, tmp, bug=bug, crash=crash)
    outcome, err = "ok", ""
    # modeled deaths routinely fail background tasks; keep the noise out
    # of stderr (the loop collects exception contexts instead)
    loggers = [logging.getLogger("dynamo_tpu"),
               logging.getLogger("dynamo_tpu.fault")]
    saved_levels = [lg.level for lg in loggers]
    for lg in loggers:
        lg.setLevel(logging.CRITICAL)
    try:
        try:
            from dynamo_tpu.analysis.detloop import run_deterministic

            run_deterministic(loop, scenario.run(h))
        except DeadlockError as e:
            outcome, err = "deadlock", str(e)
        except HorizonExceeded as e:
            outcome, err = "horizon", str(e)
        except ReplayMismatch as e:
            outcome, err = "replay-mismatch", str(e)
        except SimulatedCrash as e:
            # a crash unwound into the driver itself (death during a
            # scripted start the scenario chose not to retry) — the
            # post-run recovery checks still judge the disk state
            err = str(e)
        finally:
            loop.close()
        if scenario.post_check is not None:
            scenario.post_check(h)
    finally:
        for lg, lvl in zip(loggers, saved_levels):
            lg.setLevel(lvl)
        shutil.rmtree(tmp, ignore_errors=True)
    channels = {}
    for (svc, direction), headers in net.channel_frames().items():
        channels[f"{svc}:{direction}"] = [_op_of(hd) for hd in headers]
    frame_counts = {
        f"{net.port_names.get(port, f'port{port}')}/{conn}/{direction}":
            ctr.count
        for (port, conn, direction), ctr in sorted(net._counters.items())
    }
    payload: dict[str, Any] = {"scenario": scenario.name, "seed": seed,
                               "choices": list(loop.choices)}
    if bug:
        payload["bug"] = bug
    if crash:
        payload["crash"] = asdict(crash)
    return RunResult(
        scenario=scenario.name, seed=seed, crash=crash, bug=bug,
        outcome=outcome, error=err, violations=list(h.violations),
        trace=list(loop.trace), choices=list(loop.choices),
        census=dict(h.crash_census), channels=channels,
        frame_counts=frame_counts, token=encode_token(payload),
    )


def replay_token(token: str) -> RunResult:
    """Re-execute the exact interleaving a replay token encodes."""
    payload = decode_token(token)
    scenario = SCENARIOS[payload["scenario"]]
    return run_one(
        scenario, payload["seed"],
        crash=CrashPlan.from_json(payload.get("crash")),
        bug=payload.get("bug"),
        forced=list(payload.get("choices", [])),
    )


# -------------------------------------------------------------- exploration


@dataclass
class ScenarioReport:
    scenario: str
    results: list[RunResult]
    deterministic: bool = True


def explore_scenario(scenario: Scenario, *, seed_base: int = 0,
                     budget: int = 1,
                     bug: Optional[str] = None) -> ScenarioReport:
    """Seed sweep + determinism self-check + crash/sever matrix."""
    results = [run_one(scenario, seed_base + i, bug=bug)
               for i in range(max(1, scenario.seeds * budget))]
    base = results[0]
    twin = run_one(scenario, seed_base, bug=bug)
    deterministic = twin.trace == base.trace
    if scenario.plans is not None:
        for plan in scenario.plans(base, budget):
            results.append(
                run_one(scenario, seed_base, crash=plan, bug=bug))
    return ScenarioReport(scenario.name, results, deterministic)


def first_violation(report: ScenarioReport) -> Optional[RunResult]:
    for r in report.results:
        if r.violations or r.outcome != "ok":
            return r
    return None


def facts_from(reports: list[ScenarioReport]) -> dict:
    """Discovered protocol facts: per-channel op state machines (states
    + transition edges, unioned over every pinned run so crash-recovery
    edges are included), the crash-point census of the base run, and
    the invariant registry."""
    scenarios: dict[str, dict] = {}
    for rep in reports:
        chans: dict[str, dict[str, set]] = {}
        for r in rep.results:
            for ch, ops in r.channels.items():
                d = chans.setdefault(ch, {"states": set(), "edges": set()})
                d["states"].update(ops)
                d["edges"].update(
                    f"{a}>{b}" for a, b in zip(ops, ops[1:]))
        base = rep.results[0]
        scenarios[rep.scenario] = {
            "channels": {
                ch: {"states": sorted(d["states"]),
                     "edges": sorted(d["edges"])}
                for ch, d in sorted(chans.items())
            },
            "crash_points": dict(sorted(base.census.items())),
            "invariants": sorted(
                SCENARIOS[rep.scenario].invariants),
        }
    return scenarios


def check_proto(reports: list[ScenarioReport], manifest: ProtoManifest,
                *, drift: bool = True) -> list[ProtoFinding]:
    findings: list[ProtoFinding] = []
    for rep in reports:
        seen: set[tuple[str, str]] = set()
        for r in rep.results:
            if r.outcome != "ok" and ("PR003", r.outcome) not in seen:
                seen.add(("PR003", r.outcome))
                findings.append(ProtoFinding(
                    rep.scenario, "PR003", r.outcome,
                    f"{r.error or r.outcome} [replay {r.token}]"))
            for inv, msg in r.violations:
                if ("PR001", inv) in seen:
                    continue
                seen.add(("PR001", inv))
                findings.append(ProtoFinding(
                    rep.scenario, "PR001", inv,
                    f"{msg} [replay {r.token}]"))
        if not rep.deterministic:
            findings.append(ProtoFinding(
                rep.scenario, "PR002", "determinism",
                "two runs with the same seed produced different "
                "schedule traces"))
    if not drift:
        return findings
    observed = facts_from(reports)
    for name, facts in sorted(observed.items()):
        committed = manifest.scenarios.get(name)
        if committed is None:
            findings.append(ProtoFinding(
                name, "PR004", "+scenario",
                "scenario absent from the committed proto manifest "
                "(run --proto --update-baseline)"))
            continue
        com_ch = committed.get("channels", {})
        for ch, d in facts["channels"].items():
            want = com_ch.get(ch, {"states": [], "edges": []})
            for edge in sorted(set(d["edges"]) - set(want["edges"])):
                findings.append(ProtoFinding(
                    name, "PR004", f"{ch}+{edge}",
                    f"new transition {edge} on {ch} not in the "
                    "committed state machine"))
            for edge in sorted(set(want["edges"]) - set(d["edges"])):
                findings.append(ProtoFinding(
                    name, "PR004", f"{ch}-{edge}",
                    f"committed transition {edge} on {ch} no longer "
                    "reachable"))
        for ch in sorted(set(com_ch) - set(facts["channels"])):
            findings.append(ProtoFinding(
                name, "PR004", f"{ch}-channel",
                f"committed channel {ch} no longer observed"))
        com_labels = set(committed.get("crash_points", {}))
        obs_labels = set(facts["crash_points"])
        for lbl in sorted(obs_labels - com_labels):
            findings.append(ProtoFinding(
                name, "PR005", f"+{lbl}",
                f"new crash point {lbl} not in the committed census"))
        for lbl in sorted(com_labels - obs_labels):
            findings.append(ProtoFinding(
                name, "PR005", f"-{lbl}",
                f"committed crash point {lbl} no longer fires"))
    return findings


# --------------------------------------------------------------- CLI entry


def _budget_env() -> tuple[int, int, bool]:
    budget = max(1, int(os.environ.get("DTPROTO_BUDGET", "1") or 1))
    seed_base = int(os.environ.get("DTPROTO_SEED_BASE", "0") or 0)
    pinned = budget == 1 and seed_base == 0
    return budget, seed_base, pinned


def affected_scenarios(root: Path) -> list[str]:
    """Scenarios whose protocol code is git-dirty (``--changed``)."""
    from dynamo_tpu.analysis.cli import _git_changed_paths

    dirty = [str(p) for p in _git_changed_paths(root)]
    if any("analysis/protocheck" in d or "analysis/detloop" in d
           for d in dirty):
        return list(SCENARIOS)
    names = []
    for name, sc in SCENARIOS.items():
        if any(frag in d for d in dirty for frag in sc.touches):
            names.append(name)
    return names


def run_proto(args, out) -> int:
    """``dynamo-tpu lint --proto``: text or stable JSON, exit 1 on any
    non-accepted finding, ``--update-baseline`` re-snapshots the proto
    manifest (carrying justifications by key), ``--replay TOKEN``
    re-executes one recorded interleaving instead of sweeping."""
    token = getattr(args, "replay", None)
    if token:
        res = replay_token(token)
        if getattr(args, "fmt", "text") == "json":
            doc = {"scenario": res.scenario, "seed": res.seed,
                   "bug": res.bug, "outcome": res.outcome,
                   "error": res.error,
                   "violations": [list(v) for v in res.violations],
                   "steps": len(res.trace)}
            if res.crash:
                doc["crash"] = asdict(res.crash)
            print(json.dumps(doc, indent=2, sort_keys=True), file=out)
        else:
            head = f"{res.scenario} seed={res.seed}"
            if res.bug:
                head += f" bug={res.bug}"
            if res.crash:
                head += (f" crash={res.crash.kind}:{res.crash.label}"
                         f"#{res.crash.occurrence}")
            print(f"{head}: outcome={res.outcome}, "
                  f"{len(res.trace)} scheduled steps", file=out)
            for inv, msg in res.violations:
                print(f"  violated: {inv} - {msg}", file=out)
            if res.error:
                print(f"  error: {res.error}", file=out)
        return 1 if (res.violations or res.outcome != "ok") else 0
    manifest_path = Path(
        getattr(args, "manifest", None) or DEFAULT_PROTO_MANIFEST_PATH)
    manifest = ProtoManifest.load(manifest_path)
    budget, seed_base, pinned = _budget_env()
    root = Path(getattr(args, "root", None)
                or Path(__file__).resolve().parents[2])
    names = list(SCENARIOS)
    subset = False
    if getattr(args, "changed", False):
        names = affected_scenarios(root)
        subset = len(names) < len(SCENARIOS)
        if not names:
            print("0 protocol scenarios affected by changed files",
                  file=out)
            return 0
    reports = [
        explore_scenario(SCENARIOS[n], seed_base=seed_base, budget=budget)
        for n in names
    ]
    facts = facts_from(reports)
    # drift rules only judge the pinned full sweep: fresh seeds or a
    # bigger budget legitimately discover new edges, and a --changed
    # subset can't see every committed scenario
    drift = pinned and not subset
    findings = check_proto(reports, manifest, drift=drift)
    n_runs = sum(len(rep.results) for rep in reports) + len(reports)

    if getattr(args, "update_baseline", False):
        if subset or not pinned:
            print("refusing to update the proto manifest from a partial "
                  "or non-default-budget run", file=out)
            return 2
        keep = [f for f in findings if f.rule not in _DRIFT_RULES]
        ProtoManifest.from_facts(facts, keep, manifest).save(manifest_path)
        print(
            f"proto manifest updated: {len(facts)} scenario"
            f"{'' if len(facts) == 1 else 's'}, {len(keep)} accepted "
            f"finding{'' if len(keep) == 1 else 's'} -> {manifest_path}",
            file=out,
        )
        return 0

    fresh = manifest.filter(findings)
    n_accepted = len(findings) - len(fresh)
    if getattr(args, "fmt", "text") == "json":
        doc = {
            "findings": [f.to_json() for f in fresh],
            "accepted": n_accepted,
            "total": len(findings),
            "scenarios": sorted(names),
            "runs": n_runs,
        }
        print(json.dumps(doc, indent=2, sort_keys=True), file=out)
    else:
        for f in fresh:
            print(f.render(), file=out)
        print(
            f"{len(fresh)} protocol finding"
            f"{'s' if len(fresh) != 1 else ''} ({n_accepted} accepted) "
            f"over {len(names)} scenario{'s' if len(names) != 1 else ''},"
            f" {n_runs} deterministic runs",
            file=out,
        )
    return 1 if fresh else 0
