"""Three-plane correctness tool (docs/static_analysis.md).

Plane A (static source): per-file async-safety + JAX/TPU rules (core.py,
rules_async.py, rules_jax.py) and the interprocedural project pass
(project.py, DT005-DT008) with a shared baseline and a zero-findings
tier-1 gate.  Plane B (dynamic): the dtsan runtime sanitizer
(sanitizer.py + pytest_sanitizer.py) — task-leak checking on by default
in tier-1, full instrumentation under ``DYNAMO_SANITIZE=1``.  Plane C
(compile): the dttrace jaxpr/HLO audit (tracecheck.py, TR001-TR007) —
trace-signature census, donation aliasing, dtype propagation, and static
HBM footprint per jitted entrypoint against the committed
``trace_manifest.json`` (``dynamo-tpu lint --trace``).

tracecheck is imported lazily (it pulls in jax + the engine); reach it
via ``dynamo_tpu.analysis.tracecheck``."""

from dynamo_tpu.analysis.core import (
    DEFAULT_BASELINE_PATH,
    Baseline,
    Finding,
    Rule,
    all_rules,
    lint_file,
    lint_paths,
)
from dynamo_tpu.analysis.project import (
    ProjectIndex,
    ProjectRule,
    lint_project,
    project_rules,
)

__all__ = [
    "DEFAULT_BASELINE_PATH",
    "Baseline",
    "Finding",
    "Rule",
    "all_rules",
    "lint_file",
    "lint_paths",
    "ProjectIndex",
    "ProjectRule",
    "lint_project",
    "project_rules",
]
