"""Two-plane concurrency correctness tool (docs/static_analysis.md).

Plane A (static): per-file async-safety + JAX/TPU rules (core.py,
rules_async.py, rules_jax.py) and the interprocedural project pass
(project.py, DT005-DT008) with a shared baseline and a zero-findings
tier-1 gate.  Plane B (dynamic): the dtsan runtime sanitizer
(sanitizer.py + pytest_sanitizer.py) — task-leak checking on by default
in tier-1, full instrumentation under ``DYNAMO_SANITIZE=1``."""

from dynamo_tpu.analysis.core import (
    DEFAULT_BASELINE_PATH,
    Baseline,
    Finding,
    Rule,
    all_rules,
    lint_file,
    lint_paths,
)
from dynamo_tpu.analysis.project import (
    ProjectIndex,
    ProjectRule,
    lint_project,
    project_rules,
)

__all__ = [
    "DEFAULT_BASELINE_PATH",
    "Baseline",
    "Finding",
    "Rule",
    "all_rules",
    "lint_file",
    "lint_paths",
    "ProjectIndex",
    "ProjectRule",
    "lint_project",
    "project_rules",
]
