"""Static-analysis suite: async-safety + JAX/TPU rules with a baseline
and a zero-findings tier-1 gate (docs/static_analysis.md)."""

from dynamo_tpu.analysis.core import (
    DEFAULT_BASELINE_PATH,
    Baseline,
    Finding,
    Rule,
    all_rules,
    lint_file,
    lint_paths,
)

__all__ = [
    "DEFAULT_BASELINE_PATH",
    "Baseline",
    "Finding",
    "Rule",
    "all_rules",
    "lint_file",
    "lint_paths",
]
