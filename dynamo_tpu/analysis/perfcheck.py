"""Perf-plane static analysis (dtperf): HLO-derived roofline cost model.

The compile plane (tracecheck) proves the hot loop *compiles* the way
the scheduler assumes — one executable per declared bucket, donation
aliased, no f32 upcasts.  It says nothing about how *fast* any of it
should be, and with the TPU tunnel down (ROADMAP standing note) no perf
claim in this repo is currently verifiable on hardware.  This plane
closes that gap analytically: for every registered jitted serving
entrypoint (the tracecheck registry — five EngineCore impls, draft
proposer, block scatter, Llama/DeepSeek forwards, Pallas ops via their
XLA fallback lowerings — plus the ring-attention shard_map body traced
over an abstract 4-chip mesh), the jaxpr is walked **shape-only on
CPU** and every equation is priced:

- ``dot_general`` / ``conv_general_dilated``: ``2 * out_size * K``
  FLOPs (dtype-aware — int8 dots run at 2x the bf16 MXU rate on v5e,
  f32 at half), bytes = operands + outputs.
- gather/scatter/dynamic-slice classes: bytes actually touched
  (gathered output + indices; updates read + written), no FLOPs.
- reductions/sorts: one FLOP per input element; bytes in + out.
- elementwise: one FLOP per output element (transcendentals weighted
  ``TRANSCENDENTAL_WEIGHT``); **bytes = output only** — the fusion
  assumption: XLA fuses producers into consumers, so an elementwise
  input is not re-read from HBM.  Layout-only ops (reshape /
  broadcast / squeeze) are free.
- control flow: ``scan`` multiplies by its trip count, ``cond`` takes
  the most expensive branch, ``while`` charges one body iteration
  (trip count is data-dependent; documented undercount).
- collectives (``psum`` / ``all_gather`` / ``reduce_scatter`` /
  ``all_to_all`` / ``ppermute``): a census entry (op x axis x payload
  bytes x axis size) plus an analytic ring cost from the
  ``obs.topology`` constants table (v5e ICI link bandwidth, DCN).
  ``shard_map`` regions bind their mesh axis sizes into the walk, so
  per-shard shapes and axis sizes are both exact.

Per (entrypoint, config) the facts are: total FLOPs, total HBM bytes,
arithmetic intensity, the collective census, and a predicted step
latency under the roofline

    max(sum_dtype FLOPs_dt / peak_dt, bytes / peak_bw)
        + sum collective_cost

Facts snapshot into the committed ``perf_manifest.json`` with the same
justification/``--update-baseline`` contract as the trace and wire
manifests.  The header pins ``obs.topology.CONSTANTS_VERSION`` so a
constants tweak re-trips PF001 explicitly rather than silently moving
every baseline.

Rules:

- PF001 predicted-latency-regression — predicted step latency grew
  beyond the tolerance band vs the manifest (also fires with key
  ``constants`` on a topology-constants version mismatch, and with
  ``added``/``removed`` for uncovered entrypoints).
- PF002 unexpected-collective — intrinsic, count-keyed like TR006:
  every census entry needs a justified acceptance; a new collective
  op, a new axis, or a count change trips the gate until re-justified.
- PF003 arithmetic-intensity-drop — a compute-bound entrypoint lost
  intensity (more bytes per FLOP: a fusion broke, a layout copy or
  upcast appeared on the hot path).
- PF004 bytes-regression — a bandwidth-bound entrypoint's HBM traffic
  grew beyond tolerance (decode-class dispatches live on this side of
  the roofline; bytes ARE their latency).

Caveats (also recorded in the manifest header): roofline figures
derive from the CPU lowering — fusion is assumed for elementwise
chains, and ``while`` trip counts are unknowable statically.
Pallas-backed ops are priced on BOTH sides of the dispatch decision:
the roofline row walks the XLA fallback jaxpr CPU produces, and a
``pallas_kernel`` row prices the registered kernel from
``ops/pallas/registry.py``'s analytic cost table (the same table the
kernel plane commits per-geometry into ``kern_manifest.json`` and the
kernels pin on-device via ``cost_estimate=``).  The model's job is
to *rank and gate*, not to be a simulator; its absolute calibration is
itself observable at runtime through the predicted-vs-measured
dispatch gauge (``obs/perfmodel.py``, ``/metrics``) and the
serve_bench reconciliation table.
"""

from __future__ import annotations

import json
import math
from pathlib import Path
from typing import Callable, Optional

from dynamo_tpu.analysis.tracecheck import (
    Entrypoint,
    Manifest,
    Signature,
    TraceFinding,
    _bytes_of,
    _closed_call,
    _sds,
    build_registry,
)
from dynamo_tpu.obs import topology

__all__ = [
    "DEFAULT_MANIFEST_PATH",
    "PERF_RULES",
    "build_perf_registry",
    "check_perf_facts",
    "collect_perf_facts",
    "estimate_callable",
    "estimate_jaxpr",
    "manifest_predictions",
    "run_perf",
]

DEFAULT_MANIFEST_PATH = Path(__file__).parent / "perf_manifest.json"

PERF_RULES = {
    "PF001": ("predicted-latency-regression",
              "roofline-predicted step latency regressed beyond the "
              "tolerance band vs the committed perf manifest"),
    "PF002": ("unexpected-collective",
              "collective census entry (op x axis x count) without a "
              "justified acceptance in the manifest"),
    "PF003": ("arithmetic-intensity-drop",
              "compute-bound entrypoint lost arithmetic intensity "
              "(bytes grew faster than FLOPs)"),
    "PF004": ("bytes-regression",
              "bandwidth-bound entrypoint's modeled HBM traffic grew "
              "beyond the tolerance band"),
}

# Tolerance bands: relative drift vs the committed manifest that is
# attributed to model noise (bucket arithmetic, jaxpr layout churn)
# rather than a real hot-path change.
LATENCY_REL_TOL = 0.05    # PF001
INTENSITY_REL_TOL = 0.10  # PF003
BYTES_REL_TOL = 0.05      # PF004

# One transcendental (exp/log/tanh/erf/...) costs this many
# VPU-element ops in the model — the lowered polynomial/lookup chains
# are several ops long (pl.CostEstimate counts them separately for the
# same reason).
TRANSCENDENTAL_WEIGHT = 8

_MANIFEST_NOTE = (
    "CPU-derived roofline facts (jax.make_jaxpr over ShapeDtypeStructs; "
    "elementwise chains assumed fused, while-loops charged one "
    "iteration): predictions rank and gate relative changes — absolute "
    "calibration is tracked at runtime by the predicted-vs-measured "
    "dispatch gauge on /metrics and must be re-validated on-chip when "
    "the TPU tunnel returns (ROADMAP standing note).  Pallas-backed "
    "ops carry BOTH sides of the dispatch decision: the roofline row "
    "prices the XLA fallback jaxpr CPU lowers, and `pallas_kernel` "
    "prices the registered kernel from ops/pallas/registry.py's "
    "analytic cost table — the same table kerncheck commits "
    "per-geometry into kern_manifest.json and the kernels pin "
    "on-device via cost_estimate=."
)

# Entrypoints whose TPU path dispatches a registered Pallas kernel:
# their signatures additionally get a `pallas_kernel` estimate from the
# kernel registry's cost table.
_PALLAS_PRICED = {
    "ops.paged_attention_layer": "paged_decode_attention_mq",
    "ops.ragged_prefill_attention": "ragged_paged_prefill_attention",
}


# ------------------------------------------------------------ cost walking ----


class Costs:
    """Accumulator for one jaxpr walk: FLOPs by dtype, HBM bytes, and
    the collective census."""

    def __init__(self) -> None:
        self.flops_by_dtype: dict[str, float] = {}
        self.bytes: float = 0.0
        # "op:axis" -> {count, payload_bytes, axis_size, cost_s}
        self.collectives: dict[str, dict] = {}

    @property
    def flops(self) -> float:
        return sum(self.flops_by_dtype.values())

    def add_flops(self, dtype: str, n: float) -> None:
        if n:
            self.flops_by_dtype[dtype] = \
                self.flops_by_dtype.get(dtype, 0.0) + n

    def add_collective(self, op: str, axes: tuple[str, ...],
                       axis_size: int, payload: float,
                       mult: float) -> None:
        key = f"{op}:{','.join(axes) if axes else '?'}"
        cost = topology.collective_cost_s(op, axis_size, payload)
        e = self.collectives.setdefault(key, {
            "count": 0, "payload_bytes": 0.0, "axis_size": axis_size,
            "cost_s": 0.0,
        })
        e["count"] += int(mult)
        e["payload_bytes"] += payload * mult
        e["cost_s"] += cost * mult

    def merge_max(self, other: "Costs") -> None:
        """Branch merge (cond): keep the more expensive side per term."""
        for dt, n in other.flops_by_dtype.items():
            self.flops_by_dtype[dt] = max(
                self.flops_by_dtype.get(dt, 0.0), n)
        self.bytes = max(self.bytes, other.bytes)
        for k, e in other.collectives.items():
            mine = self.collectives.get(k)
            if mine is None or e["cost_s"] > mine["cost_s"]:
                self.collectives[k] = dict(e)


# Layout-only primitives: no math, and XLA either elides them or folds
# them into a neighbor's loop nest.
_FREE_PRIMS = {
    "reshape", "broadcast_in_dim", "squeeze", "expand_dims", "copy",
    "stop_gradient", "bitcast_convert_type", "sharding_constraint",
    "device_put", "sub_byte_view", "pvary", "psum_invariant",
}

# Data-movement primitives: bytes dominate, FLOPs ~ 0.  Value is a
# callable (eqn) -> bytes.
def _io_bytes(eqn) -> float:
    return (sum(_bytes_of(v.aval) for v in eqn.invars)
            + sum(_bytes_of(v.aval) for v in eqn.outvars))


def _out_bytes(eqn) -> float:
    return sum(_bytes_of(v.aval) for v in eqn.outvars)


_TRANSCENDENTALS = {
    "exp", "exp2", "expm1", "log", "log1p", "log2", "tanh", "logistic",
    "erf", "erf_inv", "erfc", "sin", "cos", "tan", "asin", "acos",
    "atan", "atan2", "sinh", "cosh", "pow", "rsqrt", "sqrt", "cbrt",
    "digamma", "lgamma",
}

_COLLECTIVE_PRIMS = {
    "psum", "pmax", "pmin", "all_gather", "all_to_all",
    "reduce_scatter", "psum_scatter", "ppermute", "pbroadcast",
}

# psum-family primitives use param "axes"; the rest use "axis_name".
def _collective_axes(eqn) -> tuple[str, ...]:
    ax = eqn.params.get("axes", eqn.params.get("axis_name", ()))
    if isinstance(ax, str):
        return (ax,)
    return tuple(str(a) for a in ax)


def _dot_flops(eqn) -> tuple[str, float]:
    """2 * out_size * K from dimension_numbers; dtype from the lhs (or
    the requested accumulation type)."""
    lhs = eqn.invars[0].aval
    (lhs_contract, _), _ = eqn.params["dimension_numbers"]
    k = 1
    for i in lhs_contract:
        k *= lhs.shape[i]
    out_size = sum(int(v.aval.size) for v in eqn.outvars)
    return str(lhs.dtype), 2.0 * out_size * k


def _conv_flops(eqn) -> tuple[str, float]:
    """2 * out_size * (kernel spatial x in-channel) per group."""
    lhs, rhs = eqn.invars[0].aval, eqn.invars[1].aval
    out = eqn.outvars[0].aval
    groups = int(eqn.params.get("feature_group_count", 1) or 1)
    dn = eqn.params.get("dimension_numbers")
    # rhs layout: spatial dims x in/group x out; per-output-element work
    # is rhs.size / out_channels
    out_feat = rhs.shape[dn.rhs_spec[0]] if dn is not None else \
        rhs.shape[-1]
    per_out = rhs.size / max(1, out_feat)
    return str(lhs.dtype), 2.0 * out.size * per_out / max(1, groups)


def _scatter_bytes(eqn) -> float:
    """Updates are read and written; indices read; the operand
    pass-through aliases (donation / XLA in-place) rather than
    rewriting the pool."""
    avals = [v.aval for v in eqn.invars[1:]]  # skip operand
    return 2.0 * sum(_bytes_of(a) for a in avals)


def _subjaxprs(eqn):
    """Sub-jaxprs of an eqn, handling both ClosedJaxpr params (pjit,
    scan, custom_*) and raw Jaxpr params (shard_map)."""
    for v in eqn.params.values():
        if hasattr(v, "jaxpr"):
            yield v.jaxpr
        elif hasattr(v, "eqns"):
            yield v
        elif isinstance(v, (list, tuple)):
            for x in v:
                if hasattr(x, "jaxpr"):
                    yield x.jaxpr
                elif hasattr(x, "eqns"):
                    yield x


def _walk(jaxpr, acc: Costs, mult: float,
          axis_env: dict[str, int]) -> None:
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name

        if name == "scan":
            length = float(eqn.params.get("length", 1) or 1)
            for sub in _subjaxprs(eqn):
                _walk(sub, acc, mult * length, axis_env)
            continue
        if name == "while":
            # trip count is data-dependent: charge one iteration of the
            # body (documented undercount; serving loops are scans)
            body = eqn.params.get("body_jaxpr")
            if body is not None:
                _walk(body.jaxpr, acc, mult, axis_env)
            continue
        if name == "cond":
            branches = [
                b.jaxpr for b in eqn.params.get("branches", ())
            ]
            worst = Costs()
            for b in branches:
                side = Costs()
                _walk(b, side, mult, axis_env)
                worst.merge_max(side)
            for dt, n in worst.flops_by_dtype.items():
                acc.add_flops(dt, n)
            acc.bytes += worst.bytes
            for k, e in worst.collectives.items():
                mine = acc.collectives.setdefault(k, {
                    "count": 0, "payload_bytes": 0.0,
                    "axis_size": e["axis_size"], "cost_s": 0.0,
                })
                mine["count"] += e["count"]
                mine["payload_bytes"] += e["payload_bytes"]
                mine["cost_s"] += e["cost_s"]
            continue
        if name == "shard_map":
            mesh = eqn.params.get("mesh")
            inner_env = dict(axis_env)
            if mesh is not None:
                inner_env.update(
                    {str(k): int(v) for k, v in dict(mesh.shape).items()}
                )
            for sub in _subjaxprs(eqn):
                _walk(sub, acc, mult, inner_env)
            continue

        if name in _COLLECTIVE_PRIMS:
            axes = _collective_axes(eqn)
            axis_size = 1
            for a in axes:
                axis_size *= axis_env.get(a, 1)
            payload = float(sum(_bytes_of(v.aval) for v in eqn.invars))
            acc.add_collective(name, axes, axis_size, payload, mult)
            continue

        if name in _FREE_PRIMS:
            continue
        # NOTE: the named classes below must come before the generic
        # sub-jaxpr recursion — scatter carries an update_jaxpr param
        # and would otherwise be priced as its (scalar) combiner
        if name == "dot_general":
            dt, f = _dot_flops(eqn)
            acc.add_flops(dt, f * mult)
            acc.bytes += _io_bytes(eqn) * mult
        elif name == "conv_general_dilated":
            dt, f = _conv_flops(eqn)
            acc.add_flops(dt, f * mult)
            acc.bytes += _io_bytes(eqn) * mult
        elif name in ("gather", "take", "take_along_axis"):
            # touched bytes: the gathered output + the index tensor
            idx = _bytes_of(eqn.invars[1].aval) if len(eqn.invars) > 1 \
                else 0
            acc.bytes += (_out_bytes(eqn) + idx) * mult
        elif name in ("dynamic_slice", "slice"):
            acc.bytes += _out_bytes(eqn) * mult
        elif name.startswith("scatter") or name == "dynamic_update_slice":
            acc.bytes += _scatter_bytes(eqn) * mult
            if "add" in name or "mul" in name:
                upd = eqn.invars[-1].aval
                acc.add_flops(str(upd.dtype), float(upd.size) * mult)
        elif name in ("concatenate", "pad", "transpose", "rev"):
            acc.bytes += _io_bytes(eqn) * mult
        elif name in ("sort", "top_k", "approx_top_k"):
            n = max(2, int(eqn.invars[0].aval.size))
            acc.add_flops(str(eqn.invars[0].aval.dtype),
                          n * math.log2(n) * mult)
            acc.bytes += _io_bytes(eqn) * mult
        elif name.startswith("reduce_") or name.startswith("cum") or \
                name in ("argmax", "argmin"):
            src = eqn.invars[0].aval
            acc.add_flops(str(src.dtype), float(src.size) * mult)
            acc.bytes += _io_bytes(eqn) * mult
        elif name == "convert_element_type":
            # a widening/narrowing pass re-materializes: both sides move
            acc.bytes += _io_bytes(eqn) * mult
        elif name == "iota":
            acc.bytes += _out_bytes(eqn) * mult
        else:
            # transparent wrappers: pjit, closed_call, custom_jvp/vjp,
            # remat — price the body
            subs = list(_subjaxprs(eqn))
            if subs:
                for sub in subs:
                    _walk(sub, acc, mult, axis_env)
                continue
            # elementwise default under the fusion assumption: one
            # (weighted) FLOP per output element, output bytes only
            out = eqn.outvars[0].aval
            if not hasattr(out, "size"):
                continue
            w = TRANSCENDENTAL_WEIGHT if name in _TRANSCENDENTALS else 1
            acc.add_flops(str(out.dtype), float(out.size) * w * mult)
            acc.bytes += _out_bytes(eqn) * mult


# ---------------------------------------------------------------- roofline ----


def _roofline(acc: Costs, topo_name: str = topology.DEFAULT_TOPOLOGY) \
        -> dict:
    topo = topology.TOPOLOGIES[topo_name]
    peaks = topo["peak_flops"]
    compute_s = sum(
        n / peaks.get(dt, topo["default_flops"])
        for dt, n in acc.flops_by_dtype.items()
    )
    memory_s = acc.bytes / topo["hbm_bw"]
    collective_s = sum(e["cost_s"] for e in acc.collectives.values())
    total_s = max(compute_s, memory_s) + collective_s
    return {
        "compute_ms": round(compute_s * 1e3, 6),
        "memory_ms": round(memory_s * 1e3, 6),
        "collective_ms": round(collective_s * 1e3, 6),
        "total_ms": round(total_s * 1e3, 6),
        "bound": "compute" if compute_s >= memory_s else "bandwidth",
    }


def estimate_jaxpr(jaxpr, axis_env: Optional[dict[str, int]] = None) \
        -> dict:
    """Price an (open) jaxpr: FLOPs/bytes/census + roofline dict."""
    acc = Costs()
    _walk(jaxpr, acc, 1.0, dict(axis_env or {}))
    flops = int(acc.flops)
    nbytes = int(acc.bytes)
    return {
        "flops": flops,
        "flops_by_dtype": {
            dt: int(n) for dt, n in sorted(acc.flops_by_dtype.items())
        },
        "bytes": nbytes,
        "intensity": round(flops / nbytes, 4) if nbytes else 0.0,
        "collectives": {
            k: {
                "count": e["count"],
                "payload_bytes": int(e["payload_bytes"]),
                "axis_size": e["axis_size"],
                "cost_us": round(e["cost_s"] * 1e6, 3),
            }
            for k, e in sorted(acc.collectives.items())
        },
        "predicted": _roofline(acc),
    }


def estimate_callable(fn: Callable, args: tuple,
                      statics: Optional[dict] = None,
                      axis_env: Optional[dict[str, int]] = None) -> dict:
    """Trace ``fn(*args, **statics)`` shape-only (args are pytrees of
    ShapeDtypeStruct) and price the jaxpr.  This is the entry the
    runtime reconciliation layer (``obs/perfmodel.py``) uses to predict
    a live dispatch's latency from its offered signature."""
    import jax

    statics = dict(statics or {})
    closed = jax.make_jaxpr(lambda *a: fn(*a, **statics))(*args)
    return estimate_jaxpr(closed.jaxpr, axis_env)


# ---------------------------------------------------------------- registry ----


def _ring_attention_entrypoint(axis_size: int = 4) -> Optional[Entrypoint]:
    """The one real collective site: the ring-attention shard_map body,
    traced over an ABSTRACT sp-axis mesh (no devices needed), so the
    committed census carries live ppermute entries with a nonzero ICI
    cost term.  Returns None when this jax build lacks AbstractMesh
    (the plane then simply has no collective entries)."""
    import functools

    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    try:
        from dynamo_tpu.utils.mesh import AXIS_SP, abstract_mesh

        mesh = abstract_mesh(axis_size, (AXIS_SP,))
    except Exception:
        return None
    if hasattr(jax, "shard_map"):
        smap = functools.partial(jax.shard_map, check_vma=False)
    else:
        from jax.experimental.shard_map import shard_map as _sm

        smap = functools.partial(_sm, check_rep=False)

    from dynamo_tpu.ops.ring_attention import ring_attention_inner

    inner = functools.partial(ring_attention_inner, axis_name=AXIS_SP)
    seq, pos = P(None, AXIS_SP, None, None), P(None, AXIS_SP)
    try:
        wrapped = smap(inner, mesh=mesh,
                       in_specs=(seq, seq, seq, pos, pos),
                       out_specs=seq)
    except Exception:
        return None
    h, hk, d = 4, 2, 8
    bf16, i32 = jnp.bfloat16, jnp.int32

    def build(s):
        args = (_sds((1, s, h, d), bf16), _sds((1, s, hk, d), bf16),
                _sds((1, s, hk, d), bf16), _sds((1, s), i32),
                _sds((1, s), i32))
        return Signature(f"s={s}", args, {})

    return Entrypoint(
        name=f"ops.ring_attention[sp{axis_size}]",
        axes={"s": [64, 128]},
        build=build,
        raw_fn=wrapped,
        representatives=[dict(s=128)],
    )


def _mlp_reference_entrypoint() -> Entrypoint:
    """The gated-MLP projection chain at llama3b-v5e dims — the
    MXU-bound share of a real prefill step, priced on its own.

    Under the XLA-fallback lowerings the *whole-entrypoint* intensities
    all land on the bandwidth side of the roofline (the fallback
    attention materializes f32 score matrices and gathers the padded KV
    pool — the Pallas kernels stream both on-chip).  This entry keeps a
    genuinely compute-bound row live in the committed manifest so the
    bound classifier and PF003 are exercised on real dims, not only on
    synthetic test fixtures."""
    import jax.numpy as jnp

    hidden, inter, tokens = 3072, 8192, 8192
    bf16 = jnp.bfloat16

    def mlp(x, w_gate, w_up, w_down):
        import jax

        return (jax.nn.silu(x @ w_gate) * (x @ w_up)) @ w_down

    def build(t):
        args = (_sds((t, hidden), bf16), _sds((hidden, inter), bf16),
                _sds((hidden, inter), bf16), _sds((inter, hidden), bf16))
        return Signature(f"t={t}", args, {})

    return Entrypoint(
        name="roofline.mlp_reference[llama3b-v5e]",
        axes={"t": [tokens]},
        build=build,
        raw_fn=mlp,
        representatives=[dict(t=tokens)],
    )


def build_perf_registry() -> list[Entrypoint]:
    """The tracecheck registry plus perf-only entries: the sequence-
    parallel ring attention body (the one real collective site — only
    this plane prices collectives) and the compute-bound MLP reference
    chain.  Model forwards additionally get their prefill phase as a
    priced representative (tracecheck only eval-shapes it)."""
    eps = build_registry()
    for ep in eps:
        if "phase" in ep.axes:
            reps = list(ep.representatives)
            if {"phase": "prefill"} not in reps:
                reps.append({"phase": "prefill"})
            ep.representatives = reps
    ring = _ring_attention_entrypoint()
    if ring is not None:
        eps.append(ring)
    eps.append(_mlp_reference_entrypoint())
    return eps


def _pallas_kernel_estimate(ep_name: str, sig: Signature) \
        -> Optional[dict]:
    """Price the kernel the TPU path dispatches for this signature from
    the kernel registry's analytic cost table — dims read off the
    signature's ShapeDtypeStructs, context at the worst-case static
    bound (every row at full M*Bs), the same bound the kernels pin
    on-device via ``cost_estimate=``.  Returns None for entrypoints
    with no registered kernel."""
    base = ep_name.partition("[")[0]
    kernel = _PALLAS_PRICED.get(base)
    if kernel is None:
        return None
    from dynamo_tpu.ops.pallas import registry as kreg

    if base == "ops.paged_attention_layer":
        q, cache, _, bt = sig.args[:4]
        b, s_q, h, d = q.shape
        # cache leaf layout: [L, N, 2, Bs, Hk*D] (models/llama.py)
        bs, hkd = cache.shape[3], cache.shape[4]
        cost = kreg.decode_kernel_cost(
            b, s_q, h, hkd // d, d, bs, bt.shape[1],
            [bt.shape[1] * bs] * b, cache_bytes=cache.dtype.itemsize)
    else:  # ops.ragged_prefill_attention
        q, _, _, cache, _, bt = sig.args[:6]
        _, t, h, d = q.shape
        bs, hkd = cache.shape[3], cache.shape[4]
        cost = kreg.ragged_kernel_cost(
            t, h, hkd // d, d, bs, bt.shape[1],
            [bt.shape[1] * bs] * bt.shape[0],
            cache_bytes=cache.dtype.itemsize)
    return {"kernel": kernel, **cost}


def collect_perf_facts(
        registry: Optional[list[Entrypoint]] = None) -> dict:
    """Roofline facts for every registered entrypoint, per
    representative signature (the same config matrix tracecheck
    eval-shapes).  Pure shape-level work: make_jaxpr over
    ShapeDtypeStructs — no weights, no compiles, no model math.
    Pallas-backed ops get the registry's kernel pricing attached
    alongside the fallback roofline (``pallas_kernel``)."""
    registry = registry if registry is not None else build_perf_registry()
    facts: dict[str, dict] = {}
    for ep in registry:
        fn = ep.raw_fn if ep.raw_fn is not None else ep.jit_fn
        if fn is None:
            continue
        sigs: dict[str, dict] = {}
        for rep in ep.representatives:
            sig = ep.build(**rep)
            if sig is None:
                continue
            est = estimate_callable(fn, sig.args, sig.statics)
            kern = _pallas_kernel_estimate(ep.name, sig)
            if kern is not None:
                est["pallas_kernel"] = kern
            sigs[sig.label] = est
        facts[ep.name] = {"signatures": sigs}
    return facts


# ------------------------------------------------------------------- check ----


def check_perf_facts(facts: dict, manifest: Manifest) \
        -> list[TraceFinding]:
    """Findings = drift (facts vs the committed roofline snapshot,
    PF001/PF003/PF004 with tolerance bands) + the intrinsic collective
    census (PF002, count-keyed acceptances like TR006).  Drift is
    resolved by fixing the regression or re-snapshotting with
    ``--update-baseline``; PF002 entries need a justification."""
    findings: list[TraceFinding] = []
    known = manifest.entrypoints

    header = manifest.header or {}
    committed_ver = header.get("constants_version")
    if known and committed_ver != topology.CONSTANTS_VERSION:
        findings.append(TraceFinding(
            "(topology)", "PF001", "constants",
            f"topology constants version drifted: manifest pins "
            f"{committed_ver!r}, obs.topology has "
            f"{topology.CONSTANTS_VERSION!r} — every predicted latency "
            "moved; review the constants change and re-snapshot "
            "(`dynamo-tpu lint --perf --update-baseline`)",
        ))

    for name in sorted(set(facts) - set(known)):
        findings.append(TraceFinding(
            name, "PF001", "added",
            "entrypoint has no committed roofline baseline — audit the "
            "prediction and re-snapshot "
            "(`dynamo-tpu lint --perf --update-baseline`)",
        ))
    for name in sorted(set(known) - set(facts)):
        findings.append(TraceFinding(
            name, "PF001", "removed",
            "manifest entrypoint no longer registered — re-snapshot if "
            "the removal is intended",
        ))

    for name, f in sorted(facts.items()):
        committed = known.get(name) or {}
        old_sigs = committed.get("signatures", {})
        for label, est in sorted(f.get("signatures", {}).items()):
            old = old_sigs.get(label)

            # PF002 is intrinsic: every census entry fires with its
            # count embedded in the acceptance key, so a new collective
            # op/axis OR a count change invalidates the accepted entry
            for ckey, c in est.get("collectives", {}).items():
                findings.append(TraceFinding(
                    name, "PF002", f"{label}:{ckey}x{c['count']}",
                    f"{c['count']} {ckey} collective(s) over "
                    f"{c['axis_size']} chips moving "
                    f"{c['payload_bytes']:,} B "
                    f"(+{c['cost_us']:.1f} us predicted) — accept with "
                    "a justification only if the collective is by "
                    "design on this dispatch",
                ))

            if old is None:
                if known:  # entrypoint-level "added" already fired
                    if name in known:
                        findings.append(TraceFinding(
                            name, "PF001", f"{label}:added",
                            "signature has no committed roofline "
                            "baseline — re-snapshot",
                        ))
                continue

            new_ms = est["predicted"]["total_ms"]
            old_ms = old["predicted"]["total_ms"]
            if old_ms > 0 and new_ms > old_ms * (1 + LATENCY_REL_TOL):
                findings.append(TraceFinding(
                    name, "PF001", label,
                    f"predicted step latency regressed "
                    f"{old_ms:.4f} -> {new_ms:.4f} ms "
                    f"(+{(new_ms / old_ms - 1) * 100:.1f}%, tolerance "
                    f"{LATENCY_REL_TOL * 100:.0f}%): compute "
                    f"{est['predicted']['compute_ms']:.4f} ms, memory "
                    f"{est['predicted']['memory_ms']:.4f} ms, "
                    f"collectives "
                    f"{est['predicted']['collective_ms']:.4f} ms — fix "
                    "the hot path or justify via --update-baseline",
                ))

            old_int, new_int = old["intensity"], est["intensity"]
            if old["predicted"]["bound"] == "compute" and old_int > 0 \
                    and new_int < old_int * (1 - INTENSITY_REL_TOL):
                findings.append(TraceFinding(
                    name, "PF003", label,
                    f"arithmetic intensity dropped {old_int:.2f} -> "
                    f"{new_int:.2f} FLOP/B on a compute-bound "
                    "entrypoint: bytes grew faster than FLOPs (broken "
                    "fusion, layout copy, or upcast on the hot path)",
                ))

            if old["predicted"]["bound"] == "bandwidth" and \
                    old["bytes"] > 0 and \
                    est["bytes"] > old["bytes"] * (1 + BYTES_REL_TOL):
                findings.append(TraceFinding(
                    name, "PF004", label,
                    f"modeled HBM traffic grew {old['bytes']:,} -> "
                    f"{est['bytes']:,} B "
                    f"(+{(est['bytes'] / old['bytes'] - 1) * 100:.1f}%) "
                    "on a bandwidth-bound entrypoint — bytes ARE its "
                    "latency on this side of the roofline",
                ))
    return sorted(findings)


def _perf_header() -> dict:
    return {
        "note": _MANIFEST_NOTE,
        "topology": topology.DEFAULT_TOPOLOGY,
        "constants_version": topology.CONSTANTS_VERSION,
        "tolerances": {
            "latency_rel": LATENCY_REL_TOL,
            "intensity_rel": INTENSITY_REL_TOL,
            "bytes_rel": BYTES_REL_TOL,
        },
    }


# ------------------------------------------------------------- predictions ----


_PREDICTION_CACHE: Optional[list[dict]] = None


def manifest_predictions(path: Optional[Path] = None) -> list[dict]:
    """Flat predicted-latency rows from the *committed* manifest —
    what ``/metrics`` exports as
    ``dynamo_tpu_perf_predicted_step_ms{entrypoint,config,signature}``.
    Reads the JSON once per process (no jax, no tracing)."""
    global _PREDICTION_CACHE
    if path is None and _PREDICTION_CACHE is not None:
        return _PREDICTION_CACHE
    p = Path(path) if path is not None else DEFAULT_MANIFEST_PATH
    rows: list[dict] = []
    if p.is_file():
        try:
            doc = json.loads(p.read_text())
        except (OSError, ValueError):
            doc = {}
        for name, f in sorted(doc.get("entrypoints", {}).items()):
            base, _, cfg = name.partition("[")
            cfg = cfg.rstrip("]")
            for label, est in sorted(
                    f.get("signatures", {}).items()):
                rows.append({
                    "entrypoint": base,
                    "config": cfg,
                    "signature": label,
                    "predicted_ms": est["predicted"]["total_ms"],
                    "bound": est["predicted"]["bound"],
                })
    if path is None:
        _PREDICTION_CACHE = rows
    return rows


# --------------------------------------------------------------------- CLI ----


def run_perf(args, out) -> int:
    """`dynamo-tpu lint --perf`: text or stable JSON, exit 1 on any
    non-accepted finding, `--update-baseline` re-snapshots the manifest
    (carrying justifications by key) and pins the topology-constants
    version in the header."""
    manifest_path = Path(
        getattr(args, "manifest", None) or DEFAULT_MANIFEST_PATH
    )
    manifest = Manifest.load(manifest_path)
    facts = collect_perf_facts()
    findings = check_perf_facts(facts, manifest)

    if getattr(args, "update_baseline", False):
        # drift findings (PF001/PF003/PF004) are resolved by the
        # snapshot itself; the intrinsic census (PF002) becomes
        # accepted entries
        intrinsic = [f for f in findings if f.rule == "PF002"]
        new = Manifest.from_facts(facts, intrinsic, manifest)
        new.header = _perf_header()
        new.save(manifest_path)
        print(
            f"perf manifest updated: {len(facts)} entrypoints, "
            f"{len(intrinsic)} accepted finding"
            f"{'' if len(intrinsic) == 1 else 's'} -> {manifest_path}",
            file=out,
        )
        return 0

    fresh = manifest.filter(findings)
    n_accepted = len(findings) - len(fresh)
    if getattr(args, "fmt", "text") == "json":
        doc = {
            "findings": [f.to_json() for f in fresh],
            "accepted": n_accepted,
            "total": len(findings),
            "entrypoints": sorted(facts),
        }
        print(json.dumps(doc, indent=2, sort_keys=True), file=out)
    else:
        for f in fresh:
            print(f.render(), file=out)
        print(
            f"{len(fresh)} perf finding{'s' if len(fresh) != 1 else ''} "
            f"({n_accepted} accepted) over {len(facts)} entrypoints",
            file=out,
        )
    return 1 if fresh else 0
